"""Device k-way merge + dedup as one sort kernel
(ref: analytic_engine/src/row_iter/{merge.rs,dedup.rs} and the compaction
runner's merge loop — the BASELINE.json "k-way merge-dedup lifted onto TPU").

The reference merges k sorted runs with a BinaryHeap, comparing rows one at
a time. On TPU the same job is a data-parallel sort: concatenate the runs,
sort by (primary key asc, sequence desc), and collapse duplicate keys with
a shift-compare mask. ``lax.sort`` lowers to an efficient multi-operand
device sort, and the dedup mask is one vectorized compare — no per-row
control flow anywhere.

Operand count is the whole game: XLA's variadic sort cost (and, on a
tunneled backend, the upload) scales with the number of u32 words it
carries per row. The r4 kernel carried 8; a merge's actual entropy is far
smaller — timestamps span one segment window (~2^23 ms) and sequences span
the input files (~2^7) — so the hot path packs ``(ts - ts_min, seq_max -
seq)`` into ONE u32 word picked by measured bit widths, keeps the 64-bit
tsid hash as an (hi, lo) pair, and sorts 4 operands: tsid_hi, tsid_lo,
packed rest, row index. The two wider fallbacks (u64 rest pair; the
original fully-general split of every column) engage only when the
measured spans don't fit.

64-bit keys without enabling x64: values are split into order-preserving
(hi, lo) uint32 pairs on host (ops.encoding.split_*), and the device sorts
the pair lexicographically.

Newest-wins ties without a tie-break operand: the input is REVERSED on
host before padding, and the sort is stable — among rows with identical
(key, seq) the LAST input row sorts first, which is what the reference's
overwrite-in-order memtable semantics require. Pad rows carry all-ones
keys (sort to the tail) and are identified exactly by their sorted row
index >= n_valid — no dedicated is_pad operand, and a (vanishingly
unlikely) real row whose key words are all ones still wins its tie against
the pads because it precedes them in input order.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import pad_to_bucket, shape_bucket, split_i64_sortable, split_u64

_U32_MAX = np.uint32(0xFFFFFFFF)

# Kernel-shape keys ((kind, bucket, dedup) — all jit cache keys) whose sort
# kernel has finished compiling, and those with a compile in flight. A
# multi-operand u32 sort can take MINUTES to compile on a remote/tunneled
# backend — a foreground read must never eat that stall, so callers check
# merge_dedup_ready() and fall back to the host merge until the background
# compile lands. Failed compiles back off _FAIL_RETRY_S before retrying.
_ready: set[tuple] = set()
_compiling: set[tuple] = set()
_failed_at: dict[tuple, float] = {}
_compile_lock = threading.Lock()
_FAIL_RETRY_S = 60.0


def _compile_key(key: tuple) -> None:
    kind, bucket, dedup = key
    try:
        zeros = jnp.zeros(bucket, dtype=jnp.uint32)
        if kind == "rk":
            out = _ranked_kernel(
                zeros, zeros, jnp.uint32(0xFFFFFFFF), jnp.uint32(0xFFFFFFFF),
                jnp.int32(bucket), dedup=dedup,
            )
        elif kind == "f32":
            out = _fused32_kernel(
                zeros, zeros, zeros, jnp.uint32(0xFFFFFFFF),
                jnp.int32(bucket), dedup=dedup,
            )
        elif kind == "f64":
            out = _fused64_kernel(
                zeros, zeros, zeros, zeros, jnp.uint32(0xFFFFFFFF),
                jnp.uint32(0xFFFFFFFF), jnp.int32(bucket), dedup=dedup,
            )
        else:
            out = _general_kernel(*([zeros] * 7), dedup=dedup)
        jax.block_until_ready(out)
        with _compile_lock:
            _ready.add(key)
            _failed_at.pop(key, None)
    except Exception:
        import logging
        import time

        logging.getLogger(__name__).exception(
            "background merge-kernel compile failed (%s bucket=%d dedup=%s); "
            "retrying after %.0fs", kind, bucket, dedup, _FAIL_RETRY_S,
        )
        with _compile_lock:
            _failed_at[key] = time.time()
    finally:
        with _compile_lock:
            _compiling.discard(key)


def _ready_or_start_compile(key: tuple) -> bool:
    """True when ``key``'s kernel is compiled; otherwise kicks off (at
    most one) background compile for it and returns False."""
    import time

    with _compile_lock:
        if key in _ready:
            return True
        failed = _failed_at.get(key)
        if failed is not None and time.time() - failed < _FAIL_RETRY_S:
            return False
        if key not in _compiling:
            _compiling.add(key)
            threading.Thread(
                target=_compile_key, args=(key,), daemon=True
            ).start()
        return False


def merge_dedup_ready(n: int, dedup: bool = True) -> bool:
    """Advisory pre-warm of the hot-path (packed u32) kernel for
    ``n``-row merges. Foreground callers that must never eat a compile
    stall should ALSO pass ``require_ready=True`` to
    merge_dedup_permutation — the data's measured spans may route to a
    wider kernel than the one this warms."""
    return _ready_or_start_compile(("f32", shape_bucket(n), dedup))


@functools.partial(jax.jit, static_argnames=("dedup",))
def _ranked_kernel(key_hi, key_lo, mask_hi, mask_lo, n_valid, *, dedup: bool):
    """Fastest path: the WHOLE (tsid-rank, ts, seq desc) key packed into
    one u64 (hi, lo) pair — 3 operands, 2 keys, UNSTABLE sort. Callers
    must guarantee composite uniqueness (deduped sorted runs with
    distinct per-file sequences — compaction inputs): with unique keys an
    unstable sort is deterministic, and no tie-break operand or input
    reversal is needed. ``mask_*`` zero the seq bits for the dedup
    compare. Pads carry all-ones keys (> any real composite, which fits
    63 bits) and are identified by sorted index >= n_valid."""
    n = key_hi.shape[0]
    iota = jax.lax.iota(jnp.uint32, n)
    s_hi, s_lo, s_idx = jax.lax.sort(
        (key_hi, key_lo, iota), num_keys=2, is_stable=False
    )
    perm = s_idx.astype(jnp.int32)
    if dedup:
        k_hi = s_hi & mask_hi
        k_lo = s_lo & mask_lo
        same = (k_hi[1:] == k_hi[:-1]) & (k_lo[1:] == k_lo[:-1])
        keep = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), ~same])
    else:
        keep = jnp.ones(n, dtype=jnp.bool_)
    keep = keep & (s_idx < n_valid.astype(jnp.uint32))
    return perm, keep


def fused32_sort_dedup(tsid_hi, tsid_lo, rest, rest_mask, n_valid, dedup: bool):
    """Pure-jnp body: sort by (tsid, packed (ts, seq desc)) — 4 operands,
    3 keys. Shared by the jitted single-device kernel below and the
    shard_map distributed merge (parallel/dist_merge.py), so the
    reversal/pad/mask contract lives in exactly one place.

    Input arrives REVERSED (last original row first); the stable sort
    therefore resolves exact-duplicate rows to the newest input row, and
    ``perm`` recovers original indices as ``n_valid - 1 - sorted_idx``.
    ``rest_mask`` zeroes the seq bits so the dedup compare sees (ts) only.
    """
    n = tsid_hi.shape[0]
    iota = jax.lax.iota(jnp.uint32, n)
    s_hi, s_lo, s_rest, s_idx = jax.lax.sort(
        (tsid_hi, tsid_lo, rest, iota), num_keys=3, is_stable=True
    )
    perm = n_valid - jnp.int32(1) - s_idx.astype(jnp.int32)
    if dedup:
        key_rest = s_rest & rest_mask
        same = (
            (s_hi[1:] == s_hi[:-1])
            & (s_lo[1:] == s_lo[:-1])
            & (key_rest[1:] == key_rest[:-1])
        )
        keep = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), ~same])
    else:
        keep = jnp.ones(n, dtype=jnp.bool_)
    # Pads were appended after the reversed real rows: sorted idx >= n_valid
    # identifies them exactly (their all-ones keys put them in the tail).
    keep = keep & (s_idx < n_valid.astype(jnp.uint32))
    return perm, keep


@functools.partial(jax.jit, static_argnames=("dedup",))
def _fused32_kernel(tsid_hi, tsid_lo, rest, rest_mask, n_valid, *, dedup: bool):
    return fused32_sort_dedup(tsid_hi, tsid_lo, rest, rest_mask, n_valid, dedup)


@functools.partial(jax.jit, static_argnames=("dedup",))
def _fused64_kernel(
    tsid_hi, tsid_lo, rest_hi, rest_lo, mask_hi, mask_lo, n_valid, *, dedup: bool
):
    """Wide-span variant: packed (ts, seq desc) as a u64 (hi, lo) pair —
    5 operands, 4 keys. Same reversal/stability contract as _fused32."""
    n = tsid_hi.shape[0]
    iota = jax.lax.iota(jnp.uint32, n)
    s_hi, s_lo, s_rhi, s_rlo, s_idx = jax.lax.sort(
        (tsid_hi, tsid_lo, rest_hi, rest_lo, iota), num_keys=4, is_stable=True
    )
    perm = n_valid - jnp.int32(1) - s_idx.astype(jnp.int32)
    if dedup:
        k_rhi = s_rhi & mask_hi
        k_rlo = s_rlo & mask_lo
        same = (
            (s_hi[1:] == s_hi[:-1])
            & (s_lo[1:] == s_lo[:-1])
            & (k_rhi[1:] == k_rhi[:-1])
            & (k_rlo[1:] == k_rlo[:-1])
        )
        keep = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), ~same])
    else:
        keep = jnp.ones(n, dtype=jnp.bool_)
    keep = keep & (s_idx < n_valid.astype(jnp.uint32))
    return perm, keep


@functools.partial(jax.jit, static_argnames=("dedup",))
def _general_kernel(
    is_pad, tsid_hi, tsid_lo, ts_hi, ts_lo, negseq_hi, negseq_lo, *, dedup: bool
):
    """Fully-general fallback (every 64-bit column split, 8 operands —
    the r4 kernel): engages only when the measured ts/seq spans exceed 64
    packed bits, which a segment-scoped merge doesn't produce."""
    n = is_pad.shape[0]
    iota = jax.lax.iota(jnp.uint32, n)
    # Ties on (key, seq) — duplicate keys in ONE write batch share a WAL
    # sequence — resolve to the LAST input row (row order wins, matching
    # the reference's memtable overwrite-in-order semantics): sort the
    # NEGATED index as the final key, recover perm as its complement.
    negidx = jnp.uint32(n - 1) - iota
    sorted_ops = jax.lax.sort(
        (is_pad, tsid_hi, tsid_lo, ts_hi, ts_lo, negseq_hi, negseq_lo, negidx),
        num_keys=8,
        is_stable=True,
    )
    s_pad, s_tsid_hi, s_tsid_lo, s_ts_hi, s_ts_lo, _, _, s_negidx = sorted_ops
    perm = (jnp.uint32(n - 1) - s_negidx).astype(jnp.int32)
    if dedup:
        same = (
            (s_tsid_hi[1:] == s_tsid_hi[:-1])
            & (s_tsid_lo[1:] == s_tsid_lo[:-1])
            & (s_ts_hi[1:] == s_ts_hi[:-1])
            & (s_ts_lo[1:] == s_ts_lo[:-1])
        )
        keep = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), ~same])
    else:
        keep = jnp.ones(n, dtype=jnp.bool_)
    keep = keep & (s_pad == 0)
    return perm, keep


def _pack_rest(ts64: np.ndarray, seq64: np.ndarray):
    """Measure ts/seq spans and pack both into the narrowest key that
    preserves (ts asc, seq desc) order. Returns (kind, payload):

    - ("f32", (rest_u32, mask_u32))          spans fit 32 bits together
    - ("f64", (hi, lo, mask_hi, mask_lo))    spans fit 64 bits together
    - ("gen", None)                          fall back to the general split
    """
    ts_min = np.int64(ts64.min())
    seq_max = np.uint64(seq64.max())
    # Python-int span: int64-wide ranges must not wrap (see pack_ranked_key).
    ts_bits = (int(ts64.max()) - int(ts_min)).bit_length()
    seq_bits = int(seq_max - np.uint64(seq64.min())).bit_length()
    if ts_bits + seq_bits <= 32:
        rest = (
            (ts64 - ts_min).astype(np.uint32) << np.uint32(seq_bits)
        ) | (seq_max - seq64).astype(np.uint32)
        mask = np.uint32(0xFFFFFFFF) ^ np.uint32((1 << seq_bits) - 1)
        return "f32", (rest, mask)
    if ts_bits + seq_bits <= 64:
        rest64 = (
            (ts64 - ts_min).astype(np.uint64) << np.uint64(seq_bits)
        ) | (seq_max - seq64)
        hi, lo = split_u64(rest64)
        if seq_bits >= 32:
            mask_lo = np.uint32(0)
            mask_hi = np.uint32(0xFFFFFFFF) ^ np.uint32((1 << (seq_bits - 32)) - 1)
        else:
            mask_lo = np.uint32(0xFFFFFFFF) ^ np.uint32((1 << seq_bits) - 1)
            mask_hi = np.uint32(0xFFFFFFFF)
        return "f64", (hi, lo, mask_hi, mask_lo)
    return "gen", None


class MergeHandle:
    """An in-flight device merge: the sort was dispatched asynchronously
    (JAX async dispatch — the device computes while the host keeps
    running); ``get()`` blocks for the result. Lets a caller pipeline the
    host-side payload gather of chunk i with the device sort of chunk
    i+1."""

    __slots__ = ("_out", "_n", "_key")

    def __init__(self, out, n: int, key: tuple | None) -> None:
        self._out, self._n, self._key = out, n, key

    def get(self) -> tuple[np.ndarray, np.ndarray]:
        perm, keep = jax.device_get(self._out)  # one RTT for both outputs
        if self._key is not None:  # n==0 ran no kernel: nothing compiled
            with _compile_lock:
                _ready.add(self._key)  # direct callers warm it too
        return perm[: self._n], keep[: self._n]


def pack_ranked_key(
    tsid_rank: np.ndarray,
    ts64: np.ndarray,
    seq64: np.ndarray,
    n_ranks: int,
):
    """Pack (tsid-rank, ts, seq desc) into ONE order-preserving u64 per
    row — built ONCE for a whole merge; the chunked pipeline then ships
    8 bytes/row and sorts 2 u32 keys. None when the measured bit widths
    exceed 63 (the all-ones pad value must stay strictly greater).
    Returns (composite u64 array, dedup mask_hi, mask_lo) — the masks
    zero the seq bits so the dedup compare sees (rank, ts) only."""
    ts_min = np.int64(ts64.min())
    seq_max = np.uint64(seq64.max())
    # Python-int arithmetic: an int64 span >= 2^63 must NOT wrap (a
    # wrapped width would pick a too-narrow kernel and mis-merge).
    ts_bits = (int(ts64.max()) - int(ts_min)).bit_length()
    seq_bits = int(seq_max - np.uint64(seq64.min())).bit_length()
    rank_bits = max(1, int(n_ranks - 1).bit_length())
    if rank_bits + ts_bits + seq_bits > 63:
        return None
    comp = (
        (tsid_rank.astype(np.uint64) << np.uint64(ts_bits + seq_bits))
        | ((ts64 - ts_min).astype(np.uint64) << np.uint64(seq_bits))
        | (seq_max - seq64)
    )
    if seq_bits >= 32:
        mask_lo = np.uint32(0)
        mask_hi = np.uint32(0xFFFFFFFF) ^ np.uint32((1 << (seq_bits - 32)) - 1)
    else:
        mask_lo = np.uint32(0xFFFFFFFF) ^ np.uint32((1 << seq_bits) - 1)
        mask_hi = np.uint32(0xFFFFFFFF)
    return comp, mask_hi, mask_lo


def merge_dedup_dispatch_packed(
    comp: np.ndarray,
    mask_hi: np.uint32,
    mask_lo: np.uint32,
    dedup: bool = True,
    require_ready: bool = False,
) -> MergeHandle | None:
    """Dispatch the 2-key unstable kernel on a pre-packed composite (see
    pack_ranked_key). Caller guarantees composite uniqueness. With
    ``require_ready``, None when the kernel isn't compiled yet (a
    background compile is kicked off)."""
    n = len(comp)
    if require_ready and not _ready_or_start_compile(
        ("rk", shape_bucket(n), dedup)
    ):
        return None
    hi, lo = split_u64(comp)
    args = [
        pad_to_bucket(hi, n, fill=_U32_MAX),
        pad_to_bucket(lo, n, fill=_U32_MAX),
    ]
    out = _ranked_kernel(
        *(jnp.asarray(a) for a in args),
        jnp.uint32(mask_hi), jnp.uint32(mask_lo), jnp.int32(n),
        dedup=dedup,
    )
    return MergeHandle(out, n, ("rk", shape_bucket(n), dedup))


def merge_dedup_dispatch(
    tsid: np.ndarray,
    ts: np.ndarray,
    seq: np.ndarray,
    dedup: bool = True,
    tsid_rank: np.ndarray | None = None,
    n_ranks: int = 0,
    unique: bool = False,
    require_ready: bool = False,
) -> MergeHandle | None:
    """Asynchronously dispatch the merge-sort kernel; see
    merge_dedup_permutation for semantics. The returned handle's ``get()``
    yields ``(perm, keep)``.

    ``tsid_rank``/``n_ranks``: dense ranks of each row's tsid in the
    merge's sorted tsid universe (compaction builds them for free from
    its sorted input runs). ``unique=True`` asserts no two rows share
    (tsid, ts, seq) — true for deduped runs with distinct per-file
    sequences. Together they unlock the 2-key unstable packed kernel when
    the measured bit widths fit 63 bits.

    ``require_ready``: None instead of a compile stall when the kernel
    the DATA routes to (which may be wider than the one
    merge_dedup_ready pre-warms) isn't compiled — a background compile
    starts and the caller takes its host path."""
    n = len(tsid)
    if n == 0:
        return MergeHandle(
            (np.empty(0, dtype=np.int32), np.empty(0, dtype=np.bool_)), 0,
            None,
        )

    ts64 = ts.astype(np.int64, copy=False)
    seq64 = seq.astype(np.uint64, copy=False)

    if tsid_rank is not None and unique:
        packed_key = pack_ranked_key(tsid_rank, ts64, seq64, n_ranks)
        if packed_key is not None:
            comp, mask_hi, mask_lo = packed_key
            return merge_dedup_dispatch_packed(
                comp, mask_hi, mask_lo, dedup, require_ready=require_ready
            )

    kind, packed = _pack_rest(ts64, seq64)
    if require_ready and not _ready_or_start_compile(
        (kind, shape_bucket(n), dedup)
    ):
        return None

    if kind == "gen":
        tsid_hi, tsid_lo = split_u64(tsid)
        ts_hi, ts_lo = split_i64_sortable(ts64)
        negseq = ~seq64
        negseq_hi, negseq_lo = split_u64(negseq)
        is_pad = pad_to_bucket(np.zeros(n, dtype=np.uint32), n, fill=1)
        args = [
            is_pad,
            pad_to_bucket(tsid_hi, n),
            pad_to_bucket(tsid_lo, n),
            pad_to_bucket(ts_hi, n),
            pad_to_bucket(ts_lo, n),
            pad_to_bucket(negseq_hi, n),
            pad_to_bucket(negseq_lo, n),
        ]
        out = _general_kernel(*(jnp.asarray(a) for a in args), dedup=dedup)
    else:
        # Reverse BEFORE splitting/padding: stable sort + reversed input
        # = newest input row first among exact-duplicate (key, seq) rows.
        rev = slice(None, None, -1)
        tsid_hi, tsid_lo = split_u64(tsid[rev])
        if kind == "f32":
            rest, mask = packed
            args = [
                pad_to_bucket(tsid_hi, n, fill=_U32_MAX),
                pad_to_bucket(tsid_lo, n, fill=_U32_MAX),
                pad_to_bucket(rest[rev], n, fill=_U32_MAX),
            ]
            out = _fused32_kernel(
                *(jnp.asarray(a) for a in args),
                jnp.uint32(mask), jnp.int32(n), dedup=dedup,
            )
        else:
            hi, lo, mask_hi, mask_lo = packed
            args = [
                pad_to_bucket(tsid_hi, n, fill=_U32_MAX),
                pad_to_bucket(tsid_lo, n, fill=_U32_MAX),
                pad_to_bucket(hi[rev], n, fill=_U32_MAX),
                pad_to_bucket(lo[rev], n, fill=_U32_MAX),
            ]
            out = _fused64_kernel(
                *(jnp.asarray(a) for a in args),
                jnp.uint32(mask_hi), jnp.uint32(mask_lo), jnp.int32(n),
                dedup=dedup,
            )

    return MergeHandle(out, n, (kind, shape_bucket(n), dedup))


def merge_dedup_permutation(
    tsid: np.ndarray,
    ts: np.ndarray,
    seq: np.ndarray,
    dedup: bool = True,
    tsid_rank: np.ndarray | None = None,
    n_ranks: int = 0,
    unique: bool = False,
    require_ready: bool = False,
) -> tuple[np.ndarray, np.ndarray] | None:
    """Merge-sort order + survivor mask for concatenated sorted runs.

    Returns ``(perm, keep)`` of length == len(input): ``perm`` is the row
    permutation sorting by (tsid, ts, seq desc); ``keep[i]`` says whether
    sorted position i survives dedup (first — i.e. newest-sequence — row of
    each (tsid, ts) key). Apply as ``rows.take(perm[keep])``. With
    ``require_ready``, None when the routed kernel isn't compiled yet
    (background compile started; caller takes its host path).

    The device does all comparison work; callers gather payload columns
    host-side (string columns can't live on device anyway).
    """
    h = merge_dedup_dispatch(
        tsid, ts, seq, dedup=dedup,
        tsid_rank=tsid_rank, n_ranks=n_ranks, unique=unique,
        require_ready=require_ready,
    )
    return None if h is None else h.get()
