"""Device k-way merge + dedup as one sort kernel
(ref: analytic_engine/src/row_iter/{merge.rs,dedup.rs} and the compaction
runner's merge loop — the BASELINE.json "k-way merge-dedup lifted onto TPU").

The reference merges k sorted runs with a BinaryHeap, comparing rows one at
a time. On TPU the same job is a data-parallel sort: concatenate the runs,
sort by (primary key asc, sequence desc), and collapse duplicate keys with
a shift-compare mask. ``lax.sort`` lowers to an efficient multi-operand
device sort, and the dedup mask is one vectorized compare — no per-row
control flow anywhere.

64-bit keys without enabling x64: tsid/timestamp/sequence are split into
order-preserving (hi, lo) uint32 pairs on host (ops.encoding.split_*), and
the device sorts by the pair lexicographically. Padding rows carry an
explicit is_pad key that sorts strictly after every real row, so the valid
prefix of the output is exactly the merged result.
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import pad_to_bucket, shape_bucket, split_i64_sortable, split_u64

# Kernel-shape keys ((bucket, dedup) — both are jit cache keys) whose sort
# kernel has finished compiling, and those with a compile in flight. The
# 8-operand u32 sort can take MINUTES to compile on a remote/tunneled
# backend — a foreground read must never eat that stall, so callers check
# merge_dedup_ready() and fall back to the host merge until the background
# compile lands. Failed compiles back off _FAIL_RETRY_S before retrying.
_ready: set[tuple[int, bool]] = set()
_compiling: set[tuple[int, bool]] = set()
_failed_at: dict[tuple[int, bool], float] = {}
_compile_lock = threading.Lock()
_FAIL_RETRY_S = 60.0


def _compile_bucket(key: tuple[int, bool]) -> None:
    bucket, dedup = key
    try:
        zeros = jnp.zeros(bucket, dtype=jnp.uint32)
        jax.block_until_ready(
            _merge_dedup_kernel(*([zeros] * 7), dedup=dedup)
        )
        with _compile_lock:
            _ready.add(key)
            _failed_at.pop(key, None)
    except Exception:
        import logging
        import time

        logging.getLogger(__name__).exception(
            "background merge-kernel compile failed (bucket=%d dedup=%s); "
            "retrying after %.0fs", bucket, dedup, _FAIL_RETRY_S,
        )
        with _compile_lock:
            _failed_at[key] = time.time()
    finally:
        with _compile_lock:
            _compiling.discard(key)


def merge_dedup_ready(n: int, dedup: bool = True) -> bool:
    """True when the kernel for ``n``-row merges is compiled; otherwise
    kicks off (at most one) background compile for that kernel shape and
    returns False so the caller can take the host path without stalling."""
    import time

    key = (shape_bucket(n), dedup)
    with _compile_lock:
        if key in _ready:
            return True
        failed = _failed_at.get(key)
        if failed is not None and time.time() - failed < _FAIL_RETRY_S:
            return False
        if key not in _compiling:
            _compiling.add(key)
            threading.Thread(
                target=_compile_bucket, args=(key,), daemon=True
            ).start()
        return False


@functools.partial(jax.jit, static_argnames=("dedup",))
def _merge_dedup_kernel(
    is_pad, tsid_hi, tsid_lo, ts_hi, ts_lo, negseq_hi, negseq_lo, *, dedup: bool
):
    n = is_pad.shape[0]
    iota = jax.lax.iota(jnp.uint32, n)
    # Ties on (key, seq) — duplicate keys in ONE write batch share a WAL
    # sequence — resolve to the LAST input row (row order wins, matching
    # the reference's memtable overwrite-in-order semantics): sort the
    # NEGATED index as the final key, recover perm as its complement.
    negidx = jnp.uint32(n - 1) - iota
    sorted_ops = jax.lax.sort(
        (is_pad, tsid_hi, tsid_lo, ts_hi, ts_lo, negseq_hi, negseq_lo, negidx),
        num_keys=8,
        is_stable=True,
    )
    s_pad, s_tsid_hi, s_tsid_lo, s_ts_hi, s_ts_lo, _, _, s_negidx = sorted_ops
    perm = (jnp.uint32(n - 1) - s_negidx).astype(jnp.int32)
    if dedup:
        same = (
            (s_tsid_hi[1:] == s_tsid_hi[:-1])
            & (s_tsid_lo[1:] == s_tsid_lo[:-1])
            & (s_ts_hi[1:] == s_ts_hi[:-1])
            & (s_ts_lo[1:] == s_ts_lo[:-1])
        )
        keep = jnp.concatenate([jnp.ones(1, dtype=jnp.bool_), ~same])
    else:
        keep = jnp.ones(n, dtype=jnp.bool_)
    keep = keep & (s_pad == 0)
    return perm, keep


def merge_dedup_permutation(
    tsid: np.ndarray,
    ts: np.ndarray,
    seq: np.ndarray,
    dedup: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Merge-sort order + survivor mask for concatenated sorted runs.

    Returns ``(perm, keep)`` of length == len(input): ``perm`` is the row
    permutation sorting by (tsid, ts, seq desc); ``keep[i]`` says whether
    sorted position i survives dedup (first — i.e. newest-sequence — row of
    each (tsid, ts) key). Apply as ``rows.take(perm[keep])``.

    The device does all comparison work; callers gather payload columns
    host-side (string columns can't live on device anyway).
    """
    n = len(tsid)
    if n == 0:
        return np.empty(0, dtype=np.int32), np.empty(0, dtype=np.bool_)

    tsid_hi, tsid_lo = split_u64(tsid)
    ts_hi, ts_lo = split_i64_sortable(ts)
    # Bitwise NOT of the unsigned sequence sorts descending (newest first).
    negseq = ~seq.astype(np.uint64)
    negseq_hi, negseq_lo = split_u64(negseq)

    is_pad = pad_to_bucket(np.zeros(n, dtype=np.uint32), n, fill=1)
    args = [
        is_pad,
        pad_to_bucket(tsid_hi, n),
        pad_to_bucket(tsid_lo, n),
        pad_to_bucket(ts_hi, n),
        pad_to_bucket(ts_lo, n),
        pad_to_bucket(negseq_hi, n),
        pad_to_bucket(negseq_lo, n),
    ]
    out = _merge_dedup_kernel(*(jnp.asarray(a) for a in args), dedup=dedup)
    perm, keep = jax.device_get(out)  # one RTT for both outputs
    with _compile_lock:
        _ready.add((shape_bucket(n), dedup))  # direct callers warm it too
    return perm[:n], keep[:n]
