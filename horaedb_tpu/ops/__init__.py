"""The TPU compute path.

Where the reference executes queries through DataFusion's vectorized CPU
operators and compaction through a BinaryHeap merge iterator, this package
compiles the same work into XLA programs:

- ``scan_agg``     — ONE fused jit kernel for filter -> time-bucket ->
                     group-by -> aggregate (the north-star insertion point:
                     plans whose leaves are SST scans with agg on top).
- ``merge_dedup``  — device sort-based k-way merge + duplicate collapse
                     (compaction's hot loop, ref row_iter/merge.rs).
- ``encoding``     — host-side prep: dense series codes, time buckets,
                     padding to compile-friendly shapes.

Everything here obeys XLA's rules: static shapes (inputs padded to shape
buckets), no data-dependent control flow, masks instead of branches.
"""

from .encoding import (
    PaddedBatch,
    encode_group_codes,
    pad_to_bucket,
    shape_bucket,
)
from .scan_agg import AGG_OPS, ScanAggSpec, scan_aggregate
from .scan_topk import RawScanSpec
from .merge_dedup import merge_dedup_permutation

__all__ = [
    "PaddedBatch",
    "encode_group_codes",
    "pad_to_bucket",
    "shape_bucket",
    "AGG_OPS",
    "ScanAggSpec",
    "RawScanSpec",
    "scan_aggregate",
    "merge_dedup_permutation",
]
