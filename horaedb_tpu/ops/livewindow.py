"""The fused live-window fold kernel: ingest batch -> ring partials.

The live-window state (state/livewindow.py) keeps per-(table, window,
group-set) partial aggregates in fixed-size device rings: one row per
time bucket (slot = bucket_id % depth), one column per group. An ingest
batch updates every ring array in ONE device dispatch — four scatter
adds/mins/maxs plus the counter-increment scatter fused into a single
jitted program — so write-time state maintenance costs one kernel
launch, never per-row host work.

Layout contract (prepared by the state layer on host):

- ``slot``       int32[N]: ring slot per row; ``depth`` for rows that
                 must not fold (padding, NULL values, below-tail late
                 rows) — out-of-range scatter indices drop
                 (``mode="drop"``), so masking costs nothing;
- ``grp``        int32[N]: dense group index per row;
- ``val``        f32[N]:   the value column;
- ``pair_slot``/``pair_grp``/``pair_delta``: same encoding for the
                 PromQL counter chain — one entry per consecutive
                 same-series same-bucket sample pair, carrying the
                 reset-adjusted increment attributed to the later
                 sample's bucket;
- ``reset_mask`` bool[depth]: ring slots a head advance reuses; they
                 re-initialise inside the same dispatch (no separate
                 clear kernel).

Like scan_agg's monoid state, the ring cells are (count, sum, min, max)
partials: any re-aggregation (query step == window here, so a read is a
straight gather) stays exact up to f32 accumulation.
"""

from __future__ import annotations

import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from .encoding import next_pow2


@jax.jit
def _fold_body(counts, sums, mins, maxs, inc,
               reset_mask, slot, grp, val, pair_slot, pair_grp, pair_delta):
    # Reused ring slots re-initialise first, then the batch folds in.
    rm = reset_mask[:, None]
    counts = jnp.where(rm, jnp.int32(0), counts)
    sums = jnp.where(rm, jnp.float32(0.0), sums)
    mins = jnp.where(rm, jnp.float32(jnp.inf), mins)
    maxs = jnp.where(rm, jnp.float32(-jnp.inf), maxs)
    inc = jnp.where(rm, jnp.float32(0.0), inc)
    one = jnp.ones_like(val, dtype=jnp.int32)
    counts = counts.at[slot, grp].add(one, mode="drop")
    sums = sums.at[slot, grp].add(val, mode="drop")
    mins = mins.at[slot, grp].min(val, mode="drop")
    maxs = maxs.at[slot, grp].max(val, mode="drop")
    inc = inc.at[pair_slot, pair_grp].add(pair_delta, mode="drop")
    return counts, sums, mins, maxs, inc


@jax.jit
def _gather_body(counts, sums, mins, maxs, inc, slot_idx):
    # One gather per array; stacked fetch = one host RTT for a read.
    return (
        counts[slot_idx],
        sums[slot_idx],
        mins[slot_idx],
        maxs[slot_idx],
        inc[slot_idx],
    )


def alloc_rings(depth: int, cap: int):
    """Fresh device ring arrays for ``depth`` buckets x ``cap`` groups."""
    return (
        jnp.zeros((depth, cap), dtype=jnp.int32),
        jnp.zeros((depth, cap), dtype=jnp.float32),
        jnp.full((depth, cap), jnp.inf, dtype=jnp.float32),
        jnp.full((depth, cap), -jnp.inf, dtype=jnp.float32),
        jnp.zeros((depth, cap), dtype=jnp.float32),
    )


def rings_nbytes(depth: int, cap: int) -> int:
    """Device bytes the five ring arrays occupy (4B cells)."""
    return depth * cap * 4 * 5


def _pad_rows(depth: int, slot, grp, val):
    n = len(slot)
    m = next_pow2(max(n, 1), floor=8)
    if m == n:
        return slot, grp, val
    ps = np.full(m, depth, dtype=np.int32)  # OOB slot -> dropped
    pg = np.zeros(m, dtype=np.int32)
    pv = np.zeros(m, dtype=np.float32)
    ps[:n], pg[:n], pv[:n] = slot, grp, val
    return ps, pg, pv


def fold_batch(rings, reset_mask, slot, grp, val,
               pair_slot, pair_grp, pair_delta):
    """Fold one prepared ingest batch into the rings; returns new rings.

    Row arrays are padded to powers of two on host (stable jit keys);
    padding rows carry slot == depth and drop inside the scatter.
    """
    from ..obs.device import cost_analysis, timed_dispatch
    from ..utils.querystats import note_kernel_dispatch

    depth = int(rings[0].shape[0])
    cap = int(rings[0].shape[1])
    slot, grp, val = _pad_rows(depth, slot, grp, val)
    pair_slot, pair_grp, pair_delta = _pad_rows(
        depth, pair_slot, pair_grp, pair_delta
    )
    args = (
        *rings,
        jnp.asarray(np.ascontiguousarray(reset_mask, dtype=np.bool_)),
        jnp.asarray(slot.astype(np.int32)),
        jnp.asarray(grp.astype(np.int32)),
        jnp.asarray(val.astype(np.float32)),
        jnp.asarray(pair_slot.astype(np.int32)),
        jnp.asarray(pair_grp.astype(np.int32)),
        jnp.asarray(pair_delta.astype(np.float32)),
    )
    t0 = _time.perf_counter()
    out = timed_dispatch("state_fold", lambda: _fold_body(*args))
    note_kernel_dispatch(
        ("state_fold", depth, cap, len(slot), len(pair_slot)),
        _time.perf_counter() - t0,
        kind="state_fold",
        cost_fn=lambda: cost_analysis(_fold_body, args, {}),
    )
    return out


def gather_buckets(rings, slots):
    """Read ``slots`` (list of ring slots) out of the rings — one gather
    dispatch + one host fetch; returns host numpy arrays
    (counts, sums, mins, maxs, inc), each [len(slots), cap]."""
    from ..obs.device import timed_dispatch

    n = len(slots)
    m = next_pow2(max(n, 1), floor=8)
    idx = np.zeros(m, dtype=np.int32)
    idx[:n] = np.asarray(slots, dtype=np.int32)
    out = timed_dispatch(
        "state_fold", lambda: _gather_body(*rings, jnp.asarray(idx))
    )
    host = jax.device_get(out)  # one RTT for the whole read
    return tuple(np.asarray(a)[:n] for a in host)
