"""Fused filter + top-k / bounded-selection kernels for raw reads.

The aggregate path went device-native in stages (fused scan-agg, HBM
scan cache, learned kernel routing); this module gives the last major
host-only query shape — non-aggregate reads, above all the dashboard
staple ``SELECT ... ORDER BY ts DESC LIMIT n`` — the same treatment.
Both kernels run over the scan cache's HBM-resident columns (series
codes, relative timestamps, value columns), evaluate the per-query
predicate as a device mask (series allow-list + time range + numeric
field comparisons — the exact mask ``ops.scan_agg`` builds), and return
only ROW INDICES:

- **top-k** (``ORDER BY <ts|field> [DESC] LIMIT n``): a bisection
  threshold select. ``jax.lax.top_k``/``sort`` are the obvious
  primitives but measure catastrophically (~50ms/131k rows on XLA-CPU;
  sort-based on TPU too) — instead the k-th key is found by 32 fixed
  bisection steps over the int32 key domain, each a fully-fused masked
  count-reduce (O(32n) streaming reads, no sort), then the >threshold
  rows plus lowest-row-id ties compact via cumsum + ``searchsorted``
  (~3ms for the same shape — measured 2026-08-03, XLA-CPU). Ties break
  toward the smaller resident row id — the same stable order the host
  lexsort produces. Only k indices leave the device; the host gathers
  k rows and finishes exactly.
- **bounded selection**: cumsum + ``searchsorted`` compaction of every
  passing row id into a ``HORAEDB_RAW_MAX_ROWS``-bounded buffer (the
  scatter formulation costs ~13x more on XLA-CPU — scatter is the
  priced primitive, see ops/hash_agg.py). The executor only dispatches
  it when the (exact, host-computed) candidate bound fits the buffer,
  so the compaction can never truncate silently.

Float sort keys travel through the classic order-preserving f32->int32
bit transform, so one integer threshold search serves both ``ORDER BY
ts`` and ``ORDER BY field`` and the masked-row sentinel (INT32_MIN) is
provably outside the real key domain (even ``-inf`` maps above it).

Packed variants follow ops/scan_agg's RTT-minimized serving discipline:
one content-cached session upload (the allow-list), one per-query int32
dyn upload (filter literals bitcast + time bounds), one int32 fetch.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.env import env_int
from .encoding import decode_layouts, next_pow2

_I32_MIN = -(2**31)


def raw_device_enabled() -> bool:
    """HORAEDB_RAW_DEVICE kill switch: 0/off/false pins every raw
    (non-aggregate) read to the host path. Read per query so operators
    can flip it live."""
    return os.environ.get("HORAEDB_RAW_DEVICE", "1") not in (
        "0", "off", "false",
    )


def raw_max_rows() -> int:
    """HORAEDB_RAW_MAX_ROWS: ceiling on rows a device raw read may
    select/gather (bounds both the selection buffer and top-k's
    limit+offset). Queries whose candidate bound exceeds it fall back
    to the host path. Guarded parse — a typo degrades to the default."""
    return env_int("HORAEDB_RAW_MAX_ROWS", 1 << 18)


@dataclass(frozen=True)
class RawScanSpec:
    """Static shape/op configuration — the jit cache key for raw reads.

    Exactly one of ``k`` (top-k slots) / ``select_slots`` (selection
    buffer) is nonzero; both are padded to powers of two so a LIMIT
    sweep mints a bounded number of compiled programs.
    """

    k: int = 0
    descending: bool = True
    key_is_ts: bool = True
    key_field: int = 0  # row of ``values`` when key_is_ts is False
    numeric_filters: tuple[tuple[int, str], ...] = ()
    select_slots: int = 0
    # Compressed-layout descriptors (ops.encoding, ISSUE 19) — static jit
    # keys, same contract as ScanAggSpec. The sort-key field always fully
    # decodes; filter-only dict fields stay in the code domain (the
    # executor pre-translates their literals against the sorted dict).
    value_layouts: tuple = ()
    ts_layout: tuple = ("raw",)
    series_layout: tuple = ("raw",)


def padded_k(n_rows: int, limit_plus_offset: int) -> int:
    """Top-k slot count: pow2-padded, clamped to the resident row count
    (lax.top_k requires k <= n; k == n degenerates to a full sort)."""
    return min(next_pow2(max(limit_plus_offset, 1), floor=16), max(n_rows, 1))


def padded_select_slots(estimate: int) -> int:
    """Selection buffer size: pow2 bucket of the exact candidate bound
    (floor 1024 keeps the jit-key count small for dashboard queries)."""
    return next_pow2(max(estimate, 1), floor=1024)


def f32_sort_key(v):
    """Monotone f32 -> int32: signed integer order equals float order
    (-inf < ... < -0 < +0 < ... < +inf < NaN). Real keys never reach
    INT32_MIN, so it is a safe masked-row sentinel."""
    u = jax.lax.bitcast_convert_type(v.astype(jnp.float32), jnp.uint32)
    sign = (u >> 31) == 1
    u2 = jnp.where(sign, ~u, u | jnp.uint32(0x80000000))
    return jax.lax.bitcast_convert_type(
        u2 ^ jnp.uint32(0x80000000), jnp.int32
    )


def _raw_mask(
    series_codes,
    ts_rel,
    values,
    allowed_series,
    literals,
    lo_rel,
    hi_rel,
    numeric_filters: tuple[tuple[int, int], ...],
):
    """The shared predicate mask: allow-list + time range + numeric
    filters (same static op codes as scan_agg_body)."""
    m = allowed_series[series_codes]
    m = m & (ts_rel >= lo_rel) & (ts_rel < hi_rel)
    for i, (field_idx, op_code) in enumerate(numeric_filters):
        v = values[field_idx].astype(jnp.float32)
        lit = literals[i]
        if op_code == 0:
            m = m & (v == lit)
        elif op_code == 1:
            m = m & (v != lit)
        elif op_code == 2:
            m = m & (v < lit)
        elif op_code == 3:
            m = m & (v <= lit)
        elif op_code == 4:
            m = m & (v > lit)
        else:
            m = m & (v >= lit)
    return m


def _sort_key(ts_rel, values, m, *, descending: bool, key_is_ts: bool,
              key_field: int):
    """Masked int32 sort key, largest-first == result order."""
    if key_is_ts:
        key = ts_rel.astype(jnp.int32)
    else:
        v = values[key_field].astype(jnp.float32)
        key = f32_sort_key(v)
        if not descending:
            key = -key
        # NaN samples (valid, non-NULL — np.lexsort places NaN LAST in
        # both directions, and the host path must stay the reference):
        # pin them just above the sentinel AFTER the direction flip, so
        # they rank below every real value either way instead of above
        # +inf where the bit transform puts them.
        key = jnp.where(jnp.isnan(v), jnp.int32(_I32_MIN + 1), key)
        return jnp.where(m, key, jnp.int32(_I32_MIN))
    if not descending:
        # Real keys never equal INT32_MIN (ts_rel >= 0; see f32_sort_key),
        # so the negation cannot overflow.
        key = -key
    return jnp.where(m, key, jnp.int32(_I32_MIN))


def _kth_threshold(key, k: int, key_lo, key_hi):
    """Bisection for the k-th largest key: the returned ``thr``
    satisfies count(key > thr) < k <= count(key >= thr) whenever at
    least k real (non-sentinel) keys exist. Each step is one fused
    count-reduce over the keys — O(n) streaming work per step, no sort,
    no scatter — and the loop runs log2(hi - lo) steps: callers seed
    ``[key_lo, key_hi]`` with known key bounds (the query's own time
    range for ts keys — a day of millisecond keys converges in ~27
    steps instead of 32; full int32 domain when unknown). Seeds must
    only BRACKET the real keys: key_lo strictly below every real key
    (the INT32_MIN sentinel is always below key_lo), key_hi at least
    the max real key. Overflow-safe signed midpoint via the
    (a & b) + ((a ^ b) >> 1) identity."""

    def cond(c):
        lo, hi = c
        return hi > lo + 1

    def body(c):
        lo, hi = c
        mid = (lo & hi) + ((lo ^ hi) >> 1)
        cnt = (key > mid).sum(dtype=jnp.int32)
        return jax.lax.cond(
            cnt >= k,
            lambda: (mid, hi),
            # hi stays strictly above lo (count(>t) only shrinks as t
            # grows, so the invariant count(> hi) < k survives the clamp)
            lambda: (lo, jnp.maximum(mid, lo + 1)),
        )

    lo, hi = jax.lax.while_loop(
        cond, body, (key_lo.astype(jnp.int32), key_hi.astype(jnp.int32))
    )
    return hi


def _compact(mask, slots: int):
    """Row indices of the first ``slots`` True entries, ascending —
    cumsum + searchsorted (the cumsum is monotone) instead of a scatter.
    Slots past the count return index n; callers mask them."""
    cs = jnp.cumsum(mask.astype(jnp.int32))
    j = jnp.arange(slots, dtype=jnp.int32)
    return (
        jnp.searchsorted(cs, j + 1, side="left").astype(jnp.int32),
        cs[-1] if mask.shape[0] else jnp.int32(0),
    )


def topk_key_bounds(
    descending: bool, key_is_ts: bool, lo_rel: int, hi_rel: int
) -> tuple[int, int]:
    """Host-side bisection seeds bracketing every real sort key: the
    query's own relative time range for ts keys (DESC: key == ts_rel in
    [lo_rel, hi_rel); ASC: key == -ts_rel). Float keys span the full
    int32 domain INCLUDING the NaN slot at INT32_MIN + 1 (_sort_key
    pins NaN samples there), so their lower seed is the sentinel
    itself — the strict/tie masks AND the row mask, so sentinel rows
    still can't be selected."""
    if not key_is_ts:
        return _I32_MIN, 2**31 - 1
    if descending:
        return lo_rel - 1, hi_rel
    return -hi_rel, -lo_rel + 1


def raw_topk_body(
    series_codes,
    ts_rel,
    values,
    allowed_series,
    literals,
    lo_rel,
    hi_rel,
    key_lo,
    key_hi,
    *,
    k: int,
    descending: bool,
    key_is_ts: bool,
    key_field: int,
    numeric_filters: tuple[tuple[int, int], ...],
):
    """-> (keys int32[k], row idx int32[k]); slots whose key is the
    INT32_MIN sentinel hold no passing row. The k selected rows are the
    top-k by key with ties broken toward the smaller resident row id;
    SLOT ORDER is unspecified (strict rows first in row order, then
    ties) — callers re-sort the k gathered rows anyway. Pure body —
    also the per-shard program inside parallel/dist_raw's shard_map."""
    m = _raw_mask(
        series_codes, ts_rel, values, allowed_series, literals,
        lo_rel, hi_rel, numeric_filters,
    )
    key = _sort_key(
        ts_rel, values, m,
        descending=descending, key_is_ts=key_is_ts, key_field=key_field,
    )
    thr = _kth_threshold(key, k, key_lo, key_hi)
    strict = key > thr  # sentinel rows can never exceed thr (> I32_MIN)
    tie = m & (key == thr)
    i_strict, n_strict = _compact(strict, k)
    i_tie, _ = _compact(tie, k)
    total = m.sum(dtype=jnp.int32)
    j = jnp.arange(k, dtype=jnp.int32)
    # strict rows fill the first n_strict slots; lowest-row-id ties the rest
    idx = jnp.where(
        j < n_strict,
        i_strict,
        # shift the tie stream past the strict prefix (gather-safe clamp)
        i_tie[jnp.clip(j - n_strict, 0, k - 1)],
    )
    valid = j < jnp.minimum(jnp.int32(k), total)
    n = series_codes.shape[0]
    keys_out = jnp.where(
        valid, key[jnp.clip(idx, 0, n - 1)], jnp.int32(_I32_MIN)
    )
    return keys_out, jnp.where(valid, idx, jnp.int32(-1))


def raw_select_body(
    series_codes,
    ts_rel,
    values,
    allowed_series,
    literals,
    lo_rel,
    hi_rel,
    *,
    select_slots: int,
    numeric_filters: tuple[tuple[int, int], ...],
):
    """-> (row idx int32[slots] in resident order, passing count).

    The caller guarantees count <= slots (exact host-side candidate
    bound), so the first ``count`` slots are exactly the passing rows in
    (series, ts) resident order; the rest are -1."""
    m = _raw_mask(
        series_codes, ts_rel, values, allowed_series, literals,
        lo_rel, hi_rel, numeric_filters,
    )
    idx, count = _compact(m, select_slots)
    j = jnp.arange(select_slots, dtype=jnp.int32)
    return jnp.where(j < count, idx, jnp.int32(-1)), count


# ---- RTT-minimized packed entry points ------------------------------------
#
# Same discipline as scan_agg's packed serving path: the session (the
# series allow-list) is content-cached on the cache entry (ONE upload per
# distinct tag-filter shape, zero for the dashboard steady state), the
# per-query scalars ride ONE int32 dyn buffer, and the result is ONE
# int32 fetch.


def pack_raw_dyn(
    filter_literals: Sequence[float],
    lo_rel: int,
    hi_rel: int,
    key_lo: int = _I32_MIN,
    key_hi: int = 2**31 - 1,
) -> np.ndarray:
    """[literals (f32 bitcast) | lo, hi, key_lo, key_hi] — one int32
    upload (the selection kernel ignores the trailing key seeds)."""
    lits = np.asarray(filter_literals, dtype=np.float32).view(np.int32)
    return np.concatenate(
        [lits, np.array([lo_rel, hi_rel, key_lo, key_hi], dtype=np.int32)]
    )


def _unpack_dyn(dyn, numeric_filters):
    n_f = len(numeric_filters)
    literals = jax.lax.bitcast_convert_type(dyn[:n_f], jnp.float32)
    return literals, dyn[n_f], dyn[n_f + 1], dyn[n_f + 2], dyn[n_f + 3]


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "descending", "key_is_ts", "key_field", "numeric_filters",
        "value_layouts", "ts_layout", "series_layout",
    ),
)
def raw_topk_packed(
    series_codes,
    ts_rel,
    values,
    session,  # int32[S+1]: the allow-list (raw sessions carry no group map)
    dyn,  # int32[n_f + 2]
    *,
    k: int,
    descending: bool,
    key_is_ts: bool,
    key_field: int,
    numeric_filters: tuple[tuple[int, int], ...],
    value_layouts: tuple = (),
    ts_layout: tuple = ("raw",),
    series_layout: tuple = ("raw",),
):
    """-> int32[k] resident row indices, -1 in slots with no passing row."""
    literals, lo, hi, key_lo, key_hi = _unpack_dyn(dyn, numeric_filters)
    series_codes, ts_rel, values = decode_layouts(
        series_codes, ts_rel, values, series_layout, ts_layout, value_layouts
    )
    _, idx = raw_topk_body(
        series_codes, ts_rel, values, session != 0, literals, lo, hi,
        key_lo, key_hi,
        k=k, descending=descending, key_is_ts=key_is_ts,
        key_field=key_field, numeric_filters=numeric_filters,
    )
    return idx


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "descending", "key_is_ts", "key_field", "numeric_filters",
        "value_layouts", "ts_layout", "series_layout",
    ),
)
def raw_topk_cohort(
    series_codes,
    ts_rel,
    values,
    sessions,  # int32[B, S+1]: one allow-list row per member
    dyns,  # int32[B, n_f + 4]: one packed dyn row per member
    *,
    k: int,
    descending: bool,
    key_is_ts: bool,
    key_field: int,
    numeric_filters: tuple[tuple[int, int], ...],
    value_layouts: tuple = (),
    ts_layout: tuple = ("raw",),
    series_layout: tuple = ("raw",),
):
    """Multi-query fused top-k: ``raw_topk_packed``'s body vmapped over
    the QUERY axis — B shape-identical dashboard ORDER-BY-LIMIT queries
    (same k, differing allow-lists/time bounds/literals) share one
    compiled program and one device round trip. -> int32[B, k] resident
    row indices, -1 in slots with no passing row."""
    series_codes, ts_rel, values = decode_layouts(
        series_codes, ts_rel, values, series_layout, ts_layout, value_layouts
    )

    def one(session, dyn):
        literals, lo, hi, key_lo, key_hi = _unpack_dyn(dyn, numeric_filters)
        _, idx = raw_topk_body(
            series_codes, ts_rel, values, session != 0, literals, lo, hi,
            key_lo, key_hi,
            k=k, descending=descending, key_is_ts=key_is_ts,
            key_field=key_field, numeric_filters=numeric_filters,
        )
        return idx

    return jax.vmap(one)(sessions, dyns)


@functools.partial(
    jax.jit,
    static_argnames=(
        "select_slots", "numeric_filters",
        "value_layouts", "ts_layout", "series_layout",
    ),
)
def raw_select_packed(
    series_codes,
    ts_rel,
    values,
    session,
    dyn,
    *,
    select_slots: int,
    numeric_filters: tuple[tuple[int, int], ...],
    value_layouts: tuple = (),
    ts_layout: tuple = ("raw",),
    series_layout: tuple = ("raw",),
):
    """-> int32[1 + slots]: [passing count | row indices...]."""
    literals, lo, hi, _, _ = _unpack_dyn(dyn, numeric_filters)
    series_codes, ts_rel, values = decode_layouts(
        series_codes, ts_rel, values, series_layout, ts_layout, value_layouts
    )
    out, count = raw_select_body(
        series_codes, ts_rel, values, session != 0, literals, lo, hi,
        select_slots=select_slots, numeric_filters=numeric_filters,
    )
    return jnp.concatenate([count.reshape(1), out])
