"""Pallas TPU kernel: segment reduction as one-hot MXU matmuls.

``jax.ops.segment_sum`` lowers to scatter-add, which runs on the VPU and
serializes on segment collisions. For the scan/agg shape — few thousand
live segments, millions of rows — the MXU formulation is the TPU-native
alternative (SURVEY §7 / pallas guide "quantization kernels" pattern):

    onehot[i, s] = (seg_ids[i] == s) & mask[i]          # (TILE, S) f32
    sums   += values_tile @ onehot                      # (F, S) MXU matmul
    counts += ones @ onehot                             # row of the same

The grid walks row tiles; the output block is constant across steps and
accumulates in VMEM (initialized on the first step). Segments are padded
to a multiple of 128 (lane width), rows to the f32 tile height.

Status: validated against jax.ops.segment_sum in INTERPRET MODE only (the
chip tunnel was down all round; the native Mosaic lowering has NOT run).
Standalone op in round 1: the executor keeps XLA's segment ops until the
scatter-vs-matmul tradeoff is profiled on a real chip — measure, don't
assume, and expect Mosaic to demand layout tweaks interpret mode forgives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

ROW_TILE = 1024  # rows per grid step (multiple of the 8-row f32 sublane)


def _kernel(seg_ref, mask_ref, values_ref, counts_ref, sums_ref, *, n_seg: int):
    step = pl.program_id(0)

    @pl.when(step == 0)
    def _init():
        counts_ref[:] = jnp.zeros_like(counts_ref)
        sums_ref[:] = jnp.zeros_like(sums_ref)

    seg = seg_ref[:]  # (TILE,) int32
    mask = mask_ref[:]  # (TILE,) bool
    # Zero masked rows BEFORE the matmul: 0-weight in onehot does not save
    # us from NaN/Inf in masked/padding rows (0 * NaN = NaN).
    values = values_ref[:] * mask[None, :].astype(jnp.float32)  # (F, TILE)

    # One-hot on the fly: (TILE, S). Masked/dump rows match no segment.
    seg_col = seg[:, None]
    seg_ids = jax.lax.broadcasted_iota(jnp.int32, (ROW_TILE, n_seg), 1)
    onehot = ((seg_col == seg_ids) & mask[:, None]).astype(jnp.float32)

    sums_ref[:] += jax.lax.dot_general(
        values,
        onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    counts_ref[:] += jnp.sum(onehot, axis=0, keepdims=True)


def _use_interpret() -> bool:
    # Pallas compiles natively only on TPU (the axon plugin canonicalizes
    # to tpu); everywhere else (tests on CPU) run the interpreter.
    return jax.default_backend() not in ("tpu", "axon")


@functools.partial(jax.jit, static_argnames=("n_seg", "interpret"))
def _segment_sum_matmul(seg_ids, mask, values, *, n_seg: int, interpret: bool):
    """(counts f32[1, S], sums f32[F, S]) via MXU one-hot matmuls.

    ``seg_ids`` int32[N], ``mask`` bool[N], ``values`` f32[F, N]; N must be
    a multiple of ROW_TILE (ops.encoding's shape buckets are), S a multiple
    of 128. Rows with out-of-range ids must be masked by the caller.
    """
    n = seg_ids.shape[0]
    f = values.shape[0]
    assert n % ROW_TILE == 0, f"rows {n} not a multiple of {ROW_TILE}"
    assert n_seg % 128 == 0, f"segments {n_seg} not a multiple of 128"
    grid = (n // ROW_TILE,)
    return pl.pallas_call(
        functools.partial(_kernel, n_seg=n_seg),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_TILE,), lambda i: (i,)),
            pl.BlockSpec((ROW_TILE,), lambda i: (i,)),
            pl.BlockSpec((f, ROW_TILE), lambda i: (0, i)),
        ],
        out_specs=[
            pl.BlockSpec((1, n_seg), lambda i: (0, 0)),
            pl.BlockSpec((f, n_seg), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_seg), jnp.float32),
            jax.ShapeDtypeStruct((f, n_seg), jnp.float32),
        ],
        interpret=interpret,
    )(seg_ids, mask, values)


def segment_sum_matmul(seg_ids, mask, values, *, n_seg: int):
    """See module docstring; interpret-mode off-TPU, native on chip."""
    return _segment_sum_matmul(
        seg_ids, mask, values, n_seg=n_seg, interpret=_use_interpret()
    )


def pad_segments(n_seg: int) -> int:
    return ((n_seg + 127) // 128) * 128
