"""Hash-based segment aggregation — the third group-by kernel.

Ground (arXiv 2411.13245, hash-vs-sort group-by): neither strategy
dominates — the winner flips with group cardinality and skew. The fused
kernels in ops/scan_agg.py reduce rows into a dense segment domain
``n_seg = n_groups * n_buckets``, and both existing impls pay for the
WHOLE domain: the MXU one-hot matmul does O(N * n_seg) work, and the
scatter impl pays XLA's serialized per-row scatter four times
(count/sum/min/max) plus n_seg-sized intermediates. When the rows
present touch only D << n_seg segments — a selective dashboard query
over a wide series->group map, sparse time buckets, heavy-hitter skew —
both waste their effort on empty segments.

This impl aggregates through a small hash table first:

1. multiply-shift hash of the segment id into ``H = 2^b`` slots
   (H chosen from the router's cardinality estimate, H << n_seg);
2. on-device probe/insert: linear probing, UNROLLED to a small fixed
   round count (HORAEDB_HASH_PROBE_ROUNDS, default 2 — scatter passes
   are the expensive primitive on both TPU and XLA-CPU, so the probe
   budget is a static cost cap, not a convergence loop). A round claims
   slots with a scatter-min into EMPTY slots only, so a claimed slot is
   immutable across rounds and same-round collisions break
   deterministically (the smallest segment id wins; losers re-probe).
3. per-slot aggregation with the one-hot matmul over H slots (O(N * H)
   instead of O(N * n_seg));
4. an H-row scatter of slot results into the n_seg output.

Rows that fail to place within the probe budget (collision clustering,
or more distinct segments present than the estimate promised) fall back
to the exact scatter impl under ``lax.cond``, so the kernel is CORRECT
for every input; it is merely slower when overflow triggers — bounded
at roughly one scatter pass plus the probe budget — and the router
observes that latency, so the shape stops routing to hash.

Tiny inputs skip the device entirely: below
``HORAEDB_HASH_HOST_MAX_ROWS`` valid rows a dispatch costs more than the
aggregation, so :func:`host_scan_aggregate` computes the same monoid
with exact f64 numpy on the host.
"""

from __future__ import annotations

import numpy as np

from .encoding import next_pow2

# 2^32 / golden ratio (Knuth multiplicative / Fibonacci hashing): odd,
# spreads consecutive dense segment ids across the high bits.
_MULT = np.uint32(2654435769)

# Slot-table bounds: the floor keeps the multiply-shift well-defined
# (shift < 32); it is deliberately TINY — the one-hot matmul over H
# slots is the hash impl's inner cost, and small H is the entire win —
# while the cap bounds that O(N * H) work: past it, hash stops beating
# scatter anyway.
_MIN_SLOTS = 16
_DEFAULT_MAX_SLOTS = 4096


def default_hash_slots(n_seg: int) -> int:
    """Deterministic slot count when the caller has no cardinality
    estimate: the full domain up to the cap."""
    return next_pow2(min(n_seg, _DEFAULT_MAX_SLOTS), floor=_MIN_SLOTS)


def hash_slots_for(n_seg: int, est_distinct: int | None) -> int:
    """Slot count from a cardinality estimate: 4x headroom (load factor
    <= 0.25 in the expected case) so nearly every segment places within
    the small fixed probe budget — headroom in the slot table is far
    cheaper than a trip through the full-domain overflow fallback. NOT
    clamped to n_seg: when the estimate approaches the domain a
    same-size table would run at load 1.0 and push everything through
    the fallback."""
    from ..utils.env import env_int

    cap = max(_MIN_SLOTS, env_int("HORAEDB_HASH_MAX_SLOTS", _DEFAULT_MAX_SLOTS))
    if est_distinct is None or est_distinct <= 0:
        return default_hash_slots(n_seg)
    return next_pow2(min(4 * est_distinct, cap), floor=_MIN_SLOTS)


def hash_segment_agg(seg_raw, m, agg_vals, n_seg: int, need_minmax: bool,
                     n_slots: int):
    """(counts, sums, mins, maxs) over flat segment ids, hash-table style.

    Same contract as ``_mxu_segment_agg``/``_scatter_segment_agg`` in
    ops/scan_agg.py — drop-in third arm of the impl branch there.
    """
    import jax
    import jax.numpy as jnp

    from .scan_agg import _mxu_segment_agg, _scatter_segment_agg

    from ..utils.env import env_int

    H = int(n_slots)
    assert H >= 2 and (H & (H - 1)) == 0, f"n_slots must be a power of 2, got {H}"
    shift = np.uint32(32 - int(H).bit_length() + 1)  # 32 - log2(H)
    empty = jnp.int32(2**31 - 1)  # sentinel; valid segment ids are < n_seg

    seg = jnp.where(m, seg_raw, -1)
    valid = seg >= 0
    h0 = ((seg.astype(jnp.uint32) * _MULT) >> shift).astype(jnp.int32)

    # Probe/insert, UNROLLED: scatter passes are the priced primitive
    # (serialized on TPU, a serial loop on XLA-CPU — ~constant cost per
    # pass regardless of table size), so the probe budget is a static
    # cost cap, one scatter-min per round. Unplaced rows after the last
    # round are handled exactly by the overflow fallback below — the
    # budget bounds COST, never correctness.
    rounds = min(H, max(1, env_int("HORAEDB_HASH_PROBE_ROUNDS", 2)))
    slots = jnp.full((H,), empty, dtype=jnp.int32)
    slot_of = jnp.zeros_like(seg)
    placed = ~valid
    for r in range(rounds):
        cand = (h0 + r) & (H - 1)
        cur = slots[cand]
        mine = cur == seg  # slot already owned by my segment
        try_claim = (~placed) & (cur == empty)
        # Claim only EMPTY slots (mode="drop" discards non-claimers):
        # an owned slot is immutable, so a smaller segment id arriving
        # in a later round can never steal a slot rows already hold.
        tgt = jnp.where(try_claim, cand, H)
        slots = slots.at[tgt].min(seg, mode="drop")
        won = try_claim & (slots[cand] == seg)
        newly = (~placed) & (mine | won)
        slot_of = jnp.where(newly, cand, slot_of)
        placed = placed | newly

    # Per-slot aggregation: the one-hot matmul over H slots — the whole
    # point; H << n_seg is where hash beats the full-domain impls.
    hash_m = placed & valid
    counts_h, sums_h, mins_h, maxs_h = _mxu_segment_agg(
        slot_of, hash_m, agg_vals, H, need_minmax
    )

    # Scatter slot results into the segment domain: H rows, not N.
    slot_seg = jnp.where(slots == empty, n_seg, slots)  # empty -> dump
    counts = (
        jnp.zeros((n_seg + 1,), jnp.int32).at[slot_seg].add(counts_h)[:n_seg]
    )
    if agg_vals is not None:
        F = agg_vals.shape[0]
        sums = (
            jnp.zeros((F, n_seg + 1), sums_h.dtype)
            .at[:, slot_seg].add(sums_h)[:, :n_seg]
        )
        if need_minmax:
            big = jnp.asarray(jnp.inf, dtype=mins_h.dtype)
            mins = (
                jnp.full((F, n_seg + 1), big)
                .at[:, slot_seg].min(mins_h)[:, :n_seg]
            )
            maxs = (
                jnp.full((F, n_seg + 1), -big)
                .at[:, slot_seg].max(maxs_h)[:, :n_seg]
            )
        else:
            mins = maxs = jnp.zeros_like(sums)
    else:
        sums = mins = maxs = None

    # Overflow (D > H): the unplaced remainder goes through the exact
    # scatter impl. lax.cond executes one branch at runtime, so the
    # fallback costs nothing when the slot table held everything.
    overflow = valid & ~placed

    def with_overflow(_):
        return _scatter_segment_agg(seg_raw, overflow, agg_vals, n_seg,
                                    need_minmax)

    def no_overflow(_):
        zc = jnp.zeros((n_seg,), jnp.int32)
        if agg_vals is None:
            return zc, None, None, None
        zs = jnp.zeros((agg_vals.shape[0], n_seg), sums.dtype)
        if need_minmax:
            big = jnp.asarray(jnp.inf, dtype=zs.dtype)
            return zc, zs, jnp.full_like(zs, big), jnp.full_like(zs, -big)
        return zc, zs, jnp.zeros_like(zs), jnp.zeros_like(zs)

    oc, osums, omins, omaxs = jax.lax.cond(
        overflow.any(), with_overflow, no_overflow, operand=None
    )
    counts = counts + oc
    if agg_vals is not None:
        sums = sums + osums
        if need_minmax:
            mins = jnp.minimum(mins, omins)
            maxs = jnp.maximum(maxs, omaxs)
    return counts, sums, mins, maxs


# ---- host fallback for tiny inputs ----------------------------------------


def host_segment_agg(seg: np.ndarray, m: np.ndarray, agg_vals,
                     n_seg: int, need_minmax: bool):
    """Exact f64 numpy twin of the device impls' (counts, sums, mins,
    maxs) contract — the dispatch-free path for inputs too small to pay
    a device round trip."""
    idx = np.nonzero(m)[0]
    s = np.asarray(seg)[idx].astype(np.int64)
    counts = np.bincount(s, minlength=n_seg).astype(np.int32)
    if agg_vals is None:
        return counts, None, None, None
    F = agg_vals.shape[0]
    sums = np.zeros((F, n_seg))
    mins = np.full((F, n_seg), np.inf)
    maxs = np.full((F, n_seg), -np.inf)
    for f in range(F):
        v = np.asarray(agg_vals[f], dtype=np.float64)[idx]
        sums[f] = np.bincount(s, weights=v, minlength=n_seg)
        if need_minmax:
            np.minimum.at(mins[f], s, v)
            np.maximum.at(maxs[f], s, v)
    if not need_minmax:
        mins = np.zeros_like(sums)
        maxs = np.zeros_like(sums)
    return counts, sums, mins, maxs


def host_scan_aggregate(batch, spec, filter_literals=()):
    """AggState for one padded batch, computed entirely on host.

    Applies the spec's numeric device filters with the same op codes the
    kernel uses, then folds the aggregation monoid in exact f64 — the
    "host fallback for tiny inputs" arm of the hash route.
    """
    from .scan_agg import _FILTER_OPS, AggState

    m = np.asarray(batch.mask).copy()
    values = np.asarray(batch.values)
    lits = np.asarray(filter_literals, dtype=np.float32)
    code_ops = {v: k for k, v in _FILTER_OPS.items()}
    cmp = {
        "=": np.equal, "!=": np.not_equal, "<": np.less, "<=": np.less_equal,
        ">": np.greater, ">=": np.greater_equal,
    }
    for i, (field_idx, op) in enumerate(spec.numeric_filters):
        op_str = op if isinstance(op, str) else code_ops[op]
        m &= cmp[op_str](values[field_idx], lits[i])
    n_seg = spec.n_groups * spec.n_buckets
    seg = (
        np.asarray(batch.group_codes).astype(np.int64) * spec.n_buckets
        + np.asarray(batch.bucket_ids)
    )
    agg_vals = values[: spec.n_agg_fields] if spec.n_agg_fields else None
    counts, sums, mins, maxs = host_segment_agg(
        seg, m, agg_vals, n_seg, spec.need_minmax
    )
    G, B, F = spec.n_groups, spec.n_buckets, spec.n_agg_fields
    counts = counts.reshape(G, B)
    if F:
        sums = sums.reshape(F, G, B)
        mins = mins.reshape(F, G, B)
        maxs = maxs.reshape(F, G, B)
    else:
        sums = mins = maxs = np.zeros((0, G, B))
    return AggState(counts=counts, sums=sums, mins=mins, maxs=maxs)
