"""Host-side encoding that makes columns device-friendly.

XLA wants static shapes and integer keys; time-series data arrives with
ragged row counts, 64-bit epoch timestamps, and string tags. This module is
the boundary where that impedance is resolved, all in vectorized numpy:

- ``shape_bucket``/``pad_to_bucket`` — round row counts up to a small set of
  shape buckets so jit compiles a handful of programs, not one per scan;
- ``encode_group_codes`` — dense int32 group codes from tsid + tag columns
  (per-scan ``np.unique`` at series granularity; strings are only touched
  once per unique series, never per row);
- ``time_buckets`` — int32 bucket ids from int64 epoch-ms timestamps
  (computed host-side so the device never needs 64-bit integers).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..common_types.dict_column import as_values, unique_inverse
from ..common_types.row_group import RowGroup

# Shape buckets: powers of two from 4k up. Anything smaller pads to 4096;
# each jit key above that is exactly 2x the previous, so at most ~17
# compilations cover 4k .. 256M rows.
_MIN_BUCKET = 4096


def next_pow2(n: int, floor: int = 1) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def shape_bucket(n: int) -> int:
    return next_pow2(n, _MIN_BUCKET)


def pad_to_bucket(arr: np.ndarray, n_rows: int, fill=0) -> np.ndarray:
    """Pad axis 0 up to ``shape_bucket(n_rows)`` with ``fill``."""
    target = shape_bucket(n_rows)
    if len(arr) == target:
        return arr
    pad_n = target - len(arr)
    pad_block = np.full((pad_n, *arr.shape[1:]), fill, dtype=arr.dtype)
    return np.concatenate([arr, pad_block])


@dataclass(frozen=True)
class GroupEncoding:
    """Per-row dense group codes + the decoded key values per group."""

    codes: np.ndarray  # int32 per row, in [0, num_groups)
    num_groups: int
    # For each output group, the group-by key values (one array per key
    # column, each of length num_groups) — used to label result rows.
    key_values: tuple[np.ndarray, ...]


def encode_group_codes(
    rows: RowGroup,
    group_columns: Sequence[str],
) -> GroupEncoding:
    """Dense int32 group codes for arbitrary group-by key columns.

    Strategy (all C-speed numpy, no Python per-row loops):

    1. `np.unique(tsid, return_inverse)` -> dense series index per row.
       Series count is tiny next to row count in time-series workloads.
    2. The group key of a series is constant unless the key includes
       non-tag columns; when keys are all tags (the common case), compute
       group codes at series granularity and broadcast through the inverse.
    3. Otherwise fall back to row-level np.unique over the key columns.
    """
    schema = rows.schema
    tag_names = set(schema.tag_names)
    n = len(rows)
    if not group_columns:
        return GroupEncoding(np.zeros(n, dtype=np.int32), 1, ())

    all_tags = all(c in tag_names for c in group_columns)
    tsid_idx = schema.tsid_index
    if all_tags and tsid_idx is not None and n > 0:
        tsid = rows.columns[schema.columns[tsid_idx].name]
        uniq_tsid, first_idx, inverse = np.unique(
            tsid, return_index=True, return_inverse=True
        )
        # Key values per unique series (small arrays).
        series_keys = [as_values(rows.columns[c][first_idx]) for c in group_columns]
        series_group, key_values = _codes_from_columns(series_keys)
        codes = series_group[inverse].astype(np.int32)
        return GroupEncoding(codes, len(key_values[0]) if key_values else 1, key_values)

    row_keys = [rows.columns[c] for c in group_columns]
    codes64, key_values = _codes_from_columns(row_keys)
    return GroupEncoding(codes64.astype(np.int32), len(key_values[0]) if key_values else 1, key_values)


def _codes_from_columns(cols: list) -> tuple[np.ndarray, tuple[np.ndarray, ...]]:
    """(codes, unique key values per column) for composite keys."""
    if len(cols) == 1:
        uniq, codes = unique_inverse(cols[0])
        return codes, (uniq,)
    # Composite: successive refinement — code each column, then combine.
    combined = np.zeros(len(cols[0]), dtype=np.int64)
    for c in cols:
        u, inv = unique_inverse(c)
        combined = combined * (len(u) + 1) + inv
    uniq_comb, first_idx, codes = np.unique(
        combined, return_index=True, return_inverse=True
    )
    key_values = tuple(as_values(c[first_idx]) for c in cols)
    return codes, key_values


def time_buckets(
    ts: np.ndarray, t0: int, bucket_ms: int
) -> tuple[np.ndarray, int]:
    """(int32 bucket ids relative to t0, bucket count). Host-side int64
    floor-div so the device kernel never sees 64-bit timestamps.

    Rows before ``t0`` are rejected loudly: negative segment ids would be
    SILENTLY DROPPED by XLA's scatter, corrupting aggregates. Callers must
    time-filter first (merge_read already does) and pass t0 <= min(ts).
    """
    if bucket_ms <= 0:
        raise ValueError(f"bucket_ms must be positive, got {bucket_ms}")
    b = (ts - t0) // bucket_ms
    n = int(b.max()) + 1 if len(b) else 1
    if len(b) and int(b.min()) < 0:
        raise ValueError(
            f"timestamps before bucket origin t0={t0} (min bucket {int(b.min())}); "
            "clip the batch to the query time range first"
        )
    if n > 2**31 - 1:
        raise ValueError(f"bucket count {n} overflows int32; widen bucket_ms")
    return b.astype(np.int32), max(n, 1)


def split_u64(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 -> (hi uint32, lo uint32) for device sorts without x64."""
    x = x.astype(np.uint64, copy=False)
    return (x >> np.uint64(32)).astype(np.uint32), (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def split_i64_sortable(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 -> order-preserving (hi uint32, lo uint32) pair.

    Flipping the sign bit maps int64 order onto uint64 order, so sorting by
    (hi, lo) lexicographically equals sorting by the original int64.
    """
    u = x.astype(np.int64, copy=False).view(np.uint64) ^ np.uint64(1 << 63)
    return split_u64(u)


@dataclass(frozen=True)
class PaddedBatch:
    """A scan batch padded to a shape bucket, ready for the device."""

    n_valid: int
    group_codes: np.ndarray  # int32 (padded)
    bucket_ids: np.ndarray  # int32 (padded)
    mask: np.ndarray  # bool (padded; False in the pad tail)
    values: np.ndarray  # float32, shape (n_fields, padded)

    @property
    def padded_len(self) -> int:
        return len(self.mask)


def build_padded_batch(
    group_codes: np.ndarray,
    bucket_ids: np.ndarray,
    mask: np.ndarray,
    value_cols: Sequence[np.ndarray],
) -> PaddedBatch:
    n = len(group_codes)
    target = shape_bucket(n)
    if value_cols:
        values = np.stack([v.astype(np.float32, copy=False) for v in value_cols])
        values = np.pad(values, ((0, 0), (0, target - n)))
    else:
        values = np.zeros((0, target), dtype=np.float32)
    return PaddedBatch(
        n_valid=n,
        group_codes=pad_to_bucket(group_codes, n),
        bucket_ids=pad_to_bucket(bucket_ids, n),
        mask=pad_to_bucket(mask.astype(np.bool_), n, fill=False),
        values=values,
    )


# ---------------------------------------------------------------------------
# Compressed device layouts (ISSUE 19)
#
# The scan cache stores columns in HBM; capacity, not kernel speed, bounds
# how much of the working set gets device-path serving. These codecs trade
# a few register-level ops per row for 4-8x fewer HBM bytes:
#
# - ``pack_bits``/``unpack_bits`` — a uint32 word stream holding fixed-width
#   codes (1..16 bits). The device unpack is random-access (any gather index
#   works), so the same stream serves full scans AND decode-on-gather.
# - ``dict_encode`` — sorted-dictionary encoding for low-cardinality
#   columns: bit-packed codes + a small pow2-padded dictionary. Sorted
#   dictionaries let the executor pre-translate comparison literals into
#   the code domain host-side (filters never decode).
# - ``delta_for_encode`` — block frame-of-reference for sorted-ish int32
#   streams (series codes, per-series relative timestamps): one int32 base
#   per 128-row block + bit-packed offsets.
#
# All codecs are LOSSLESS and verified by bit-exact host roundtrip at
# encode time; callers fall back to the raw layout on any mismatch (the
# -0.0/0.0 collapse under np.unique is caught exactly this way).
#
# Layout descriptors are small hashable tuples that ride jit static args
# (flipping a layout re-keys the trace — the PR-6 lesson):
#
#   value field:  ("raw",) | ("bf16",) | ("dict", width, full_decode)
#   timestamps:   ("raw",) | ("dict", width) | ("delta", width)
#   series codes: ("raw",) | ("delta", width)
# ---------------------------------------------------------------------------

RAW_LAYOUT = ("raw",)
BF16_LAYOUT = ("bf16",)

# Frame-of-reference block size. 128 divides every shape bucket (pow2 >=
# 4096), and series codes — consecutive np.unique inverses, non-decreasing
# — span at most 128 distinct values per block, so offsets always fit 8 bits.
FOR_BLOCK = 128
_FOR_SHIFT = 7

_MAX_CODE_WIDTH = 16


def _bit_width(max_value: int) -> int:
    """Bits needed to store values in [0, max_value] (min 1)."""
    return max(1, int(max_value).bit_length())


def pack_bits(values: np.ndarray, width: int) -> np.ndarray:
    """Pack unsigned ints (< 2**width) into a dense uint32 word stream.

    One trailing safety word is appended so the device unpack may always
    read ``words[wi + 1]`` without bounds checks.
    """
    if not 1 <= width <= _MAX_CODE_WIDTH:
        raise ValueError(f"width must be in [1, {_MAX_CODE_WIDTH}], got {width}")
    v = values.astype(np.uint64, copy=False)
    n = len(v)
    n_words = (n * width + 31) // 32 + 1
    pos = np.arange(n, dtype=np.uint64) * np.uint64(width)
    wi = (pos >> np.uint64(5)).astype(np.int64)
    sh = pos & np.uint64(31)
    shifted = v << sh  # width<=16, sh<=31 -> fits u64
    words = np.zeros(n_words, dtype=np.uint64)
    np.bitwise_or.at(words, wi, shifted & np.uint64(0xFFFFFFFF))
    np.bitwise_or.at(words, wi + 1, shifted >> np.uint64(32))
    return (words & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def unpack_bits_host(words: np.ndarray, width: int, n: int) -> np.ndarray:
    """Host-side mirror of the device unpack (roundtrip verification)."""
    w64 = words.astype(np.uint64)
    pos = np.arange(n, dtype=np.uint64) * np.uint64(width)
    wi = (pos >> np.uint64(5)).astype(np.int64)
    sh = pos & np.uint64(31)
    lo = w64[wi] >> sh
    # shift-by-32 is undefined on fixed-width ints: guard the aligned case
    hi = np.where(sh == np.uint64(0), np.uint64(0), w64[wi + 1] << (np.uint64(32) - sh))
    return ((lo | hi) & np.uint64((1 << width) - 1)).astype(np.uint32)


def unpack_bits(words, width: int, idx):
    """Device random-access unpack: codes at row positions ``idx``.

    ``words`` is the uint32 stream (with safety word); ``idx`` any int32
    index array. Two gathers + shifts, all in registers — HBM traffic is
    the packed words, never a decoded column.
    """
    p = idx.astype(jnp.uint32) * jnp.uint32(width)
    wi = (p >> 5).astype(jnp.int32)
    sh = p & jnp.uint32(31)
    lo = words[wi] >> sh
    # (32 - sh) & 31 keeps the shift in range; the sh==0 lane is masked off
    hi = jnp.where(
        sh == 0, jnp.uint32(0), words[wi + 1] << ((jnp.uint32(32) - sh) & jnp.uint32(31))
    )
    return (lo | hi) & jnp.uint32((1 << width) - 1)


@dataclass(frozen=True)
class DictEncoded:
    """Sorted-dictionary encoding of one padded column."""

    words: np.ndarray  # uint32 packed codes (+ safety word)
    dictionary: np.ndarray  # sorted values, pow2-padded with the max value
    dict_host: np.ndarray  # unpadded sorted dictionary (literal translation)
    width: int  # bits per code
    encoding: str  # "dict8" | "dict16"

    @property
    def nbytes(self) -> int:
        return int(self.words.nbytes + self.dictionary.nbytes)


def dict_encode(padded: np.ndarray, max_cardinality: int) -> Optional[DictEncoded]:
    """Dictionary-encode a padded f32/int32 column, or None if ineligible.

    Eligible when the column is NaN-free and its cardinality fits both the
    cap and a 16-bit code. The dictionary is sorted (np.unique), so code
    order == value order and comparison literals translate host-side via
    searchsorted. A bit-exact roundtrip is verified before accepting.
    """
    if padded.dtype.kind == "f" and np.isnan(padded).any():
        return None
    uniq = np.unique(padded)
    if len(uniq) > max_cardinality or len(uniq) > (1 << _MAX_CODE_WIDTH):
        return None
    width = _bit_width(len(uniq) - 1) if len(uniq) > 1 else 1
    codes = np.searchsorted(uniq, padded).astype(np.uint32)
    words = pack_bits(codes, width)
    decoded = uniq[unpack_bits_host(words, width, len(padded))]
    # bitwise comparison: catches -0.0/0.0 collapse and any packing bug
    if decoded.view(np.int32).tobytes() != padded.view(np.int32).tobytes():
        return None
    n_dict = next_pow2(len(uniq), floor=8)
    dictionary = np.pad(uniq, (0, n_dict - len(uniq)), mode="edge")
    return DictEncoded(
        words=words,
        dictionary=dictionary,
        dict_host=uniq,
        width=width,
        encoding="dict8" if width <= 8 else "dict16",
    )


@dataclass(frozen=True)
class DeltaEncoded:
    """Block frame-of-reference encoding of one padded int32 column."""

    words: np.ndarray  # uint32 packed offsets (+ safety word)
    base: np.ndarray  # int32 per-block minima, len == n/FOR_BLOCK
    width: int  # bits per offset
    encoding: str = "delta"

    @property
    def nbytes(self) -> int:
        return int(self.words.nbytes + self.base.nbytes)


def delta_for_encode(arr: np.ndarray, max_bits: int) -> Optional[DeltaEncoded]:
    """Delta/FOR-encode a padded int32 column, or None if offsets overflow.

    ``len(arr)`` must be a multiple of FOR_BLOCK (every shape bucket is).
    The global offset width is the max block range — one scattered block
    (e.g. a pad boundary) can reject the whole column, which is fine: the
    tuner falls back to dict or raw.
    """
    if len(arr) % FOR_BLOCK:
        return None
    blocks = arr.astype(np.int64, copy=False).reshape(-1, FOR_BLOCK)
    base = blocks.min(axis=1)
    offsets = blocks - base[:, None]
    width = _bit_width(int(offsets.max()) if len(arr) else 0)
    if width > min(max_bits, _MAX_CODE_WIDTH):
        return None
    words = pack_bits(offsets.ravel().astype(np.uint32), width)
    base32 = base.astype(np.int32)
    decoded = base32[np.arange(len(arr)) >> _FOR_SHIFT] + unpack_bits_host(
        words, width, len(arr)
    ).astype(np.int32)
    if not np.array_equal(decoded, arr):
        return None
    return DeltaEncoded(words=words, base=base32, width=width)


# ---- device-side layout decode (shared by scan_agg / scan_topk) -----------


def _iota(n_rows: int):
    return jnp.arange(n_rows, dtype=jnp.int32)


def decode_series(parts, layout, n_rows: int, idx=None):
    """int32 series codes under ``layout`` — all rows (idx=None) or a gather.

    ``parts`` is the device part tuple: ("raw",) -> (codes,);
    ("delta", w) -> (words, base).
    """
    if layout[0] == "raw":
        return parts[0] if idx is None else parts[0][idx]
    words, base = parts
    ix = _iota(n_rows) if idx is None else idx
    return base[ix >> _FOR_SHIFT] + unpack_bits(words, layout[1], ix).astype(jnp.int32)


def decode_ts(parts, layout, n_rows: int, idx=None):
    """int32 relative timestamps under ``layout``."""
    if layout[0] == "raw":
        return parts[0] if idx is None else parts[0][idx]
    ix = _iota(n_rows) if idx is None else idx
    if layout[0] == "dict":
        words, dictionary = parts
        return dictionary[unpack_bits(words, layout[1], ix)]
    words, base = parts
    return base[ix >> _FOR_SHIFT] + unpack_bits(words, layout[1], ix).astype(jnp.int32)


def decode_value(parts, layout, n_rows: int, idx=None):
    """f32 values under a value-field layout.

    ``("dict", w, False)`` (filter-only fields) returns the CODES as f32 —
    the executor pre-translated the comparison literal into the code
    domain, so the predicate never touches the dictionary.
    """
    if layout[0] in ("raw", "bf16"):
        arr = parts[0] if idx is None else parts[0][idx]
        return arr.astype(jnp.float32)
    words, dictionary = parts
    codes = unpack_bits(words, layout[1], _iota(n_rows) if idx is None else idx)
    if len(layout) > 2 and not layout[2]:
        return codes.astype(jnp.float32)
    return dictionary[codes]


def layout_rows(parts, layout) -> int:
    """Static logical row count of one encoded/raw part tuple."""
    if layout[0] == "delta":
        return parts[1].shape[0] * FOR_BLOCK
    return parts[0].shape[0]


def _as_parts(x):
    return x if isinstance(x, tuple) else (x,)


def decode_layouts(
    series_codes, ts_rel, values, series_layout, ts_layout, value_layouts, idx=None
):
    """Reconstruct kernel inputs from their resident layouts.

    With ``idx`` given, only those row positions decode (decode-on-gather:
    the selective path ships an M-row index and the device reads M encoded
    rows, not N). Raw inputs pass through untouched — legacy callers
    (dist paths, direct tests) never pay for the generality. Encoded
    values come back as a LIST of per-field rows; the kernels stack only
    what they aggregate.
    """
    if (
        series_layout[0] == "raw"
        and ts_layout[0] == "raw"
        and not any(l[0] not in ("raw", "bf16") for l in value_layouts)
        and not isinstance(values, tuple)
    ):
        if idx is None:
            return _as_parts(series_codes)[0], _as_parts(ts_rel)[0], values
        return (
            _as_parts(series_codes)[0][idx],
            _as_parts(ts_rel)[0][idx],
            values[:, idx],
        )
    sc_parts = _as_parts(series_codes)
    ts_parts = _as_parts(ts_rel)
    n_rows = layout_rows(sc_parts, series_layout)
    sc = decode_series(sc_parts, series_layout, n_rows, idx)
    tr = decode_ts(ts_parts, ts_layout, n_rows, idx)
    layouts = value_layouts or tuple(("raw",) for _ in values)
    vals = [
        decode_value(_as_parts(p), l, n_rows, idx) for p, l in zip(values, layouts)
    ]
    return sc, tr, vals
