"""Host-side encoding that makes columns device-friendly.

XLA wants static shapes and integer keys; time-series data arrives with
ragged row counts, 64-bit epoch timestamps, and string tags. This module is
the boundary where that impedance is resolved, all in vectorized numpy:

- ``shape_bucket``/``pad_to_bucket`` — round row counts up to a small set of
  shape buckets so jit compiles a handful of programs, not one per scan;
- ``encode_group_codes`` — dense int32 group codes from tsid + tag columns
  (per-scan ``np.unique`` at series granularity; strings are only touched
  once per unique series, never per row);
- ``time_buckets`` — int32 bucket ids from int64 epoch-ms timestamps
  (computed host-side so the device never needs 64-bit integers).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import numpy as np

from ..common_types.dict_column import as_values, unique_inverse
from ..common_types.row_group import RowGroup

# Shape buckets: powers of two from 4k up. Anything smaller pads to 4096;
# each jit key above that is exactly 2x the previous, so at most ~17
# compilations cover 4k .. 256M rows.
_MIN_BUCKET = 4096


def next_pow2(n: int, floor: int = 1) -> int:
    b = floor
    while b < n:
        b <<= 1
    return b


def shape_bucket(n: int) -> int:
    return next_pow2(n, _MIN_BUCKET)


def pad_to_bucket(arr: np.ndarray, n_rows: int, fill=0) -> np.ndarray:
    """Pad axis 0 up to ``shape_bucket(n_rows)`` with ``fill``."""
    target = shape_bucket(n_rows)
    if len(arr) == target:
        return arr
    pad_n = target - len(arr)
    pad_block = np.full((pad_n, *arr.shape[1:]), fill, dtype=arr.dtype)
    return np.concatenate([arr, pad_block])


@dataclass(frozen=True)
class GroupEncoding:
    """Per-row dense group codes + the decoded key values per group."""

    codes: np.ndarray  # int32 per row, in [0, num_groups)
    num_groups: int
    # For each output group, the group-by key values (one array per key
    # column, each of length num_groups) — used to label result rows.
    key_values: tuple[np.ndarray, ...]


def encode_group_codes(
    rows: RowGroup,
    group_columns: Sequence[str],
) -> GroupEncoding:
    """Dense int32 group codes for arbitrary group-by key columns.

    Strategy (all C-speed numpy, no Python per-row loops):

    1. `np.unique(tsid, return_inverse)` -> dense series index per row.
       Series count is tiny next to row count in time-series workloads.
    2. The group key of a series is constant unless the key includes
       non-tag columns; when keys are all tags (the common case), compute
       group codes at series granularity and broadcast through the inverse.
    3. Otherwise fall back to row-level np.unique over the key columns.
    """
    schema = rows.schema
    tag_names = set(schema.tag_names)
    n = len(rows)
    if not group_columns:
        return GroupEncoding(np.zeros(n, dtype=np.int32), 1, ())

    all_tags = all(c in tag_names for c in group_columns)
    tsid_idx = schema.tsid_index
    if all_tags and tsid_idx is not None and n > 0:
        tsid = rows.columns[schema.columns[tsid_idx].name]
        uniq_tsid, first_idx, inverse = np.unique(
            tsid, return_index=True, return_inverse=True
        )
        # Key values per unique series (small arrays).
        series_keys = [as_values(rows.columns[c][first_idx]) for c in group_columns]
        series_group, key_values = _codes_from_columns(series_keys)
        codes = series_group[inverse].astype(np.int32)
        return GroupEncoding(codes, len(key_values[0]) if key_values else 1, key_values)

    row_keys = [rows.columns[c] for c in group_columns]
    codes64, key_values = _codes_from_columns(row_keys)
    return GroupEncoding(codes64.astype(np.int32), len(key_values[0]) if key_values else 1, key_values)


def _codes_from_columns(cols: list) -> tuple[np.ndarray, tuple[np.ndarray, ...]]:
    """(codes, unique key values per column) for composite keys."""
    if len(cols) == 1:
        uniq, codes = unique_inverse(cols[0])
        return codes, (uniq,)
    # Composite: successive refinement — code each column, then combine.
    combined = np.zeros(len(cols[0]), dtype=np.int64)
    for c in cols:
        u, inv = unique_inverse(c)
        combined = combined * (len(u) + 1) + inv
    uniq_comb, first_idx, codes = np.unique(
        combined, return_index=True, return_inverse=True
    )
    key_values = tuple(as_values(c[first_idx]) for c in cols)
    return codes, key_values


def time_buckets(
    ts: np.ndarray, t0: int, bucket_ms: int
) -> tuple[np.ndarray, int]:
    """(int32 bucket ids relative to t0, bucket count). Host-side int64
    floor-div so the device kernel never sees 64-bit timestamps.

    Rows before ``t0`` are rejected loudly: negative segment ids would be
    SILENTLY DROPPED by XLA's scatter, corrupting aggregates. Callers must
    time-filter first (merge_read already does) and pass t0 <= min(ts).
    """
    if bucket_ms <= 0:
        raise ValueError(f"bucket_ms must be positive, got {bucket_ms}")
    b = (ts - t0) // bucket_ms
    n = int(b.max()) + 1 if len(b) else 1
    if len(b) and int(b.min()) < 0:
        raise ValueError(
            f"timestamps before bucket origin t0={t0} (min bucket {int(b.min())}); "
            "clip the batch to the query time range first"
        )
    if n > 2**31 - 1:
        raise ValueError(f"bucket count {n} overflows int32; widen bucket_ms")
    return b.astype(np.int32), max(n, 1)


def split_u64(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """uint64 -> (hi uint32, lo uint32) for device sorts without x64."""
    x = x.astype(np.uint64, copy=False)
    return (x >> np.uint64(32)).astype(np.uint32), (x & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def split_i64_sortable(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """int64 -> order-preserving (hi uint32, lo uint32) pair.

    Flipping the sign bit maps int64 order onto uint64 order, so sorting by
    (hi, lo) lexicographically equals sorting by the original int64.
    """
    u = x.astype(np.int64, copy=False).view(np.uint64) ^ np.uint64(1 << 63)
    return split_u64(u)


@dataclass(frozen=True)
class PaddedBatch:
    """A scan batch padded to a shape bucket, ready for the device."""

    n_valid: int
    group_codes: np.ndarray  # int32 (padded)
    bucket_ids: np.ndarray  # int32 (padded)
    mask: np.ndarray  # bool (padded; False in the pad tail)
    values: np.ndarray  # float32, shape (n_fields, padded)

    @property
    def padded_len(self) -> int:
        return len(self.mask)


def build_padded_batch(
    group_codes: np.ndarray,
    bucket_ids: np.ndarray,
    mask: np.ndarray,
    value_cols: Sequence[np.ndarray],
) -> PaddedBatch:
    n = len(group_codes)
    target = shape_bucket(n)
    if value_cols:
        values = np.stack([v.astype(np.float32, copy=False) for v in value_cols])
        values = np.pad(values, ((0, 0), (0, target - n)))
    else:
        values = np.zeros((0, target), dtype=np.float32)
    return PaddedBatch(
        n_valid=n,
        group_codes=pad_to_bucket(group_codes, n),
        bucket_ids=pad_to_bucket(bucket_ids, n),
        mask=pad_to_bucket(mask.astype(np.bool_), n, fill=False),
        values=values,
    )
