"""The fused scan/filter/time-bucket/group-by/aggregate kernel.

This is the north-star insertion point (BASELINE.json): a plan whose leaves
are SST scans with filter + group-by-time + aggregate on top compiles into
ONE XLA program. The reference executes the same shape of work as a
DataFusion operator pipeline (filter -> repartition -> partial agg -> final
agg, survey §3.2); here XLA fuses mask computation, bucketing, and segment
reductions into a single device launch over dense column buffers.

Layout contract (prepared by ops.encoding on host):

- ``group_codes`` int32[N]: dense group index per row;
- ``bucket_ids``  int32[N]: time bucket per row;
- ``mask``        bool[N]:  validity & tag-filter & pad mask;
- ``values``      f32[F, N]: field columns (agg fields first, then any
                  fields referenced only by numeric filters);
- numeric filters evaluate ON DEVICE: ops are static (part of the jit
  key), literals are traced scalars (no recompile when the constant
  changes).

Aggregation state is the classic monoid (count, sum, min, max): partials
from different batches/SSTs/devices combine associatively — the same
combine drives multi-batch scans, distributed partial aggregation over a
mesh (psum), and final agg after dedup.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.env import env_int
from .encoding import PaddedBatch, decode_layouts as _decode_layouts, next_pow2

AGG_OPS = ("count", "sum", "min", "max", "avg")

# Numeric filter ops, by static code (part of the jit cache key).
_FILTER_OPS = {"=": 0, "!=": 1, "<": 2, "<=": 3, ">": 4, ">=": 5}

# The three segment-reduction implementations. TPU scatter
# (segment_sum/min/max) is serialized and slow (~10-20ms/M rows measured
# on v5e); for small-to-medium segment counts a one-hot matmul rides the
# MXU and a fused masked broadcast-reduce handles min/max — 5-100x
# faster. Above the threshold the matmul's O(N*n_seg) work loses to
# scatter's O(N). The hash impl (ops/hash_agg.py) aggregates through a
# small slot table first — the winner when the rows present touch far
# fewer segments than the domain holds (low cardinality, heavy skew).
# Which impl serves a query is decided per (plan shape, segment bucket)
# by the learned router (query/path_router.KernelRouter); the spec's
# ``segment_impl`` carries the choice into the jit cache key.
SEGMENT_KERNELS = ("mxu", "scatter", "hash")
# f32 one-hot counts are exact up to 2^24 rows per segment; beyond that the
# count matvec runs in row chunks with int32 accumulation between chunks.
_COUNT_CHUNK = 1 << 24


def pinned_segment_impl() -> str:
    """The HORAEDB_SEGMENT_IMPL kill switch: pins ONE static impl for
    every query shape (exists to bisect lowerings — the override must
    cover every shape, including global aggregates). Empty string means
    auto. Read per call so tests/operators can flip it live."""
    v = os.environ.get("HORAEDB_SEGMENT_IMPL", "auto")
    return v if v in SEGMENT_KERNELS else ""


def mxu_max_segments() -> int:
    """Static auto-heuristic crossover (measured ~8-16k segments at 1M
    rows). Guarded: a malformed value degrades to the default instead of
    aborting import."""
    return env_int("HORAEDB_MXU_MAX_SEGMENTS", 8192)


def resolve_segment_impl(n_seg: int, requested: str = "auto") -> str:
    """Which impl a kernel trace will take for ``n_seg`` — "single",
    "mxu", "scatter" or "hash". Host-side mirror of the in-trace branch
    (deterministic: static args + backend only), so the router and the
    ledger can name the kernel without re-deriving the rules."""
    pinned = pinned_segment_impl()
    if pinned:
        return pinned
    if n_seg == 1:
        # Global aggregate: both scatter (4 scalarized segment_* ops)
        # and MXU (a width-1 one-hot matmul) waste passes; four
        # streaming reduces are the bandwidth floor.
        return "single"
    if requested in SEGMENT_KERNELS:
        return requested
    return (
        "mxu"
        if jax.default_backend() == "tpu" and n_seg <= mxu_max_segments()
        else "scatter"
    )


@dataclass(frozen=True)
class ScanAggSpec:
    """Static shape/op configuration — the jit cache key."""

    n_groups: int  # padded
    n_buckets: int  # padded
    n_agg_fields: int
    # ((value_row_index, op_str), ...) evaluated on device against literals
    numeric_filters: tuple[tuple[int, str], ...] = ()
    # False when no min/max aggregate is requested: the kernel skips the
    # min/max reductions entirely and returns zeros in their slots.
    need_minmax: bool = True
    # Segment-reduction impl for this dispatch: "auto" (static
    # heuristic) or one of SEGMENT_KERNELS as chosen by the learned
    # router. Static jit arg — the chosen kernel IS part of the compile
    # cache key, on the direct, cached, and shard_map dist paths alike.
    segment_impl: str = "auto"
    # Hash-impl slot-table size (power of 2; 0 = derive from n_seg).
    # Sized from the router's cardinality estimate, bucketed to powers
    # of two so it mints a bounded number of jit keys.
    hash_slots: int = 0
    # Compressed-layout descriptors (ops.encoding, ISSUE 19). Static and
    # hashable: flipping a column's layout re-keys the trace, exactly like
    # a segment-impl change. () / ("raw",) are the legacy dense layouts.
    value_layouts: tuple = ()  # per-field, e.g. (("raw",), ("dict", 7, True))
    ts_layout: tuple = ("raw",)
    series_layout: tuple = ("raw",)

    def padded(self) -> "ScanAggSpec":
        # Ungrouped specs (n_groups == 1) skip group padding entirely: the
        # group count is not query-dependent for them (one stable compile),
        # and padding to 8 would multiply segment work for nothing. When
        # additionally n_buckets == 1 (global aggregate), n_seg stays 1
        # and the pure-reduction kernel applies; bucketed ungrouped
        # queries still pad n_buckets below.
        return ScanAggSpec(
            n_groups=next_pow2(self.n_groups, floor=8) if self.n_groups > 1 else 1,
            n_buckets=next_pow2(self.n_buckets, floor=1),
            n_agg_fields=self.n_agg_fields,
            numeric_filters=self.numeric_filters,
            need_minmax=self.need_minmax,
            segment_impl=self.segment_impl,
            hash_slots=self.hash_slots,
            value_layouts=self.value_layouts,
            ts_layout=self.ts_layout,
            series_layout=self.series_layout,
        )


def _mxu_counts(seg, m, n_seg: int):
    """Per-segment row counts via one-hot matvec on the MXU.

    ``seg`` must be -1 for masked rows (one_hot maps OOB to a zero row).
    0/1 products are exact in any matmul precision; chunked int32
    accumulation keeps counts exact past 2^24 rows per segment.
    """
    n = seg.shape[0]
    mf = m.astype(jnp.float32)
    if n <= _COUNT_CHUNK:
        oh = jax.nn.one_hot(seg, n_seg, dtype=jnp.float32)
        return (mf @ oh).astype(jnp.int32)
    n_chunks = -(-n // _COUNT_CHUNK)
    pad = n_chunks * _COUNT_CHUNK - n
    seg_c = jnp.pad(seg, (0, pad), constant_values=-1).reshape(n_chunks, _COUNT_CHUNK)
    m_c = jnp.pad(mf, (0, pad)).reshape(n_chunks, _COUNT_CHUNK)

    def step(acc, xs):
        s, mm = xs
        oh = jax.nn.one_hot(s, n_seg, dtype=jnp.float32)
        return acc + (mm @ oh).astype(jnp.int32), None

    counts, _ = jax.lax.scan(step, jnp.zeros((n_seg,), jnp.int32), (seg_c, m_c))
    return counts


def _mxu_segment_agg(seg_raw, m, agg_vals, n_seg: int, need_minmax: bool):
    """(counts, sums, mins, maxs) over flat segment ids, MXU-style.

    sums ride a (F, N) @ (N, n_seg) one-hot matmul at precision=highest
    (f32-faithful; 'default' bf16 inputs cost ~1e-3 relative error);
    min/max are a fused masked broadcast-reduce over (F, n_seg, N) —
    XLA tiles it without materializing, and scatter never appears.
    """
    seg = jnp.where(m, seg_raw, -1)
    counts = _mxu_counts(seg, m, n_seg)
    if agg_vals is None:
        return counts, None, None, None
    mf = m.astype(agg_vals.dtype)
    oh = jax.nn.one_hot(seg, n_seg, dtype=jnp.float32)
    sums = jax.lax.dot_general(
        agg_vals * mf, oh, (((1,), (0,)), ((), ())), precision="highest"
    )  # (F, n_seg)
    if need_minmax:
        big = jnp.asarray(jnp.inf, dtype=agg_vals.dtype)
        ids = jnp.arange(n_seg, dtype=seg.dtype)
        eq = seg[None, :] == ids[:, None]  # (n_seg, N), fused into the reduces
        mins = jnp.min(jnp.where(eq[None], agg_vals[:, None, :], big), axis=-1)
        maxs = jnp.max(jnp.where(eq[None], agg_vals[:, None, :], -big), axis=-1)
    else:
        mins = maxs = jnp.zeros_like(sums)
    return counts, sums, mins, maxs


def _single_segment_agg(m, agg_vals, need_minmax: bool):
    """n_seg == 1 (global aggregate, no GROUP BY / no time bucket): plain
    masked reductions. Both the scatter path (4 scalarized segment_* ops)
    and the MXU path (a width-1 one-hot matmul) waste passes here; four
    streaming reduces are the bandwidth floor. ~25% faster than scatter
    on XLA-CPU at 2M rows (measured on the high-cpu-all shape)."""
    counts = m.sum(dtype=jnp.int32)[None]
    if agg_vals is None:
        return counts, None, None, None
    mf = m.astype(agg_vals.dtype)
    sums = (agg_vals * mf).sum(axis=1, keepdims=True)
    if need_minmax:
        big = jnp.asarray(jnp.inf, dtype=agg_vals.dtype)
        mins = jnp.where(m, agg_vals, big).min(axis=1, keepdims=True)
        maxs = jnp.where(m, agg_vals, -big).max(axis=1, keepdims=True)
    else:
        mins = maxs = jnp.zeros_like(sums)
    return counts, sums, mins, maxs


def _scatter_segment_agg(seg_raw, m, agg_vals, n_seg: int, need_minmax: bool):
    """(counts, sums, mins, maxs) via segment_* scatter ops (CPU/GPU, or
    large segment counts where O(N*n_seg) matmul work loses to O(N))."""
    seg = jnp.where(m, seg_raw, n_seg)  # masked rows land in a dump slot
    counts = jax.ops.segment_sum(m.astype(jnp.int32), seg, num_segments=n_seg + 1)[:n_seg]
    if agg_vals is None:
        return counts, None, None, None
    mf = m.astype(agg_vals.dtype)
    sums = jax.ops.segment_sum((agg_vals * mf).T, seg, num_segments=n_seg + 1)[:n_seg].T
    if need_minmax:
        big = jnp.asarray(jnp.inf, dtype=agg_vals.dtype)
        mins = jax.ops.segment_min(
            jnp.where(m, agg_vals, big).T, seg, num_segments=n_seg + 1
        )[:n_seg].T
        maxs = jax.ops.segment_max(
            jnp.where(m, agg_vals, -big).T, seg, num_segments=n_seg + 1
        )[:n_seg].T
    else:
        mins = maxs = jnp.zeros_like(sums)
    return counts, sums, mins, maxs


def scan_agg_body(
    group_codes,
    bucket_ids,
    mask,
    values,
    literals,
    *,
    n_groups: int,
    n_buckets: int,
    n_agg_fields: int,
    numeric_filters: tuple[tuple[int, int], ...] = (),
    need_minmax: bool = True,
    segment_impl: str = "auto",
    hash_slots: int = 0,
):
    """Pure kernel body — also the per-shard program inside shard_map
    (parallel/dist_agg.py wraps it with psum/pmin/pmax collectives)."""
    m = mask
    for i, (field_idx, op_code) in enumerate(numeric_filters):
        v = values[field_idx]
        lit = literals[i]
        if op_code == 0:
            m = m & (v == lit)
        elif op_code == 1:
            m = m & (v != lit)
        elif op_code == 2:
            m = m & (v < lit)
        elif op_code == 3:
            m = m & (v <= lit)
        elif op_code == 4:
            m = m & (v > lit)
        else:
            m = m & (v >= lit)

    n_seg = n_groups * n_buckets
    seg_raw = group_codes * n_buckets + bucket_ids
    # ``values`` may be a list of per-field rows (the encoded-layout decode
    # produces one array per field): stack only the agg fields — fields
    # referenced solely by filters never materialize a decoded column.
    if isinstance(values, (list, tuple)):
        agg_vals = jnp.stack(values[:n_agg_fields]) if n_agg_fields else None
    else:
        agg_vals = values[:n_agg_fields] if n_agg_fields else None
    # Dispatch entry points (scan_aggregate, the executor's cached-packed
    # call, dist_agg's step builders) resolve the impl ON HOST and pass
    # the concrete name as this static arg — so flipping the env pin /
    # threshold mints a NEW jit key instead of silently reusing a warm
    # trace. The in-body resolve below is only a safety net for callers
    # that still pass "auto" (identity for concrete names).
    impl_name = (
        segment_impl
        if segment_impl in ("single",) + SEGMENT_KERNELS
        else resolve_segment_impl(n_seg, segment_impl)
    )
    if impl_name == "single":
        counts, sums, mins, maxs = _single_segment_agg(m, agg_vals, need_minmax)
    elif impl_name == "hash":
        from .hash_agg import default_hash_slots, hash_segment_agg

        counts, sums, mins, maxs = hash_segment_agg(
            seg_raw, m, agg_vals, n_seg, need_minmax,
            hash_slots or default_hash_slots(n_seg),
        )
    else:
        impl = _mxu_segment_agg if impl_name == "mxu" else _scatter_segment_agg
        counts, sums, mins, maxs = impl(seg_raw, m, agg_vals, n_seg, need_minmax)

    counts = counts.reshape(n_groups, n_buckets)
    if n_agg_fields:
        shape = (n_agg_fields, n_groups, n_buckets)
        sums = sums.reshape(shape)
        mins = mins.reshape(shape)
        maxs = maxs.reshape(shape)
    else:
        vdtype = (
            jnp.float32 if isinstance(values, (list, tuple)) else values.dtype
        )
        zero = jnp.zeros((0, n_groups, n_buckets), dtype=vdtype)
        sums = mins = maxs = zero
    return counts, sums, mins, maxs


_fused_scan_agg = functools.partial(
    jax.jit,
    static_argnames=(
        "n_groups", "n_buckets", "n_agg_fields", "numeric_filters",
        "need_minmax", "segment_impl", "hash_slots",
    ),
)(scan_agg_body)


def cached_scan_agg_body(
    series_codes,  # int32[N] (padded rows carry code == n_series)
    ts_rel,  # int32[N], ms relative to the cache's min timestamp
    values,  # f32[F, N] device-resident value columns
    group_of_series,  # int32[S+1]; last entry is the pad series' dump group
    allowed_series,  # bool[S+1];  last entry False (pad rows masked out)
    literals,  # f32[n_filters]
    lo_rel,  # int32 scalar: inclusive range start (relative)
    hi_rel,  # int32 scalar: exclusive range end (relative)
    t0_rel,  # int32 scalar: bucket origin (relative, <= lo_rel)
    bucket_ms,  # int32 scalar: bucket width (1 when not bucketing)
    *,
    n_groups: int,
    n_buckets: int,
    n_agg_fields: int,
    numeric_filters: tuple[tuple[int, int], ...],
    need_minmax: bool = True,
    segment_impl: str = "auto",
    hash_slots: int = 0,
    value_layouts: tuple = (),
    ts_layout: tuple = ("raw",),
    series_layout: tuple = ("raw",),
):
    """The steady-state serving kernel over HBM-resident columns.

    Everything per-query is SMALL: the series->group map, the series
    allow-list (tag filters evaluated per series on host), scalar time
    bounds, and filter literals. The big arrays (series codes, relative
    timestamps, value columns) stay on device across queries — uploads are
    O(series + scalars), not O(rows).

    Compressed layouts (ISSUE 19): when the layout descriptors say so,
    ``series_codes``/``ts_rel`` arrive as encoded part tuples and
    ``values`` as a tuple of per-field part tuples. The decode below runs
    in registers at the top of the fused program — HBM traffic is the
    encoded bytes, and filter-only dict fields compare raw codes against
    host-pre-translated literals without ever touching the dictionary.

    Pure body: also the per-shard program when the cache is sharded over a
    mesh (parallel/dist_agg.make_cached_dist_scan_agg wraps it with
    psum/pmin/pmax collectives — that path always runs the raw layout).
    """
    series_codes, ts_rel, values = _decode_layouts(
        series_codes, ts_rel, values, series_layout, ts_layout, value_layouts
    )
    mask = allowed_series[series_codes]
    mask = mask & (ts_rel >= lo_rel) & (ts_rel < hi_rel)
    bucket = jnp.clip((ts_rel - t0_rel) // bucket_ms, 0, n_buckets - 1).astype(jnp.int32)
    group_codes = group_of_series[series_codes]
    if not isinstance(values, (list, tuple)):
        # bf16-resident value columns (HORAEDB_CACHE_DTYPE) upcast here:
        # accumulation always runs in f32 (no-op when already f32)
        values = values.astype(jnp.float32)
    return scan_agg_body(
        group_codes,
        bucket,
        mask,
        values,
        literals,
        n_groups=n_groups,
        n_buckets=n_buckets,
        n_agg_fields=n_agg_fields,
        numeric_filters=numeric_filters,
        need_minmax=need_minmax,
        segment_impl=segment_impl,
        hash_slots=hash_slots,
    )


cached_scan_agg = functools.partial(
    jax.jit,
    static_argnames=(
        "n_groups", "n_buckets", "n_agg_fields", "numeric_filters",
        "need_minmax", "segment_impl", "hash_slots",
        "value_layouts", "ts_layout", "series_layout",
    ),
)(cached_scan_agg_body)


@functools.partial(
    jax.jit,
    static_argnames=(
        "n_groups", "n_buckets", "n_agg_fields", "numeric_filters",
        "need_minmax", "segment_impl", "hash_slots",
    ),
)
def selective_cached_scan_agg(
    row_idx,  # int32[M] indices into the resident arrays (pad -> pad row)
    series_codes,
    ts_rel,
    values,
    group_of_series,
    allowed_series,
    literals,
    lo_rel,
    hi_rel,
    t0_rel,
    bucket_ms,
    *,
    n_groups: int,
    n_buckets: int,
    n_agg_fields: int,
    numeric_filters: tuple[tuple[int, int], ...],
    need_minmax: bool = True,
    segment_impl: str = "auto",
    hash_slots: int = 0,
):
    """Cached kernel over a GATHERED subset of the resident rows.

    The cache layout is sorted by (series, ts), so a selective query — a
    few series out of thousands, the TSBS single-groupby shape — touches
    only its series' contiguous ranges: the host ships an M-row index
    (M << N), the device gathers from HBM and aggregates. Full scans keep
    the plain ``cached_scan_agg``; the executor picks by selectivity.
    """
    sc = series_codes[row_idx]
    tr = ts_rel[row_idx]
    vals = values[:, row_idx]
    return cached_scan_agg_body(
        sc, tr, vals, group_of_series, allowed_series, literals,
        lo_rel, hi_rel, t0_rel, bucket_ms,
        n_groups=n_groups,
        n_buckets=n_buckets,
        n_agg_fields=n_agg_fields,
        numeric_filters=numeric_filters,
        need_minmax=need_minmax,
        segment_impl=segment_impl,
        hash_slots=hash_slots,
    )


# ---- RTT-minimized packed serving path ------------------------------------
#
# On a tunneled/remote accelerator every host->device buffer transfer and
# every device->host fetch is a network round trip. The un-packed cached
# kernel ships ~7 small buffers per query (group map, allow list, literals,
# four scalars, optionally a row index) and fetches four result buffers —
# each a potential RTT. The packed variants collapse that to:
#
#   * ONE per-shape "session" upload (group map + allow list, content-hash
#     cached on the entry so repeated dashboard queries skip it entirely),
#   * ONE per-query int32 "dyn" upload (filter literals bitcast to int32,
#     the four time scalars, and — for the selective kernel — the gathered
#     row index), and
#   * ONE packed f32 result fetch (counts bitcast into the same buffer as
#     sums/mins/maxs).
#
# Steady state = 1 upload + 1 execute + 1 fetch. The reference never needs
# this because DataFusion executes in-process; a tunneled TPU makes dispatch
# cost a first-class design constraint (BASELINE.md north star).


def pack_session(group_of_series: np.ndarray, allowed_series: np.ndarray) -> np.ndarray:
    """[group map | allow list] as one int32 buffer (one upload)."""
    return np.concatenate(
        [group_of_series.astype(np.int32), allowed_series.astype(np.int32)]
    )


def pack_dyn(
    filter_literals: Sequence[float],
    lo_rel: int,
    hi_rel: int,
    t0_rel: int,
    bucket_ms: int,
    row_idx: np.ndarray | None = None,
) -> np.ndarray:
    """Per-query dynamic inputs as one int32 buffer (one upload).

    f32 literals travel bitcast (the kernel bitcasts them back); the
    selective kernel's row index rides the same buffer.
    """
    lits = np.asarray(filter_literals, dtype=np.float32).view(np.int32)
    scalars = np.array([lo_rel, hi_rel, t0_rel, bucket_ms], dtype=np.int32)
    if row_idx is None:
        return np.concatenate([lits, scalars])
    return np.concatenate([lits, scalars, row_idx.astype(np.int32, copy=False)])


def _packed_body(
    series_codes,
    ts_rel,
    values,
    session,  # int32[2*(S+1)]: [group map | allow list]
    dyn,  # int32[n_f + 4 (+ M)]: [literals(bitcast) | lo,hi,t0,width | idx]
    *,
    n_groups: int,
    n_buckets: int,
    n_agg_fields: int,
    numeric_filters: tuple[tuple[int, int], ...],
    need_minmax: bool,
    segment_impl: str = "auto",
    hash_slots: int = 0,
    selective: bool = False,
    value_layouts: tuple = (),
    ts_layout: tuple = ("raw",),
    series_layout: tuple = ("raw",),
):
    s1 = session.shape[0] // 2
    gos = session[:s1]
    allow = session[s1:] != 0
    n_f = len(numeric_filters)
    literals = jax.lax.bitcast_convert_type(dyn[:n_f], jnp.float32)
    lo, hi, t0, width = dyn[n_f], dyn[n_f + 1], dyn[n_f + 2], dyn[n_f + 3]
    if selective:
        # decode-on-gather: only the M shipped row positions are read from
        # the encoded streams; the full columns never decode
        idx = dyn[n_f + 4 :]
        series_codes, ts_rel, values = _decode_layouts(
            series_codes, ts_rel, values, series_layout, ts_layout,
            value_layouts, idx=idx,
        )
        value_layouts, ts_layout, series_layout = (), ("raw",), ("raw",)
    counts, sums, mins, maxs = cached_scan_agg_body(
        series_codes, ts_rel, values, gos, allow, literals, lo, hi, t0, width,
        n_groups=n_groups,
        n_buckets=n_buckets,
        n_agg_fields=n_agg_fields,
        numeric_filters=numeric_filters,
        need_minmax=need_minmax,
        segment_impl=segment_impl,
        hash_slots=hash_slots,
        value_layouts=value_layouts,
        ts_layout=ts_layout,
        series_layout=series_layout,
    )
    parts = [
        jax.lax.bitcast_convert_type(counts.reshape(-1), jnp.float32),
        sums.reshape(-1),
    ]
    if need_minmax:
        parts.extend([mins.reshape(-1), maxs.reshape(-1)])
    return jnp.concatenate(parts)


cached_scan_agg_packed = functools.partial(
    jax.jit,
    static_argnames=(
        "n_groups", "n_buckets", "n_agg_fields", "numeric_filters",
        "need_minmax", "segment_impl", "hash_slots", "selective",
        "value_layouts", "ts_layout", "series_layout",
    ),
)(_packed_body)


def _cohort_body(
    series_codes,
    ts_rel,
    values,
    sessions,  # int32[B, 2*(S+1)]: one packed session row per member
    dyns,  # int32[B, n_f + 4]: one packed dyn row per member
    *,
    n_groups: int,
    n_buckets: int,
    n_agg_fields: int,
    numeric_filters: tuple[tuple[int, int], ...],
    need_minmax: bool,
    segment_impl: str = "auto",
    hash_slots: int = 0,
    value_layouts: tuple = (),
    ts_layout: tuple = ("raw",),
    series_layout: tuple = ("raw",),
):
    """The multi-query fused serving kernel: ``_packed_body`` vmapped
    over the QUERY axis. The big resident arrays (series codes, relative
    timestamps, value columns — raw or encoded part tuples alike)
    broadcast across the batch — HBM is read by one compiled program
    serving B logical queries, instead of B dispatches each paying its
    own device RTT. Selective row-gather is per-query-variable-length and
    therefore excluded: cohort members always run the full-scan kernel."""
    one = functools.partial(
        _packed_body,
        n_groups=n_groups,
        n_buckets=n_buckets,
        n_agg_fields=n_agg_fields,
        numeric_filters=numeric_filters,
        need_minmax=need_minmax,
        segment_impl=segment_impl,
        hash_slots=hash_slots,
        selective=False,
        value_layouts=value_layouts,
        ts_layout=ts_layout,
        series_layout=series_layout,
    )
    return jax.vmap(
        lambda s, d: one(series_codes, ts_rel, values, s, d)
    )(sessions, dyns)


cached_scan_agg_cohort = functools.partial(
    jax.jit,
    static_argnames=(
        "n_groups", "n_buckets", "n_agg_fields", "numeric_filters",
        "need_minmax", "segment_impl", "hash_slots",
        "value_layouts", "ts_layout", "series_layout",
    ),
)(_cohort_body)


def unpack_packed_state(packed, spec: "ScanAggSpec") -> "AggState":
    """ONE blocking device fetch -> writable host AggState.

    counts travel bitcast as f32; the host views the bytes back as int32.
    Arrays are copies (``_fold_delta`` accumulates in place).
    """
    arr = np.asarray(jax.device_get(packed))
    G, B, F = spec.n_groups, spec.n_buckets, spec.n_agg_fields
    gb = G * B
    counts = arr[:gb].view(np.int32).reshape(G, B).copy()
    sums = arr[gb : gb + F * gb].astype(np.float64).reshape(F, G, B)
    if spec.need_minmax and F:
        mins = arr[gb + F * gb : gb + 2 * F * gb].astype(np.float64).reshape(F, G, B)
        maxs = arr[gb + 2 * F * gb :].astype(np.float64).reshape(F, G, B)
    else:
        mins = np.zeros((F, G, B))
        maxs = np.zeros((F, G, B))
    return AggState(counts=counts, sums=sums, mins=mins, maxs=maxs)


@dataclass
class AggState:
    """Combinable partial aggregates (numpy, on host after device exit)."""

    counts: np.ndarray  # (G, B) int
    sums: np.ndarray  # (F, G, B)
    mins: np.ndarray  # (F, G, B)
    maxs: np.ndarray  # (F, G, B)

    def combine(self, other: "AggState") -> "AggState":
        return AggState(
            counts=self.counts + other.counts,
            sums=self.sums + other.sums,
            mins=np.minimum(self.mins, other.mins),
            maxs=np.maximum(self.maxs, other.maxs),
        )


def scan_aggregate(
    batch: PaddedBatch,
    spec: ScanAggSpec,
    filter_literals: Sequence[float] = (),
) -> AggState:
    """Run the fused kernel on one padded batch; returns host partials.

    ``spec`` should already be ``.padded()`` — callers slice the outputs
    back down to true group/bucket counts after combining partials.
    """
    import time as _time

    from ..utils.querystats import note_kernel_dispatch

    # Host-side impl resolution: the CONCRETE kernel name becomes the
    # static jit arg, so a live flip of HORAEDB_SEGMENT_IMPL /
    # HORAEDB_MXU_MAX_SEGMENTS re-keys (and re-traces) warm shapes
    # instead of silently serving the stale compiled branch.
    impl = resolve_segment_impl(
        spec.n_groups * spec.n_buckets, spec.segment_impl
    )

    # Router-chosen hash route, tiny input: a device dispatch costs more
    # than the aggregation — exact f64 numpy serves it instead. Never
    # taken under the HORAEDB_SEGMENT_IMPL kill switch (pinning exists
    # to bisect device lowerings, so it must actually run them).
    if (
        impl == "hash"
        and not pinned_segment_impl()
        and batch.n_valid <= env_int("HORAEDB_HASH_HOST_MAX_ROWS", 4096)
    ):
        from .hash_agg import host_scan_aggregate

        return host_scan_aggregate(batch, spec, filter_literals)

    from ..obs.device import cost_analysis, timed_dispatch

    args = (
        jnp.asarray(batch.group_codes),
        jnp.asarray(batch.bucket_ids),
        jnp.asarray(batch.mask),
        jnp.asarray(batch.values),
        coerce_literals(filter_literals),
    )
    kwargs = dict(
        n_groups=spec.n_groups,
        n_buckets=spec.n_buckets,
        n_agg_fields=spec.n_agg_fields,
        numeric_filters=encode_filter_ops(spec.numeric_filters),
        need_minmax=spec.need_minmax,
        segment_impl=impl,
        hash_slots=spec.hash_slots,
    )
    t0 = _time.perf_counter()
    counts, sums, mins, maxs = timed_dispatch(
        "fused", lambda: _fused_scan_agg(*args, **kwargs)
    )
    state = state_to_host(counts, sums, mins, maxs)
    # Per-query compile accounting: a never-seen static shape's first
    # dispatch pays the XLA compile — its wall time is the honest cost a
    # latency cliff needs attributed (ledger jit_* fields + the device
    # plane's kernel_compile event; cost_fn adds XLA cost_analysis
    # flops/bytes under HORAEDB_DEVICE_COST_ANALYSIS=1).
    note_kernel_dispatch(
        ("fused", batch.values.shape, spec.n_groups, spec.n_buckets,
         spec.n_agg_fields, spec.numeric_filters, spec.need_minmax,
         impl, spec.hash_slots),
        _time.perf_counter() - t0,
        kind="fused",
        cost_fn=lambda: cost_analysis(_fused_scan_agg, args, kwargs),
    )
    return state


def encode_filter_ops(
    filters: tuple[tuple[int, str], ...]
) -> tuple[tuple[int, int], ...]:
    """Op strings -> the static integer codes scan_agg_body branches on."""
    return tuple((fi, _FILTER_OPS[op]) for fi, op in filters)


def coerce_literals(filter_literals: Sequence[float]):
    return jnp.asarray(np.asarray(filter_literals, dtype=np.float32))


def state_to_host(counts, sums, mins, maxs) -> AggState:
    # One device_get over the pytree = one host<->device round trip; four
    # separate np.asarray fetches cost four RTTs on a tunneled backend.
    counts, sums, mins, maxs = jax.device_get((counts, sums, mins, maxs))
    return AggState(
        counts=np.asarray(counts),
        sums=np.asarray(sums, dtype=np.float64),
        mins=np.asarray(mins, dtype=np.float64),
        maxs=np.asarray(maxs, dtype=np.float64),
    )
