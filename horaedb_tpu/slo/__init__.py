"""Service-level objectives: the database grades its own service levels
over its self-monitoring history — see slo/evaluator.py for the
subsystem overview."""

from .evaluator import (
    BURN_WINDOWS,
    SLO_METRIC_FAMILIES,
    SloEvaluator,
    registered_evaluators,
)
from .model import (
    SloError,
    SloObjective,
    complies,
    parse_objective_line,
    validate_objective,
)

__all__ = [
    "BURN_WINDOWS",
    "SLO_METRIC_FAMILIES",
    "SloError",
    "SloEvaluator",
    "SloObjective",
    "complies",
    "parse_objective_line",
    "registered_evaluators",
    "validate_objective",
]
