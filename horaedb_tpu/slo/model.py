"""SLO objective definitions
(ref: the Google SRE workbook's multi-window multi-burn-rate alerting,
re-homed INSIDE the database — in the StreamBox-HBM stance (PAPERS.md)
service-level verdicts are continuous queries over the node's own
telemetry stream, not an external scraper's recomputation).

One objective line declares a service-level *indicator* (a PromQL
expression over the node's own ``system_metrics.samples`` history — the
PR-5 fallback resolves any metric family against it), a *compliance
bound* (the top-level comparison), and a *target* good-time fraction:

    cheap_p99 := histogram_quantile(0.99,
        rate(horaedb_query_class_duration_seconds_bucket{class="cheap"}[1m])
    ) <= 0.5 target 99.9%

Each evaluation round the indicator either complies or violates; the
evaluator (slo/evaluator.py) turns the violation-time fraction over
sliding fast/slow windows into burn rates against the error budget
``1 - target``. The comparison is parsed HERE, not left to PromQL's
filter semantics — a compliant round must still report its value (the
current p99, the current ratio), which PromQL comparison filtering
would drop.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field

from ..proxy.promql import PromQLError, parse_promql

# Objective names surface as system.public.slo rows, event attrs, and
# metric label values — same SQL-safe discipline as rule names.
_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")

_TARGET_TAIL = re.compile(r"\s+target\s+(\d+(?:\.\d+)?)\s*%\s*$")

COMPARE_OPS = ("<=", ">=", "<", ">")


class SloError(ValueError):
    pass


@dataclass
class SloObjective:
    """One service-level objective.

    ``expr OP bound`` is the per-round compliance test; ``target`` is the
    good-time fraction the objective promises (error budget =
    ``1 - target``). ``source`` follows the rules convention ("config"
    lines reload each start; nothing else mints objectives yet, but the
    field keeps the persistence story symmetrical)."""

    name: str
    expr: str
    op: str
    bound: float
    target: float = 0.99
    labels: dict[str, str] = field(default_factory=dict)
    source: str = "config"

    @property
    def budget(self) -> float:
        return 1.0 - self.target

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "expr": self.expr,
            "op": self.op,
            "bound": self.bound,
            "target": self.target,
            "source": self.source,
        }


def validate_objective(obj: SloObjective) -> SloObjective:
    """Fail loudly at config load, not at the first evaluation round."""
    if not _NAME_RE.match(obj.name or ""):
        raise SloError(
            f"objective name {obj.name!r} must match [A-Za-z_][A-Za-z0-9_]*"
        )
    if obj.op not in COMPARE_OPS:
        raise SloError(
            f"objective {obj.name!r}: comparison must be one of "
            f"{', '.join(COMPARE_OPS)}"
        )
    if not (0.0 < obj.target < 1.0):
        raise SloError(
            f"objective {obj.name!r}: target must be in (0%, 100%) "
            f"exclusive, got {obj.target * 100:g}%"
        )
    try:
        parse_promql(obj.expr)
    except PromQLError as e:
        raise SloError(f"objective {obj.name!r}: bad expr: {e}") from None
    return obj


def _split_comparison(expr: str) -> tuple[str, str, float]:
    """Split ``EXPR OP BOUND`` on the LAST depth-0 comparison operator.

    Depth-0 means outside every (), [], {} and quoted string — a ``>``
    inside a selector's regex matcher or a nested comparison inside
    parens must not be mistaken for the objective's bound. The bound
    side must be a bare number (objectives compare an indicator to a
    constant; an expression bound belongs inside the indicator)."""
    depth = 0
    quote = None
    split_at = None
    i = 0
    while i < len(expr):
        ch = expr[i]
        if quote:
            if ch == "\\":
                i += 2
                continue
            if ch == quote:
                quote = None
        elif ch in "'\"":
            quote = ch
        elif ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif depth == 0 and ch in "<>":
            width = 2 if expr[i : i + 2] in ("<=", ">=") else 1
            split_at = (i, width)
            i += width
            continue
        i += 1
    if split_at is None:
        raise SloError(
            f"objective needs a top-level comparison (EXPR {' | '.join(COMPARE_OPS)} BOUND): {expr!r}"
        )
    pos, width = split_at
    lhs = expr[:pos].strip()
    op = expr[pos : pos + width]
    rhs = expr[pos + width :].strip()
    try:
        bound = float(rhs)
    except ValueError:
        raise SloError(
            f"objective bound must be a number, got {rhs!r}"
        ) from None
    if not lhs:
        raise SloError(f"objective has an empty indicator: {expr!r}")
    return lhs, op, bound


def parse_objective_line(line: str, source: str = "config") -> SloObjective:
    """``NAME := EXPR OP BOUND [target 99.9%]`` — the ``[slo]`` config
    line form (TOML-subset-friendly, like the [rules] lines)."""
    name, sep, rest = line.partition(":=")
    if not sep:
        raise SloError(f"bad objective line {line!r}: expected 'NAME := EXPR'")
    name, rest = name.strip(), rest.strip()
    target = 0.99
    m = _TARGET_TAIL.search(rest)
    if m is not None:
        target = float(m.group(1)) / 100.0
        rest = rest[: m.start()].rstrip()
    expr, op, bound = _split_comparison(rest)
    return validate_objective(
        SloObjective(
            name=name, expr=expr, op=op, bound=bound, target=target,
            source=source,
        )
    )


def complies(op: str, value: float, bound: float) -> bool:
    if op == "<=":
        return value <= bound
    if op == "<":
        return value < bound
    if op == ">=":
        return value >= bound
    return value > bound
