"""SLO evaluator — multi-window burn rates over the node's own telemetry
(ref: the SRE-workbook multi-window multi-burn-rate method; the
Compiler-First State Space Duality stance (PAPERS.md) of O(1) incremental
window maintenance instead of recompute-per-query; StreamBox-HBM's
continuous queries over the system's own stream).

One ``SloEvaluator`` per node rides the rules engine's evaluation
cadence (rules/engine.RuleEngine ticks it at the end of every round —
the SLO plane deliberately has no second periodic loop to drift against
the rules/alerts it judges). Each round, per objective:

1. the indicator (PromQL over ``system_metrics.samples`` /
   ``query_stats`` history — the PR-5 samples fallback) instant-evaluates
   to a vector; the WORST series value is compared to the bound;
2. the round's (duration, violated?) sample is pushed into two sliding
   windows — fast (default 5m) and slow (default 1h) — maintained
   INCREMENTALLY: a deque of round samples with running bad/total-time
   sums, O(1) amortized per round, never a rescan of the history;
3. burn rate = violation-time fraction / error budget (``1 - target``).
   An objective starts BURNING when both windows' burn rates reach the
   threshold (the fast window catches it now, the slow window proves it
   is sustained — a blip cannot page); it RECOVERS when the fast window
   comes back under. Transitions journal as typed ``slo_burn`` /
   ``slo_recovered`` events (trace-linked, counted like every kind).

Verdicts serve as ``system.public.slo`` on all three wire protocols
(table_engine/system.SloTable) and as JSON at ``/debug/slo``; the
``horaedb_slo_*`` families are eagerly registered (per-objective labels
at load) under the standard registry-lint contract.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import deque
from typing import Optional

from ..utils.events import record_event
from ..utils.metrics import REGISTRY
from .model import SloObjective, complies, parse_objective_line

# Declared registry of the SLO metric families — tests/test_observability
# TestSloRegistryLint checks each is registered live, convention-clean,
# and documented, and that no stray horaedb_slo_* family exists.
SLO_METRIC_FAMILIES = (
    "horaedb_slo_objectives_total",
    "horaedb_slo_evaluations_total",
    "horaedb_slo_eval_failures_total",
    "horaedb_slo_burning_total",
    "horaedb_slo_burn_rate_ratio",
    "horaedb_slo_breaches_total",
)

BURN_WINDOWS = ("fast", "slow")

# Registered at import so the unlabeled families exist from the first
# scrape; the per-objective labeled series register at evaluator load.
_M_OBJECTIVES = REGISTRY.gauge(
    "horaedb_slo_objectives_total", "SLO objectives currently loaded"
)
_M_EVALS = REGISTRY.counter(
    "horaedb_slo_evaluations_total", "per-objective SLO evaluation rounds"
)
_M_FAILURES = REGISTRY.counter(
    "horaedb_slo_eval_failures_total",
    "objective evaluations that raised (isolated per round)",
)
_M_BURNING = REGISTRY.gauge(
    "horaedb_slo_burning_total", "objectives currently burning"
)

# Evaluators register here so system.public.slo and /debug/slo can
# materialize verdicts without a handle on the server (same discipline
# as rules/engine._ENGINES).
_EVALUATORS: "weakref.WeakSet[SloEvaluator]" = weakref.WeakSet()


def registered_evaluators() -> list["SloEvaluator"]:
    return list(_EVALUATORS)


class _Window:
    """One sliding window of per-round (ts, duration, violated) samples
    with running sums — push is O(1) amortized (each sample enters and
    leaves the deque exactly once); reading a burn rate is O(1) always.
    This is the incremental-maintenance core: the alternative (re-folding
    the samples history per round) rescans O(window / interval) rows for
    every objective, every round, forever."""

    __slots__ = ("span_ms", "_q", "total_ms", "bad_ms")

    def __init__(self, span_ms: int) -> None:
        self.span_ms = int(span_ms)
        self._q: deque = deque()  # (ts_ms, dt_ms, bad_dt_ms)
        self.total_ms = 0
        self.bad_ms = 0

    def push(self, ts_ms: int, dt_ms: int, bad: bool) -> None:
        bad_dt = dt_ms if bad else 0
        self._q.append((ts_ms, dt_ms, bad_dt))
        self.total_ms += dt_ms
        self.bad_ms += bad_dt
        horizon = ts_ms - self.span_ms
        while self._q and self._q[0][0] <= horizon:
            _, dt, bad_dt = self._q.popleft()
            self.total_ms -= dt
            self.bad_ms -= bad_dt

    def bad_fraction(self) -> float:
        return self.bad_ms / self.total_ms if self.total_ms else 0.0


class _ObjectiveState:
    """One objective's live verdict + windows + breach history."""

    def __init__(self, obj: SloObjective, fast_ms: int, slow_ms: int) -> None:
        self.objective = obj
        from ..proxy.promql import parse_promql

        self.parsed = parse_promql(obj.expr)
        self.fast = _Window(fast_ms)
        self.slow = _Window(slow_ms)
        self.state = "ok"  # "ok" | "burning"
        self.value: Optional[float] = None
        self.compliant: Optional[bool] = None
        self.since_ms = 0  # current state's entry time
        self.last_eval_ms = 0
        self.rounds = 0
        self.no_data_rounds = 0  # consecutive empty-vector evals
        self.breach_count = 0
        self.breaches: deque = deque(maxlen=64)  # breach history for ctl
        self.last_error = ""


class SloEvaluator:
    """Maintains every objective's verdict; ticked by the rules engine."""

    def __init__(
        self,
        conn,
        section=None,
        node: str = "standalone",
    ) -> None:
        from ..utils.config import SloSection

        self.conn = conn
        self.section = section if section is not None else SloSection()
        self.node = node
        self.burn_threshold = float(self.section.burn_threshold)
        fast_ms = int(self.section.fast_window_s * 1000)
        slow_ms = int(self.section.slow_window_s * 1000)
        self._states: dict[str, _ObjectiveState] = {}
        self._lock = threading.Lock()
        self.rounds = 0
        self.last_eval_ms = 0
        self._m_burn: dict[tuple[str, str], object] = {}
        self._m_breaches: dict[str, object] = {}
        for line in self.section.objectives:
            obj = parse_objective_line(line)
            if obj.name in self._states:
                from .model import SloError

                raise SloError(
                    f"duplicate objective name {obj.name!r} — a silent "
                    "overwrite would drop a declared SLO"
                )
            self._states[obj.name] = _ObjectiveState(obj, fast_ms, slow_ms)
            # eager per-objective series: the burn-rate gauge and the
            # breach counter exist before the first round
            for window in BURN_WINDOWS:
                self._m_burn[(obj.name, window)] = REGISTRY.gauge(
                    "horaedb_slo_burn_rate_ratio",
                    "error-budget burn rate per objective and window",
                    labels={"objective": obj.name, "window": window},
                )
            self._m_breaches[obj.name] = REGISTRY.counter(
                "horaedb_slo_breaches_total",
                "ok -> burning transitions per objective",
                labels={"objective": obj.name},
            )
        _M_OBJECTIVES.set(len(self._states))
        _EVALUATORS.add(self)

    def __len__(self) -> int:
        return len(self._states)

    # ---- one round ------------------------------------------------------

    def evaluate_round(self, now_ms: Optional[int] = None) -> None:
        """Evaluate every objective once; per-objective errors are
        isolated (a broken indicator must not take down the others).
        Called by the rules engine at the end of each eval round —
        backpressure sheds (OverloadedError) cannot arise here: the
        evaluator only READS."""
        if not self._states:
            return
        now_ms = int(time.time() * 1000) if now_ms is None else now_ms
        # the indicator reads (PromQL over the samples history — the slow
        # part) run OUTSIDE the lock: snapshot()/stats() are called from
        # serving paths, and holding the lock across a database read per
        # objective would stall them for the whole round. Only one rules
        # loop ticks this evaluator, so unlocked reads don't race each
        # other; the cheap state mutation takes the lock per objective.
        for state in list(self._states.values()):
            try:
                vals = self._indicator_values(state, now_ms)
                with self._lock:
                    self._apply_round(state, vals, now_ms)
                    state.last_error = ""
            except Exception as e:
                with self._lock:
                    state.last_error = f"{type(e).__name__}: {e}"[:200]
                _M_FAILURES.inc()
            _M_EVALS.inc()
        with self._lock:
            self.rounds += 1
            self.last_eval_ms = now_ms
            _M_BURNING.set(
                sum(1 for s in self._states.values() if s.state == "burning")
            )

    def _indicator_values(
        self, state: _ObjectiveState, now_ms: int
    ) -> list[float]:
        from ..proxy.promql import evaluate_expr_instant

        vec = evaluate_expr_instant(self.conn, state.parsed, now_ms)
        vals = []
        for s in vec:
            try:
                v = float(s["value"][1])
            except (TypeError, ValueError):
                continue
            if v == v:  # drop NaN (e.g. histogram_quantile over no traffic)
                vals.append(v)
        return vals

    def _apply_round(
        self, state: _ObjectiveState, vals: list[float], now_ms: int
    ) -> None:
        obj = state.objective
        if vals:
            # the WORST series decides the round: for an upper bound the
            # max violates first, for a lower bound the min
            worst = max(vals) if obj.op in ("<=", "<") else min(vals)
            state.value = worst
            state.compliant = complies(obj.op, worst, obj.bound)
            state.no_data_rounds = 0
        else:
            # no data = no evidence of violation (counted as good time,
            # surfaced as no_data_rounds — a freshness objective on the
            # pipeline itself is the guard against a silent dead feed)
            state.value = None
            state.compliant = True
            state.no_data_rounds += 1
        state.rounds += 1
        if state.last_eval_ms:
            # the round's wall time, capped at the fast window: a paused
            # process must not poison the windows with one giant sample
            dt = min(
                max(1, now_ms - state.last_eval_ms), state.fast.span_ms
            )
            bad = not state.compliant
            state.fast.push(now_ms, dt, bad)
            state.slow.push(now_ms, dt, bad)
        state.last_eval_ms = now_ms
        if state.since_ms == 0:
            state.since_ms = now_ms
        burn_fast = state.fast.bad_fraction() / obj.budget
        burn_slow = state.slow.bad_fraction() / obj.budget
        self._m_burn[(obj.name, "fast")].set(burn_fast)
        self._m_burn[(obj.name, "slow")].set(burn_slow)
        thr = self.burn_threshold
        if (
            state.state != "burning"
            and burn_fast >= thr
            and burn_slow >= thr
        ):
            state.state = "burning"
            state.since_ms = now_ms
            state.breach_count += 1
            self._m_breaches[obj.name].inc()
            state.breaches.append(
                {
                    "at_ms": now_ms,
                    "value": state.value,
                    "burn_fast": round(burn_fast, 4),
                    "burn_slow": round(burn_slow, 4),
                    "recovered_at_ms": 0,
                }
            )
            record_event(
                "slo_burn", table="",
                objective=obj.name, value=state.value,
                burn_fast=round(burn_fast, 4), burn_slow=round(burn_slow, 4),
                target=obj.target,
            )
        elif state.state == "burning" and burn_fast < thr:
            burned_s = round((now_ms - state.since_ms) / 1000.0, 3)
            state.state = "ok"
            state.since_ms = now_ms
            if state.breaches:
                state.breaches[-1]["recovered_at_ms"] = now_ms
            record_event(
                "slo_recovered", table="",
                objective=obj.name, after_s=burned_s,
                burn_fast=round(burn_fast, 4),
            )

    # ---- serving --------------------------------------------------------

    def snapshot(self) -> list[dict]:
        """One verdict row per objective — /debug/slo, system.public.slo,
        and ``horaectl slo`` all read this."""
        out = []
        with self._lock:
            for state in sorted(self._states.values(),
                                key=lambda s: s.objective.name):
                obj = state.objective
                budget = obj.budget
                visible_state = state.state
                if state.state == "ok" and state.no_data_rounds > 0:
                    visible_state = "no_data"
                out.append(
                    {
                        "name": obj.name,
                        "expr": f"{obj.expr} {obj.op} {obj.bound:g}",
                        "target": obj.target,
                        "state": visible_state,
                        "value": state.value,
                        "bound": obj.bound,
                        "burn_fast": round(
                            state.fast.bad_fraction() / budget, 4
                        ),
                        "burn_slow": round(
                            state.slow.bad_fraction() / budget, 4
                        ),
                        "good_fast": round(1 - state.fast.bad_fraction(), 6),
                        "good_slow": round(1 - state.slow.bad_fraction(), 6),
                        "fast_window_s": state.fast.span_ms / 1000.0,
                        "slow_window_s": state.slow.span_ms / 1000.0,
                        "breaches": state.breach_count,
                        "since_ms": state.since_ms,
                        "last_eval_ms": state.last_eval_ms,
                        "rounds": state.rounds,
                        "no_data_rounds": state.no_data_rounds,
                        "last_error": state.last_error,
                        "node": self.node,
                    }
                )
        return out

    def breach_history(self) -> list[dict]:
        """Every objective's recent ok -> burning transitions (newest
        last), for ``horaectl slo`` and the simulator's post-mortem."""
        out = []
        with self._lock:
            for state in self._states.values():
                for b in state.breaches:
                    out.append({"objective": state.objective.name, **b})
        return sorted(out, key=lambda b: b["at_ms"])

    def stats(self) -> dict:
        with self._lock:
            burning = sum(
                1 for s in self._states.values() if s.state == "burning"
            )
            return {
                "objectives": len(self._states),
                "burning": burning,
                "rounds": self.rounds,
                "last_eval_ms": self.last_eval_ms,
                "fast_window_s": self.section.fast_window_s,
                "slow_window_s": self.section.slow_window_s,
                "burn_threshold": self.burn_threshold,
                "last_errors": {
                    s.objective.name: s.last_error
                    for s in self._states.values()
                    if s.last_error
                },
            }
