"""Table abstraction layer (ref: src/table_engine).

``Table``/``TableEngine`` interfaces, read/write request types, predicates
with time-range extraction, partition rules, and the in-memory test engine.
"""

from .predicate import ColumnFilter, FilterOp, Predicate

__all__ = ["ColumnFilter", "FilterOp", "Predicate"]
