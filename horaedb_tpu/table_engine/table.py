"""Table interface (ref: src/table_engine/src/table.rs Table trait
:512-569 and engine.rs TableEngine :323-363).

The query layer programs against ``Table``; implementations:

- ``AnalyticTable``     — the LSM engine (engine/), the real thing
- ``PartitionedTable``  — virtual table fanning out to sub-tables by a
                          partition rule (ref: partition_table_engine)
- ``MemoryTable``       — dict-backed fake for tests / system tables
                          (ref: table_engine/src/memory.rs)
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional, Sequence

import numpy as np

from ..common_types.row_group import RowGroup
from ..common_types.schema import Schema, project_schema
from ..engine.options import TableOptions
from .predicate import Predicate


class Table(ABC):
    @property
    @abstractmethod
    def name(self) -> str: ...

    @property
    @abstractmethod
    def schema(self) -> Schema: ...

    @property
    @abstractmethod
    def options(self) -> TableOptions: ...

    @abstractmethod
    def write(self, rows: RowGroup) -> int:
        """Durable write; returns number of rows written."""

    @abstractmethod
    def read(
        self,
        predicate: Predicate | None = None,
        projection: Optional[Sequence[str]] = None,
    ) -> RowGroup: ...

    @abstractmethod
    def flush(self) -> None: ...

    @abstractmethod
    def compact(self) -> None: ...

    @abstractmethod
    def alter_schema(self, schema: Schema) -> None: ...

    def alter_options(self, options: TableOptions) -> None:
        raise NotImplementedError

    def physical_datas(self) -> list:
        """Engine-level TableData handles backing this table (empty for
        non-engine tables). Catalog close/drop iterate these uniformly."""
        return []

    def metrics(self) -> dict:
        return {"table": self.name}

    def read_windows(self, predicate=None, projection=None):
        """Yield the scan as BOUNDED per-segment-window row sets (the
        memory-capped aggregate path consumes these one at a time and
        combines AggStates instead of materializing the whole table; ref:
        instance/read.rs:165-190 returns N streams, not one array).
        Correct per window because the primary key includes the
        timestamp: duplicates of a key can never straddle segment
        windows. Default: one piece (non-engine tables are small)."""
        yield self.read(predicate, projection)

    def partial_agg(self, spec: dict):
        """Pushed-down partial aggregate over this table's OWN data
        (ref: dist_sql_query partial agg below the scan). Runs wherever
        the data lives — remote handles forward it over the wire.

        Returns (names, arrays, stage_metrics) — the metrics travel back
        to the coordinator for EXPLAIN ANALYZE (ref: the reference ships
        remote plan metrics in RemoteTaskContext.remote_metrics)."""
        import time

        from ..query.partial import compute_partial

        t0 = time.perf_counter()
        sub: dict = {}
        names, arrays = compute_partial(self, spec, sub)
        return names, arrays, [{
            "partition": self.name,
            "remote": False,
            **sub,  # scan_ms / rows_scanned / path / agg_ms — same span
            # shape as remote partitions, so stage trees stay uniform
            "elapsed_ms": round((time.perf_counter() - t0) * 1000, 3),
            "groups": int(len(arrays[0])) if arrays else 0,
        }]


class AnalyticTable(Table):
    """The storage engine behind the Table interface."""

    def __init__(self, instance, data) -> None:
        self.instance = instance
        self.data = data

    @property
    def name(self) -> str:
        return self.data.name

    @property
    def schema(self) -> Schema:
        return self.data.schema

    @property
    def options(self) -> TableOptions:
        return self.data.options

    def write(self, rows: RowGroup) -> int:
        self.instance.write(self.data, rows)
        return len(rows)

    def read(self, predicate=None, projection=None) -> RowGroup:
        return self.instance.read(self.data, predicate, projection=projection)

    def window_starts(self, predicate=None) -> list[int]:
        """Aligned segment-window starts the (time-pruned) file set and
        memtables cover — the unit of both the bounded scan and the
        remote streaming read. Empty when the table has no segment
        duration (callers fall back to one whole read)."""
        from ..table_engine.predicate import Predicate as P

        predicate = predicate or P.all_time()
        seg_ms = self.data.options.segment_duration_ms
        tr = predicate.time_range
        if not seg_ms:
            return []
        starts: set[int] = set()
        spans: list[tuple[int, int]] = []
        for h in self.data.version.levels.all_files():
            ftr = h.meta.time_range
            spans.append((ftr.inclusive_start, ftr.exclusive_end))
        for mem in [*self.data.version.immutables(), self.data.version.mutable]:
            if not mem.is_empty():
                mtr = mem.time_range()
                spans.append((mtr.inclusive_start, mtr.exclusive_end))
        for lo, hi in spans:
            lo = max(lo, tr.inclusive_start)
            hi = min(hi, tr.exclusive_end)
            if hi <= lo:
                continue
            w = (lo // seg_ms) * seg_ms
            while w < hi:
                starts.add(w)
                w += seg_ms
        return sorted(starts)

    def read_window(self, start: int, predicate=None, projection=None) -> RowGroup:
        """The normal merge read restricted to one aligned window — a
        complete, deduplicated answer for its time slice."""
        from ..common_types.time_range import TimeRange
        from ..table_engine.predicate import Predicate as P

        predicate = predicate or P.all_time()
        seg_ms = self.data.options.segment_duration_ms
        tr = predicate.time_range
        w_pred = P(
            TimeRange(
                max(start, tr.inclusive_start),
                min(start + seg_ms, tr.exclusive_end),
            ),
            predicate.filters,
        )
        return self.read(w_pred, projection)

    def read_windows(self, predicate=None, projection=None):
        """Per-segment-window reads (see window_starts/read_window)."""
        starts = self.window_starts(predicate)
        if not starts:
            yield self.read(predicate, projection)
            return
        for w in starts:
            rows = self.read_window(w, predicate, projection)
            if len(rows):
                yield rows

    def flush(self) -> None:
        self.instance.flush_table(self.data)

    def compact(self) -> None:
        self.instance.compact_table(self.data)

    def alter_schema(self, schema: Schema) -> None:
        self.instance.alter_schema(self.data, schema)

    def alter_options(self, options: TableOptions) -> None:
        from ..engine.manifest import AlterOptions

        with self.data.serial_lock:
            self.data.options = options
            self.data.version.set_options(options)
            self.data.manifest.append_edits([AlterOptions(options.to_dict())])

    def physical_datas(self) -> list:
        return [self.data]

    def metrics(self) -> dict:
        return self.data.metrics()


def read_one_page(table, predicate, projection, after):
    """ONE page of a stateless windowed read -> (rows | None, next_token).

    The single definition of the pagination protocol: the remote service
    answers ReadPage with it, and RoutedSubTable drives local resolutions
    through it page by page (so route retries and close-deferral guards
    hold per page). ``after`` is the previous page's token (an exclusive
    window-start lower bound); ``next=None`` terminates the stream.
    Tables without segment windows are one terminal page."""
    starts = (
        table.window_starts(predicate)
        if isinstance(table, AnalyticTable)
        else []
    )
    if not starts:
        if after is not None:
            return None, None
        return table.read(predicate, projection), None
    remaining = [w for w in starts if after is None or w > after]
    if not remaining:
        return None, None
    w = remaining[0]
    return (
        table.read_window(w, predicate, projection),
        w if len(remaining) > 1 else None,
    )


class MemoryTable(Table):
    """Unordered in-memory fake (ref: table_engine/src/memory.rs)."""

    def __init__(self, name: str, schema: Schema, options: TableOptions | None = None):
        self._name = name
        self._schema = schema
        self._options = options or TableOptions()
        self._parts: list[RowGroup] = []

    @property
    def name(self) -> str:
        return self._name

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def options(self) -> TableOptions:
        return self._options

    def write(self, rows: RowGroup) -> int:
        self._parts.append(rows)
        return len(rows)

    def read(self, predicate=None, projection=None) -> RowGroup:
        schema = project_schema(self._schema, projection)
        if not self._parts:
            empty = {c.name: np.empty(0, dtype=c.kind.numpy_dtype) for c in schema.columns}
            return RowGroup(schema, empty)
        rows = RowGroup.concat(self._parts)
        if predicate is not None:
            ts = rows.timestamps
            tr = predicate.time_range
            rows = rows.filter((ts >= tr.inclusive_start) & (ts < tr.exclusive_end))
        if projection is not None:
            names = schema.names()
            rows = RowGroup(
                schema,
                {k: rows.columns[k] for k in names},
                {k: v for k, v in rows.validity.items() if k in names},
            )
        return rows

    def flush(self) -> None:
        pass

    def compact(self) -> None:
        pass

    def alter_schema(self, schema: Schema) -> None:
        self._schema = schema
