"""Scan predicates (ref: src/table_engine/src/predicate.rs).

A ``Predicate`` is the filter contract between the query layer and storage:
a time range (always extracted — it drives segment/SST/row-group pruning)
plus a conjunction of simple column filters. Storage uses it for min-max
pruning; the TPU scan kernel evaluates the exact filters on device.

Filters are deliberately first-order (col op literal): that is what can be
pushed below the scan and compiled into the fused kernel. Anything richer
stays in the executor's post-filter.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Sequence

import numpy as np

from ..common_types.time_range import TimeRange

# THE comparison-op table — every layer that evaluates `col op literal`
# (host expression eval, delta fold, partial push-down) shares it so
# filter semantics cannot diverge.
NUMPY_CMP = {
    "=": np.equal,
    "!=": np.not_equal,
    "<": np.less,
    "<=": np.less_equal,
    ">": np.greater,
    ">=": np.greater_equal,
}


class FilterOp(enum.Enum):
    EQ = "="
    NE = "!="
    LT = "<"
    LE = "<="
    GT = ">"
    GE = ">="
    IN = "in"


@dataclass(frozen=True)
class ColumnFilter:
    column: str
    op: FilterOp
    value: Any  # literal, or tuple of literals for IN

    def evaluate_min_max(self, lo: Any, hi: Any) -> bool:
        """Can any row with column values in [lo, hi] satisfy this filter?

        Used for row-group pruning; must never return False for a group
        that contains a matching row (pruning is only an optimization).
        """
        if lo is None or hi is None:
            return True
        try:
            if self.op is FilterOp.EQ:
                return lo <= self.value <= hi
            if self.op is FilterOp.NE:
                return not (lo == hi == self.value)
            if self.op is FilterOp.LT:
                return lo < self.value
            if self.op is FilterOp.LE:
                return lo <= self.value
            if self.op is FilterOp.GT:
                return hi > self.value
            if self.op is FilterOp.GE:
                return hi >= self.value
            if self.op is FilterOp.IN:
                return any(lo <= v <= hi for v in self.value)
        except TypeError:
            return True  # incomparable types: don't prune
        return True


@dataclass(frozen=True)
class Predicate:
    time_range: TimeRange = field(default_factory=TimeRange.min_to_max)
    filters: tuple[ColumnFilter, ...] = ()
    # Scan hint: the reader may stop once this many matching rows are
    # collected (LIMIT pushdown, ref: the reference pushes fetch limits
    # into ScanRequest). Only set when every WHERE conjunct is already
    # captured by time_range/filters applied AT the scan — a residual
    # filter evaluated later would silently under-return.
    limit: "int | None" = None

    @staticmethod
    def all_time(filters: Sequence[ColumnFilter] = ()) -> "Predicate":
        return Predicate(TimeRange.min_to_max(), tuple(filters))

    def with_time_range(self, tr: TimeRange) -> "Predicate":
        return Predicate(tr, self.filters, self.limit)

    def with_limit(self, n: "int | None") -> "Predicate":
        return Predicate(self.time_range, self.filters, n)

    def restricted_to(self, columns: set[str]) -> "Predicate":
        """Keep only filters on the given columns (plus the time range).

        Used by dedup scans: pruning a row group by a VALUE filter may drop
        the newest version of a key while an older version survives in an
        unpruned group, resurfacing overwritten data. Key-column filters
        (and the time range — the timestamp is a key column) can never
        separate two versions of the same key, so they remain safe."""
        kept = tuple(f for f in self.filters if f.column in columns)
        if len(kept) == len(self.filters):
            return self
        return Predicate(self.time_range, kept)

    def filters_on(self, column: str) -> list[ColumnFilter]:
        return [f for f in self.filters if f.column == column]

    def referenced_columns(self) -> set[str]:
        return {f.column for f in self.filters}
