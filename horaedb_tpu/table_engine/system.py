"""System catalog virtual tables
(ref: src/system_catalog/src/tables.rs — ``system.public.tables`` lists
every user table as rows (timestamp, catalog, schema, table_name,
table_id, engine); served straight from the catalog manager, never
stored).

The virtual table implements the same ``Table`` interface real tables
do, so the whole query layer — projections, filters, aggregates, EXPLAIN
— works on it unchanged. Reads materialize a fresh RowGroup from the
catalog registry on every scan (the listing IS the current state).
"""

from __future__ import annotations

import numpy as np

from ..common_types import ColumnSchema, DatumKind, RowGroup, Schema
from .table import Table, TableOptions

TABLES_NAME = "system.public.tables"

_TABLES_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("catalog", DatumKind.STRING, is_nullable=False),
        ColumnSchema("schema", DatumKind.STRING, is_nullable=False),
        ColumnSchema("table_name", DatumKind.STRING, is_nullable=False),
        ColumnSchema("table_id", DatumKind.UINT64, is_nullable=False),
        ColumnSchema("engine", DatumKind.STRING, is_nullable=False),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "catalog", "schema", "table_name"],
)


class SystemTablesTable(Table):
    """``system.public.tables`` (read-only)."""

    def __init__(self, catalog) -> None:
        self.catalog = catalog
        self._options = TableOptions()

    @property
    def name(self) -> str:
        return TABLES_NAME

    @property
    def schema(self) -> Schema:
        return _TABLES_SCHEMA

    @property
    def options(self) -> TableOptions:
        return self._options

    def write(self, rows) -> int:
        raise ValueError(f"{TABLES_NAME} is read-only")

    def read(self, predicate=None, projection=None) -> RowGroup:
        names = sorted(self.catalog.table_names())
        ids = []
        for n in names:
            e = self.catalog.entry(n)
            ids.append(int(e.table_id) if e is not None else 0)
        rows = RowGroup(
            _TABLES_SCHEMA,
            {
                "timestamp": np.zeros(len(names), dtype=np.int64),
                "catalog": np.array(["horaedb"] * len(names), dtype=object),
                "schema": np.array(["public"] * len(names), dtype=object),
                "table_name": np.array(names, dtype=object),
                "table_id": np.array(ids, dtype=np.uint64),
                "engine": np.array(["Analytic"] * len(names), dtype=object),
            },
        )
        if predicate is not None:
            # The executor drops timestamp conjuncts from its residual
            # WHERE on the promise that storage applied the time range
            # exactly — honor that promise here too.
            tr = predicate.time_range
            ts = rows.timestamps
            mask = (ts >= tr.inclusive_start) & (ts < tr.exclusive_end)
            if not mask.all():
                rows = rows.take(np.nonzero(mask)[0])
        if projection is not None:
            from ..engine.merge import project_schema

            proj = project_schema(rows.schema, projection)
            rows = RowGroup(
                proj, {c.name: rows.columns[c.name] for c in proj.columns}
            )
        return rows

    def flush(self) -> None:
        pass

    def compact(self) -> None:
        pass

    def alter_schema(self, schema) -> None:
        raise ValueError(f"{TABLES_NAME} is read-only")


def open_system_table(catalog, name: str):
    """The catalog's virtual-table hook: a Table for system names, else
    None (regular resolution proceeds)."""
    if name.lower() == TABLES_NAME:
        return SystemTablesTable(catalog)
    return None
