"""System catalog virtual tables
(ref: src/system_catalog/src/tables.rs — ``system.public.tables`` lists
every user table as rows (timestamp, catalog, schema, table_name,
table_id, engine); served straight from the catalog manager, never
stored).

Virtual tables implement the same ``Table`` interface real tables do, so
the whole query layer — projections, filters, aggregates, EXPLAIN, every
wire protocol (HTTP SQL, MySQL, PostgreSQL) — works on them unchanged.
Reads materialize a fresh RowGroup on every scan (the listing IS the
current state).

The tables:

- ``system.public.tables``      — the catalog registry
- ``system.public.query_stats`` — the bounded ring of finalized per-query
  cost ledgers (utils/querystats.STATS_STORE), joinable on request_id;
  one row per recent query with route + every ledger cost field
- ``system.public.metrics``     — a live snapshot of the Prometheus
  registry (one row per sample: family, kind, labels, value)
- ``system.public.workload``    — the workload manager's live state
  (admission slots/queues, dedup flights, quota buckets) plus every
  ``horaedb_admission_*`` counter, as (category, name, label, value)
  rows — the SQL face of /debug/workload
- ``system.public.events``      — the engine event journal
  (utils/events.EVENT_STORE): typed lifecycle events (flush freeze/dump/
  install, compaction, write-stall enter/exit, sheds, WAL replay, DDL,
  shard freeze/thaw), each carrying the trace_id of the request that
  caused it — joinable against query_stats.request_id and the
  /debug/trace store
- ``system.public.alerts``      — the rule engine's alert state
  (rules/engine.RuleEngine): one row per live pending/firing alert
  series plus the recently-resolved ring, labels rendered in the
  standard folded form — the SQL face of /debug/alerts on every wire
- ``system.public.slo``         — the SLO plane's verdicts
  (slo/evaluator.SloEvaluator): one row per objective with its state
  (ok|burning|no_data), current indicator value vs bound, fast/slow
  burn rates over the sliding windows, and the breach count — the SQL
  face of /debug/slo; the tenant simulator's acceptance gate reads it
- ``system.public.device``      — the device telemetry plane's HBM
  residency inventory (obs/device.device_inventory): one row per
  (table, column, component) with dtype, resident bytes, rows,
  last-hit age, and eviction counts; ``component='column'`` rows sum
  exactly to the scan cache's own device_bytes accounting — the usage
  map the dtype/layout auto-tuners read, the SQL face of /debug/device
- ``system.public.decisions``   — the decision plane's journal
  (obs/decisions.DECISION_JOURNAL): one row per adaptive-loop decision
  (kernel router, admission, elastic, dtype tuner, deadline sheds) with
  its choice, features, predicted value, realized outcome, and relative
  error; trace-linked like events — the SQL face of /debug/decisions
- ``system.public.calibration`` — the decision plane's per-loop grading
  (signed/abs relative-error EWMA + fast/slow windows) plus the exact
  issued/resolved/expired/missed/unresolved accounting ledger — the
  tenant simulator's reconciliation gate reads it
- ``system.public.profile``     — the continuous profile plane
  (obs/profile.PROFILE): one row per live (span path, route, shape)
  key with count, total/exclusive milliseconds, EWMA + fast/slow
  window means, and a last-exemplar trace_id linking to
  /debug/trace/{id}; ``<root>/(untracked)`` rows carry the wall time
  no child span covered — the coverage contract the tenantsim gate
  asserts from this table
- ``system.public.traces``      — the bounded trace store
  (utils/tracectx.TRACE_STORE): one row per recent/slow finished
  trace (trace_id, name, at, duration_ms, spans, slow) — the SQL face
  of /debug/trace on every wire
"""

from __future__ import annotations

import numpy as np

from ..common_types import ColumnSchema, DatumKind, RowGroup, Schema
from ..utils.querystats import FLOAT_FIELDS, NUMERIC_FIELDS, STATS_STORE
from .table import Table, TableOptions

TABLES_NAME = "system.public.tables"
QUERY_STATS_NAME = "system.public.query_stats"
METRICS_NAME = "system.public.metrics"
WORKLOAD_NAME = "system.public.workload"
EVENTS_NAME = "system.public.events"
ALERTS_NAME = "system.public.alerts"
SLO_NAME = "system.public.slo"
QUERIES_NAME = "system.public.queries"
DEVICE_NAME = "system.public.device"
DECISIONS_NAME = "system.public.decisions"
CALIBRATION_NAME = "system.public.calibration"
PROFILE_NAME = "system.public.profile"
TRACES_NAME = "system.public.traces"


class _VirtualTable(Table):
    """Read-only table materialized from in-process state on every scan."""

    def __init__(self) -> None:
        self._options = TableOptions()

    @property
    def options(self) -> TableOptions:
        return self._options

    def write(self, rows) -> int:
        raise ValueError(f"{self.name} is read-only")

    def _materialize(self) -> RowGroup:
        raise NotImplementedError

    def read(self, predicate=None, projection=None) -> RowGroup:
        rows = self._materialize()
        if predicate is not None:
            # The executor drops timestamp conjuncts from its residual
            # WHERE on the promise that storage applied the time range
            # exactly — honor that promise here too.
            tr = predicate.time_range
            ts = rows.timestamps
            mask = (ts >= tr.inclusive_start) & (ts < tr.exclusive_end)
            if not mask.all():
                rows = rows.take(np.nonzero(mask)[0])
        if projection is not None:
            from ..engine.merge import project_schema

            proj = project_schema(rows.schema, projection)
            rows = RowGroup(
                proj, {c.name: rows.columns[c.name] for c in proj.columns},
                {k: v for k, v in rows.validity.items()
                 if any(c.name == k for c in proj.columns)},
            )
        return rows

    def flush(self) -> None:
        pass

    def compact(self) -> None:
        pass

    def alter_schema(self, schema) -> None:
        raise ValueError(f"{self.name} is read-only")


_TABLES_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("catalog", DatumKind.STRING, is_nullable=False),
        ColumnSchema("schema", DatumKind.STRING, is_nullable=False),
        ColumnSchema("table_name", DatumKind.STRING, is_nullable=False),
        ColumnSchema("table_id", DatumKind.UINT64, is_nullable=False),
        ColumnSchema("engine", DatumKind.STRING, is_nullable=False),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "catalog", "schema", "table_name"],
)


class SystemTablesTable(_VirtualTable):
    """``system.public.tables`` (read-only)."""

    def __init__(self, catalog) -> None:
        super().__init__()
        self.catalog = catalog

    @property
    def name(self) -> str:
        return TABLES_NAME

    @property
    def schema(self) -> Schema:
        return _TABLES_SCHEMA

    def _materialize(self) -> RowGroup:
        names = sorted(self.catalog.table_names())
        ids = []
        for n in names:
            e = self.catalog.entry(n)
            ids.append(int(e.table_id) if e is not None else 0)
        return RowGroup(
            _TABLES_SCHEMA,
            {
                "timestamp": np.zeros(len(names), dtype=np.int64),
                "catalog": np.array(["horaedb"] * len(names), dtype=object),
                "schema": np.array(["public"] * len(names), dtype=object),
                "table_name": np.array(names, dtype=object),
                "table_id": np.array(ids, dtype=np.uint64),
                "engine": np.array(["Analytic"] * len(names), dtype=object),
            },
        )


def _query_stats_schema() -> Schema:
    """Derived from the ledger field registry — a new ledger field gets
    its column here without a second list to forget."""
    cols = [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("request_id", DatumKind.UINT64, is_nullable=False),
        ColumnSchema("sql", DatumKind.STRING),
        ColumnSchema("route", DatumKind.STRING),
        ColumnSchema("kernel", DatumKind.STRING),
        ColumnSchema("table_name", DatumKind.STRING),
        ColumnSchema("duration_ms", DatumKind.DOUBLE),
    ]
    cols += [ColumnSchema(f, DatumKind.INT64) for f in NUMERIC_FIELDS]
    cols += [ColumnSchema(f, DatumKind.DOUBLE) for f in FLOAT_FIELDS]
    return Schema.build(
        cols,
        timestamp_column="timestamp",
        primary_key=["timestamp", "request_id"],
    )


_QUERY_STATS_SCHEMA = _query_stats_schema()


class QueryStatsTable(_VirtualTable):
    """``system.public.query_stats``: recent finalized query ledgers."""

    @property
    def name(self) -> str:
        return QUERY_STATS_NAME

    @property
    def schema(self) -> Schema:
        return _QUERY_STATS_SCHEMA

    def _materialize(self) -> RowGroup:
        entries = STATS_STORE.list()
        n = len(entries)

        def ints(key, coerce=int) -> np.ndarray:
            out = np.zeros(n, dtype=np.int64)
            for i, e in enumerate(entries):
                v = e.get(key, 0)
                try:
                    out[i] = coerce(v)
                except (TypeError, ValueError):
                    out[i] = 0
            return out

        data: dict[str, np.ndarray] = {
            "timestamp": ints("timestamp"),
            # request ids are the proxy's integer counter; anything else
            # (embedded callers) coerces to 0 rather than failing the scan
            "request_id": ints("request_id").astype(np.uint64),
            "sql": np.array([str(e.get("sql", "")) for e in entries], dtype=object),
            "route": np.array([str(e.get("route", "")) for e in entries], dtype=object),
            "kernel": np.array(
                [str(e.get("kernel", "")) for e in entries], dtype=object
            ),
            "table_name": np.array(
                [str(e.get("table_name", "")) for e in entries], dtype=object
            ),
            "duration_ms": np.array(
                [float(e.get("duration_ms", 0.0)) for e in entries], dtype=np.float64
            ),
        }
        for f in NUMERIC_FIELDS:
            data[f] = ints(f)
        for f in FLOAT_FIELDS:
            data[f] = np.array(
                [float(e.get(f, 0.0)) for e in entries], dtype=np.float64
            )
        return RowGroup(_QUERY_STATS_SCHEMA, data)


_METRICS_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("name", DatumKind.STRING, is_nullable=False),
        ColumnSchema("kind", DatumKind.STRING, is_nullable=False),
        ColumnSchema("labels", DatumKind.STRING),
        ColumnSchema("value", DatumKind.DOUBLE),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "name", "labels"],
)


class MetricsTable(_VirtualTable):
    """``system.public.metrics``: live registry snapshot as rows.

    Counters/gauges contribute one row each; histograms contribute
    ``<name>_count`` and ``<name>_sum`` rows (bucket vectors stay on
    /metrics — SQL dashboards want the scalars)."""

    @property
    def name(self) -> str:
        return METRICS_NAME

    @property
    def schema(self) -> Schema:
        return _METRICS_SCHEMA

    def _materialize(self) -> RowGroup:
        import time

        from ..utils.metrics import Histogram, _render_labels, REGISTRY

        now = int(time.time() * 1000)
        names, kinds, labels, values = [], [], [], []
        for family, members in sorted(REGISTRY.families().items()):
            for m in members:
                rendered = _render_labels(m.labels)
                if isinstance(m, Histogram):
                    with m._lock:
                        total, sum_ = m._total, m._sum
                    names += [f"{family}_count", f"{family}_sum"]
                    kinds += ["histogram", "histogram"]
                    labels += [rendered, rendered]
                    values += [float(total), float(sum_)]
                else:
                    names.append(family)
                    kinds.append(m.TYPE)
                    labels.append(rendered)
                    values.append(float(m.value))
        n = len(names)
        return RowGroup(
            _METRICS_SCHEMA,
            {
                "timestamp": np.full(n, now, dtype=np.int64),
                "name": np.array(names, dtype=object),
                "kind": np.array(kinds, dtype=object),
                "labels": np.array(labels, dtype=object),
                "value": np.array(values, dtype=np.float64),
            },
        )


_WORKLOAD_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("category", DatumKind.STRING, is_nullable=False),
        ColumnSchema("name", DatumKind.STRING, is_nullable=False),
        ColumnSchema("label", DatumKind.STRING),
        ColumnSchema("value", DatumKind.DOUBLE),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "category", "name", "label"],
)


class WorkloadTable(_VirtualTable):
    """``system.public.workload``: the workload manager's live state as
    rows, observable over every wire protocol.

    Live gauges (slots in use, queue depths, dedup flights, quota bucket
    tokens) read from the process's registered WorkloadManagers (summed
    when several proxies coexist); every ``horaedb_admission_*`` metric
    family contributes counter rows under category ``counters`` (name =
    family, so the lint contract 'family -> system-table row' is
    mechanical). Histogram families surface as ``count``/``sum`` labeled
    rows under the family name."""

    @property
    def name(self) -> str:
        return WORKLOAD_NAME

    @property
    def schema(self) -> Schema:
        return _WORKLOAD_SCHEMA

    def _materialize(self) -> RowGroup:
        import time

        from ..utils.metrics import Histogram, _render_labels, REGISTRY
        from ..wlm import registered_managers

        now = int(time.time() * 1000)
        # (category, name, label) -> summed value
        rows: dict[tuple[str, str, str], float] = {}

        def add(category: str, name: str, label: str, value: float) -> None:
            key = (category, name, label)
            rows[key] = rows.get(key, 0.0) + float(value)

        for mgr in registered_managers():
            adm = mgr.admission.snapshot()
            for k in ("total_units", "units_in_use", "memory_budget_bytes",
                      "memory_in_use_bytes", "expensive_cap", "queue_limit"):
                add("admission", k, "", adm[k])
            for cls, units in adm["class_units"].items():
                add("admission", "class_units", cls, units)
            for cls, depth in adm["queue_depth"].items():
                add("admission", "queue_depth", cls, depth)
            ded = mgr.dedup.snapshot()
            for k in ("inflight_leaders", "waiting_followers", "write_epoch"):
                add("dedup", k, "", ded[k])
            q = mgr.quota.snapshot()
            for t in q["blocked"]:
                add("quota", "blocked", t, 1)
            for b in q["quotas"]:
                label = f"{b['scope']}:{b['name']}:{b['kind']}"
                add("quota", "bucket_rate", label, b["rate"])
                add("quota", "bucket_tokens", label, b["tokens"])
        for family, members in sorted(REGISTRY.families().items()):
            if not family.startswith("horaedb_admission_"):
                continue
            for m in members:
                rendered = _render_labels(m.labels)
                if isinstance(m, Histogram):
                    with m._lock:
                        total, sum_ = m._total, m._sum
                    add("counters", family, "count", total)
                    add("counters", family, "sum", sum_)
                else:
                    add("counters", family, rendered, m.value)
        keys = sorted(rows)
        n = len(keys)
        return RowGroup(
            _WORKLOAD_SCHEMA,
            {
                "timestamp": np.full(n, now, dtype=np.int64),
                "category": np.array([k[0] for k in keys], dtype=object),
                "name": np.array([k[1] for k in keys], dtype=object),
                "label": np.array([k[2] for k in keys], dtype=object),
                "value": np.array([rows[k] for k in keys], dtype=np.float64),
            },
        )


_EVENTS_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("seq", DatumKind.UINT64, is_nullable=False),
        ColumnSchema("kind", DatumKind.STRING, is_nullable=False),
        ColumnSchema("table_name", DatumKind.STRING),
        ColumnSchema("trace_id", DatumKind.UINT64),
        ColumnSchema("attrs", DatumKind.STRING),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "seq"],
)


class EventsTable(_VirtualTable):
    """``system.public.events``: the engine event journal as rows.

    ``attrs`` is the event's attribute dict rendered as sorted-key JSON
    (utils/events.render_attrs); ``trace_id`` is 0 when the event fired
    outside any traced request (periodic scans, lease watch)."""

    @property
    def name(self) -> str:
        return EVENTS_NAME

    @property
    def schema(self) -> Schema:
        return _EVENTS_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..utils.events import EVENT_STORE, render_attrs

        entries = EVENT_STORE.list()

        def tid(e) -> int:
            # embedded callers may trace with non-integer ids; the
            # UINT64 column coerces those to 0 rather than failing scans
            try:
                return int(e["trace_id"] or 0)
            except (TypeError, ValueError):
                return 0

        return RowGroup(
            _EVENTS_SCHEMA,
            {
                "timestamp": np.array(
                    [e["timestamp"] for e in entries], dtype=np.int64
                ),
                "seq": np.array([e["seq"] for e in entries], dtype=np.uint64),
                "kind": np.array([e["kind"] for e in entries], dtype=object),
                "table_name": np.array(
                    [e["table"] for e in entries], dtype=object
                ),
                "trace_id": np.array(
                    [tid(e) for e in entries], dtype=np.uint64
                ),
                "attrs": np.array(
                    [render_attrs(e["attrs"]) for e in entries], dtype=object
                ),
            },
        )


_ALERTS_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("rule", DatumKind.STRING, is_nullable=False),
        ColumnSchema("labels", DatumKind.STRING),
        ColumnSchema("state", DatumKind.STRING, is_nullable=False),
        ColumnSchema("value", DatumKind.DOUBLE),
        ColumnSchema("active_since", DatumKind.INT64),
        ColumnSchema("fired_at", DatumKind.INT64),
        ColumnSchema("resolved_at", DatumKind.INT64),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "rule", "labels"],
)


class AlertsTable(_VirtualTable):
    """``system.public.alerts``: the rule engine's alert lifecycle state
    as rows (pending/firing live, recently-resolved ring), summed over
    every registered RuleEngine in the process. ``timestamp`` is the
    instance's state-entry time (fired_at for firing, resolved_at for
    resolved, active_since for pending) so dashboards sort naturally."""

    @property
    def name(self) -> str:
        return ALERTS_NAME

    @property
    def schema(self) -> Schema:
        return _ALERTS_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..rules import registered_engines
        from ..utils.metrics import _render_labels

        entries = []
        for eng in registered_engines():
            entries.extend(eng.alerts_snapshot())

        def ts_of(e: dict) -> int:
            if e["state"] == "resolved":
                return e["resolved_at_ms"]
            if e["state"] == "firing":
                return e["fired_at_ms"]
            return e["active_since_ms"]

        return RowGroup(
            _ALERTS_SCHEMA,
            {
                "timestamp": np.array(
                    [ts_of(e) for e in entries], dtype=np.int64
                ),
                "rule": np.array([e["rule"] for e in entries], dtype=object),
                "labels": np.array(
                    [_render_labels(e["labels"]) for e in entries], dtype=object
                ),
                "state": np.array([e["state"] for e in entries], dtype=object),
                "value": np.array(
                    [float(e["value"]) for e in entries], dtype=np.float64
                ),
                "active_since": np.array(
                    [e["active_since_ms"] for e in entries], dtype=np.int64
                ),
                "fired_at": np.array(
                    [e["fired_at_ms"] for e in entries], dtype=np.int64
                ),
                "resolved_at": np.array(
                    [e["resolved_at_ms"] for e in entries], dtype=np.int64
                ),
            },
        )


_SLO_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("objective", DatumKind.STRING, is_nullable=False),
        ColumnSchema("node", DatumKind.STRING),
        ColumnSchema("state", DatumKind.STRING, is_nullable=False),
        ColumnSchema("value", DatumKind.DOUBLE),
        ColumnSchema("bound", DatumKind.DOUBLE),
        ColumnSchema("target", DatumKind.DOUBLE),
        ColumnSchema("burn_fast", DatumKind.DOUBLE),
        ColumnSchema("burn_slow", DatumKind.DOUBLE),
        ColumnSchema("good_fast", DatumKind.DOUBLE),
        ColumnSchema("good_slow", DatumKind.DOUBLE),
        ColumnSchema("breaches", DatumKind.INT64),
        ColumnSchema("since", DatumKind.INT64),
        ColumnSchema("expr", DatumKind.STRING),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "objective"],
)


class SloTable(_VirtualTable):
    """``system.public.slo``: the SLO plane's verdicts as rows, summed
    over every registered SloEvaluator in the process. ``timestamp`` is
    the objective's last evaluation time; ``state`` is ok|burning|
    no_data; ``value`` is the indicator's worst series at that round
    (NaN while no data has ever arrived); burn rates are the sliding
    fast/slow window burn rates against the error budget ``1-target``."""

    @property
    def name(self) -> str:
        return SLO_NAME

    @property
    def schema(self) -> Schema:
        return _SLO_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..slo import registered_evaluators

        entries = []
        for ev in registered_evaluators():
            entries.extend(ev.snapshot())

        def val(e) -> float:
            return float("nan") if e["value"] is None else float(e["value"])

        return RowGroup(
            _SLO_SCHEMA,
            {
                "timestamp": np.array(
                    [e["last_eval_ms"] for e in entries], dtype=np.int64
                ),
                "objective": np.array(
                    [e["name"] for e in entries], dtype=object
                ),
                "node": np.array([e["node"] for e in entries], dtype=object),
                "state": np.array([e["state"] for e in entries], dtype=object),
                "value": np.array([val(e) for e in entries], dtype=np.float64),
                "bound": np.array(
                    [float(e["bound"]) for e in entries], dtype=np.float64
                ),
                "target": np.array(
                    [float(e["target"]) for e in entries], dtype=np.float64
                ),
                "burn_fast": np.array(
                    [float(e["burn_fast"]) for e in entries], dtype=np.float64
                ),
                "burn_slow": np.array(
                    [float(e["burn_slow"]) for e in entries], dtype=np.float64
                ),
                "good_fast": np.array(
                    [float(e["good_fast"]) for e in entries], dtype=np.float64
                ),
                "good_slow": np.array(
                    [float(e["good_slow"]) for e in entries], dtype=np.float64
                ),
                "breaches": np.array(
                    [int(e["breaches"]) for e in entries], dtype=np.int64
                ),
                "since": np.array(
                    [int(e["since_ms"]) for e in entries], dtype=np.int64
                ),
                "expr": np.array([e["expr"] for e in entries], dtype=object),
            },
        )


_QUERIES_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("query_id", DatumKind.UINT64, is_nullable=False),
        ColumnSchema("request_id", DatumKind.UINT64),
        ColumnSchema("sql", DatumKind.STRING),
        ColumnSchema("tenant", DatumKind.STRING),
        ColumnSchema("protocol", DatumKind.STRING),
        ColumnSchema("class", DatumKind.STRING),
        ColumnSchema("state", DatumKind.STRING),
        ColumnSchema("elapsed_ms", DatumKind.DOUBLE),
        ColumnSchema("deadline_ms", DatumKind.INT64),
        ColumnSchema("remaining_ms", DatumKind.INT64),
        ColumnSchema("cancelled", DatumKind.INT64),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "query_id"],
)


class QueriesTable(_VirtualTable):
    """``system.public.queries``: the live in-flight query registry
    (utils/deadline.QUERY_REGISTRY) — one row per running statement with
    its budget, remaining time, coarse state (running/queued/executing/
    cancelled) and the ``query_id`` that ``KILL QUERY <id>`` /
    ``horaectl query kill`` / ``DELETE /debug/queries/{id}`` target.
    ``remaining_ms`` is -1 for unbounded queries. The statement reading
    this table appears in it too (it is itself a live query)."""

    @property
    def name(self) -> str:
        return QUERIES_NAME

    @property
    def schema(self) -> Schema:
        return _QUERIES_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..utils.deadline import QUERY_REGISTRY

        entries = QUERY_REGISTRY.list()
        return RowGroup(
            _QUERIES_SCHEMA,
            {
                "timestamp": np.array(
                    [int(e["started_ms"]) for e in entries], dtype=np.int64
                ),
                "query_id": np.array(
                    [int(e["query_id"]) for e in entries], dtype=np.uint64
                ),
                "request_id": np.array(
                    [int(e["request_id"] or 0) for e in entries],
                    dtype=np.uint64,
                ),
                "sql": np.array([e["sql"] for e in entries], dtype=object),
                "tenant": np.array(
                    [e["tenant"] for e in entries], dtype=object
                ),
                "protocol": np.array(
                    [e["protocol"] for e in entries], dtype=object
                ),
                "class": np.array(
                    [e["class"] for e in entries], dtype=object
                ),
                "state": np.array(
                    [e["state"] for e in entries], dtype=object
                ),
                "elapsed_ms": np.array(
                    [float(e["elapsed_ms"]) for e in entries],
                    dtype=np.float64,
                ),
                "deadline_ms": np.array(
                    [int(e["deadline_ms"]) for e in entries], dtype=np.int64
                ),
                "remaining_ms": np.array(
                    [int(e["remaining_ms"]) for e in entries], dtype=np.int64
                ),
                "cancelled": np.array(
                    [int(e["cancelled"]) for e in entries], dtype=np.int64
                ),
            },
        )


_DEVICE_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("table_name", DatumKind.STRING, is_nullable=False),
        ColumnSchema("column_name", DatumKind.STRING),
        ColumnSchema("component", DatumKind.STRING, is_nullable=False),
        ColumnSchema("dtype", DatumKind.STRING),
        ColumnSchema("bytes", DatumKind.INT64),
        ColumnSchema("rows", DatumKind.INT64),
        ColumnSchema("last_hit_age_ms", DatumKind.INT64),
        ColumnSchema("evictions", DatumKind.INT64),
        # compressed-layout inventory (ISSUE 19): the resident encoding
        # (raw|bf16|dict8|dict16|delta) and the LOGICAL rows the encoded
        # bytes serve — rows-per-HBM-byte reads straight off this table
        ColumnSchema("encoding", DatumKind.STRING),
        ColumnSchema("logical_rows", DatumKind.INT64),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "table_name", "column_name", "component"],
)


class DeviceTable(_VirtualTable):
    """``system.public.device``: per-(table, column, dtype) HBM residency
    from the device telemetry plane (obs/device) — resident bytes, row
    counts, last-hit age, per-table eviction counts. ``component``
    distinguishes the scan cache's resident columns (whose bytes sum to
    its internal ``device_bytes`` accounting) from session/stack uploads
    and zero-byte rows for evicted tables. ``last_hit_age_ms`` is -1
    when the entry was never served."""

    @property
    def name(self) -> str:
        return DEVICE_NAME

    @property
    def schema(self) -> Schema:
        return _DEVICE_SCHEMA

    def _materialize(self) -> RowGroup:
        import time

        from ..obs.device import device_inventory

        entries = device_inventory()
        now = int(time.time() * 1000)
        n = len(entries)
        return RowGroup(
            _DEVICE_SCHEMA,
            {
                "timestamp": np.full(n, now, dtype=np.int64),
                "table_name": np.array(
                    [str(e.get("table_name", "")) for e in entries],
                    dtype=object,
                ),
                "column_name": np.array(
                    [str(e.get("column_name", "")) for e in entries],
                    dtype=object,
                ),
                "component": np.array(
                    [str(e.get("component", "")) for e in entries],
                    dtype=object,
                ),
                "dtype": np.array(
                    [str(e.get("dtype", "")) for e in entries], dtype=object
                ),
                "bytes": np.array(
                    [int(e.get("bytes", 0)) for e in entries], dtype=np.int64
                ),
                "rows": np.array(
                    [int(e.get("rows", 0)) for e in entries], dtype=np.int64
                ),
                "last_hit_age_ms": np.array(
                    [int(e.get("last_hit_age_ms", -1)) for e in entries],
                    dtype=np.int64,
                ),
                "evictions": np.array(
                    [int(e.get("evictions", 0)) for e in entries],
                    dtype=np.int64,
                ),
                "encoding": np.array(
                    [str(e.get("encoding", "")) for e in entries],
                    dtype=object,
                ),
                "logical_rows": np.array(
                    [int(e.get("logical_rows", 0)) for e in entries],
                    dtype=np.int64,
                ),
            },
        )


_DECISIONS_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("id", DatumKind.UINT64, is_nullable=False),
        ColumnSchema("loop", DatumKind.STRING, is_nullable=False),
        ColumnSchema("decision_key", DatumKind.STRING),
        ColumnSchema("choice", DatumKind.STRING),
        ColumnSchema("features", DatumKind.STRING),
        ColumnSchema("predicted", DatumKind.DOUBLE),
        ColumnSchema("resolved", DatumKind.BOOLEAN),
        ColumnSchema("resolved_at", DatumKind.INT64),
        ColumnSchema("actual", DatumKind.DOUBLE),
        ColumnSchema("outcome", DatumKind.STRING),
        ColumnSchema("error", DatumKind.DOUBLE),
        ColumnSchema("trace_id", DatumKind.UINT64),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "id"],
)


class DecisionsTable(_VirtualTable):
    """``system.public.decisions``: the decision journal as rows — one
    per adaptive-loop decision with the choice, features-at-decision-
    time (sorted-key JSON like events.attrs), the predicted value, and
    — once resolved — the realized outcome and relative error. NULL
    ``predicted``/``actual``/``error`` mean "not numeric-graded";
    ``outcome='expired'`` rows aged out or were evicted unresolved."""

    @property
    def name(self) -> str:
        return DECISIONS_NAME

    @property
    def schema(self) -> Schema:
        return _DECISIONS_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..obs.decisions import DECISION_JOURNAL
        from ..utils.events import render_attrs

        entries = DECISION_JOURNAL.list()

        def tid(e) -> int:
            try:
                return int(e["trace_id"] or 0)
            except (TypeError, ValueError):
                return 0

        def opt(field) -> tuple[np.ndarray, np.ndarray]:
            vals = np.array(
                [
                    0.0 if e[field] is None else float(e[field])
                    for e in entries
                ],
                dtype=np.float64,
            )
            mask = np.array(
                [e[field] is not None for e in entries], dtype=bool
            )
            return vals, mask

        predicted, predicted_ok = opt("predicted")
        actual, actual_ok = opt("actual")
        error, error_ok = opt("error")
        return RowGroup(
            _DECISIONS_SCHEMA,
            {
                "timestamp": np.array(
                    [e["timestamp"] for e in entries], dtype=np.int64
                ),
                "id": np.array([e["id"] for e in entries], dtype=np.uint64),
                "loop": np.array([e["loop"] for e in entries], dtype=object),
                "decision_key": np.array(
                    [e["key"] for e in entries], dtype=object
                ),
                "choice": np.array(
                    [e["choice"] for e in entries], dtype=object
                ),
                "features": np.array(
                    [render_attrs(e["features"]) for e in entries],
                    dtype=object,
                ),
                "predicted": predicted,
                "resolved": np.array(
                    [bool(e["resolved"]) for e in entries], dtype=bool
                ),
                "resolved_at": np.array(
                    [int(e["resolved_at"] or 0) for e in entries],
                    dtype=np.int64,
                ),
                "actual": actual,
                "outcome": np.array(
                    [e["outcome"] for e in entries], dtype=object
                ),
                "error": error,
                "trace_id": np.array(
                    [tid(e) for e in entries], dtype=np.uint64
                ),
            },
            validity={
                "predicted": predicted_ok,
                "actual": actual_ok,
                "error": error_ok,
            },
        )


_CALIBRATION_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("loop", DatumKind.STRING, is_nullable=False),
        ColumnSchema("samples", DatumKind.INT64),
        ColumnSchema("ewma_signed", DatumKind.DOUBLE),
        ColumnSchema("ewma_abs", DatumKind.DOUBLE),
        ColumnSchema("fast_signed", DatumKind.DOUBLE),
        ColumnSchema("fast_abs", DatumKind.DOUBLE),
        ColumnSchema("fast_n", DatumKind.INT64),
        ColumnSchema("slow_signed", DatumKind.DOUBLE),
        ColumnSchema("slow_abs", DatumKind.DOUBLE),
        ColumnSchema("slow_n", DatumKind.INT64),
        ColumnSchema("miscalibrated", DatumKind.BOOLEAN),
        ColumnSchema("issued", DatumKind.INT64),
        ColumnSchema("resolved", DatumKind.INT64),
        ColumnSchema("expired", DatumKind.INT64),
        ColumnSchema("missed", DatumKind.INT64),
        ColumnSchema("unresolved", DatumKind.INT64),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "loop"],
)


class CalibrationTable(_VirtualTable):
    """``system.public.calibration``: one row per adaptive loop with the
    decision plane's grading (relative-error EWMA + fast/slow window
    means; NULL until the loop has a graded sample) and the exact
    accounting ledger — ``issued == resolved + expired + unresolved``
    holds on every read, the reconciliation the tenantsim gate asserts
    from this table."""

    @property
    def name(self) -> str:
        return CALIBRATION_NAME

    @property
    def schema(self) -> Schema:
        return _CALIBRATION_SCHEMA

    def _materialize(self) -> RowGroup:
        import time

        from ..obs.decisions import DECISION_JOURNAL

        rows = DECISION_JOURNAL.calibration()
        now = int(time.time() * 1000)
        n = len(rows)

        def opt(field) -> tuple[np.ndarray, np.ndarray]:
            vals = np.array(
                [
                    0.0 if r[field] is None else float(r[field])
                    for r in rows
                ],
                dtype=np.float64,
            )
            mask = np.array([r[field] is not None for r in rows], dtype=bool)
            return vals, mask

        cols: dict = {
            "timestamp": np.full(n, now, dtype=np.int64),
            "loop": np.array([r["loop"] for r in rows], dtype=object),
            "miscalibrated": np.array(
                [bool(r["miscalibrated"]) for r in rows], dtype=bool
            ),
        }
        for f in ("samples", "fast_n", "slow_n", "issued", "resolved",
                  "expired", "missed", "unresolved"):
            cols[f] = np.array([int(r[f]) for r in rows], dtype=np.int64)
        validity = {}
        for f in ("ewma_signed", "ewma_abs", "fast_signed", "fast_abs",
                  "slow_signed", "slow_abs"):
            cols[f], validity[f] = opt(f)
        return RowGroup(_CALIBRATION_SCHEMA, cols, validity=validity)


_PROFILE_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("path", DatumKind.STRING, is_nullable=False),
        ColumnSchema("route", DatumKind.STRING),
        ColumnSchema("shape", DatumKind.STRING),
        ColumnSchema("count", DatumKind.INT64),
        ColumnSchema("total_ms", DatumKind.DOUBLE),
        ColumnSchema("exclusive_ms", DatumKind.DOUBLE),
        ColumnSchema("ewma_ms", DatumKind.DOUBLE),
        ColumnSchema("fast_ms", DatumKind.DOUBLE),
        ColumnSchema("fast_n", DatumKind.INT64),
        ColumnSchema("slow_ms", DatumKind.DOUBLE),
        ColumnSchema("slow_n", DatumKind.INT64),
        ColumnSchema("trace_id", DatumKind.STRING),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "path"],
)


class ProfileTable(_VirtualTable):
    """``system.public.profile``: the streaming profile aggregator as
    rows — one per live (path, route, shape) key, exclusive-heavy
    first. ``timestamp`` is the key's last fold; ``trace_id`` the last
    exemplar (join against system.public.traces or /debug/trace/{id}).
    The ``<root>/(untracked)`` rows are the accounting remainder —
    ``sum(exclusive_ms)`` over a root's non-root paths equals the
    root's ``total_ms`` exactly (the fold invariant)."""

    @property
    def name(self) -> str:
        return PROFILE_NAME

    @property
    def schema(self) -> Schema:
        return _PROFILE_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..obs.profile import PROFILE

        rows = PROFILE.list()

        def opt(field) -> tuple[np.ndarray, np.ndarray]:
            vals = np.array(
                [0.0 if r[field] is None else float(r[field]) for r in rows],
                dtype=np.float64,
            )
            mask = np.array([r[field] is not None for r in rows], dtype=bool)
            return vals, mask

        ewma, ewma_ok = opt("ewma_ms")
        return RowGroup(
            _PROFILE_SCHEMA,
            {
                "timestamp": np.array(
                    [int(r["last_at"] * 1000) for r in rows], dtype=np.int64
                ),
                "path": np.array([r["path"] for r in rows], dtype=object),
                "route": np.array([r["route"] for r in rows], dtype=object),
                "shape": np.array([r["shape"] for r in rows], dtype=object),
                "count": np.array(
                    [int(r["count"]) for r in rows], dtype=np.int64
                ),
                "total_ms": np.array(
                    [float(r["total_ms"]) for r in rows], dtype=np.float64
                ),
                "exclusive_ms": np.array(
                    [float(r["exclusive_ms"]) for r in rows],
                    dtype=np.float64,
                ),
                "ewma_ms": ewma,
                "fast_ms": np.array(
                    [float(r["fast_ms"]) for r in rows], dtype=np.float64
                ),
                "fast_n": np.array(
                    [int(r["fast_n"]) for r in rows], dtype=np.int64
                ),
                "slow_ms": np.array(
                    [float(r["slow_ms"]) for r in rows], dtype=np.float64
                ),
                "slow_n": np.array(
                    [int(r["slow_n"]) for r in rows], dtype=np.int64
                ),
                "trace_id": np.array(
                    [str(r["last_trace_id"]) for r in rows], dtype=object
                ),
            },
            validity={"ewma_ms": ewma_ok},
        )


_TRACES_SCHEMA = Schema.build(
    [
        ColumnSchema("timestamp", DatumKind.TIMESTAMP, is_nullable=False),
        ColumnSchema("trace_id", DatumKind.STRING, is_nullable=False),
        ColumnSchema("name", DatumKind.STRING, is_nullable=False),
        ColumnSchema("duration_ms", DatumKind.DOUBLE),
        ColumnSchema("spans", DatumKind.INT64),
        ColumnSchema("slow", DatumKind.BOOLEAN),
    ],
    timestamp_column="timestamp",
    primary_key=["timestamp", "trace_id"],
)


class TracesTable(_VirtualTable):
    """``system.public.traces``: the bounded in-process trace store as
    rows (newest first in the underlying listing, dedup'd across the
    recent and slow rings). ``timestamp`` is the trace's start;
    ``trace_id`` joins /debug/trace/{id} and the profile plane's
    exemplars."""

    @property
    def name(self) -> str:
        return TRACES_NAME

    @property
    def schema(self) -> Schema:
        return _TRACES_SCHEMA

    def _materialize(self) -> RowGroup:
        from ..utils.tracectx import TRACE_STORE

        rows = TRACE_STORE.list()
        return RowGroup(
            _TRACES_SCHEMA,
            {
                "timestamp": np.array(
                    [int(float(r["at"]) * 1000) for r in rows],
                    dtype=np.int64,
                ),
                "trace_id": np.array(
                    [str(r["trace_id"]) for r in rows], dtype=object
                ),
                "name": np.array([r["name"] for r in rows], dtype=object),
                "duration_ms": np.array(
                    [float(r["duration_ms"] or 0.0) for r in rows],
                    dtype=np.float64,
                ),
                "spans": np.array(
                    [int(r["spans"]) for r in rows], dtype=np.int64
                ),
                "slow": np.array(
                    [bool(r["slow"]) for r in rows], dtype=bool
                ),
            },
        )


def open_system_table(catalog, name: str):
    """The catalog's virtual-table hook: a Table for system names, else
    None (regular resolution proceeds)."""
    low = name.lower()
    if low == TABLES_NAME:
        return SystemTablesTable(catalog)
    if low == QUERY_STATS_NAME:
        return QueryStatsTable()
    if low == METRICS_NAME:
        return MetricsTable()
    if low == WORKLOAD_NAME:
        return WorkloadTable()
    if low == EVENTS_NAME:
        return EventsTable()
    if low == ALERTS_NAME:
        return AlertsTable()
    if low == SLO_NAME:
        return SloTable()
    if low == QUERIES_NAME:
        return QueriesTable()
    if low == DEVICE_NAME:
        return DeviceTable()
    if low == DECISIONS_NAME:
        return DecisionsTable()
    if low == CALIBRATION_NAME:
        return CalibrationTable()
    if low == PROFILE_NAME:
        return ProfileTable()
    if low == TRACES_NAME:
        return TracesTable()
    return None
