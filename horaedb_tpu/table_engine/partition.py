"""Partition rules + the partitioned virtual table
(ref: src/table_engine/src/partition/{mod.rs:90-136,rule/}, and
src/partition_table_engine/src/{partition.rs,scan_builder.rs}).

A partitioned table is a logical table over N physical sub-tables:

- writes split by the rule — ONE vectorized pass computes every row's
  partition (ref fans out row-by-row; here the rule maps dense columns);
- reads scatter to the sub-tables and either concatenate rows or (for
  aggregates) combine per-partition partial AggStates — the same monoid
  the mesh collectives use, so a partition maps 1:1 onto a future shard.

Rules (mirroring the reference's three):
- ``KeyRule``    — hash of key tag columns mod N (default for PARTITION BY KEY)
- ``HashRule``   — hash of an integer column mod N
- ``RandomRule`` — round-robin-ish scatter for append-only workloads
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Optional, Sequence

import numpy as np

from ..common_types.row_group import RowGroup
from ..common_types.schema import Schema, compute_tsid
from ..engine.options import TableOptions, UpdateMode
from .predicate import ColumnFilter, FilterOp, Predicate
from .table import Table


class PartitionRule(ABC):
    def __init__(self, columns: tuple[str, ...], num_partitions: int) -> None:
        if num_partitions <= 0:
            raise ValueError("num_partitions must be positive")
        self.columns = columns
        self.num_partitions = num_partitions

    @abstractmethod
    def partition_of_rows(self, rows: RowGroup) -> np.ndarray:
        """int partition id per row (vectorized)."""

    def prune(self, predicate: Predicate) -> Optional[list[int]]:
        """Partitions that may match, or None = all.

        Only exact-equality (EQ on every rule column, or IN) can prune —
        same as the reference's rule-based locate-for-read.
        """
        return None

    def to_dict(self) -> dict:
        return {
            "method": self.method,
            "columns": list(self.columns),
            "num_partitions": self.num_partitions,
        }


class KeyRule(PartitionRule):
    """Hash of the named key/tag column values (ref: rule/key.rs)."""

    method = "key"

    def partition_of_rows(self, rows: RowGroup) -> np.ndarray:
        cols = [rows.column(c) for c in self.columns]
        h = compute_tsid(cols, num_rows=len(rows))
        return (h % np.uint64(self.num_partitions)).astype(np.int64)

    def partition_of_values(self, values: Sequence) -> int:
        arrays = [np.array([v], dtype=object) for v in values]
        h = compute_tsid(arrays, num_rows=1)
        return int(h[0] % np.uint64(self.num_partitions))

    def prune(self, predicate: Predicate) -> Optional[list[int]]:
        # Need an EQ (or IN) constraint on EVERY rule column.
        per_col: list[list] = []
        for c in self.columns:
            eqs = [f for f in predicate.filters_on(c) if f.op is FilterOp.EQ]
            ins = [f for f in predicate.filters_on(c) if f.op is FilterOp.IN]
            if eqs:
                per_col.append([eqs[0].value])
            elif ins:
                per_col.append(list(ins[0].value))
            else:
                return None
        import itertools

        parts = {
            self.partition_of_values(combo)
            for combo in itertools.product(*per_col)
        }
        return sorted(parts)


class HashRule(PartitionRule):
    """Modulo hash of one integer column (ref: rule/hash.rs linear hash)."""

    method = "hash"

    def __init__(self, columns: tuple[str, ...], num_partitions: int) -> None:
        if len(columns) != 1:
            raise ValueError("HashRule takes exactly one column")
        super().__init__(columns, num_partitions)

    def partition_of_rows(self, rows: RowGroup) -> np.ndarray:
        col = rows.column(self.columns[0])
        return (col.astype(np.int64) % self.num_partitions + self.num_partitions) % self.num_partitions

    def prune(self, predicate: Predicate) -> Optional[list[int]]:
        eqs = [f for f in predicate.filters_on(self.columns[0]) if f.op is FilterOp.EQ]
        if not eqs:
            return None
        v = int(eqs[0].value)
        return [(v % self.num_partitions + self.num_partitions) % self.num_partitions]


class RandomRule(PartitionRule):
    """Scatter without locate support — append-only tables only."""

    method = "random"

    def partition_of_rows(self, rows: RowGroup) -> np.ndarray:
        return np.random.default_rng().integers(0, self.num_partitions, len(rows))


def make_rule(method: str, columns: Sequence[str], num_partitions: int) -> PartitionRule:
    m = method.lower()
    if m == "key":
        return KeyRule(tuple(columns), num_partitions)
    if m == "hash":
        return HashRule(tuple(columns), num_partitions)
    if m == "random":
        return RandomRule(tuple(columns), num_partitions)
    raise ValueError(f"unknown partition method {method!r}")


def sub_table_name(table: str, partition: int) -> str:
    """Reference naming: __<table>_<partition> (partition.rs sub tables)."""
    return f"__{table}_{partition}"


class PartitionedTable(Table):
    def __init__(
        self,
        name: str,
        rule: PartitionRule,
        sub_tables: list[Table],
    ) -> None:
        if len(sub_tables) != rule.num_partitions:
            raise ValueError("sub table count != num_partitions")
        self._name = name
        self.rule = rule
        self.sub_tables = sub_tables

    @property
    def name(self) -> str:
        return self._name

    @property
    def schema(self) -> Schema:
        return self.sub_tables[0].schema

    @property
    def options(self) -> TableOptions:
        return self.sub_tables[0].options

    # ---- scatter write --------------------------------------------------
    def write(self, rows: RowGroup) -> int:
        parts = self.rule.partition_of_rows(rows)
        for p in np.unique(parts):
            idx = np.nonzero(parts == p)[0]
            self.sub_tables[int(p)].write(rows.take(idx))
        return len(rows)

    # ---- scatter/gather read --------------------------------------------
    def read(self, predicate=None, projection=None) -> RowGroup:
        predicate = predicate or Predicate.all_time()
        keep = self.rule.prune(predicate)
        targets = (
            self.sub_tables
            if keep is None
            else [self.sub_tables[i] for i in keep]
        )
        from ..utils.querystats import record as _qs_record

        _qs_record(fanout=len(targets))
        parts = [t.read(predicate, projection) for t in targets]
        non_empty = [p for p in parts if len(p)]
        if not non_empty:
            return parts[0]  # empty, right schema — already fetched
        return RowGroup.concat(non_empty)

    def partial_agg(self, spec: dict):
        """Scatter the pushed-down aggregate to every (unpruned) partition
        — each runs against its OWN data, remote ones across the wire —
        and concatenate the partial batches (combining stays associative,
        so the caller's single final combine still works)."""
        from ..remote.codec import predicate_from_dict
        from ..utils.runtime import scatter_pool

        keep = self.rule.prune(predicate_from_dict(spec["predicate"]))
        targets = (
            self.sub_tables if keep is None else [self.sub_tables[i] for i in keep]
        )
        from ..utils.querystats import record as _qs_record

        _qs_record(fanout=len(targets))
        if len(targets) == 1:
            return targets[0].partial_agg(spec)
        import contextvars

        from ..utils.tracectx import span

        def one(t):
            # copied context per task: partition spans (and remote span
            # grafts from the wire) attach under the coordinator's tree
            with span("partition", partition=t.name):
                return t.partial_agg(spec)

        ctxs = [contextvars.copy_context() for _ in targets]
        parts = list(
            scatter_pool().map(
                lambda ct: ct[0].run(one, ct[1]), zip(ctxs, targets)
            )
        )
        names = None
        merged: dict[str, list] = {}
        stage_metrics: list = []
        for p_names, p_arrays, p_metrics in parts:
            stage_metrics.extend(p_metrics)
            if not len(p_arrays) or not len(p_arrays[0]):
                continue
            names = p_names
            for nm, arr in zip(p_names, p_arrays):
                merged.setdefault(nm, []).append(arr)
        if names is None:
            return parts[0][0], parts[0][1], stage_metrics
        return names, [np.concatenate(merged[nm]) for nm in names], stage_metrics

    def flush(self) -> None:
        for t in self.sub_tables:
            t.flush()

    def compact(self) -> None:
        for t in self.sub_tables:
            t.compact()

    def alter_schema(self, schema: Schema) -> None:
        for t in self.sub_tables:
            t.alter_schema(schema)

    def alter_options(self, options: TableOptions) -> None:
        for t in self.sub_tables:
            t.alter_options(options)

    def physical_datas(self) -> list:
        return [d for t in self.sub_tables for d in t.physical_datas()]

    def metrics(self) -> dict:
        subs = [t.metrics() for t in self.sub_tables]
        return {
            "table": self._name,
            "partitions": len(subs),
            "memtable_bytes": sum(m.get("memtable_bytes", 0) for m in subs),
            "num_ssts": sum(m.get("num_ssts", 0) for m in subs),
            "sst_bytes": sum(m.get("sst_bytes", 0) for m in subs),
        }
