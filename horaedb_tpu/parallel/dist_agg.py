"""Sharded scan/aggregate: the distributed query step
(ref: df_engine_extensions/src/dist_sql_query — partial agg pushed to data
nodes, final agg at the coordinator; resolver.rs:76-120).

TPU-native re-expression: ``shard_map`` over a 1-D mesh axis ``"shard"``.
Each device runs the SAME fused scan/agg body on its row shard (rows are
sharded along axis 0 / the trailing row axis of values), then the
aggregation monoid combines across devices with XLA collectives:

    counts, sums -> psum        mins -> pmin        maxs -> pmax

which ride ICI inside a slice and DCN across slices — XLA picks the
collective implementation; the program is identical from 1 to N devices.
"""

from __future__ import annotations

import functools
import threading
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.31 re-exports it at top level
    from jax import shard_map
except ImportError:  # older jax: experimental module only
    from jax.experimental.shard_map import shard_map

from ..ops.encoding import PaddedBatch
from ..ops.scan_agg import (
    AggState,
    ScanAggSpec,
    cached_scan_agg_body,
    coerce_literals,
    encode_filter_ops,
    scan_agg_body,
    state_to_host,
)

SHARD_AXIS = "shard"

# Compiled steps keyed by (mesh, spec): jax.jit caches by function identity,
# so rebuilding the shard_map closure per call would re-compile every time.
# LRU-bounded with the same discipline (and the same bound) as
# PathRouter.MAX_KEYS — distinct query shapes must not grow it without
# limit over a server's lifetime; dict insertion order is the recency
# order, re-inserting moves a key to the back.
_STEP_CACHE: dict = {}
_STEP_LOCK = threading.Lock()


def _step_cache_max() -> int:
    from ..query.path_router import MAX_KEYS

    return MAX_KEYS


def _combine(state):
    """The aggregation monoid as mesh collectives (final aggregate)."""
    counts, sums, mins, maxs = state
    return (
        jax.lax.psum(counts, SHARD_AXIS),
        jax.lax.psum(sums, SHARD_AXIS),
        jax.lax.pmin(mins, SHARD_AXIS),
        jax.lax.pmax(maxs, SHARD_AXIS),
    )


def _resolved(spec: ScanAggSpec) -> ScanAggSpec:
    """Resolve the segment impl ON HOST so the concrete kernel name is
    what keys the step cache and the jit trace — a live flip of
    HORAEDB_SEGMENT_IMPL / HORAEDB_MXU_MAX_SEGMENTS re-keys warm shapes
    instead of silently serving the stale compiled branch."""
    import dataclasses

    from ..ops.scan_agg import resolve_segment_impl

    impl = resolve_segment_impl(
        spec.n_groups * spec.n_buckets, spec.segment_impl
    )
    if impl == spec.segment_impl:
        return spec
    return dataclasses.replace(spec, segment_impl=impl)


def cached_step(cache_key, build) -> Callable:
    """THE compiled-step LRU: get-or-build under the lock, bounded at
    PathRouter.MAX_KEYS, dict insertion order = recency. One discipline
    for every shard_map step cache (the agg steps here, the raw-read
    steps in parallel/dist_raw) — distinct key spaces share one bound."""
    with _STEP_LOCK:
        cached = _STEP_CACHE.pop(cache_key, None)
        if cached is not None:
            _STEP_CACHE[cache_key] = cached  # LRU touch
            return cached
    step = build()
    with _STEP_LOCK:
        while len(_STEP_CACHE) >= _step_cache_max():
            _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
        _STEP_CACHE[cache_key] = step
    return step


def _build_step(mesh: Mesh, spec: ScanAggSpec, tag: str, body, in_specs) -> Callable:
    """shard_map(body)+combine, jitted and cached per (mesh, spec, tag)."""
    spec = _resolved(spec)

    def build():
        static_filters = encode_filter_ops(spec.numeric_filters)

        def per_shard(*args):
            return _combine(
                body(
                    *args,
                    n_groups=spec.n_groups,
                    n_buckets=spec.n_buckets,
                    n_agg_fields=spec.n_agg_fields,
                    numeric_filters=static_filters,
                    need_minmax=spec.need_minmax,
                    segment_impl=spec.segment_impl,
                    hash_slots=spec.hash_slots,
                )
            )

        return jax.jit(
            shard_map(
                per_shard, mesh=mesh, in_specs=in_specs,
                out_specs=(P(), P(), P(), P()),
            )
        )

    return cached_step((mesh, spec, tag), build)


def make_dist_scan_agg(mesh: Mesh, spec: ScanAggSpec) -> Callable:
    """Compile (or fetch cached) the sharded scan/agg step for ``spec``.

    Returns ``step(group_codes, bucket_ids, mask, values, literals)`` where
    row-dimension inputs are sharded over the mesh axis and the output
    aggregate state is replicated (fully combined) on every device.
    """
    return _build_step(
        mesh,
        spec,
        "scan",
        scan_agg_body,
        in_specs=(
            P(SHARD_AXIS),  # group codes (rows)
            P(SHARD_AXIS),  # bucket ids (rows)
            P(SHARD_AXIS),  # mask (rows)
            P(None, SHARD_AXIS),  # value columns (fields, rows)
            P(None),  # filter literals
        ),
    )


def make_cached_dist_scan_agg(mesh: Mesh, spec: ScanAggSpec) -> Callable:
    """Sharded version of the HBM-resident cached kernel.

    The cache's big per-row arrays (series codes, relative timestamps,
    value columns) live SHARDED across the mesh (scan_cache places them
    with ``P("shard")``); per-query small inputs (series→group map, allow
    list, literals, time scalars) are replicated. Each device aggregates
    its row shard, then the monoid combines via collectives — the default
    serving path on a multi-chip mesh, not a demo path.
    """
    return _build_step(
        mesh,
        spec,
        "cached",
        cached_scan_agg_body,
        in_specs=(
            P(SHARD_AXIS),  # series codes (rows)
            P(SHARD_AXIS),  # relative timestamps (rows)
            P(None, SHARD_AXIS),  # value columns (fields, rows)
            P(None),  # series -> group map (replicated)
            P(None),  # series allow list (replicated)
            P(None),  # filter literals
            P(), P(), P(), P(),  # time-range / bucket scalars
        ),
    )


def dist_scan_aggregate(
    mesh: Mesh,
    batch: PaddedBatch,
    spec: ScanAggSpec,
    filter_literals=(),
) -> AggState:
    """Convenience wrapper: pad the batch to a multiple of the mesh size,
    run the sharded step, return host-side combined partials."""
    n_dev = mesh.devices.size
    padded = batch.padded_len
    group_codes, bucket_ids, mask, values = (
        batch.group_codes, batch.bucket_ids, batch.mask, batch.values,
    )
    rem = padded % n_dev
    if rem:
        # Shape buckets are powers of two, so this only triggers on
        # non-power-of-two meshes. Pad rows are masked out, so they never
        # touch the aggregates.
        extra = n_dev - rem
        group_codes = np.pad(group_codes, (0, extra))
        bucket_ids = np.pad(bucket_ids, (0, extra))
        mask = np.pad(mask, (0, extra))  # False fill
        values = np.pad(values, ((0, 0), (0, extra)))
    step = make_dist_scan_agg(mesh, spec)
    import time as _time

    from ..obs.device import timed_dispatch
    from ..utils.querystats import note_kernel_dispatch

    t0 = _time.perf_counter()
    counts, sums, mins, maxs = timed_dispatch(
        "fused_dist",
        lambda: step(
            jnp.asarray(group_codes),
            jnp.asarray(bucket_ids),
            jnp.asarray(mask),
            jnp.asarray(values),
            coerce_literals(filter_literals),
        ),
    )
    state = state_to_host(counts, sums, mins, maxs)
    # Compile accounting for the sharded fused path — a first-sighting
    # shard_map compile is a MULTI-SECOND stall on real chips and must
    # journal/mark compile_hit like every other dispatch point (the
    # single-device path accounts inside scan_aggregate; this wrapper is
    # the dist equivalent). ``spec`` is the same static key that keys
    # the step cache; ``values.shape`` carries the padded batch bucket.
    note_kernel_dispatch(
        ("fused-dist", int(n_dev), values.shape, spec),
        _time.perf_counter() - t0,
        kind="fused_dist",
    )
    return state
