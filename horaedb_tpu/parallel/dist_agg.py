"""Sharded scan/aggregate: the distributed query step
(ref: df_engine_extensions/src/dist_sql_query — partial agg pushed to data
nodes, final agg at the coordinator; resolver.rs:76-120).

TPU-native re-expression: ``shard_map`` over a 1-D mesh axis ``"shard"``.
Each device runs the SAME fused scan/agg body on its row shard (rows are
sharded along axis 0 / the trailing row axis of values), then the
aggregation monoid combines across devices with XLA collectives:

    counts, sums -> psum        mins -> pmin        maxs -> pmax

which ride ICI inside a slice and DCN across slices — XLA picks the
collective implementation; the program is identical from 1 to N devices.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from ..ops.encoding import PaddedBatch
from ..ops.scan_agg import (
    AggState,
    ScanAggSpec,
    coerce_literals,
    encode_filter_ops,
    scan_agg_body,
    state_to_host,
)

SHARD_AXIS = "shard"

# Compiled steps keyed by (mesh, spec): jax.jit caches by function identity,
# so rebuilding the shard_map closure per call would re-compile every time.
_STEP_CACHE: dict = {}


def make_dist_scan_agg(mesh: Mesh, spec: ScanAggSpec) -> Callable:
    """Compile (or fetch cached) the sharded scan/agg step for ``spec``.

    Returns ``step(group_codes, bucket_ids, mask, values, literals)`` where
    row-dimension inputs are sharded over the mesh axis and the output
    aggregate state is replicated (fully combined) on every device.
    """
    cache_key = (mesh, spec)
    cached = _STEP_CACHE.get(cache_key)
    if cached is not None:
        return cached
    static_filters = encode_filter_ops(spec.numeric_filters)

    def per_shard(group_codes, bucket_ids, mask, values, literals):
        counts, sums, mins, maxs = scan_agg_body(
            group_codes,
            bucket_ids,
            mask,
            values,
            literals,
            n_groups=spec.n_groups,
            n_buckets=spec.n_buckets,
            n_agg_fields=spec.n_agg_fields,
            numeric_filters=static_filters,
        )
        # Final aggregate: the monoid combine as mesh collectives.
        counts = jax.lax.psum(counts, SHARD_AXIS)
        sums = jax.lax.psum(sums, SHARD_AXIS)
        mins = jax.lax.pmin(mins, SHARD_AXIS)
        maxs = jax.lax.pmax(maxs, SHARD_AXIS)
        return counts, sums, mins, maxs

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(None, SHARD_AXIS), P(None)),
        out_specs=(P(), P(), P(), P()),
    )
    step = jax.jit(sharded)
    _STEP_CACHE[cache_key] = step
    return step


def dist_scan_aggregate(
    mesh: Mesh,
    batch: PaddedBatch,
    spec: ScanAggSpec,
    filter_literals=(),
) -> AggState:
    """Convenience wrapper: pad the batch to a multiple of the mesh size,
    run the sharded step, return host-side combined partials."""
    n_dev = mesh.devices.size
    padded = batch.padded_len
    if padded % n_dev:
        raise ValueError(
            f"padded batch length {padded} not divisible by mesh size {n_dev} "
            "(shape buckets are powers of two; use a power-of-two mesh)"
        )
    step = make_dist_scan_agg(mesh, spec)
    counts, sums, mins, maxs = step(
        jnp.asarray(batch.group_codes),
        jnp.asarray(batch.bucket_ids),
        jnp.asarray(batch.mask),
        jnp.asarray(batch.values),
        coerce_literals(filter_literals),
    )
    return state_to_host(counts, sums, mins, maxs)
