"""Distributed execution over a device mesh.

Where the reference distributes queries by shipping serialized DataFusion
subplans over gRPC to remote nodes and merging arrow streams back
(SURVEY §2.5, df_engine_extensions dist push-down), the TPU-native design
expresses the same partial-aggregate/final-aggregate split as ONE SPMD
program: rows are sharded across a ``jax.sharding.Mesh`` axis, every device
runs the fused scan/agg kernel on its shard, and XLA collectives (psum /
pmin / pmax over ICI) do the final combine. No plan codec, no RPC on the
data path.
"""

from .dist_agg import dist_scan_aggregate, make_dist_scan_agg
from .dist_merge import dist_merge_dedup

__all__ = ["dist_scan_aggregate", "make_dist_scan_agg", "dist_merge_dedup"]
