"""Device mesh provider for the serving path.

The executor asks for THE mesh (all visible local devices on a 1-D
``"shard"`` axis) and shards large scans over it; small scans stay
single-device where dispatch overhead would dominate. The same mesh shape
scales from 1 chip to a pod slice — XLA lays collectives onto ICI/DCN
(ref boundary: df_engine_extensions/src/dist_sql_query/resolver.rs:105-120,
where the reference decides local vs distributed execution).
"""

from __future__ import annotations

import threading
from typing import Optional

_lock = threading.Lock()
_cached = None
_cached_key = None

# Below this many valid rows a sharded dispatch costs more than it saves
# (measured on the 8-device CPU mesh; revisit with on-chip profiles).
DEFAULT_DIST_MIN_ROWS = 1 << 18


def dist_min_rows() -> int:
    from ..utils.env import env_int

    return env_int("HORAEDB_DIST_MIN_ROWS", DEFAULT_DIST_MIN_ROWS)


def serving_mesh(min_devices: int = 2) -> Optional["jax.sharding.Mesh"]:
    """The 1-D mesh over all local devices, or None when not worth it.

    Cached per device-set; safe to call per query. ``None`` means "run
    single-device" (fewer than ``min_devices`` devices visible).
    """
    import jax

    global _cached, _cached_key
    devices = jax.devices()
    if len(devices) < min_devices:
        return None
    key = tuple(id(d) for d in devices)
    with _lock:
        if _cached_key != key:
            from jax.sharding import Mesh

            import numpy as np

            _cached = Mesh(np.array(devices), ("shard",))
            _cached_key = key
        return _cached
