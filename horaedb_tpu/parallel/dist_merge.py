"""Distributed merge-dedup: the compaction sort kernel under shard_map
(ref: the reference's compaction runs node-local,
analytic_engine/src/compaction/runner/local_runner.rs — a TPU pod can
instead split one merge across chips because the key space partitions
cleanly).

The same tsid-range chunking the single-chip pipeline uses
(engine/compaction.py _device_merge) maps chunks onto MESH DEVICES: every
duplicate key shares a chunk, so each device sorts + dedups its own slice
with ZERO collectives, and the chunk outputs concatenate in split order.
shard_map runs the per-device kernel body SPMD over the mesh — one
compile, n devices, each sorting bucket-padded u32 operands.
"""

from __future__ import annotations

import numpy as np


def dist_merge_dedup(
    mesh,
    tsid: np.ndarray,
    ts: np.ndarray,
    seq: np.ndarray,
    dedup: bool = True,
) -> np.ndarray:
    """Global row selection (indices into the input, in merged key order)
    for a k-way merge-dedup sharded over ``mesh``. Semantics match
    ops.merge_dedup.merge_dedup_permutation: sort by (tsid, ts, seq
    desc), keep the newest row per (tsid, ts) key."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..ops.encoding import next_pow2, split_u64
    from ..ops.merge_dedup import _pack_rest, fused32_sort_dedup

    n = len(tsid)
    n_dev = int(mesh.devices.size)
    if n == 0:
        return np.empty(0, dtype=np.int64)

    ts64 = ts.astype(np.int64, copy=False)
    seq64 = seq.astype(np.uint64, copy=False)

    # tsid-value chunk boundaries from a stride sample: duplicates of a
    # key can never straddle devices, which is what makes the merge
    # embarrassingly parallel.
    step = max(1, n // 65536)
    sample = np.sort(tsid[::step])
    splits = sample[
        [min(len(sample) - 1, (len(sample) * (i + 1)) // n_dev)
         for i in range(n_dev - 1)]
    ]
    cid = np.searchsorted(splits, tsid, side="right")
    idxs = [np.flatnonzero(cid == d) for d in range(n_dev)]
    bucket = next_pow2(max((len(i) for i in idxs), default=1), floor=256)

    # Same packed rest word (and span measurement) as the single-chip
    # fused kernel — ONE implementation; global spans so every device
    # shares one mask. Wide spans RAISE: callers must pre-chunk by time
    # (a segment-scoped merge always fits).
    kind, packed = _pack_rest(ts64, seq64)
    if kind != "f32":
        raise ValueError(
            "dist merge requires packed (ts, seq) spans <= 32 bits; "
            "pre-chunk by time first"
        )
    rest_full, rest_mask = packed

    U32_MAX = np.uint32(0xFFFFFFFF)
    op_hi = np.full((n_dev, bucket), U32_MAX, dtype=np.uint32)
    op_lo = np.full((n_dev, bucket), U32_MAX, dtype=np.uint32)
    op_rest = np.full((n_dev, bucket), U32_MAX, dtype=np.uint32)
    n_valid = np.zeros((n_dev, 1), dtype=np.int32)
    for d, idx in enumerate(idxs):
        k = len(idx)
        n_valid[d, 0] = k
        if k == 0:
            continue
        rev = idx[::-1]  # reversed + stable sort = newest input row wins
        hi, lo = split_u64(tsid[rev])
        op_hi[d, :k] = hi
        op_lo[d, :k] = lo
        op_rest[d, :k] = rest_full[rev]

    def body(hi, lo, rest, nv):
        perm, keep = fused32_sort_dedup(
            hi[0], lo[0], rest[0], jnp.uint32(rest_mask), nv[0, 0], dedup
        )
        return perm[None, :], keep[None, :]

    step_fn = jax.jit(
        shard_map(
            body,
            mesh=mesh,
            in_specs=(P("shard", None),) * 3 + (P("shard", None),),
            out_specs=(P("shard", None), P("shard", None)),
        )
    )
    perm, keep = jax.device_get(
        step_fn(
            *(jnp.asarray(a) for a in (op_hi, op_lo, op_rest)),
            jnp.asarray(n_valid),
        )
    )

    out = []
    for d, idx in enumerate(idxs):
        if len(idx):
            sel = perm[d][keep[d]]
            out.append(idx[sel])
    return (
        np.concatenate(out) if out else np.empty(0, dtype=np.int64)
    )
