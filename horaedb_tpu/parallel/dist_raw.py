"""Sharded raw reads: fused filter + top-k / selection over a mesh.

When a table's scan-cache entry is sharded across the chip mesh
(scan_cache places the big row arrays with ``P("shard")``), raw reads
run the SAME kernel bodies as the single-device path (ops/scan_topk)
per shard under ``shard_map``:

- **top-k**: each device computes its local top-k (k slots each — the
  global top-k is necessarily a subset of the union of per-shard
  top-ks), converts local row offsets to GLOBAL resident row ids via
  ``axis_index`` (shards are contiguous row blocks), and ships k keys +
  k ids home; the host merges n_dev sorted k-lists (tiny) into the
  global top-k with the same key-desc/rowid-asc tie order.
- **selection**: each device compacts its passing rows into its own
  bounded buffer; buffers concatenate in shard order == global resident
  (series, ts) order, so the host just stitches valid prefixes.

Compiled steps live in parallel/dist_agg's LRU-bounded step cache
(``cached_step`` — one discipline, one bound, distinct key spaces).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.31 re-exports it at top level
    from jax import shard_map
except ImportError:  # older jax: experimental module only
    from jax.experimental.shard_map import shard_map

from ..ops.scan_agg import encode_filter_ops
from ..ops.scan_topk import _I32_MIN, RawScanSpec, raw_select_body, raw_topk_body
from .dist_agg import cached_step

SHARD_AXIS = "shard"

_IN_SPECS = (
    P(SHARD_AXIS),  # series codes (rows)
    P(SHARD_AXIS),  # relative timestamps (rows)
    P(None, SHARD_AXIS),  # value columns (fields, rows)
    P(None),  # series allow list (replicated)
    P(None),  # filter literals
    P(), P(),  # time-range scalars
    P(), P(),  # bisection key-bound seeds (topk; select ignores)
)


def make_dist_raw_topk(mesh: Mesh, spec: RawScanSpec) -> Callable:
    """step(codes, ts_rel, values, allow, literals, lo, hi) ->
    (keys int32[n_dev*k], global row idx int32[n_dev*k])."""
    static_filters = encode_filter_ops(spec.numeric_filters)
    key = ("raw_topk", spec.k, spec.descending, spec.key_is_ts,
           spec.key_field, static_filters)

    def build():
        def per_shard(codes, ts_rel, values, allow, literals, lo, hi,
                      key_lo, key_hi):
            vals, idx = raw_topk_body(
                codes, ts_rel, values, allow, literals, lo, hi,
                key_lo, key_hi,
                k=spec.k, descending=spec.descending,
                key_is_ts=spec.key_is_ts, key_field=spec.key_field,
                numeric_filters=static_filters,
            )
            offset = jax.lax.axis_index(SHARD_AXIS).astype(jnp.int32)
            return vals, idx + offset * jnp.int32(codes.shape[0])

        return jax.jit(
            shard_map(
                per_shard, mesh=mesh, in_specs=_IN_SPECS,
                out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
                # the bisection while_loop has no replication rule; every
                # output is explicitly sharded, so the check adds nothing
                check_rep=False,
            )
        )

    return cached_step((mesh, key), build)


def make_dist_raw_select(mesh: Mesh, spec: RawScanSpec) -> Callable:
    """step(codes, ts_rel, values, allow, literals, lo, hi) ->
    (row idx int32[n_dev*slots], per-shard counts int32[n_dev])."""
    static_filters = encode_filter_ops(spec.numeric_filters)
    key = ("raw_select", spec.select_slots, static_filters)

    def build():
        def per_shard(codes, ts_rel, values, allow, literals, lo, hi,
                      _key_lo, _key_hi):
            out, count = raw_select_body(
                codes, ts_rel, values, allow, literals, lo, hi,
                select_slots=spec.select_slots,
                numeric_filters=static_filters,
            )
            offset = jax.lax.axis_index(SHARD_AXIS).astype(jnp.int32)
            # global row ids; -1 pad slots stay -1
            out = jnp.where(
                out >= 0, out + offset * jnp.int32(codes.shape[0]), out
            )
            return out, count.reshape(1)

        return jax.jit(
            shard_map(
                per_shard, mesh=mesh, in_specs=_IN_SPECS,
                out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
            )
        )

    return cached_step((mesh, key), build)


def dist_raw_topk(
    mesh: Mesh, spec: RawScanSpec, codes, ts_rel, values, allow,
    literals, lo_rel: int, hi_rel: int, key_lo: int, key_hi: int,
    need: int,
) -> np.ndarray:
    """Run the sharded top-k and merge the per-shard k-lists on host.

    -> global resident row ids of the top-``need`` passing rows,
    selected with the single-device tie rule (key first, then smaller
    resident row id). ``need`` may EXCEED ``spec.k``: the executor
    clamps per-shard k to the shard length (a shard shorter than the
    request contributes all its rows), so the merged union holds up to
    n_dev * k candidates and must be cut at the REQUESTED count, never
    at the shard-clamped k."""
    step = make_dist_raw_topk(mesh, spec)
    keys, idx = jax.device_get(
        step(codes, ts_rel, values, allow,
             jnp.asarray(np.asarray(literals, dtype=np.float32)),
             jnp.int32(lo_rel), jnp.int32(hi_rel),
             jnp.int32(key_lo), jnp.int32(key_hi))
    )
    keys = np.asarray(keys)
    idx = np.asarray(idx)
    valid = keys != _I32_MIN
    keys, idx = keys[valid], idx[valid]
    # merge n_dev k-lists: key desc, row id asc on ties (lexsort is
    # ascending and stable; negate keys, secondary key = row id)
    order = np.lexsort((idx, -keys.astype(np.int64)))
    return idx[order[:need]]


def dist_raw_select(
    mesh: Mesh, spec: RawScanSpec, codes, ts_rel, values, allow,
    literals, lo_rel: int, hi_rel: int,
) -> tuple[np.ndarray, int]:
    """Run the sharded selection; -> (global row ids in resident order,
    total passing count). Counts can exceed a shard's buffer only if the
    caller's candidate bound was wrong — it returns the truth so the
    executor can fall back instead of serving a truncated result."""
    step = make_dist_raw_select(mesh, spec)
    out, counts = jax.device_get(
        step(codes, ts_rel, values, allow,
             jnp.asarray(np.asarray(literals, dtype=np.float32)),
             jnp.int32(lo_rel), jnp.int32(hi_rel),
             jnp.int32(0), jnp.int32(0))
    )
    out = np.asarray(out).reshape(-1, spec.select_slots)
    counts = np.asarray(counts)
    total = int(counts.sum())
    if (counts > spec.select_slots).any():
        return np.empty(0, dtype=np.int32), total
    parts = [
        out[d, : int(counts[d])] for d in range(len(counts)) if counts[d]
    ]
    idx = (
        np.concatenate(parts) if parts else np.empty(0, dtype=np.int32)
    )
    return idx, total
