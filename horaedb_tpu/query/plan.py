"""Plan sum type (ref: query_frontend/src/plan.rs:67).

Each variant carries everything its interpreter needs; ``QueryPlan``
additionally carries the extracted pushdown ``Predicate`` (time range +
simple filters — ref: table_engine predicate extraction) and a priority
decision (ref: plan.rs:105 ``decide_query_priority`` — long-time-range
queries are demoted to the low-priority runtime).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Optional

from ..common_types.schema import Schema
from ..common_types.time_range import TimeRange
from ..engine.options import TableOptions
from ..table_engine.predicate import Predicate
from . import ast


class QueryPriority(enum.Enum):
    HIGH = "high"
    LOW = "low"


# Queries spanning more than this are "expensive" and run at low priority
# (the reference's threshold is config-driven; same default spirit).
EXPENSIVE_QUERY_RANGE_MS = 24 * 3_600_000


@dataclass(frozen=True)
class AggCall:
    """One aggregate in the select list."""

    func: str  # count | sum | min | max | avg | registry UDAF name
    column: Optional[str]  # None for count(*)
    output_name: str
    distinct: bool = False
    # Second column for binary aggregates (corr/covar: corr(x, y)).
    column2: Optional[str] = None
    # Trailing literal arguments (approx_percentile_cont(v, 0.9) -> (0.9,)).
    params: tuple = ()
    # agg(col) FILTER (WHERE cond) — evaluated per aggregate on the host
    # path (a filtered aggregate never rides the fused device kernel).
    filter_where: Optional[ast.Expr] = None


@dataclass(frozen=True)
class GroupKey:
    """A group-by key: a plain column or time_bucket(ts, interval)."""

    column: Optional[str] = None  # plain column grouping
    time_bucket_ms: Optional[int] = None  # time_bucket grouping width
    output_name: str = ""


@dataclass(frozen=True)
class QueryPlan:
    table: str
    schema: Schema
    select: ast.Select
    predicate: Predicate
    # Aggregation shape, filled when the query is scan+group+agg:
    aggs: tuple[AggCall, ...] = ()
    group_keys: tuple[GroupKey, ...] = ()
    is_aggregate: bool = False
    priority: QueryPriority = QueryPriority.HIGH
    # Arithmetic-over-aggregate select items: (output_name, expr) where
    # expr references hidden __aggN result columns; evaluated per group
    # AFTER aggregation (any path), then the hidden columns are dropped.
    agg_exprs: tuple[tuple[str, ast.Expr], ...] = ()


@dataclass(frozen=True)
class InsertPlan:
    table: str
    schema: Schema
    rows: tuple[dict, ...]


@dataclass(frozen=True)
class CreateTablePlan:
    table: str
    schema: Schema
    options: TableOptions
    raw_options: dict[str, str]
    if_not_exists: bool = False
    partition_by: Optional[ast.PartitionBy] = None


@dataclass(frozen=True)
class DropTablePlan:
    table: str
    if_exists: bool = False


@dataclass(frozen=True)
class DescribePlan:
    table: str


@dataclass(frozen=True)
class ShowTablesPlan:
    pass


@dataclass(frozen=True)
class ShowCreatePlan:
    table: str


@dataclass(frozen=True)
class ExistsPlan:
    table: str


@dataclass(frozen=True)
class ExplainPlan:
    inner: "QueryPlan | UnionPlan"
    analyze: bool = False


@dataclass(frozen=True)
class AlterTablePlan:
    table: str
    add_columns: tuple = ()
    set_options: dict[str, str] = field(default_factory=dict)


@dataclass(frozen=True)
class KillQueryPlan:
    """KILL QUERY <id>: flip the cancel flag on a live query in the
    process-global registry (utils/deadline.QUERY_REGISTRY)."""

    query_id: int


@dataclass(frozen=True)
class UnionPlan:
    """UNION [ALL]: branch plans executed independently, results aligned
    by position (names from the first branch), folded left-to-right —
    ``all_flags[i]`` is the i-th operator's ALL-ness; a distinct UNION
    dedups the accumulated result — then the union-level ORDER BY/LIMIT
    (ref: DataFusion's union plan surface,
    query_engine/src/datafusion_impl/mod.rs:54)."""

    branches: tuple[QueryPlan, ...]
    all_flags: tuple[bool, ...] = ()
    order_by: tuple = ()
    limit: "int | None" = None
    offset: int = 0


@dataclass(frozen=True)
class CTEPlan:
    """WITH bindings + the outer statement, both UNPLANNED: a cte's output
    schema only exists once it materializes, so interpreters plan lazily
    against the overlay of already-materialized ctes."""

    ctes: tuple  # ((name, ast.Select | ast.UnionSelect), ...)
    inner: object  # ast.Select | ast.UnionSelect (ctes stripped)


Plan = (
    QueryPlan
    | InsertPlan
    | CreateTablePlan
    | DropTablePlan
    | DescribePlan
    | ShowTablesPlan
    | ShowCreatePlan
    | ExistsPlan
    | AlterTablePlan
    | ExplainPlan
    | UnionPlan
    | CTEPlan
    | KillQueryPlan
)
