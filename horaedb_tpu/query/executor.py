"""Query executor (ref: src/query_engine + DataFusion's operators).

Two execution paths, chosen per plan — mirroring the reference's
``ExecutableScanBuilder``/Resolver plugin boundary (dist_sql_query/mod.rs)
where the north star inserts the TPU backend:

- **fused device path**: scan + filter + group-by(tags, time_bucket) +
  {count,sum,min,max,avg} compiles into the single ops.scan_agg kernel.
  Numeric field filters evaluate on device; tag/string filters and
  anything non-simple evaluate host-side as a row mask feeding the kernel.
- **host fallback**: vectorized numpy evaluation (projection, exact
  filters, sort, limit) — the CPU executor the device path is diffed and
  benchmarked against.

SQL NULL semantics: expression evaluation tracks a validity mask alongside
values; WHERE treats NULL comparisons as false (3-valued logic collapsed),
aggregates skip NULL inputs.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional, Sequence

import numpy as np

from ..common_types.dict_column import DictColumn, as_values, unique_inverse
from ..common_types.row_group import RowGroup
from ..common_types.schema import Schema
from ..common_types.time_range import MAX_TIMESTAMP, MIN_TIMESTAMP
from ..engine.options import parse_duration_ms
from ..ops import ScanAggSpec, encode_group_codes, scan_aggregate
from ..ops.encoding import build_padded_batch, time_buckets
from ..table_engine.predicate import NUMPY_CMP, FilterOp, Predicate
from ..utils import querystats
from . import ast
from .plan import AggCall, GroupKey, QueryPlan

@dataclass
class ResultSet:
    """Query output: named columns + optional per-column NULL masks."""

    names: list[str]
    columns: list[np.ndarray]
    nulls: dict[str, np.ndarray] | None = None
    # per-request metric tree, attached by the executor (ref: trace_metric)
    metrics: dict | None = None

    @property
    def num_rows(self) -> int:
        return len(self.columns[0]) if self.columns else 0

    def to_pylist(self) -> list[dict[str, Any]]:
        out = []
        nulls = self.nulls or {}
        for i in range(self.num_rows):
            row = {}
            for name, col in zip(self.names, self.columns):
                m = nulls.get(name)
                if m is not None and m[i]:
                    row[name] = None
                else:
                    v = col[i]
                    row[name] = v.item() if isinstance(v, np.generic) else v
            out.append(row)
        return out

    def column(self, name: str) -> np.ndarray:
        return self.columns[self.names.index(name)]

    @staticmethod
    def empty(names: list[str]) -> "ResultSet":
        return ResultSet(names, [np.empty(0, dtype=object) for _ in names])


class ExprError(ValueError):
    pass


# ---- host expression evaluation (values + validity) ---------------------


def eval_expr(e: ast.Expr, rows: RowGroup) -> tuple[np.ndarray, np.ndarray]:
    """-> (values, valid mask). Vectorized over all rows."""
    n = len(rows)
    if isinstance(e, ast.Column):
        return rows.column(e.name), rows.valid_mask(e.name)
    if isinstance(e, ast.Literal):
        if e.value is None:
            return np.zeros(n), np.zeros(n, dtype=bool)
        return np.full(n, e.value), np.ones(n, dtype=bool)
    if isinstance(e, ast.UnaryOp):
        v, m = eval_expr(e.operand, rows)
        if e.op == "-":
            return -v, m
        if e.op == "NOT":
            return ~v.astype(bool), m
        raise ExprError(f"unknown unary op {e.op}")
    if isinstance(e, ast.BinaryOp):
        return _eval_binary(e, rows)
    if isinstance(e, ast.WindowFunc):
        from .window import eval_window

        return eval_window(e, rows, eval_expr)
    if isinstance(e, ast.FuncCall):
        return _eval_func(e, rows)
    if isinstance(e, ast.CorrelatedLookup):
        return _eval_correlated_lookup(e, rows)
    if isinstance(e, ast.InList):
        v, m = eval_expr(e.expr, rows)
        lits = [
            lit.value for lit in e.values if isinstance(lit, ast.Literal)
        ]
        if isinstance(v, DictColumn) and len(lits) == len(e.values):
            hit = v.map_values(lambda vals: np.isin(vals, lits))
        else:
            v = as_values(v)
            hit = np.zeros(n, dtype=bool)
            for lit in e.values:
                lv, _ = eval_expr(lit, rows)
                hit |= v == as_values(lv)
        if e.negated:
            hit = ~hit
        return hit, m
    if isinstance(e, ast.Between):
        v, m = eval_expr(e.expr, rows)
        lo, ml = eval_expr(e.low, rows)
        hi, mh = eval_expr(e.high, rows)
        res = (v >= lo) & (v <= hi)
        if e.negated:
            res = ~res
        return res, m & ml & mh
    if isinstance(e, ast.IsNull):
        _, m = eval_expr(e.expr, rows)
        res = m if e.negated else ~m
        return res, np.ones(n, dtype=bool)
    if isinstance(e, ast.Like):
        return _eval_like(e, rows)
    if isinstance(e, ast.Case):
        return _eval_case(e, rows)
    if isinstance(e, ast.Cast):
        return _eval_cast(e, rows)
    raise ExprError(f"unsupported expression: {e}")


def _eval_like(e: ast.Like, rows: RowGroup) -> tuple[np.ndarray, np.ndarray]:
    """LIKE via one compiled regex over the column's UNIQUE values (dict
    columns match on the dictionary, not the rows)."""
    import re

    v, m = eval_expr(e.expr, rows)
    # % -> .*, _ -> . — everything else regex-escaped; anchored both ends.
    rx = re.compile(
        "".join(
            ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
            for ch in e.pattern
        )
        + r"\Z",
        re.DOTALL | (re.IGNORECASE if e.case_insensitive else 0),
    )

    def match_values(vals: np.ndarray) -> np.ndarray:
        return np.fromiter(
            (isinstance(x, str) and rx.match(x) is not None for x in vals),
            dtype=bool,
            count=len(vals),
        )

    if isinstance(v, DictColumn):
        hit = v.map_values(match_values)
    else:
        hit = match_values(as_values(v))
    return (~hit if e.negated else hit), m


def _eval_case(e: ast.Case, rows: RowGroup) -> tuple[np.ndarray, np.ndarray]:
    """First-match-wins; rows matching no branch (and no ELSE) are NULL."""
    n = len(rows)
    taken = np.zeros(n, dtype=bool)
    out = None
    valid = np.zeros(n, dtype=bool)
    branches = list(e.whens) + (
        [(None, e.else_)] if e.else_ is not None else []
    )
    for cond, result in branches:
        if cond is None:
            sel = ~taken
        else:
            cv, cm = eval_expr(cond, rows)
            sel = ~taken & cm & as_values(cv).astype(bool)
        if not sel.any():
            continue
        rv, rm = eval_expr(result, rows)
        rv = as_values(rv)
        if out is None:
            # Allocate from the first taken branch's dtype; mixed branch
            # types promote to object below.
            out = np.zeros(n, dtype=rv.dtype)
        if out.dtype != rv.dtype:
            out = out.astype(object)
        out[sel] = rv[sel]
        valid[sel] = rm[sel]
        taken |= sel
    if out is None:
        out = np.zeros(n)
    return out, valid


_CAST_NUMPY = {
    "bigint": np.int64, "int": np.int64, "integer": np.int64, "int64": np.int64,
    "smallint": np.int64, "tinyint": np.int64, "uint64": np.int64,
    "double": np.float64, "float": np.float64, "real": np.float64,
    "boolean": np.bool_, "bool": np.bool_,
    "timestamp": np.int64,
    "string": None, "varchar": None, "text": None,  # None -> str()
}


def _eval_cast(e: ast.Cast, rows: RowGroup) -> tuple[np.ndarray, np.ndarray]:
    v, m = eval_expr(e.expr, rows)
    v = as_values(v)
    if e.type_name not in _CAST_NUMPY:
        raise ExprError(f"unsupported CAST target type {e.type_name!r}")
    target = _CAST_NUMPY[e.type_name]
    if target is None:
        out = np.array([str(x) for x in v], dtype=object)
        return out, m
    try:
        if v.dtype == object or v.dtype.kind in "US":
            # String -> number errors on bad VALID strings (SQL casts are
            # strict), but NULL rows carry the '' kind-default fill and
            # are masked out — neutralize them before the strict cast.
            filled = np.where(m, v, "0")
            if target is np.int64:
                # Integer strings above 2^53 lose precision through
                # float64; parse directly and only route decimal/exponent
                # forms through the float path. Out-of-range integers must
                # ERROR (strict cast), not wrap through the float detour.
                try:
                    out = filled.astype(np.int64)
                except (ValueError, TypeError):
                    # Per-ELEMENT fallback: one decimal/exponent string in
                    # the column must not send the exact integer strings
                    # beside it through the lossy float64 detour. A cheap
                    # digit test (no per-element exceptions) picks the
                    # exact path; everything else parses as float and
                    # truncates on store. 'nan'/'inf' strings error here
                    # (strict cast) — the old whole-array C cast silently
                    # produced INT64_MIN garbage for them.
                    out = np.empty(len(filled), dtype=np.int64)
                    for i, s in enumerate(filled):
                        t = str(s)
                        body = t[1:] if t[:1] in "+-" else t
                        if body.isdigit():
                            out[i] = int(t)
                        else:
                            out[i] = np.float64(s)  # truncating int store
            else:
                out = filled.astype(np.float64).astype(target)
        elif target is np.int64 and v.dtype.kind == "f":
            out = np.trunc(np.where(m, v, 0)).astype(np.int64)
        else:
            out = np.where(m, v, 0).astype(target) if v.dtype.kind != "b" else v.astype(target)
    except (ValueError, TypeError, OverflowError) as ex:
        raise ExprError(f"CAST failed: {ex}")
    return out, m


def _eval_correlated_lookup(
    e: "ast.CorrelatedLookup", rows: RowGroup
) -> tuple[np.ndarray, np.ndarray]:
    """Per-row lookup of a decorrelated scalar subquery's result by the
    outer correlation columns. Fully vectorized for any key arity via the
    same composite-code factorization the join uses. Semantics:

    - missing key OR NULL outer key  -> ``default`` (0 for COUNT, else NULL):
      a NULL key equality matches nothing, i.e. the empty group;
    - key whose value is NULL        -> NULL;
    - key marked CORRELATED_DUP      -> error, but ONLY if probed.
    """
    n = len(rows)
    m = len(e.keys)
    k = len(e.outer_cols)

    vals = list(e.values)
    null_v = np.array([v is None for v in vals], dtype=bool)
    dup_v = np.array([v is ast.CORRELATED_DUP for v in vals], dtype=bool)
    clean = [v for v in vals if v is not None and v is not ast.CORRELATED_DUP]
    if all(
        isinstance(v, (int, np.integer)) and not isinstance(v, (bool, np.bool_))
        for v in clean
    ) and (e.default is None or isinstance(e.default, int)):
        dtype = np.dtype(np.int64)
    elif all(
        isinstance(v, (int, float, np.number)) and not isinstance(v, (bool, np.bool_))
        for v in clean
    ):
        dtype = np.dtype(np.float64)
    else:
        dtype = np.dtype(object)
    # NULL/missing slots carry a well-typed fill (the engine-wide
    # convention — see RowGroup): "" for object/string values, 0 for
    # numerics. An arbitrary 0 inside an object column would break
    # downstream sorts/uniques with a str-vs-int TypeError.
    fill = "" if dtype == object else 0
    val_arr = np.full(m, fill, dtype=dtype)
    for i, v in enumerate(vals):
        if not (null_v[i] or dup_v[i]):
            val_arr[i] = v

    out = np.full(n, fill, dtype=dtype)
    mask = np.zeros(n, dtype=bool)
    if n == 0:
        return out, mask

    valid = np.ones(n, dtype=bool)
    for c in e.outer_cols:
        valid &= rows.valid_mask(c.name)

    hit = np.zeros(n, dtype=bool)
    idx = np.zeros(n, dtype=np.int64)
    if m:
        outer_arrays = [
            np.asarray(as_values(rows.column(c.name)), dtype=object)
            for c in e.outer_cols
        ]
        key_arrays = [
            np.array([key[j] for key in e.keys], dtype=object) for j in range(k)
        ]
        from .join import _composite_codes

        lc, rc = _composite_codes(outer_arrays, key_arrays)
        order = np.argsort(rc, kind="stable")
        rc_s = rc[order]
        pos = np.minimum(np.searchsorted(rc_s, lc, side="left"), m - 1)
        hit = (rc_s[pos] == lc) & valid
        idx = order[pos]
        if dup_v.any():
            probed_dup = hit & dup_v[idx]
            if probed_dup.any():
                j = int(idx[np.nonzero(probed_dup)[0][0]])
                raise ExprError(
                    "correlated scalar subquery returned more than one "
                    f"row for correlation key {e.keys[j]}"
                )
        real = hit & ~null_v[idx]
        out[real] = val_arr[idx[real]]
        mask[real] = True
    miss = ~hit
    if e.default is not None:
        out[miss] = e.default
        mask[miss] = True
    return out, mask


def _eval_binary(e: ast.BinaryOp, rows: RowGroup) -> tuple[np.ndarray, np.ndarray]:
    op = e.op.upper()
    lv, lm = eval_expr(e.left, rows)
    rv, rm = eval_expr(e.right, rows)
    # Dictionary fast path: compare the VOCABULARY against the literal and
    # gather through codes (O(|vocab|) compares instead of O(n)).
    if op in NUMPY_CMP:
        fn = NUMPY_CMP[op]
        if isinstance(lv, DictColumn) and isinstance(e.right, ast.Literal):
            return lv.map_values(lambda vals: fn(vals, e.right.value)), lm & rm
        if isinstance(rv, DictColumn) and isinstance(e.left, ast.Literal):
            return rv.map_values(lambda vals: fn(e.left.value, vals)), lm & rm
    lv, rv = as_values(lv), as_values(rv)
    if op == "AND":
        # NULL AND false == false: a side that is definitively false wins.
        l = lv.astype(bool) & lm
        r = rv.astype(bool) & rm
        return l & r, np.ones(len(rows), dtype=bool)
    if op == "OR":
        l = lv.astype(bool) & lm
        r = rv.astype(bool) & rm
        return l | r, np.ones(len(rows), dtype=bool)
    valid = lm & rm
    if op == "+":
        return lv + rv, valid
    if op == "-":
        return lv - rv, valid
    if op == "*":
        return lv * rv, valid
    if op == "/":
        with np.errstate(divide="ignore", invalid="ignore"):
            out = lv / rv
        return out, valid & (rv != 0)
    if op == "%":
        with np.errstate(divide="ignore", invalid="ignore"):
            out = np.mod(lv, rv)
        return out, valid & (rv != 0)
    if op in NUMPY_CMP:
        return NUMPY_CMP[op](lv, rv), valid
    raise ExprError(f"unknown binary op {e.op}")


def _eval_func(e: ast.FuncCall, rows: RowGroup) -> tuple[np.ndarray, np.ndarray]:
    """Scalar function dispatch through the registry (ref: df_operator
    FunctionRegistry — time_bucket/abs are built-ins, users register more)."""
    from .functions import REGISTRY

    entry = REGISTRY.scalar(e.name)
    if entry is None:
        raise ExprError(f"unsupported function {e.name!r} in row expression")
    if e.filter_where is not None:
        raise ExprError(f"FILTER is only valid on aggregate functions, not {e.name!r}")
    fn, raw_args = entry
    if raw_args:
        # first arg evaluated; the rest pass as raw AST (literal params)
        args = [eval_expr(e.args[0], rows), *e.args[1:]]
    else:
        args = [eval_expr(a, rows) for a in e.args]
    return fn(args, rows)


# ---- executor ------------------------------------------------------------


def _translate_code_literal(dict_host: np.ndarray, op: str, lit) -> float:
    """Pre-translate a numeric filter literal into the CODE domain of a
    dictionary-encoded column (ISSUE 19): the dictionary is sorted, so
    code order == value order and every comparison op maps onto the same
    op over code indices — the kernel filters bit-packed codes without
    ever touching the dictionary. The op never changes (it is a static
    jit key); only the literal moves, and literals ride the dynamic
    buffer. Codes are < 2^16, exact in f32."""
    lit32 = np.float32(lit) if dict_host.dtype.kind == "f" else lit
    if op == "<" or op == ">=":
        # value < lit  <=>  code < left;  value >= lit  <=>  code >= left
        return float(np.searchsorted(dict_host, lit32, "left"))
    if op == "<=" or op == ">":
        # value <= lit <=> code <= right-1; value > lit <=> code > right-1
        return float(np.searchsorted(dict_host, lit32, "right") - 1)
    # "=" / "!=": the exact code, or a sentinel no code (>= 0) can equal
    i = int(np.searchsorted(dict_host, lit32, "left"))
    if i < len(dict_host) and dict_host[i] == lit32:
        return float(i)
    return -1.0


@dataclass
class CachedAggPrep:
    """A fully-prepared cached-aggregate device dispatch — the output of
    the "plan -> device spec" half (Executor.prepare_cached_agg) and the
    input of the "spec -> dispatch" half. Everything per-query the
    kernel needs is HERE (small host arrays + scalars), so shape-
    identical preps can be MERGED into one batched dispatch before any
    device work happens (Executor.dispatch_cached_agg_cohort)."""

    plan: Any
    m: dict
    entry: Any  # scan-cache entry holding the HBM-resident columns
    spec: Any  # padded ScanAggSpec with the CONCRETE segment impl
    krec: Any  # kernel-router token (None when routing doesn't apply)
    value_names: list
    literals: list
    device_filters: list
    gos: np.ndarray  # series -> group map (+ pad slot)
    allow: np.ndarray  # tag-filter allow-list (+ pad slot; delta fold)
    allow_scan: np.ndarray  # allow AND value-stat pruning (scan only)
    row_idx: Optional[np.ndarray]  # selective gather index, or None
    lo: int
    hi: int
    t0: int
    width: Optional[int]
    n_buckets: int
    empty_range: bool
    lo_rel: int
    hi_rel: int
    t0_rel: int
    width_i: int
    kernel_key: tuple
    tag_keys: list
    key_values: tuple
    agg_cols: list
    num_groups: int
    delta: Any
    # static per-field layout descriptors (ISSUE 19) — jit-key fragments:
    # a column re-encoding between preps must not share a traced kernel
    value_layouts: tuple = ()

    def fuse_key(self, i: int) -> tuple:
        """Grouping key for cohort merging: preps agreeing on the cache
        entry, the static spec, and the value-column layout share one
        fused dispatch. Selective (gathered) and mesh-sharded dispatches
        cannot ride the batched kernel — they stay solo (index-unique
        key)."""
        if self.row_idx is not None or self.entry.mesh is not None:
            return ("solo", i)
        return (
            id(self.entry), self.spec, tuple(self.value_names),
            self.value_layouts,
        )


class Executor:
    """Executes QueryPlans against Tables (AnalyticTable / PartitionedTable
    / MemoryTable — anything behind the table_engine.Table interface)."""

    def __init__(self) -> None:
        # observability: which path ran last
        # ("device-cached" | "device" | "host")
        self.last_path: str = ""
        # per-request metric tree (ref: trace_metric MetricsCollector —
        # stage timings threaded through the read path)
        self.last_metrics: dict = {}
        from .scan_cache import ScanCache
        from .path_router import PathRouter

        self.scan_cache = ScanCache()
        self.path_router = PathRouter()
        self._adaptive: bool | None = None  # resolved lazily (imports jax)

    def execute(
        self, plan: QueryPlan, table, _skip_cached_agg: bool = False
    ) -> ResultSet:
        """``_skip_cached_agg``: execute_cohort's fallback for a member
        whose cached-path prepare already bailed — the bail is
        deterministic for the same state, so retrying it here would
        only double the prepare work and the cache_misses count."""
        import time as _time

        from ..utils.deadline import checkpoint as _deadline_checkpoint

        # Cooperative checkpoint at executor entry (and again before
        # each scan batch / window / device dispatch below): a cancelled
        # or expired query unwinds HERE, with the admission slot
        # released by the admit context manager's finally.
        _deadline_checkpoint("executing")
        t_start = _time.perf_counter()
        # Per-call dict threaded through the stages and attached to the
        # RESULT — concurrent queries never share mutable metric state.
        m: dict = {"table": plan.table}
        import os as _os

        cache_on = _os.environ.get("HORAEDB_SCAN_CACHE", "1") != "0"
        # Adaptive routing: on accelerators with real dispatch latency the
        # profitable path is an empirical question — serve from whichever
        # path has measured faster for this query shape (path_router.py).
        route = None
        if plan.is_aggregate:
            if self._adaptive is None:
                from .path_router import adaptive_enabled

                self._adaptive = adaptive_enabled()
            # Only shapes the device kernels can serve are worth routing;
            # everything else goes straight to its natural path.
            if self._adaptive and self._agg_device_shape(plan) is not None:
                from .path_router import plan_shape_key

                key = plan_shape_key(plan)
                route = self.path_router.choose(key)
                m["_adaptive_key"] = key
                m["route"] = route
        # Memory bound: when pruned SST metadata says the scan would
        # materialize more than HORAEDB_AGG_MEMORY_MB, aggregate per
        # segment window through the partial machinery instead — checked
        # BEFORE the cache path, whose build would materialize the whole
        # table (ref: instance/read.rs:165-190 streaming reads).
        bounded = False
        if plan.is_aggregate and route != "host" and table.physical_datas():
            from .partial import _agg_memory_cap_bytes, _scan_estimate_bytes

            cap = _agg_memory_cap_bytes()
            bounded = bool(cap) and _scan_estimate_bytes(
                table, plan.predicate, self._projection(plan)
            ) > cap
        if (
            plan.is_aggregate and cache_on and route != "host"
            and not bounded and not _skip_cached_agg
        ):
            cached = self._try_cached_agg(plan, table, m)
            if cached is not None:
                path = "device-cached"
                return self._finish_metrics(m, t_start, path, cached)
        # Partitioned tables: push the aggregate DOWN to each partition
        # (local kernel per partition; remote partitions over the wire —
        # ref: dist_sql_query resolver push-down) and combine partials.
        if plan.is_aggregate and hasattr(table, "sub_tables") and route != "host":
            out = self._try_partitioned_agg(plan, table, m)
            if out is not None:
                return self._finish_metrics(m, t_start, "device-partial", out)
        # Bounded plain-table aggregate: same partial machinery the
        # partitioned scatter uses (Table.partial_agg -> compute_partial,
        # which iterates per-window pieces under the cap). The hint rides
        # in the spec so compute_partial neither re-walks the metadata
        # nor can disagree near the cap boundary; partitioned scatters
        # never set it — each owner estimates its OWN data.
        if bounded and not hasattr(table, "sub_tables"):
            out = self._try_partitioned_agg(plan, table, m, bounded_hint=True)
            if out is not None:
                return self._finish_metrics(m, t_start, "device-partial", out)
        # Plan-subtree shipping: window/topk/distinct/full-agg/filter
        # shapes execute on partition owners instead of pulling raw rows
        # (ref: dist_sql_query resolver execute_physical_plan push-down).
        if hasattr(table, "sub_tables"):
            from .dist_plan import try_dist_plan

            out = try_dist_plan(self, plan, table, m)
            if out is not None:
                return self._finish_metrics(m, t_start, "dist-plan", out)
        from ..utils.tracectx import span as _span

        # Raw (non-aggregate) reads: the same HBM-serving treatment the
        # aggregate paths got — fused filter + top-k / bounded selection
        # over the scan cache, returning only row indices to gather.
        # Routed by the SAME PathRouter learned discipline (probe device
        # vs host per plan shape, serve the winner, re-probe).
        raw_eligible = False
        raw_attempted = False
        if (
            not plan.is_aggregate
            and cache_on
            and not hasattr(table, "sub_tables")
            and table.physical_datas()
        ):
            raw_shape = self._raw_device_shape(plan)
            # LIMIT-pushdown-safe plans (no residual, no ORDER BY) stop
            # the host scan at LIMIT rows — near O(limit) by
            # construction; the device path cannot beat that.
            if raw_shape is not None and not self._limit_pushdown_safe(plan):
                raw_eligible = True
                from ..ops.scan_topk import raw_device_enabled

                raw_route = None
                if raw_device_enabled():
                    # Unlike the aggregate paths (where the device kernel
                    # wins on every backend and only the DISPATCH cost is
                    # in question), raw device-vs-host is an empirical
                    # race everywhere — the host path's early-exit scan
                    # and the kernel's O(n) masked passes cross over with
                    # table size, selectivity, and backend. Always route
                    # through the learned PathRouter; only the explicit
                    # HORAEDB_ADAPTIVE_PATH=0 override pins device-first.
                    from .path_router import plan_shape_key, raw_adaptive_enabled

                    if raw_adaptive_enabled():
                        key = plan_shape_key(plan)
                        raw_route = self.path_router.choose(key)
                        m["_adaptive_key"] = key
                        m["route"] = raw_route
                    if raw_route != "host":
                        raw_attempted = True
                        out = self._try_raw_device(plan, table, raw_shape, m)
                        if out is not None:
                            return self._finish_metrics(
                                m, t_start, "raw_device", out
                            )

        t_scan = _time.perf_counter()
        projection = self._projection(plan)
        predicate = plan.predicate
        if not plan.is_aggregate and self._limit_pushdown_safe(plan):
            # LIMIT pushdown: the scan may stop early. Only when no
            # residual WHERE / ORDER BY / DISTINCT needs the complete set.
            # OFFSET rows are still scanned (then skipped in assembly).
            predicate = predicate.with_limit(
                plan.select.limit + plan.select.offset
            )
            from ..engine.options import UpdateMode

            if getattr(
                getattr(table, "options", None), "update_mode", None
            ) is UpdateMode.APPEND:
                # only the append scan actually early-stops; don't claim
                # the optimization on dedup scans that ignore the hint
                m["limit_pushdown"] = plan.select.limit
        with _span("scan", table=plan.table) as sp:
            rows = table.read(predicate, projection=projection)
            sp.set(rows=len(rows))
        m["scan_ms"] = round((_time.perf_counter() - t_scan) * 1000, 3)
        m["rows_scanned"] = len(rows)
        querystats.record(scan_rows=len(rows))
        if plan.is_aggregate and route != "host" and self._device_capable(plan, rows):
            with _span("aggregate", path="device"):
                out = self._execute_agg_device(plan, rows, m)
            path = "device-dist" if "mesh_devices" in m else "device"
        elif plan.is_aggregate:
            path = "host"
            with _span("aggregate", path="host"):
                out = self._execute_agg_host(plan, rows)
        else:
            path = "host"
            if raw_eligible and not raw_attempted:
                # eligible but never dispatched (kill switch or the
                # router chose host): attribute the serve honestly
                querystats.note_raw_scan("host")
            with _span("project"):
                out = self._execute_projection(plan, rows, m)
        return self._finish_metrics(m, t_start, path, out)

    def _finish_metrics(
        self, m: dict, t_start: float, path: str, out: ResultSet
    ) -> ResultSet:
        import time as _time

        m["path"] = path
        m["result_rows"] = out.num_rows
        m["total_ms"] = round((_time.perf_counter() - t_start) * 1000, 3)
        # The ledger's route is which of the six executor paths actually
        # served the request (the cost side of the span tree).
        querystats.set_route(path)
        akey = m.pop("_adaptive_key", None)
        raw_fellback = bool(m.pop("_raw_fallback", False))
        if akey is not None and m.get("cache") != "build":
            # one-off cache-build cost must not poison the device estimate;
            # a raw attempt that bounced to host charges the DEVICE arm
            # (attempt + host serve — see _try_raw_device)
            kind = (
                "device" if raw_fellback or path != "host" else "host"
            )
            self.path_router.record(akey, kind, _time.perf_counter() - t_start)
        out.metrics = m
        # Observability conveniences; atomic rebinds (read-only snapshots
        # for tests/dashboards — per-request truth travels on the result).
        self.last_path = path
        self.last_metrics = m
        return out

    # ---- common ----------------------------------------------------------
    def _projection(self, plan: QueryPlan) -> Optional[list[str]]:
        """Columns the query touches (None = all, for SELECT *)."""
        names: list[str] = []
        stmt = plan.select
        for item in stmt.items:
            if isinstance(item.expr, ast.Star):
                return None
            names.extend(c.name for c in _columns_of(item.expr))
        for e in (stmt.where, *stmt.group_by):
            if e is not None:
                names.extend(c.name for c in _columns_of(e))
        # ORDER BY may name select aliases — only real columns join the scan.
        for o in stmt.order_by:
            names.extend(
                c.name for c in _columns_of(o.expr) if plan.schema.has_column(c.name)
            )
        return list(dict.fromkeys(names))

    def _residual_where(self, plan: QueryPlan) -> Optional[ast.Expr]:
        """WHERE minus what the predicate captured == what must still be
        evaluated exactly. Conservative: everything except pure timestamp
        range conjuncts (storage applies the time range exactly)."""
        where = plan.select.where
        if where is None:
            return None
        ts = plan.schema.timestamp_name
        from .planner import _as_simple_cmp, _conjuncts

        keep = []
        for conj in _conjuncts(where):
            simple = _as_simple_cmp(conj)
            if simple is not None and simple[0] == ts and simple[1] != "!=":
                continue  # exact via storage time filter
            if (
                isinstance(conj, ast.Between)
                and not conj.negated
                and isinstance(conj.expr, ast.Column)
                and conj.expr.name == ts
                # Must match extract_predicate's pushdown condition exactly:
                # only plain-literal bounds were turned into the time range.
                and isinstance(conj.low, ast.Literal)
                and isinstance(conj.high, ast.Literal)
            ):
                continue
            keep.append(conj)
        if not keep:
            return None
        out = keep[0]
        for c in keep[1:]:
            out = ast.BinaryOp("AND", out, c)
        return out

    def _limit_pushdown_safe(self, plan: QueryPlan) -> bool:
        """True when the scan may stop at LIMIT rows without changing the
        result: no ORDER BY / DISTINCT / join / GROUP BY (those need every
        row), and no residual WHERE — _residual_where is the single source
        of truth for "what storage did NOT apply", so a limit pushes down
        exactly when the projection has nothing left to filter."""
        sel = plan.select
        if sel is None or sel.limit is None:
            return False
        if sel.order_by or sel.distinct or sel.join is not None or sel.group_by:
            return False
        from .planner import _walk

        if any(
            isinstance(e, ast.WindowFunc)
            for item in sel.items
            for e in _walk(item.expr)
        ):
            return False  # window frames need the complete row set
        return self._residual_where(plan) is None

    def _try_partitioned_agg(
        self, plan: QueryPlan, table, m: dict, bounded_hint: bool = False
    ) -> Optional[ResultSet]:
        from .partial import assemble_result, combine_partials, spec_from_plan

        spec = spec_from_plan(self, plan)
        if spec is None:
            return None  # shape not pushable: gather-rows fallback below
        if bounded_hint:
            spec["bounded_hint"] = True
        from ..utils.tracectx import span as _span, wire_context

        wire = wire_context()
        if wire is not None:
            # remote partitions serve under the coordinator's trace id and
            # ship their span subtree home in the RPC response
            spec["trace"] = wire
            m["request_id"] = wire["request_id"]
        with _span("partial_agg", table=plan.table):
            names, arrays, stage_metrics = table.partial_agg(spec)
        with _span("combine") as sp:
            combined, n_groups = combine_partials([(names, arrays)], spec)
            sp.set(groups=n_groups)
            rule = getattr(table, "rule", None)  # plain tables: bounded path
            if rule is not None:
                keep = rule.prune(plan.predicate)
                m["partitions"] = (
                    len(keep) if keep is not None else len(table.sub_tables)
                )
            m["partial_stages"] = stage_metrics
            return assemble_result(plan, combined, n_groups, spec)

    # ---- learned kernel routing --------------------------------------------
    def _route_kernel(self, plan: QueryPlan, spec, n_rows: int,
                      est_distinct):
        """Learned segment-impl choice for a padded spec (the "database
        picks its own data structures" loop): seed from estimated group
        cardinality + observed query_stats history, then serve the
        measured winner with periodic re-probes. Returns (spec, token);
        token is None when routing doesn't apply (n_seg == 1, pinned
        HORAEDB_SEGMENT_IMPL, or router disabled)."""
        from .path_router import plan_shape_key

        ledger = querystats.current_ledger()
        return route_segment_kernel(
            plan_shape_key(plan), spec, n_rows, est_distinct,
            sql=ledger.sql if ledger else "",
        )

    def _finish_kernel(self, krec, spec, m: dict, state,
                       seconds: float, n_valid=None) -> None:
        finish_segment_kernel(krec, spec, m, state, seconds, n_valid)

    # ---- device path -------------------------------------------------------
    def _agg_device_shape(self, plan: QueryPlan):
        """(tag_keys, bucket_key, agg_cols) when the aggregation shape fits
        the device kernels, else None. Shared by the cached and uncached
        device paths — eligibility rules live HERE only."""
        schema = plan.schema
        tag_names = set(schema.tag_names)
        bucket_keys = [k for k in plan.group_keys if k.time_bucket_ms is not None]
        if len(bucket_keys) > 1:
            return None
        for k in plan.group_keys:
            if k.column is not None and k.column not in tag_names:
                return None
        for a in plan.aggs:
            if a.distinct or a.func not in ("count", "sum", "min", "max", "avg"):
                return None  # registry aggregates run on the host path
            if a.filter_where is not None:
                return None  # per-aggregate FILTER masks run on the host path
            if a.column is not None and not schema.column(a.column).kind.is_numeric:
                return None
        tag_keys = [k for k in plan.group_keys if k.column is not None]
        agg_cols = list(dict.fromkeys(a.column for a in plan.aggs if a.column))
        return tag_keys, (bucket_keys[0] if bucket_keys else None), agg_cols

    def _split_residual_filters(self, plan: QueryPlan):
        """Residual WHERE conjuncts -> (numeric device filters, the rest).

        Shared classification: a conjunct becomes a device filter when it
        is ``float_column op numeric_literal``; everything else stays an
        AST conjunct for the caller to evaluate (host mask, or per-series
        for the cached path)."""
        from .planner import _as_simple_cmp, _conjuncts

        schema = plan.schema
        device_filters: list[tuple[str, str, float]] = []
        other: list[ast.Expr] = []
        residual = self._residual_where(plan)
        if residual is not None:
            for conj in _conjuncts(residual):
                simple = _as_simple_cmp(conj)
                if (
                    simple is not None
                    and schema.has_column(simple[0])
                    and schema.column(simple[0]).kind.is_float
                    and isinstance(simple[2], (int, float))
                ):
                    device_filters.append(simple)
                else:
                    other.append(conj)
        return device_filters, other

    def _device_capable(self, plan: QueryPlan, rows: RowGroup) -> bool:
        if self._agg_device_shape(plan) is None:
            return False
        for a in plan.aggs:
            # One shared device mask can't express per-field NULL sets; a
            # NULL in any aggregated column routes to the host path where
            # aggregates skip NULLs per field.
            if a.column is not None and not rows.valid_mask(a.column).all():
                return False
        return True

    def _execute_agg_device(
        self, plan: QueryPlan, rows: RowGroup, m: dict | None = None
    ) -> ResultSet:
        from ..utils.deadline import checkpoint as _deadline_checkpoint

        # last cheap exit before committing to a device dispatch
        _deadline_checkpoint("dispatch")
        tag_keys, bucket_key, agg_cols = self._agg_device_shape(plan)
        # Numeric field filters -> device; the rest -> host row mask.
        device_filters, host_residue = self._split_residual_filters(plan)

        n = len(rows)
        mask = np.ones(n, dtype=bool)
        for conj in host_residue:
            v, valid = eval_expr(conj, rows)
            mask &= v.astype(bool) & valid

        enc = encode_group_codes(rows, [k.column for k in tag_keys])

        if bucket_key is not None:
            width = bucket_key.time_bucket_ms
            tr = plan.predicate.time_range
            t0 = tr.inclusive_start if tr.inclusive_start != MIN_TIMESTAMP else (
                int(rows.timestamps.min()) if n else 0
            )
            t0 = (t0 // width) * width
            bucket_ids, n_buckets = (
                time_buckets(rows.timestamps, t0, width) if n else (np.zeros(0, np.int32), 1)
            )
        else:
            width = None
            t0 = 0
            bucket_ids, n_buckets = np.zeros(n, dtype=np.int32), 1

        filter_cols = [f[0] for f in device_filters]
        value_names = list(dict.fromkeys(agg_cols + filter_cols))
        value_arrays = [as_values(rows.column(c)) for c in value_names]
        batch = build_padded_batch(enc.codes, bucket_ids, mask, value_arrays)
        spec = ScanAggSpec(
            n_groups=max(enc.num_groups, 1),
            n_buckets=n_buckets,
            n_agg_fields=len(agg_cols),
            numeric_filters=tuple(
                (value_names.index(col), op) for col, op, _ in device_filters
            ),
            need_minmax=_plan_needs_minmax(plan),
        ).padded()
        literals = [lit for _, _, lit in device_filters]

        # Learned kernel choice. Group codes are dense (np.unique), so
        # groups x buckets is an exact ceiling on live segments; bucket
        # sparsity (and router history) can only pull it down.
        spec, krec = self._route_kernel(
            plan, spec, n_rows=n,
            est_distinct=max(enc.num_groups, 1) * n_buckets,
        )

        # Large scans shard over the device mesh (partial agg per device,
        # monoid combine via psum/pmin/pmax collectives); small ones stay
        # single-device where dispatch overhead dominates. SAME kernel
        # body either way (parallel/dist_agg wraps ops/scan_agg — the
        # routed segment_impl rides the spec into the shard_map step).
        from ..parallel.mesh import dist_min_rows, serving_mesh

        import time as _time

        mesh = serving_mesh()
        t_kernel = _time.perf_counter()
        if mesh is not None and batch.n_valid >= dist_min_rows():
            from ..parallel.dist_agg import dist_scan_aggregate

            state = dist_scan_aggregate(mesh, batch, spec, literals)
            if m is not None:
                m["mesh_devices"] = int(mesh.devices.size)
        else:
            state = scan_aggregate(batch, spec, literals)
        if m is not None:
            self._finish_kernel(
                krec, spec, m, state,
                _time.perf_counter() - t_kernel, n_valid=batch.n_valid,
            )

        return self._assemble_agg_result(
            plan, tag_keys, enc.key_values, agg_cols, state,
            max(enc.num_groups, 1), n_buckets, t0, width,
        )

    def _assemble_agg_result(
        self, plan, tag_keys, key_values, agg_cols, state, G, B, t0, width
    ) -> ResultSet:
        counts = state.counts[:G, :B]
        sums = state.sums[:, :G, :B]
        mins = state.mins[:, :G, :B]
        maxs = state.maxs[:, :G, :B]

        live = counts > 0  # (G, B)
        g_idx, b_idx = np.nonzero(live)
        if len(g_idx) == 0 and not plan.group_keys:
            # SQL: an ungrouped aggregate over zero rows yields ONE row
            # (count 0, other aggregates NULL).
            return _order_and_limit(_empty_ungrouped_agg_row(plan), plan)

        names: list[str] = []
        columns: list[np.ndarray] = []
        nulls: dict[str, np.ndarray] = {}
        agg_expr_map = dict(plan.agg_exprs)
        computed = None
        if agg_expr_map:
            base = {
                k.column: (np.asarray(key_values[ki])[g_idx], None)
                for ki, k in enumerate(tag_keys)
            }
            for a in plan.aggs:
                base[a.output_name] = (
                    _agg_output(a, agg_cols, counts, sums, mins, maxs, g_idx, b_idx),
                    None,
                )
            computed = eval_agg_exprs(plan, base)
        for item in plan.select.items:
            out_name = item.output_name
            e = item.expr
            if out_name in agg_expr_map:
                v, nm = computed[out_name]
                columns.append(v)
                if nm is not None:
                    nulls[out_name] = nm
                names.append(out_name)
            elif isinstance(e, ast.Column):
                ki = [k.column for k in tag_keys].index(e.name)
                columns.append(np.asarray(key_values[ki])[g_idx])
                names.append(out_name)
            elif isinstance(e, ast.FuncCall) and e.name in ("time_bucket", "date_trunc"):
                columns.append(t0 + b_idx.astype(np.int64) * (width or 1))
                names.append(out_name)
            else:
                agg_i = [a.output_name for a in plan.aggs].index(out_name)
                a = plan.aggs[agg_i]
                col = _agg_output(a, agg_cols, counts, sums, mins, maxs, g_idx, b_idx)
                columns.append(col)
                names.append(out_name)
        result = ResultSet(names, columns, nulls or None)
        return _order_and_limit(result, plan)

    # ---- device-cached path (HBM-resident columns) ---------------------------
    #
    # Split into "plan -> device spec" (prepare_cached_agg: eligibility,
    # cache entry, per-series filters, time math, kernel routing — pure
    # host work producing a CachedAggPrep) and "spec -> dispatch"
    # (dispatch_cached_agg / dispatch_cached_agg_cohort: the device
    # call, delta fold, result assembly). The split is what lets cohort
    # batching MERGE shape-identical specs into one fused dispatch
    # (wlm/batch + ops/scan_agg.cached_scan_agg_cohort).

    def _try_cached_agg(self, plan: QueryPlan, table, m: dict) -> Optional[ResultSet]:
        """Serve an aggregate from device-resident scan state, or None.

        Ships only O(series)+O(1) data per query; see query/scan_cache.py.
        """
        prep = self.prepare_cached_agg(plan, table, m)
        if prep is None:
            return None
        return self.dispatch_cached_agg(prep)

    def prepare_cached_agg(
        self, plan: QueryPlan, table, m: dict, allow_selective: bool = True
    ) -> Optional["CachedAggPrep"]:
        """The "plan -> device spec" half: everything up to (but not
        including) the kernel dispatch. Returns None exactly where the
        cached path used to bail (caller falls through to the uncached
        paths). ``allow_selective=False`` skips the gathered-subset
        optimization so the resulting spec stays cohort-mergeable (the
        batched kernel cannot vmap over per-query-variable row
        indices)."""
        schema = plan.schema
        if schema.tsid_index is None or not table.physical_datas():
            return None
        if hasattr(table, "sub_tables") and len(table.physical_datas()) != len(
            table.sub_tables
        ):
            # Remote partitions: their writes are invisible to the local
            # fingerprint/delta — caching would serve stale aggregates
            # forever. The partitioned push-down path handles these.
            return None
        shape = self._agg_device_shape(plan)
        if shape is None:
            return None
        tag_keys, bucket_key, agg_cols = shape
        if bucket_key is not None and bucket_key.time_bucket_ms > 2**31 - 1:
            return None  # relative-int32 bucket math can't express it

        # Residual conjuncts must all be numeric device filters or
        # series-level (tag-only) filters; anything else -> uncached paths.
        tag_names = set(schema.tag_names)
        device_filters, other = self._split_residual_filters(plan)
        series_filters: list = []
        for conj in other:
            if _is_series_conjunct(conj, tag_names):
                series_filters.append(conj)
            else:
                return None

        filter_cols = [f[0] for f in device_filters]
        value_names = list(dict.fromkeys(agg_cols + filter_cols))

        # Dtype auto-tuning feedback: which aggregates/filters touch each
        # value column decides whether its resident copy may be bf16
        # (HORAEDB_CACHE_DTYPE=auto) — see ScanCache.note_usage.
        self.scan_cache.note_usage(
            table.name,
            value_names,
            sum_cols={
                a.column for a in plan.aggs
                if a.column and a.func in ("sum", "avg")
            },
            filter_cols=set(filter_cols),
        )

        entry, built, delta = self.scan_cache.get(
            table, value_names, read_rows=lambda: table.read(Predicate.all_time())
        )
        if entry is None or delta is None:
            # an ELIGIBLE query the cache couldn't serve (first sighting,
            # raced write, budget refusal) — a miss in the ledger's terms
            querystats.record(cache_misses=1)
            return None
        # NULL agg inputs need per-field masks — not expressible here.
        for c in agg_cols:
            if not entry.all_valid.get(c, False):
                return None
        # Unflushed delta rows fold into the aggregate ON TOP of the HBM
        # base — but only when provably sound (see _delta_soundness).
        if len(delta) and not self._delta_soundness(table, entry, delta, agg_cols):
            return None
        # Eligibility confirmed: only now record cache facts (a bail-out
        # above must not leave 'cache' lying in a host-path metric tree).
        m["cache"] = "build" if built else ("hit+delta" if len(delta) else "hit")
        m["rows_scanned"] = entry.n_valid + len(delta)
        querystats.record(scan_rows=entry.n_valid + len(delta))
        if built:
            querystats.record(cache_misses=1)
        else:
            querystats.record(cache_hits=1, cache_bytes=entry.device_bytes)
        if len(delta):
            m["delta_rows"] = len(delta)
            querystats.record(memtable_rows=len(delta))

        # Series-level small arrays (one row per unique series); validity
        # slices carry over so NULL-tag semantics match the host path.
        S = entry.n_series
        series_rows = None
        if tag_keys or series_filters:
            series_rows = entry.series_rows  # derived at build, one row/series
        if tag_keys:
            from ..ops.encoding import _codes_from_columns

            series_group, key_values = _codes_from_columns(
                [series_rows.columns[k.column] for k in tag_keys]
            )
            num_groups = len(key_values[0])
        else:
            series_group = np.zeros(S, dtype=np.int64)
            key_values = ()
            num_groups = 1
        allowed = np.ones(S, dtype=bool)
        for conj in series_filters:
            v, valid = eval_expr(conj, series_rows)
            allowed &= np.asarray(as_values(v)).astype(bool) & valid
        # Value-stat series pruning (the cached analog of row-group
        # min/max pruning): a series none of whose BASE values can pass a
        # numeric filter is excluded from the scan — but NOT from the
        # delta fold, whose fresh rows the base stats don't cover; the
        # delta applies the filters exactly per row.
        scan_allowed = allowed
        stats = entry.series_value_stats or {}
        for col, op, lit in device_filters:
            st = stats.get(col)
            if st is None:
                continue
            mins, maxs = st
            could = _series_could_match(mins, maxs, op, lit)
            if could is not None:
                if scan_allowed is allowed:
                    scan_allowed = allowed.copy()
                scan_allowed &= could

        # Time range + bucketing, relative to the cache origin. An empty
        # intersection keeps rel bounds at (0, 0) — NOT raw epoch deltas,
        # which overflow int32. Data bounds include the delta (fresh rows
        # usually extend past the cached max timestamp).
        tr = plan.predicate.time_range
        data_min, data_max = entry.min_ts, entry.max_ts
        if len(delta):
            # span already validated by _delta_soundness
            d_ts = delta.timestamps
            data_min = min(data_min, int(d_ts.min()))
            data_max = max(data_max, int(d_ts.max()))
        lo = max(tr.inclusive_start, data_min)
        hi = min(tr.exclusive_end, data_max + 1)
        empty_range = hi <= lo
        width = bucket_key.time_bucket_ms if bucket_key is not None else None
        if empty_range:
            t0 = entry.min_ts
            lo = hi = entry.min_ts
            n_buckets = 1
        elif width is not None:
            t0 = (lo // width) * width
            n_buckets = max(1, -(-(hi - t0) // width))
        else:
            t0 = lo
            n_buckets = 1

        spec = ScanAggSpec(
            n_groups=max(num_groups, 1),
            n_buckets=n_buckets,
            n_agg_fields=len(agg_cols),
            numeric_filters=tuple(
                (value_names.index(col), op) for col, op, _ in device_filters
            ),
            need_minmax=_plan_needs_minmax(plan),
        ).padded()

        # Learned kernel choice. Unlike the direct path, the cached
        # domain spans EVERY group in the table while the allow-list may
        # keep a handful of series — exactly the sparse regime where the
        # hash impl beats full-domain scatter/MXU. Estimate live
        # segments from the groups the allowed series can actually
        # reach (exact on the group axis, ceiling on the bucket axis).
        if scan_allowed.any():
            active_groups = len(np.unique(series_group[scan_allowed]))
        else:
            active_groups = 1
        spec, krec = self._route_kernel(
            plan, spec, n_rows=entry.n_valid,
            est_distinct=max(active_groups, 1) * n_buckets,
        )
        # Resolve "auto"/pin to the CONCRETE impl on host: it keys the
        # packed jit call below, so flipping the env knobs re-traces warm
        # shapes instead of silently reusing the stale compiled branch.
        import dataclasses

        from ..ops.scan_agg import resolve_segment_impl

        spec = dataclasses.replace(
            spec,
            segment_impl=resolve_segment_impl(
                spec.n_groups * spec.n_buckets, spec.segment_impl
            ),
        )

        gos = np.append(series_group, 0).astype(np.int32)  # pad series -> masked
        allow = np.append(allowed, False)  # delta fold: NO value pruning
        allow_scan = (
            allow
            if scan_allowed is allowed
            else np.append(scan_allowed, False)
        )
        if scan_allowed is not allowed:
            # value-stat prunes only — not series tag filters excluded
            m["series_pruned"] = int(allowed.sum() - scan_allowed.sum())
        # Compressed-layout routing (ISSUE 19): per-field static layout
        # descriptors. Aggregated fields fully decode on device; a field
        # only FILTERS touch stays in the bit-packed code domain — its
        # literals pre-translate against the sorted dictionary here, so
        # the kernel compares codes and never materializes the column.
        agg_set = set(agg_cols)
        value_layouts = tuple(
            entry.value_layout(c, full_decode=(c in agg_set))
            for c in value_names
        )
        literals = [
            _translate_code_literal(
                entry.value_cols_dev[col].dict_host, op, lit
            )
            if (lay := value_layouts[value_names.index(col)])[0] == "dict"
            and not lay[2]
            else lit
            for col, op, lit in device_filters
        ]
        lo_rel = lo - entry.min_ts
        hi_rel = hi - entry.min_ts
        t0_rel = max(t0 - entry.min_ts, -(2**31) + 1) if not empty_range else 0
        width_i = width if width else 1
        kernel_key = (
            spec.n_groups, spec.n_buckets, spec.n_agg_fields,
            spec.numeric_filters, spec.need_minmax,
            spec.segment_impl, spec.hash_slots,
            value_layouts, entry.ts_layout, entry.series_layout,
        )
        row_idx = None
        if entry.mesh is None and allow_selective and not empty_range:
            row_idx = self._selective_row_idx(entry, scan_allowed, lo, hi)
            if row_idx is not None:
                m["cache_rows"] = int((row_idx != entry.n_valid).sum())
        return CachedAggPrep(
            plan=plan, m=m, entry=entry, spec=spec, krec=krec,
            value_names=value_names, literals=literals,
            device_filters=device_filters,
            gos=gos, allow=allow, allow_scan=allow_scan, row_idx=row_idx,
            lo=lo, hi=hi, t0=t0, width=width, n_buckets=n_buckets,
            empty_range=empty_range,
            lo_rel=lo_rel, hi_rel=hi_rel, t0_rel=t0_rel, width_i=width_i,
            kernel_key=kernel_key,
            tag_keys=tag_keys, key_values=key_values, agg_cols=agg_cols,
            num_groups=num_groups, delta=delta,
            value_layouts=value_layouts,
        )

    def dispatch_cached_agg(self, prep: "CachedAggPrep") -> ResultSet:
        """The "spec -> dispatch" half for ONE prepared query: device
        call (mesh shard_map or the RTT-minimized packed path), delta
        fold, result assembly — exactly the pre-split cached path."""
        from ..utils.deadline import checkpoint as _deadline_checkpoint

        # last cheap exit before committing to the device dispatch
        # (cohort dispatches intentionally skip this: a cohort carries
        # MANY budgets; members observe their own at the batch layer)
        _deadline_checkpoint("dispatch")
        import jax.numpy as jnp

        from ..ops.scan_agg import coerce_literals, encode_filter_ops, state_to_host

        plan, m, entry, spec = prep.plan, prep.m, prep.entry, prep.spec
        value_names, literals = prep.value_names, prep.literals
        lo_rel, hi_rel = prep.lo_rel, prep.hi_rel
        t0_rel, width_i = prep.t0_rel, prep.width_i
        gos, allow_scan = prep.gos, prep.allow_scan
        row_idx, kernel_key = prep.row_idx, prep.kernel_key
        values_dev = entry.values_for(value_names)
        import time as _time

        t_kernel = _time.perf_counter()
        if entry.mesh is not None:
            # Sharded entry: the big arrays live split across the mesh —
            # run the shard_map cached kernel (the DEFAULT multi-device
            # serving path; single-device deployments take the packed arm).
            from ..parallel.dist_agg import make_cached_dist_scan_agg

            from ..obs.device import timed_dispatch

            step = make_cached_dist_scan_agg(entry.mesh, spec)
            out = timed_dispatch(
                "cached_dist",
                lambda: step(
                    entry.series_codes_dev,
                    entry.ts_rel_dev,
                    values_dev,
                    jnp.asarray(gos),
                    jnp.asarray(allow_scan),
                    coerce_literals(literals),
                    np.int32(lo_rel),
                    np.int32(hi_rel),
                    np.int32(t0_rel),
                    np.int32(width_i),
                ),
            )
            m["mesh_devices"] = int(entry.mesh.devices.size)
            state = state_to_host(*out)
            querystats.note_kernel_dispatch(
                ("cached-dist", int(entry.mesh.devices.size), *kernel_key),
                _time.perf_counter() - t_kernel,
                kind="cached_dist",
            )
        else:
            # Single-device serving: the RTT-minimized packed path — one
            # content-cached session upload, one dyn upload, one execute,
            # one packed fetch (ops/scan_agg.py "packed serving path").
            from ..ops.scan_agg import (
                cached_scan_agg_packed,
                pack_dyn,
                unpack_packed_state,
            )

            from ..obs.device import cost_analysis, timed_dispatch

            session_dev = entry.session_for(gos, allow_scan)
            dyn = pack_dyn(literals, lo_rel, hi_rel, t0_rel, width_i, row_idx)
            pargs = (
                entry.series_parts,
                entry.ts_parts,
                values_dev,
                session_dev,
                jnp.asarray(dyn),
            )
            pkwargs = dict(
                n_groups=spec.n_groups,
                n_buckets=spec.n_buckets,
                n_agg_fields=spec.n_agg_fields,
                numeric_filters=encode_filter_ops(spec.numeric_filters),
                need_minmax=spec.need_minmax,
                segment_impl=spec.segment_impl,
                hash_slots=spec.hash_slots,
                selective=row_idx is not None,
                value_layouts=prep.value_layouts,
                ts_layout=entry.ts_layout,
                series_layout=entry.series_layout,
            )
            packed = timed_dispatch(
                "cached_packed",
                lambda: cached_scan_agg_packed(*pargs, **pkwargs),
            )
            state = unpack_packed_state(packed, spec)
            querystats.note_kernel_dispatch(
                ("cached-packed", row_idx is not None, *kernel_key),
                _time.perf_counter() - t_kernel,
                kind="cached_packed",
                cost_fn=lambda: cost_analysis(
                    cached_scan_agg_packed, pargs, pkwargs
                ),
            )
        self._finish_kernel(
            prep.krec, spec, m, state, _time.perf_counter() - t_kernel
        )
        if len(prep.delta) and not prep.empty_range:
            self._fold_delta(
                state, prep.delta, entry, plan.schema, gos, prep.allow,
                prep.agg_cols, value_names, prep.device_filters,
                prep.lo, prep.hi, prep.t0, prep.width, prep.n_buckets,
            )
        return self._assemble_agg_result(
            plan, prep.tag_keys, prep.key_values, prep.agg_cols, state,
            max(prep.num_groups, 1), prep.n_buckets, prep.t0, prep.width,
        )

    def dispatch_cached_agg_cohort(
        self, preps: list["CachedAggPrep"]
    ) -> list:
        """ONE fused device dispatch serving every prep in ``preps``
        (all sharing one cache entry and one static spec — the caller
        groups by ``CachedAggPrep.fuse_key``). The per-query session and
        dyn buffers stack into a ``[B, ...]`` batch axis and the vmapped
        packed kernel serves the whole cohort in a single execute; each
        member's state then demuxes, folds its own delta, and assembles
        its own ResultSet. Returns one ResultSet-or-exception per prep,
        positionally (error isolation: a member whose demux/assembly
        fails poisons only its own slot)."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from ..ops.encoding import next_pow2
        from ..ops.scan_agg import (
            cached_scan_agg_cohort,
            encode_filter_ops,
            pack_dyn,
            pack_session,
            unpack_packed_state,
        )

        p0 = preps[0]
        entry, spec = p0.entry, p0.spec
        sessions = np.stack(
            [pack_session(p.gos, p.allow_scan) for p in preps]
        )
        dyns = np.stack(
            [
                pack_dyn(p.literals, p.lo_rel, p.hi_rel, p.t0_rel, p.width_i)
                for p in preps
            ]
        )
        B = len(preps)
        # pow2-bucketed batch axis bounds the jit-key count; pad members
        # replicate the last row and their outputs are discarded
        Bp = next_pow2(B, floor=2)
        if Bp > B:
            sessions = np.concatenate(
                [sessions, np.repeat(sessions[-1:], Bp - B, axis=0)]
            )
            dyns = np.concatenate([dyns, np.repeat(dyns[-1:], Bp - B, axis=0)])
        values_dev = entry.values_for(p0.value_names)
        from ..obs.device import timed_dispatch

        t_kernel = _time.perf_counter()
        packed = timed_dispatch(
            "cached_cohort",
            lambda: cached_scan_agg_cohort(
                entry.series_parts,
                entry.ts_parts,
                values_dev,
                jnp.asarray(sessions),
                jnp.asarray(dyns),
                n_groups=spec.n_groups,
                n_buckets=spec.n_buckets,
                n_agg_fields=spec.n_agg_fields,
                numeric_filters=encode_filter_ops(spec.numeric_filters),
                need_minmax=spec.need_minmax,
                segment_impl=spec.segment_impl,
                hash_slots=spec.hash_slots,
                value_layouts=p0.value_layouts,
                ts_layout=entry.ts_layout,
                series_layout=entry.series_layout,
            ),
        )
        rows = np.asarray(jax.device_get(packed))
        elapsed = _time.perf_counter() - t_kernel
        querystats.note_kernel_dispatch(
            ("cached-cohort", Bp, *p0.kernel_key), elapsed,
            kind="cached_cohort",
        )
        outs: list = []
        for j, p in enumerate(preps):
            try:
                state = unpack_packed_state(rows[j], spec)
                # router/cardinality feedback once per DISPATCH (j == 0),
                # with the elapsed AMORTIZED over the cohort — the
                # router's per-shape EWMA mixes these with solo-dispatch
                # samples, and a raw B-wide wall time would make the
                # serving impl look up to Bx slower than it is per query
                self._finish_kernel(
                    p.krec if j == 0 else None, spec, p.m, state,
                    elapsed / B,
                )
                p.m["batch_cohort"] = B
                if len(p.delta) and not p.empty_range:
                    self._fold_delta(
                        state, p.delta, entry, p.plan.schema, p.gos, p.allow,
                        p.agg_cols, p.value_names, p.device_filters,
                        p.lo, p.hi, p.t0, p.width, p.n_buckets,
                    )
                outs.append(
                    self._assemble_agg_result(
                        p.plan, p.tag_keys, p.key_values, p.agg_cols, state,
                        max(p.num_groups, 1), p.n_buckets, p.t0, p.width,
                    )
                )
            except BaseException as e:
                outs.append(e)
        return outs

    def execute_cohort(self, plans: list, table) -> list:
        """Execute a cohort of shape-identical plans against one table,
        fusing as many as possible into single batched device dispatches
        (wlm/batch hands cohorts here via the interpreter). Returns one
        ResultSet-or-exception per plan, positionally — error isolation
        is per member. Members the cached path cannot serve (cache
        bail-out, memory-bounded scans, selective/mesh entries) fall
        back to the ordinary solo ``execute`` path."""
        import os
        import time as _time

        outcomes: list = [None] * len(plans)
        preps: list[tuple[int, CachedAggPrep, float]] = []
        cache_on = os.environ.get("HORAEDB_SCAN_CACHE", "1") != "0"
        fusable_table = not hasattr(table, "sub_tables")
        for i, plan in enumerate(plans):
            t_start = _time.perf_counter()
            prep = None
            tried_cached = False
            if plan.is_aggregate and cache_on and fusable_table and table.physical_datas():
                # mirror execute()'s memory bound: the cache build would
                # materialize the whole table, so over-cap scans must
                # take the partial machinery instead
                from .partial import _agg_memory_cap_bytes, _scan_estimate_bytes

                cap = _agg_memory_cap_bytes()
                bounded = bool(cap) and _scan_estimate_bytes(
                    table, plan.predicate, self._projection(plan)
                ) > cap
                if not bounded:
                    m = {"table": plan.table}
                    tried_cached = True
                    try:
                        prep = self.prepare_cached_agg(
                            plan, table, m, allow_selective=False
                        )
                    except BaseException as e:
                        outcomes[i] = e
                        continue
            if prep is None:
                try:
                    outcomes[i] = self.execute(
                        plan, table, _skip_cached_agg=tried_cached
                    )
                except BaseException as e:
                    outcomes[i] = e
            else:
                preps.append((i, prep, t_start))
        groups: dict = {}
        for i, prep, t_start in preps:
            groups.setdefault(prep.fuse_key(i), []).append((i, prep, t_start))
        for grp in groups.values():
            if len(grp) == 1:
                i, prep, t_start = grp[0]
                try:
                    if prep.row_idx is None and prep.entry.mesh is None \
                            and not prep.empty_range:
                        # a lone member pays no merge constraint:
                        # restore the solo path's selective row-gather
                        # that prepare skipped for cohort mergeability
                        # (allow_scan minus the pad slot IS the pruned
                        # series allow-list prepare derived it from)
                        prep.row_idx = self._selective_row_idx(
                            prep.entry, prep.allow_scan[:-1],
                            prep.lo, prep.hi,
                        )
                        if prep.row_idx is not None:
                            prep.m["cache_rows"] = int(
                                (prep.row_idx != prep.entry.n_valid).sum()
                            )
                    out = self.dispatch_cached_agg(prep)
                    outcomes[i] = self._finish_metrics(
                        prep.m, t_start, "device-cached", out
                    )
                except BaseException as e:
                    outcomes[i] = e
                continue
            try:
                results = self.dispatch_cached_agg_cohort(
                    [p for _, p, _ in grp]
                )
            except BaseException:
                # wholesale fused failure: per-member solo fallback, so
                # one bad cohort cannot take its members down with it
                for i, prep, t_start in grp:
                    try:
                        outcomes[i] = self.execute(plans[i], table)
                    except BaseException as e:
                        outcomes[i] = e
                continue
            for (i, prep, t_start), r in zip(grp, results):
                if isinstance(r, BaseException):
                    outcomes[i] = r
                else:
                    outcomes[i] = self._finish_metrics(
                        prep.m, t_start, "device-cached", r
                    )
        return outcomes

    def _selective_row_idx(
        self, entry, allowed: np.ndarray, lo: int, hi: int
    ) -> Optional[np.ndarray]:
        """Gather indices for a selective query, or None for a full scan.

        Worth it when tag filters keep few series AND those series' rows
        (narrowed by time inside each sorted series range) are a small
        fraction of the table — then shipping an M-row index beats making
        the kernel chew N rows (ref analog: pruning to relevant SSTs).
        """
        offsets = entry.series_offsets
        if offsets is None or entry.built_seqs is None:
            return None
        sel = np.nonzero(allowed)[0]
        S = entry.n_series
        # All (or most) series selected: the full-scan kernel wins.
        if len(sel) == 0 or len(sel) > 256 or len(sel) * 4 > S:
            return None
        # int32 relative timestamps survive the host-rows drop; clamp the
        # bounds into their domain before searching.
        ts_rel = entry.ts_rel_host  # sorted within each series range
        lo_rel = int(np.clip(lo - entry.min_ts, -(2**31) + 1, 2**31 - 1))
        hi_rel = int(np.clip(hi - entry.min_ts, -(2**31) + 1, 2**31 - 1))
        parts = []
        total = 0
        for s in sel:
            s0, s1 = int(offsets[s]), int(offsets[s + 1])
            a = s0 + int(np.searchsorted(ts_rel[s0:s1], lo_rel, "left"))
            b = s0 + int(np.searchsorted(ts_rel[s0:s1], hi_rel, "left"))
            if b > a:
                parts.append(np.arange(a, b, dtype=np.int32))
                total += b - a
        if total == 0 or total * 4 > entry.n_valid:
            return None  # selected rows not sparse enough to pay gather
        from ..ops.encoding import pad_to_bucket

        idx = np.concatenate(parts) if len(parts) > 1 else parts[0]
        # pad slots point at the explicit pad row (code n_series, masked)
        return pad_to_bucket(idx, total, fill=np.int32(entry.n_valid))

    def _delta_soundness(self, table, entry, delta, agg_cols) -> bool:
        """May ``delta`` be ADDED on top of the cached base aggregate?

        Sound when: no NULL agg inputs, every delta series already exists
        in the base (group mapping is per-series), and — for OVERWRITE
        tables — no delta row can overwrite a base row (strictly newer
        timestamps) nor another delta row (unique keys within the delta).
        """
        from ..engine.options import UpdateMode

        for c in agg_cols:
            if not delta.valid_mask(c).all():
                return False
        d_ts_all = delta.timestamps
        if (
            max(entry.max_ts, int(d_ts_all.max()))
            - min(entry.min_ts, int(d_ts_all.min()))
            >= 2**31 - 1
        ):
            return False  # delta pushes the span past int32-relative math
        schema = delta.schema
        tsid_name = schema.columns[schema.tsid_index].name
        d_tsid = delta.columns[tsid_name]
        n_series = len(entry.series_tsids)
        sidx = np.searchsorted(entry.series_tsids, d_tsid)
        known = sidx < n_series
        safe_idx = np.clip(sidx, 0, n_series - 1)
        known &= entry.series_tsids[safe_idx] == d_tsid
        if not known.all():
            return False  # brand-new series: base group mapping can't place it
        if table.options.update_mode is not UpdateMode.APPEND:
            d_ts = delta.timestamps
            if int(d_ts.min()) <= entry.max_ts:
                return False  # could overwrite a base row
            pairs = np.stack([d_tsid.astype(np.int64), d_ts.astype(np.int64)])
            if np.unique(pairs, axis=1).shape[1] != len(delta):
                return False  # delta overwrites within itself
        return True

    def _fold_delta(
        self, state, delta, entry, schema, gos, allow,
        agg_cols, value_names, device_filters,
        lo, hi, t0, width, n_buckets,
    ) -> None:
        """Accumulate unflushed rows into the kernel's host-side partials.

        The delta is small (one memtable's worth at most), so vectorized
        numpy accumulation costs microseconds while the many-million-row
        base stays in HBM untouched."""
        tsid_name = schema.columns[schema.tsid_index].name
        sidx = np.searchsorted(entry.series_tsids, delta.columns[tsid_name])
        d_ts = delta.timestamps
        mask = allow[sidx] & (d_ts >= lo) & (d_ts < hi)
        for col, op, lit in device_filters:
            v = as_values(delta.column(col)).astype(np.float64)
            mask &= NUMPY_CMP[op](v, lit) & delta.valid_mask(col)
        if not mask.any():
            return
        idx = np.nonzero(mask)[0]
        g = gos[sidx[idx]].astype(np.int64)
        if width is not None:
            b = np.clip((d_ts[idx] - t0) // width, 0, n_buckets - 1).astype(np.int64)
        else:
            b = np.zeros(len(idx), dtype=np.int64)
        np.add.at(state.counts, (g, b), 1)
        for fi, col in enumerate(agg_cols):
            v = as_values(delta.column(col))[idx].astype(np.float64)
            np.add.at(state.sums[fi], (g, b), v)
            np.minimum.at(state.mins[fi], (g, b), v)
            np.maximum.at(state.maxs[fi], (g, b), v)

    # ---- device raw reads (non-aggregate over the HBM scan cache) ----------
    def _raw_device_shape(self, plan: QueryPlan) -> Optional[dict]:
        """Shape descriptor when a non-aggregate plan fits the device
        raw-read kernels, else None. Eligibility mirrors the cached agg
        path: the residual WHERE must decompose into series-level
        (tag-only) conjuncts + numeric float-field comparisons.

        ``topk_ok`` marks the stricter sub-shape the top-k kernel can
        serve (single ORDER BY key on ts or a float column, LIMIT
        present, no DISTINCT/window — those need the complete row set);
        everything else eligible runs as a bounded selection, whose
        complete passing set makes ANY downstream projection exact."""
        stmt = plan.select
        if plan.is_aggregate or stmt.group_by or stmt.join is not None:
            return None
        schema = plan.schema
        if schema.tsid_index is None:
            return None
        device_filters, other = self._split_residual_filters(plan)
        tag_names = set(schema.tag_names)
        series_filters: list = []
        for conj in other:
            if _is_series_conjunct(conj, tag_names):
                series_filters.append(conj)
            else:
                return None
        order = None  # (column, is_ts, ascending)
        topk_ok = False
        if len(stmt.order_by) == 1 and stmt.limit is not None:
            o = stmt.order_by[0]
            expr = o.expr
            aliases = {
                item.alias: item.expr for item in stmt.items if item.alias
            }
            if (
                isinstance(expr, ast.Column)
                and expr.name in aliases
                and not schema.has_column(expr.name)
            ):
                expr = aliases[expr.name]
            if isinstance(expr, ast.Column) and schema.has_column(expr.name):
                name = expr.name
                if name == schema.timestamp_name:
                    order = (name, True, o.ascending)
                elif schema.column(name).kind.is_float:
                    order = (name, False, o.ascending)
            if order is not None and not stmt.distinct:
                from .planner import _walk

                topk_ok = not any(
                    isinstance(e, ast.WindowFunc)
                    for item in stmt.items
                    for e in _walk(item.expr)
                )
        return {
            "device_filters": device_filters,
            "series_filters": series_filters,
            "order": order,
            "topk_ok": topk_ok,
        }

    def _try_raw_device(
        self, plan: QueryPlan, table, shape: dict, m: dict
    ) -> Optional[ResultSet]:
        out = self._try_raw_device_inner(plan, table, shape, m)
        if out is None and "_adaptive_key" in m:
            # A bounced attempt must still feed the router's DEVICE arm:
            # the serve falls through to host, but recording it as a
            # host sample would leave device_n < 2 forever — the router
            # would stay in its probe phase and re-pay the failed
            # attempt (cache lookup, per-series filters, eligibility)
            # on every single query. Charged as device, the attempt+host
            # total can only measure >= the pure host arm, so a shape
            # that persistently bounces converges to the host route.
            m["_raw_fallback"] = True
        return out

    def _try_raw_device_inner(
        self, plan: QueryPlan, table, shape: dict, m: dict
    ) -> Optional[ResultSet]:
        """Serve a non-aggregate read from device-resident scan state,
        or None (caller falls through to the host projection path).

        The kernels return only ROW INDICES (<= k for top-k, <= the
        HORAEDB_RAW_MAX_ROWS budget for selections); the host gathers
        those rows from the entry's resident copy, folds the unflushed
        memtable delta (filtered exactly on host), and runs the ordinary
        projection machinery over the small candidate set — so ORDER BY
        ties, NULL ranks, aliases and expressions behave exactly like
        the host path."""
        import time as _time

        import jax
        import jax.numpy as jnp

        from ..ops.scan_agg import encode_filter_ops
        from ..ops.scan_topk import (
            RawScanSpec,
            pack_raw_dyn,
            padded_k,
            padded_select_slots,
            raw_max_rows,
            raw_select_packed,
            raw_topk_packed,
            topk_key_bounds,
        )
        from ..utils.tracectx import span as _span

        device_filters = shape["device_filters"]
        series_filters = shape["series_filters"]
        order = shape["order"]
        stmt = plan.select

        filter_cols = [f[0] for f in device_filters]
        key_col = order[0] if order is not None and not order[1] else None
        value_names = list(
            dict.fromkeys(filter_cols + ([key_col] if key_col else []))
        )
        # Filters/sort keys compare against the RESIDENT values — bf16
        # residency would reclassify rows near thresholds, so raw usage
        # pins these columns f32 (same contract as agg filter columns).
        self.scan_cache.note_usage(
            table.name, value_names, sum_cols=(),
            filter_cols=set(value_names),
        )
        entry, built, delta = self.scan_cache.get(
            table, value_names,
            read_rows=lambda: table.read(Predicate.all_time()),
        )
        if entry is None or delta is None:
            querystats.record(cache_misses=1)
            querystats.note_raw_scan("fallback")
            return None
        # The selected rows gather from the entry's HOST copy; entries
        # whose host rows were dropped under the budget can't serve raw.
        if entry.rows is None:
            querystats.note_raw_scan("fallback")
            return None
        # NULLs in a filtered/sorted column: the resident column holds
        # the fill value where the host path 3-value NULL-compares.
        for c in value_names:
            if not entry.all_valid.get(c, False):
                querystats.note_raw_scan("fallback")
                return None
        if len(delta) and not self._raw_delta_sound(table, entry, delta):
            querystats.note_raw_scan("fallback")
            return None

        # Series allow-list (tag filters, per series on host) + value-
        # stat pruning. Unlike the agg path the pruned list IS the allow
        # list: the delta never consults it (filtered exactly below).
        S = entry.n_series
        allowed = np.ones(S, dtype=bool)
        for conj in series_filters:
            v, valid = eval_expr(conj, entry.series_rows)
            allowed &= np.asarray(as_values(v)).astype(bool) & valid
        stats = entry.series_value_stats or {}
        for col, op, lit in device_filters:
            st = stats.get(col)
            if st is None:
                continue
            could = _series_could_match(st[0], st[1], op, lit)
            if could is not None:
                allowed = allowed & could

        tr = plan.predicate.time_range
        lo = max(tr.inclusive_start, entry.min_ts)
        hi = min(tr.exclusive_end, entry.max_ts + 1)
        empty_range = hi <= lo or not allowed.any()
        lo_rel = lo - entry.min_ts if not empty_range else 0
        hi_rel = hi - entry.min_ts if not empty_range else 0

        budget = raw_max_rows()
        limit = stmt.limit
        offset = stmt.offset or 0
        estimate = None
        if shape["topk_ok"] and limit + offset <= budget:
            kind = "topk"
        else:
            estimate = (
                self._raw_candidate_estimate(entry, allowed, lo_rel, hi_rel)
                if not empty_range
                else 0
            )
            if estimate > budget:
                # deliberate selectivity-based route: the host serves
                querystats.note_raw_scan("host")
                return None
            kind = "select"

        # Eligibility confirmed — record cache facts (a bail-out above
        # must not leave 'cache' lying in a host-path metric tree).
        m["cache"] = "build" if built else ("hit+delta" if len(delta) else "hit")
        m["rows_scanned"] = entry.n_valid + len(delta)
        querystats.record(scan_rows=entry.n_valid + len(delta))
        if built:
            querystats.record(cache_misses=1)
        else:
            querystats.record(cache_hits=1, cache_bytes=entry.device_bytes)
        if len(delta):
            m["delta_rows"] = len(delta)
            querystats.record(memtable_rows=len(delta))

        # Compressed layouts (ISSUE 19): raw reads return ROW INDICES and
        # gather from the host copy, so no field ever needs its decoded
        # values on device — dictionary columns stay in the code domain
        # even as the SORT KEY (the dictionary is sorted: code order ==
        # value order, ties included), and filter literals pre-translate.
        value_layouts = tuple(
            entry.value_layout(c, full_decode=False) for c in value_names
        )
        literals = [
            _translate_code_literal(
                entry.value_cols_dev[col].dict_host, op, lit
            )
            if value_layouts[value_names.index(col)][0] == "dict"
            else lit
            for col, op, lit in device_filters
        ]
        nfilters = tuple(
            (value_names.index(c), op) for c, op, _ in device_filters
        )
        idx = np.empty(0, dtype=np.int64)
        t_kernel = _time.perf_counter()
        if not empty_range:
            values_dev = entry.values_for(value_names)
            allow_arr = np.append(allowed, False)  # pad series masked
            n_dev = int(entry.mesh.devices.size) if entry.mesh is not None else 1
            if kind == "topk":
                k = padded_k(entry.n_valid, limit + offset)
                if entry.mesh is not None:
                    # per-shard k is bounded by the shard length; a shard
                    # smaller than k contributes ALL its rows — still a
                    # superset of the global top-k
                    k = min(k, entry.padded_rows // n_dev)
                spec = RawScanSpec(
                    k=k,
                    descending=not order[2],
                    key_is_ts=order[1],
                    numeric_filters=nfilters,
                    key_field=(
                        value_names.index(order[0]) if not order[1] else 0
                    ),
                )
            else:
                spec = RawScanSpec(
                    select_slots=padded_select_slots(max(estimate or 1, 1)),
                    numeric_filters=nfilters,
                )
            kernel_key = (
                "raw", kind, n_dev, spec.k, spec.select_slots,
                spec.descending, spec.key_is_ts, spec.key_field, nfilters,
                value_layouts, entry.ts_layout, entry.series_layout,
            )
            key_lo = key_hi = 0
            if kind == "topk":
                key_lo, key_hi = topk_key_bounds(
                    spec.descending, spec.key_is_ts, lo_rel, hi_rel
                )
            from ..obs.device import timed_dispatch

            if entry.mesh is not None:
                from ..parallel.dist_raw import dist_raw_select, dist_raw_topk

                m["mesh_devices"] = n_dev
                if kind == "topk":
                    dkind = "raw_topk_dist"
                    idx = timed_dispatch(
                        dkind,
                        lambda: dist_raw_topk(
                            entry.mesh, spec, entry.series_codes_dev,
                            entry.ts_rel_dev, values_dev,
                            jnp.asarray(allow_arr), literals, lo_rel, hi_rel,
                            key_lo, key_hi, need=limit + offset,
                        ),
                    )
                else:
                    dkind = "raw_select_dist"
                    idx, total = timed_dispatch(
                        dkind,
                        lambda: dist_raw_select(
                            entry.mesh, spec, entry.series_codes_dev,
                            entry.ts_rel_dev, values_dev,
                            jnp.asarray(allow_arr), literals, lo_rel, hi_rel,
                        ),
                    )
                    if total > len(idx):
                        self._raw_bail(m)
                        return None
            else:
                session_dev = entry.raw_session_for(allow_arr)
                dyn = jnp.asarray(
                    pack_raw_dyn(literals, lo_rel, hi_rel, key_lo, key_hi)
                )
                if kind == "topk":
                    dkind = "raw_topk"
                    packed = timed_dispatch(
                        dkind,
                        lambda: raw_topk_packed(
                            entry.series_parts, entry.ts_parts,
                            values_dev, session_dev, dyn,
                            k=spec.k, descending=spec.descending,
                            key_is_ts=spec.key_is_ts,
                            key_field=spec.key_field,
                            numeric_filters=encode_filter_ops(nfilters),
                            value_layouts=value_layouts,
                            ts_layout=entry.ts_layout,
                            series_layout=entry.series_layout,
                        ),
                    )
                    got = np.asarray(jax.device_get(packed))
                    idx = got[got >= 0]
                else:
                    dkind = "raw_select"
                    packed = timed_dispatch(
                        dkind,
                        lambda: raw_select_packed(
                            entry.series_parts, entry.ts_parts,
                            values_dev, session_dev, dyn,
                            select_slots=spec.select_slots,
                            numeric_filters=encode_filter_ops(nfilters),
                            value_layouts=value_layouts,
                            ts_layout=entry.ts_layout,
                            series_layout=entry.series_layout,
                        ),
                    )
                    got = np.asarray(jax.device_get(packed))
                    total = int(got[0])
                    if total > spec.select_slots:
                        self._raw_bail(m)
                        return None
                    idx = got[1 : 1 + total]
            querystats.note_kernel_dispatch(
                kernel_key, _time.perf_counter() - t_kernel, kind=dkind
            )

        base = (
            entry.rows.take(np.asarray(idx, dtype=np.int64))
            if len(idx)
            else entry.rows.slice(0, 0)
        )
        combined = base
        if len(delta):
            d_rows = self._raw_delta_rows(plan, delta)
            if len(d_rows):
                combined = RowGroup.concat([base, d_rows])
        m["raw_kernel"] = kind
        m["raw_candidates"] = int(len(idx))
        with _span("raw_project", table=plan.table):
            out = self._execute_projection(plan, combined, m)
        querystats.note_raw_scan(
            kind + ("_dist" if entry.mesh is not None else ""),
            kernel="raw_" + kind,
            rows=out.num_rows,
        )
        return out

    @staticmethod
    def _raw_bail(m: dict) -> None:
        """A device attempt bounced AFTER the cache facts were stamped
        (the can't-happen selection overflow): scrub them so the host
        serve's metric tree doesn't claim a cache it didn't use."""
        for k in ("cache", "rows_scanned", "delta_rows", "mesh_devices"):
            m.pop(k, None)
        querystats.note_raw_scan("fallback")

    def _raw_candidate_estimate(
        self, entry, allowed: np.ndarray, lo_rel: int, hi_rel: int
    ) -> int:
        """EXACT count of resident rows in allowed series within the
        relative time range, ignoring numeric filters (which only
        shrink it) — the bound that gates the selection buffer, so the
        device compaction can never truncate. O(S log rows) host work
        over the per-series sorted ranges."""
        if not allowed.any():
            return 0
        ts_rel = entry.ts_rel_host
        full_range = lo_rel <= 0 and (
            len(ts_rel) == 0 or hi_rel > int(ts_rel.max())
        )
        if allowed.all() and full_range:
            return entry.n_valid
        offsets = entry.series_offsets
        total = 0
        for s in np.nonzero(allowed)[0]:
            s0, s1 = int(offsets[s]), int(offsets[s + 1])
            if full_range:
                total += s1 - s0
            else:
                a = np.searchsorted(ts_rel[s0:s1], lo_rel, "left")
                b = np.searchsorted(ts_rel[s0:s1], hi_rel, "left")
                total += int(b - a)
        return total

    def _raw_delta_sound(self, table, entry, delta) -> bool:
        """May the unflushed delta be UNIONED with the cached base for a
        raw read? APPEND tables: always (duplicates are data). OVERWRITE
        tables: only when no delta row can shadow a base row (strictly
        newer timestamps) nor another delta row (unique keys within the
        delta) — the union would otherwise return a stale base row
        beside its overwrite. New series in the delta are fine: raw
        reads filter the delta rows directly, no base mapping needed."""
        from ..engine.options import UpdateMode

        if table.options.update_mode is UpdateMode.APPEND:
            return True
        d_ts = delta.timestamps
        if int(d_ts.min()) <= entry.max_ts:
            return False
        schema = delta.schema
        tsid_name = schema.columns[schema.tsid_index].name
        pairs = np.stack([
            delta.columns[tsid_name].astype(np.int64),
            d_ts.astype(np.int64),
        ])
        return np.unique(pairs, axis=1).shape[1] == len(delta)

    def _raw_delta_rows(self, plan: QueryPlan, delta):
        """Delta rows passing the query's time range + FULL residual
        WHERE, evaluated exactly on host — the delta is one memtable's
        worth at most, and exact evaluation also covers series the base
        has never seen."""
        tr = plan.predicate.time_range
        d_ts = delta.timestamps
        mask = (d_ts >= tr.inclusive_start) & (d_ts < tr.exclusive_end)
        residual = self._residual_where(plan)
        if residual is not None and len(delta):
            v, valid = eval_expr(residual, delta)
            mask &= np.asarray(as_values(v)).astype(bool) & valid
        return delta if mask.all() else delta.filter(mask)

    # ---- host fallback -----------------------------------------------------
    def _execute_agg_host(self, plan: QueryPlan, rows: RowGroup) -> ResultSet:
        from ..utils.deadline import checkpoint as _deadline_checkpoint

        _deadline_checkpoint("executing")
        residual = self._residual_where(plan)
        if residual is not None and len(rows):
            v, m = eval_expr(residual, rows)
            rows = rows.filter(v.astype(bool) & m)

        # Group keys as value arrays. NULL keys form their own group
        # (standard SQL) — validity joins the grouping code so NULL never
        # collapses into the column's fill value.
        key_arrays: list = []
        key_valids: list = []  # None when every row is valid
        key_names: list[str] = []
        for k in plan.group_keys:
            if k.column is not None:
                key_arrays.append(rows.column(k.column))
                vm = rows.valid_mask(k.column)
                key_valids.append(None if vm.all() else vm)
            else:
                key_arrays.append((rows.timestamps // k.time_bucket_ms) * k.time_bucket_ms)
                key_valids.append(None)
            key_names.append(k.output_name)

        n = len(rows)
        if key_arrays:
            combined = np.zeros(n, dtype=np.int64)
            for arr, vm in zip(key_arrays, key_valids):
                u, inv = unique_inverse(arr)
                if vm is not None:
                    inv = np.where(vm, inv + 1, 0)  # code 0 = the NULL group
                    combined = combined * (len(u) + 2) + inv
                else:
                    combined = combined * (len(u) + 1) + inv
            uniq_comb, first_idx, codes = np.unique(
                combined, return_index=True, return_inverse=True
            )
            group_count = len(uniq_comb)
        else:
            if n == 0:
                return _order_and_limit(_empty_ungrouped_agg_row(plan), plan)
            codes = np.zeros(n, dtype=np.int64)
            first_idx = np.zeros(1, dtype=np.int64)
            group_count = 1

        names: list[str] = []
        columns: list[np.ndarray] = []
        nulls: dict[str, np.ndarray] = {}
        agg_expr_map = dict(plan.agg_exprs)
        computed = None
        base: dict = {}
        if agg_expr_map:
            for ki, gk in enumerate(plan.group_keys):
                if gk.column is None:
                    continue
                vm = key_valids[ki]
                base[gk.column] = (
                    as_values(key_arrays[ki][first_idx]),
                    None if vm is None else ~vm[first_idx],
                )
            for a in plan.aggs:
                base[a.output_name] = _host_agg(a, rows, codes, group_count)
            computed = eval_agg_exprs(plan, base)
        for item in plan.select.items:
            out_name = item.output_name
            e = item.expr
            if out_name in agg_expr_map:
                v, nm = computed[out_name]
                columns.append(v)
                if nm is not None:
                    nulls[out_name] = nm
                names.append(out_name)
            elif isinstance(e, ast.Column) or (
                isinstance(e, ast.FuncCall) and e.name in ("time_bucket", "date_trunc")
            ):
                # Resolve by the EXPRESSION, not the select item's output
                # name: an aliased key (SELECT host AS h ... GROUP BY
                # host) has output_name 'h' while the GroupKey carries
                # the column name.
                if isinstance(e, ast.Column):
                    ki = next(
                        (
                            i
                            for i, gk in enumerate(plan.group_keys)
                            if gk.column == e.name
                        ),
                        None,
                    )
                    if ki is None:
                        ki = key_names.index(out_name)
                else:
                    ki = key_names.index(str(e))
                columns.append(as_values(key_arrays[ki][first_idx]))
                vmk = key_valids[ki]
                if vmk is not None and not vmk[first_idx].all():
                    nulls[out_name] = ~vmk[first_idx]
                names.append(out_name)
            else:
                agg_i = [a.output_name for a in plan.aggs].index(out_name)
                a = plan.aggs[agg_i]
                # The agg_exprs base already paid for every aggregate —
                # don't run _host_agg (O(rows)) a second time.
                col, null = (
                    base[out_name]
                    if out_name in base
                    else _host_agg(a, rows, codes, group_count)
                )
                columns.append(col)
                if null is not None:
                    nulls[out_name] = null
                names.append(out_name)
        result = ResultSet(names, columns, nulls or None)
        return _order_and_limit(result, plan)

    def _execute_projection(
        self, plan: QueryPlan, rows: RowGroup, m: dict | None = None
    ) -> ResultSet:
        from ..utils.deadline import checkpoint as _deadline_checkpoint

        _deadline_checkpoint("executing")
        residual = self._residual_where(plan)
        if residual is not None and len(rows):
            v, vm = eval_expr(residual, rows)
            rows = rows.filter(v.astype(bool) & vm)

        # Sort BEFORE projecting: ORDER BY may reference any table column
        # or expression, not just select-list outputs. Select aliases are
        # resolved back to their expressions first.
        stmt = plan.select
        if stmt.order_by and len(rows):
            aliases = {
                item.alias: item.expr for item in stmt.items if item.alias
            }
            keys = []
            for o in reversed(stmt.order_by):
                expr = o.expr
                if isinstance(expr, ast.Column) and expr.name in aliases and not rows.schema.has_column(expr.name):
                    expr = aliases[expr.name]
                kv, km = eval_expr(expr, rows)
                if isinstance(kv, DictColumn):
                    kv = kv.sort_ranks()
                keys.append(kv if o.ascending else _desc_key(kv))
                keys.append(_null_rank(km, o))
            # Rows already in the requested order skip the sort entirely:
            # storage hands over presorted rows for the common dashboard
            # shapes (ORDER BY ts within one series; ORDER BY matching
            # the (series, ts) stored order; the raw device path's
            # resident-order selections) and a stable sort of a sorted
            # sequence is the identity — one O(n·k) adjacent-compare
            # pass replaces the O(n log n) lexsort.
            if _lex_presorted(keys):
                if m is not None:
                    m["sort_skipped"] = True
            else:
                rows = rows.take(np.lexsort(tuple(keys)))
        from .planner import _walk

        has_window = any(
            isinstance(e, ast.WindowFunc)
            for item in stmt.items
            for e in _walk(item.expr)
        )
        if (stmt.limit is not None or stmt.offset) and not stmt.distinct and not has_window:
            # DISTINCT must dedupe BEFORE the limit applies; window frames
            # must see the complete (sorted) row set before truncation
            stop = (stmt.offset + stmt.limit) if stmt.limit is not None else len(rows)
            rows = rows.slice(stmt.offset, stop)

        names: list[str] = []
        columns: list[np.ndarray] = []
        nulls: dict[str, np.ndarray] = {}
        for item in plan.select.items:
            if isinstance(item.expr, ast.Star):
                for c in rows.schema.columns:
                    if c.name.startswith("__hidden_"):
                        continue  # cte-internal synthesized columns
                    names.append(c.name)
                    columns.append(as_values(rows.column(c.name)))
                    vm = rows.valid_mask(c.name)
                    if not vm.all():
                        nulls[c.name] = ~vm
                continue
            v, vm = eval_expr(item.expr, rows)
            names.append(item.output_name)
            columns.append(as_values(v))
            if not vm.all():
                nulls[item.output_name] = ~vm
        result = ResultSet(names, columns, nulls or None)
        if stmt.distinct:
            result = _distinct_result(result)
        if (stmt.distinct or has_window) and (stmt.limit is not None or stmt.offset):
            result = _slice_result(result, stmt.offset, stmt.limit)
        return result


def route_segment_kernel(shape_key, spec, n_rows: int, est_distinct,
                         sql: str = ""):
    """Module-level core of the learned segment-impl choice — shared by
    the executor's direct/cached/dist paths AND the partial-agg
    push-down (query/partial.py runs on partition owners with no
    Executor instance in scope). Returns (spec, token); token is None
    when routing doesn't apply (n_seg == 1, pinned HORAEDB_SEGMENT_IMPL,
    or router disabled)."""
    from ..ops.scan_agg import pinned_segment_impl
    from .path_router import (
        KERNEL_ROUTER,
        bootstrap_observed_segments,
        candidate_kernels,
        kernel_routing_enabled,
        seed_kernel,
    )

    n_seg = spec.n_groups * spec.n_buckets
    if n_seg <= 1 or pinned_segment_impl() or not kernel_routing_enabled():
        return spec, None
    key = (shape_key, n_seg.bit_length())
    obs = KERNEL_ROUTER.observed_segments(key)
    if obs is None and sql:
        # never-seen key: the query_stats ring may remember how many
        # live segments this SQL shape produced before (agg_segments)
        obs = bootstrap_observed_segments(sql)
        if obs is not None:
            KERNEL_ROUTER.note_segments(key, obs)
    est = obs if obs is not None else est_distinct
    if est is not None:
        est = max(1, min(int(est), n_seg, max(int(n_rows), 1)))
    import dataclasses

    import jax

    from ..ops.hash_agg import hash_slots_for

    candidates = candidate_kernels(n_seg, n_rows, est)
    impl = KERNEL_ROUTER.choose(
        key,
        seed_kernel(n_seg, est, jax.default_backend()),
        candidates,
    )
    spec = dataclasses.replace(
        spec,
        segment_impl=impl,
        hash_slots=hash_slots_for(n_seg, est) if impl == "hash" else 0,
    )
    # Decision plane: journal the pick with the EWMA's own prediction of
    # what this impl costs for this shape (None until the impl has a
    # clean sample — those picks resolve ungraded). The id rides the
    # router token to finish_segment_kernel, where the same amortized
    # dispatch seconds that feed the EWMA also grade the prediction.
    from ..obs.decisions import record_decision

    predicted = KERNEL_ROUTER.stats(key).get("t", {}).get(impl)
    dec_id = record_decision(
        "kernel_router",
        key=f"{shape_key[0] if shape_key else ''}#b{n_seg.bit_length()}",
        choice=impl,
        features={
            "n_seg": n_seg,
            "est_segments": est,
            "candidates": list(candidates),
        },
        predicted=predicted,
    )
    return spec, (key, impl, dec_id)


def finish_segment_kernel(krec, spec, m: dict, state,
                          seconds: float, n_valid=None) -> None:
    """Close one aggregation dispatch: feed the router's EWMA and
    observed-cardinality loop, stamp the metric tree, the ledger
    ``kernel`` field, and the horaedb_agg_kernel_total family."""
    from ..ops.scan_agg import (
        pinned_segment_impl,
        resolve_segment_impl,
    )
    from .path_router import KERNEL_ROUTER

    n_seg = spec.n_groups * spec.n_buckets
    impl = resolve_segment_impl(n_seg, spec.segment_impl)
    live = int((state.counts > 0).sum())
    if krec is not None:
        from ..obs.decisions import resolve_decision

        key, routed, dec_id = krec
        if live > 0:
            # Degenerate dispatches (empty time range, filter matching
            # nothing) are excluded from BOTH feedback loops: their
            # near-zero latency would make whichever impl served them
            # look unbeatable under the min-biased estimator, and a
            # live count of 0 would EWMA the cardinality estimate toward
            # a tiny hash table the next real query overflows.
            # the honest cost of CHOOSING this impl for the shape —
            # including the tiny-input host fallback when hash took it
            KERNEL_ROUTER.record(key, routed, seconds)
            KERNEL_ROUTER.note_segments(key, live)
            resolve_decision(
                dec_id, actual=seconds, outcome="served",
                loop="kernel_router",
            )
        else:
            # degenerate: the decision closes (no leaked pending entry)
            # but must not grade the EWMA's prediction
            resolve_decision(
                dec_id, actual=seconds, outcome="degenerate",
                loop="kernel_router", calibrate=False,
            )
    if (
        impl == "hash"
        and n_valid is not None
        and not pinned_segment_impl()
    ):
        from ..utils.env import env_int

        if n_valid <= env_int("HORAEDB_HASH_HOST_MAX_ROWS", 4096):
            impl = "host"  # scan_aggregate's dispatch-free arm
    m["kernel"] = impl
    querystats.note_agg_kernel(impl, segments=live)


def _series_could_match(
    mins: np.ndarray, maxs: np.ndarray, op: str, lit: float
) -> Optional[np.ndarray]:
    """Per-series bool: could ANY value in [min, max] satisfy ``op lit``?
    Conservative (False only when provably no row passes); None for
    operators without a sound interval rule."""
    if op == ">":
        return maxs > lit
    if op == ">=":
        return maxs >= lit
    if op == "<":
        return mins < lit
    if op == "<=":
        return mins <= lit
    if op in ("=", "=="):
        return (mins <= lit) & (maxs >= lit)
    # No != rule: stats ignore NaN samples (fmin/fmax), but the kernel's
    # IEEE compare counts NaN rows for `v != lit` — a min==max==lit series
    # holding a NaN would prune rows the unpruned paths return.
    return None


def _plan_needs_minmax(plan) -> bool:
    """False when no aggregate in the plan reads min/max — the device
    kernel then skips those reductions entirely."""
    return any(a.func in ("min", "max") for a in plan.aggs)


def _is_series_conjunct(conj: ast.Expr, tag_names: set) -> bool:
    """True when the conjunct only references tag columns — its value is
    constant per series, so it can evaluate on the (small) series set."""
    cols = _columns_of(conj)
    return bool(cols) and all(c.name in tag_names for c in cols)


def _empty_ungrouped_agg_row(plan: QueryPlan) -> ResultSet:
    agg_expr_map = dict(plan.agg_exprs)
    computed = None
    if agg_expr_map:
        # SQL zero-row defaults per aggregate (count 0, others NULL),
        # then the expression evaluates over that one row.
        base = {
            a.output_name: (
                (np.array([0], dtype=np.int64), None)
                if a.func == "count"
                else (np.array([np.nan]), np.array([True]))
            )
            for a in plan.aggs
        }
        computed = eval_agg_exprs(plan, base)
    names, columns, nulls = [], [], {}
    for item in plan.select.items:
        out_name = item.output_name
        names.append(out_name)
        if out_name in agg_expr_map:
            v, nm = computed[out_name]
            columns.append(v)
            if nm is not None:
                nulls[out_name] = nm
            continue
        agg = next((a for a in plan.aggs if a.output_name == out_name), None)
        if agg is not None and agg.func == "count":
            columns.append(np.array([0], dtype=np.int64))
        else:
            columns.append(np.array([np.nan]))
            nulls[out_name] = np.array([True])
    return ResultSet(names, columns, nulls or None)


def _agg_output(
    a: AggCall,
    agg_cols: list[str],
    counts: np.ndarray,
    sums: np.ndarray,
    mins: np.ndarray,
    maxs: np.ndarray,
    g_idx: np.ndarray,
    b_idx: np.ndarray,
) -> np.ndarray:
    if a.func == "count":
        return counts[g_idx, b_idx].astype(np.int64)
    fi = agg_cols.index(a.column)
    if a.func == "sum":
        return sums[fi, g_idx, b_idx]
    if a.func == "min":
        return mins[fi, g_idx, b_idx]
    if a.func == "max":
        return maxs[fi, g_idx, b_idx]
    if a.func == "avg":
        with np.errstate(divide="ignore", invalid="ignore"):
            return sums[fi, g_idx, b_idx] / counts[g_idx, b_idx]
    raise ExprError(f"unknown aggregate {a.func}")


def _host_agg(
    a: AggCall, rows: RowGroup, codes: np.ndarray, group_count: int
) -> tuple[np.ndarray, Optional[np.ndarray]]:
    # agg(col) FILTER (WHERE cond): rows failing the per-aggregate filter
    # are invisible to THIS aggregate only (SQL NULL semantics: a NULL
    # condition fails the filter).
    fmask = None
    if a.filter_where is not None:
        fv, fm = eval_expr(a.filter_where, rows)
        fmask = fm & as_values(fv).astype(bool)
    if a.func == "count" and a.column is None:
        counted = codes if fmask is None else codes[fmask]
        return np.bincount(counted, minlength=group_count).astype(np.int64), None
    if a.func not in ("count", "sum", "min", "max", "avg"):
        from .functions import REGISTRY

        if a.distinct:
            # Silent DISTINCT-dropping would be a wrong answer, not a
            # missing feature.
            raise ExprError(f"DISTINCT is not supported with {a.func}")
        binary_fn = REGISTRY.binary_aggregate(a.func)
        if binary_fn is not None:
            v1, v2 = rows.valid_mask(a.column), rows.valid_mask(a.column2)
            if fmask is not None:
                v1, v2 = v1 & fmask, v2 & fmask
            return binary_fn(
                as_values(rows.column(a.column)), v1,
                as_values(rows.column(a.column2)), v2,
                codes, group_count,
            )
        agg_fn = REGISTRY.aggregate(a.func)
        if agg_fn is None:
            raise ExprError(f"unknown aggregate {a.func}")
        v1 = rows.valid_mask(a.column)
        if fmask is not None:
            v1 = v1 & fmask
        return agg_fn(
            rows.column(a.column), v1, codes, group_count, *a.params,
        )
    col = as_values(rows.column(a.column))
    valid = rows.valid_mask(a.column)
    if fmask is not None:
        valid = valid & fmask
    if a.distinct:
        if a.func != "count":
            raise ExprError("DISTINCT only supported with count")
        out = np.zeros(group_count, dtype=np.int64)
        for g in range(group_count):
            out[g] = len(np.unique(col[(codes == g) & valid]))
        return out, None
    vals = col.astype(np.float64) if col.dtype != object else col
    out = np.zeros(group_count, dtype=np.float64)
    nullmask = np.zeros(group_count, dtype=bool)
    cnt = np.bincount(codes, weights=valid.astype(np.float64), minlength=group_count)
    if a.func == "count":
        return cnt.astype(np.int64), None
    if a.func == "sum":
        out = np.bincount(codes, weights=np.where(valid, vals, 0.0), minlength=group_count)
        nullmask = cnt == 0
        return out, nullmask if nullmask.any() else None
    if a.func in ("min", "max"):
        nullmask = cnt == 0
        if vals.dtype == object:
            # Strings: per-group python reduction (group count is small).
            out_obj = np.empty(group_count, dtype=object)
            for g in range(group_count):
                gv = vals[(codes == g) & valid]
                out_obj[g] = (min(gv) if a.func == "min" else max(gv)) if len(gv) else None
            return out_obj, nullmask if nullmask.any() else None
        fill = np.inf if a.func == "min" else -np.inf
        masked = np.where(valid, vals, fill)
        out = np.full(group_count, fill)
        np.minimum.at(out, codes, masked) if a.func == "min" else np.maximum.at(
            out, codes, masked
        )
        return out, nullmask if nullmask.any() else None
    if a.func == "avg":
        s = np.bincount(codes, weights=np.where(valid, vals, 0.0), minlength=group_count)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = s / cnt
        nullmask = cnt == 0
        return out, nullmask if nullmask.any() else None
    raise ExprError(f"unknown aggregate {a.func}")


def _lex_presorted(keys: list) -> bool:
    """True when rows are ALREADY in ``np.lexsort(keys)`` order, i.e.
    the stable sort would be the identity permutation. One vectorized
    adjacent-compare pass per key — O(n·k) against the sort's
    O(n log n). Conservative: incomparable keys (mixed-type object
    columns) and NaN pairs report unsorted and fall through to lexsort.
    """
    n = len(keys[0])
    if n <= 1:
        return True
    strict = np.zeros(n - 1, dtype=bool)
    eq = np.ones(n - 1, dtype=bool)
    try:
        for key in reversed(keys):  # np.lexsort: the LAST key is primary
            key = np.asarray(key)
            a, b = key[:-1], key[1:]
            strict |= eq & (a < b)
            eq &= a == b
    except TypeError:
        return False
    return bool((strict | eq).all())


def _desc_key(arr: np.ndarray) -> np.ndarray:
    """A lexsort key sorting ``arr`` descending (strings via code negate)."""
    if arr.dtype == object:
        _, inv = np.unique(arr, return_inverse=True)
        return -inv
    if arr.dtype.kind in "fiu":
        return -arr.astype(np.float64)
    return arr  # bool/other: DESC not meaningfully supported


def eval_agg_exprs(
    plan: QueryPlan, base: dict[str, tuple[np.ndarray, Optional[np.ndarray]]]
) -> dict[str, tuple[np.ndarray, Optional[np.ndarray]]]:
    """Evaluate the plan's arithmetic-over-aggregate select items per
    group. ``base`` maps group-key column names and (hidden + named)
    aggregate output names to (values, nullmask|None); returns the same
    shape for each computed output."""
    names, cols, nulls = [], [], {}
    for name, (v, nm) in base.items():
        names.append(name)
        cols.append(np.asarray(v))
        if nm is not None:
            nulls[name] = nm
    shim = _ResultRows(ResultSet(names, cols, nulls or None))
    out = {}
    for name, expr in plan.agg_exprs:
        v, m = eval_expr(expr, shim)
        out[name] = (as_values(v), None if m.all() else ~m)
    return out


class _ResultRows:
    """Row-like shim so eval_expr can run over a ResultSet (HAVING)."""

    def __init__(self, result: ResultSet) -> None:
        self._r = result
        self._nulls = result.nulls or {}

    def __len__(self) -> int:
        return self._r.num_rows

    def column(self, name: str):
        return self._r.column(name)

    def valid_mask(self, name: str) -> np.ndarray:
        null = self._nulls.get(name)
        if null is None:
            return np.ones(self._r.num_rows, dtype=bool)
        return ~null


def _subst_having(e: ast.Expr, mapping: dict[str, str]) -> ast.Expr:
    """Rewrite select-list expressions in HAVING into result columns."""
    key = str(e)
    if key in mapping:
        return ast.Column(mapping[key])
    if isinstance(e, ast.Column) and e.name in mapping:
        return ast.Column(mapping[e.name])
    if isinstance(e, ast.BinaryOp):
        return ast.BinaryOp(
            e.op, _subst_having(e.left, mapping), _subst_having(e.right, mapping)
        )
    if isinstance(e, ast.UnaryOp):
        return ast.UnaryOp(e.op, _subst_having(e.operand, mapping))
    if isinstance(e, ast.FuncCall):
        raise ExprError(
            f"HAVING references {e} which is not in the SELECT list — "
            "add it (optionally aliased) to SELECT"
        )
    return e


def _apply_having(result: ResultSet, plan: QueryPlan) -> ResultSet:
    having = plan.select.having
    if having is None or result.num_rows == 0:
        return result
    mapping: dict[str, str] = {}
    for item in plan.select.items:
        mapping[str(item.expr)] = item.output_name
        if item.alias:
            mapping[item.alias] = item.output_name
    expr = _subst_having(having, mapping)
    shim = _ResultRows(result)
    v, m = eval_expr(expr, shim)
    mask = np.asarray(as_values(v)).astype(bool) & m
    if mask.all():
        return result
    idx = np.nonzero(mask)[0]
    return ResultSet(
        result.names,
        [c[idx] for c in result.columns],
        {k: n[idx] for k, n in (result.nulls or {}).items()} or None,
        result.metrics,
    )


def _distinct_result(result: ResultSet) -> ResultSet:
    """SELECT DISTINCT: drop duplicate output rows, keep first occurrence.

    NULLs participate as their own key bit — a NULL row must not collapse
    with a real row that happens to hold the null-fill value."""
    n = result.num_rows
    if n <= 1:
        return result
    nulls = result.nulls or {}
    combined = np.zeros(n, dtype=np.int64)
    for name, col in zip(result.names, result.columns):
        _, inv = unique_inverse(as_values(col))
        combined = combined * (int(inv.max()) + 2) + inv
        null = nulls.get(name)
        combined = combined * 2 + (null.astype(np.int64) if null is not None else 0)
    _, first = np.unique(combined, return_index=True)
    idx = np.sort(first)
    if len(idx) == n:
        return result
    return ResultSet(
        result.names,
        [c[idx] for c in result.columns],
        {k: m[idx] for k, m in (result.nulls or {}).items()} or None,
        result.metrics,
    )


def _order_and_limit(result: ResultSet, plan: QueryPlan) -> ResultSet:
    result = _apply_having(result, plan)
    stmt = plan.select
    if stmt.distinct:
        # Aggregate paths: DISTINCT over the grouped output rows, before
        # ORDER/LIMIT (group keys are unique, but aggregates may not be
        # selected alongside them).
        result = _distinct_result(result)
    if stmt.order_by and result.num_rows:
        keys = []
        for o in reversed(stmt.order_by):
            name = None
            if isinstance(o.expr, ast.Column):
                name = o.expr.name
            key_src = None
            resolved = None
            if name is not None and name in result.names:
                resolved = name
            elif str(o.expr) in result.names:
                resolved = str(o.expr)
            else:
                # order by an alias
                for item in stmt.items:
                    if item.alias and str(o.expr) == item.alias:
                        resolved = item.alias
                        break
            if resolved is None:
                raise ExprError(f"ORDER BY expression not in select list: {o.expr}")
            key_src = result.column(resolved)
            null_mask = (result.nulls or {}).get(resolved)
            valid = (
                np.ones(len(key_src), dtype=bool)
                if null_mask is None
                else ~null_mask
            )
            keys.append(key_src if o.ascending else _desc_key(key_src))
            keys.append(_null_rank(valid, o))
        order = np.lexsort(tuple(keys))
        result = ResultSet(
            result.names,
            [c[order] for c in result.columns],
            {k: v[order] for k, v in (result.nulls or {}).items()} or None,
        )
    if stmt.limit is not None or stmt.offset:
        result = _slice_result(result, stmt.offset, stmt.limit)
    return result


def _null_rank(valid: np.ndarray, o: ast.OrderItem) -> np.ndarray:
    """Sort key placing NULLs per NULLS FIRST/LAST (SQL default: LAST
    when ASC, FIRST when DESC). Appended AFTER the value key, so it is
    the more significant of the pair in np.lexsort."""
    nulls_last = o.nulls_last if o.nulls_last is not None else o.ascending
    nullness = (~valid).astype(np.int8)
    return nullness if nulls_last else -nullness


def _slice_result(result: ResultSet, offset: int, limit: Optional[int]) -> ResultSet:
    stop = (offset + limit) if limit is not None else result.num_rows
    return ResultSet(
        result.names,
        [c[offset:stop] for c in result.columns],
        {k: v[offset:stop] for k, v in (result.nulls or {}).items()} or None,
    )


def _columns_of(e: ast.Expr) -> list[ast.Column]:
    from .planner import _walk

    return [x for x in _walk(e) if isinstance(x, ast.Column)]
