"""Function registry — scalar + aggregate UDFs
(ref: src/df_operator/src/registry.rs:48-65 — FunctionRegistry loaded at
startup, setup.rs:203; built-ins time_bucket and thetasketch_distinct
under df_operator/src/udfs/).

Scalar functions evaluate vectorized on host rows (and the planner folds
``time_bucket`` into the device kernel's bucket stage — registration here
is the EXTENSIBILITY point, not the fast path). Aggregate functions plug
into the host aggregation fallback; the (count,sum,min,max,avg) core runs
fused on device and is not routed through the registry.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np


class FunctionError(ValueError):
    pass


class FunctionRegistry:
    """name -> implementation, for scalars and aggregates.

    Scalar signature:   fn(args, rows) -> (values, valid_mask)
        where ``args`` is a list of (values, valid_mask) pairs already
        evaluated, and ``rows`` the source RowGroup (for length/schema).
    Aggregate signature: fn(values, valid, codes, n_groups)
        -> (per-group values, per-group null mask | None)
    """

    def __init__(self) -> None:
        self._scalars: dict[str, Callable] = {}
        self._aggregates: dict[str, Callable] = {}
        self._lock = threading.Lock()

    # ---- registration ---------------------------------------------------
    def register_scalar(self, name: str, fn: Callable, raw_args: bool = False) -> None:
        with self._lock:
            self._scalars[name.lower()] = (fn, raw_args)

    def register_aggregate(self, name: str, fn: Callable) -> None:
        with self._lock:
            self._aggregates[name.lower()] = fn

    # ---- lookup ---------------------------------------------------------
    def scalar(self, name: str):
        return self._scalars.get(name.lower())

    def aggregate(self, name: str):
        return self._aggregates.get(name.lower())

    def aggregate_names(self) -> set[str]:
        return set(self._aggregates)


# ---- built-ins -----------------------------------------------------------


def _time_bucket(args, rows):
    """time_bucket(ts, '1h') — ALSO compiled into the device kernel's
    bucket stage when it appears as a group key; this host form covers
    projections and fallbacks."""
    from ..engine.options import parse_duration_ms
    from . import ast

    # raw_args: receives the unevaluated exprs for the literal width
    (ts_vals, ts_valid), width_expr = args
    if not isinstance(width_expr, ast.Literal):
        raise FunctionError("time_bucket width must be a literal duration")
    width = parse_duration_ms(width_expr.value)
    return (ts_vals // width) * width, ts_valid


def _abs(args, rows):
    v, m = args[0]
    return np.abs(v), m


def _thetasketch_distinct(values, valid, codes, n_groups):
    """Approximate-distinct analog (ref: udfs/thetasketch_distinct.rs).

    The reference uses a theta sketch to bound memory on huge
    cardinalities; columnar numpy counts distinct exactly in one
    sort-unique pass — same answer, no sketch error, acceptable memory at
    the scales a single node aggregates post-scan."""
    from ..common_types.dict_column import DictColumn, unique_inverse

    out = np.zeros(n_groups, dtype=np.int64)
    idx = np.nonzero(valid)[0]
    if len(idx):
        if isinstance(values, DictColumn):
            val_codes = values.codes[idx]
        else:
            _, val_codes = unique_inverse(np.asarray(values)[idx])
        pairs = np.unique(
            np.stack([codes[idx].astype(np.int64), val_codes.astype(np.int64)]),
            axis=1,
        )
        grp, cnt = np.unique(pairs[0], return_counts=True)
        out[grp] = cnt
    return out, None


def default_registry() -> FunctionRegistry:
    reg = FunctionRegistry()
    reg.register_scalar("time_bucket", _time_bucket, raw_args=True)
    reg.register_scalar("abs", _abs)
    reg.register_aggregate("thetasketch_distinct", _thetasketch_distinct)
    return reg


REGISTRY = default_registry()
