"""Function registry — scalar + aggregate UDFs
(ref: src/df_operator/src/registry.rs:48-65 — FunctionRegistry loaded at
startup, setup.rs:203; built-ins time_bucket and thetasketch_distinct
under df_operator/src/udfs/).

Scalar functions evaluate vectorized on host rows (and the planner folds
``time_bucket`` into the device kernel's bucket stage — registration here
is the EXTENSIBILITY point, not the fast path). Aggregate functions plug
into the host aggregation fallback; the (count,sum,min,max,avg) core runs
fused on device and is not routed through the registry.
"""

from __future__ import annotations

import threading
from typing import Callable

import numpy as np


class FunctionError(ValueError):
    pass


class FunctionRegistry:
    """name -> implementation, for scalars and aggregates.

    Scalar signature:   fn(args, rows) -> (values, valid_mask)
        where ``args`` is a list of (values, valid_mask) pairs already
        evaluated, and ``rows`` the source RowGroup (for length/schema).
    Aggregate signature: fn(values, valid, codes, n_groups, *params)
        -> (per-group values, per-group null mask | None)
        where ``params`` are trailing LITERAL arguments from the call
        (e.g. the 0.9 of approx_percentile_cont(v, 0.9)).
    Binary aggregate signature (two-column aggregates — corr, covar):
        fn(v1, valid1, v2, valid2, codes, n_groups)
        -> (per-group values, per-group null mask | None)
    """

    def __init__(self) -> None:
        self._scalars: dict[str, Callable] = {}
        self._aggregates: dict[str, Callable] = {}
        self._binary_aggregates: dict[str, Callable] = {}
        self._numeric_only: set[str] = set()
        self._lock = threading.Lock()

    # ---- registration ---------------------------------------------------
    def register_scalar(self, name: str, fn: Callable, raw_args: bool = False) -> None:
        with self._lock:
            self._scalars[name.lower()] = (fn, raw_args)

    def register_aggregate(
        self, name: str, fn: Callable, numeric_only: bool = False
    ) -> None:
        with self._lock:
            self._aggregates[name.lower()] = fn
            if numeric_only:
                self._numeric_only.add(name.lower())

    def register_binary_aggregate(
        self, name: str, fn: Callable, numeric_only: bool = True
    ) -> None:
        with self._lock:
            self._binary_aggregates[name.lower()] = fn
            if numeric_only:
                self._numeric_only.add(name.lower())

    def numeric_only(self, name: str) -> bool:
        """True if the aggregate's column arguments must be numeric — the
        planner rejects string columns up front instead of letting numpy
        die mid-execution."""
        return name.lower() in self._numeric_only

    # ---- lookup ---------------------------------------------------------
    def scalar(self, name: str):
        return self._scalars.get(name.lower())

    def aggregate(self, name: str):
        return self._aggregates.get(name.lower())

    def binary_aggregate(self, name: str):
        return self._binary_aggregates.get(name.lower())

    def aggregate_names(self) -> set[str]:
        return set(self._aggregates) | set(self._binary_aggregates)


# ---- built-ins -----------------------------------------------------------


def _time_bucket(args, rows):
    """time_bucket(ts, '1h' | <ms>) — ALSO compiled into the device
    kernel's bucket stage when it appears as a group key; this host form
    covers projections and fallbacks."""
    from ..engine.options import parse_duration_ms
    from . import ast

    # raw_args: receives the unevaluated exprs for the literal width
    (ts_vals, ts_valid), width_expr = args
    if not isinstance(width_expr, ast.Literal):
        raise FunctionError("time_bucket width must be a literal duration")
    if isinstance(width_expr.value, str):
        width = parse_duration_ms(width_expr.value)
    else:
        width = int(width_expr.value)
    if width <= 0:
        raise FunctionError("time_bucket width must be positive")
    return (ts_vals // width) * width, ts_valid


def _date_trunc(args, rows):
    """date_trunc('minute', ts) — the fixed-width units, truncating to the
    bucket start in ms (the group-key form rides the device bucket stage).

    Registered raw_args: the convention evaluates args[0] and passes the
    rest as raw AST, so the evaluated unit arrives as a broadcast string
    array and the timestamp expression is evaluated here."""
    from . import ast
    from .executor import eval_expr

    (unit_vals, _), ts_expr = args
    if len(unit_vals) == 0:
        # Zero input rows: the unit broadcast is empty too — an empty
        # result, not a type error.
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=bool)
    unit = unit_vals[0]
    if not isinstance(unit, str):
        raise FunctionError("date_trunc unit must be a string literal")
    from .planner import _DATE_TRUNC_MS

    width = _DATE_TRUNC_MS.get(unit.lower())
    if width is None:
        raise FunctionError(f"unsupported date_trunc unit {unit!r}")
    if not isinstance(ts_expr, ast.Column):
        raise FunctionError("date_trunc expects a timestamp column")
    ts_vals, ts_valid = eval_expr(ts_expr, rows)
    return (ts_vals // width) * width, ts_valid


def _abs(args, rows):
    v, m = args[0]
    return np.abs(v), m


def _vals(pair):
    from ..common_types.dict_column import as_values

    v, m = pair
    return as_values(v), m


def _coalesce(args, rows):
    """First non-NULL argument per row."""
    n = len(rows)
    out = None
    valid = np.zeros(n, dtype=bool)
    for pair in args:
        v, m = _vals(pair)
        if out is None:
            out = np.zeros(n, dtype=v.dtype)
        if out.dtype != v.dtype:
            out = out.astype(object)
        take = ~valid & m
        out[take] = v[take]
        valid |= m
        if valid.all():
            break
    if out is None:
        out = np.zeros(n)
    return out, valid


def _make_str_fn(fn):
    def impl(args, rows):
        v, m = _vals(args[0])
        # Non-string VALID values cast implicitly (upper(1.5) -> '1.5',
        # the common engine behavior); invalid rows keep a placeholder
        # and stay masked.
        out = np.array(
            [fn(x if isinstance(x, str) else str(x)) if ok else ""
             for x, ok in zip(v, m)],
            dtype=object,
        )
        return out, m

    return impl


def _length(args, rows):
    v, m = _vals(args[0])
    out = np.fromiter(
        (len(x if isinstance(x, str) else str(x)) if ok else 0
         for x, ok in zip(v, m)),
        dtype=np.int64, count=len(v),
    )
    return out, m


def _concat(args, rows):
    """NULL arguments concatenate as empty and the result is never NULL
    (Postgres concat semantics: all-NULL args yield '')."""
    n = len(rows)
    parts = []
    for pair in args:
        v, m = _vals(pair)
        parts.append([str(x) if ok else "" for x, ok in zip(v, m)])
    out = np.array(["".join(p[i] for p in parts) for i in range(n)], dtype=object)
    return out, np.ones(n, dtype=bool)


def _make_math_fn(fn, domain=None):
    def impl(args, rows):
        v, m = _vals(args[0])
        vf = v.astype(np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            out = fn(vf)
        if domain is not None:
            m = m & domain(vf)
        return out, m

    return impl


def _round(args, rows):
    """round(v [, digits]) — registered raw_args, so ``digits`` arrives
    as raw AST and non-literal precision is rejected loudly instead of
    silently applying row 0's value to every row."""
    from . import ast

    (v, m), *rest = args
    from ..common_types.dict_column import as_values

    v = as_values(v)
    digits = 0
    if rest:
        d = rest[0]
        if not isinstance(d, ast.Literal) or not isinstance(d.value, int):
            raise FunctionError("round() digits must be an integer literal")
        digits = d.value
    return np.round(v.astype(np.float64), digits), m


def _power(args, rows):
    b, mb = _vals(args[0])
    e, me = _vals(args[1])
    with np.errstate(divide="ignore", invalid="ignore"):
        out = np.power(b.astype(np.float64), e.astype(np.float64))
    return out, mb & me & np.isfinite(out)


def _now(args, rows):
    import time as _t

    n = len(rows)
    return np.full(n, int(_t.time() * 1000), dtype=np.int64), np.ones(n, dtype=bool)


def _thetasketch_distinct(values, valid, codes, n_groups):
    """Approximate-distinct analog (ref: udfs/thetasketch_distinct.rs).

    The reference uses a theta sketch to bound memory on huge
    cardinalities; columnar numpy counts distinct exactly in one
    sort-unique pass — same answer, no sketch error, acceptable memory at
    the scales a single node aggregates post-scan."""
    from ..common_types.dict_column import DictColumn, unique_inverse

    out = np.zeros(n_groups, dtype=np.int64)
    idx = np.nonzero(valid)[0]
    if len(idx):
        if isinstance(values, DictColumn):
            val_codes = values.codes[idx]
        else:
            _, val_codes = unique_inverse(np.asarray(values)[idx])
        pairs = np.unique(
            np.stack([codes[idx].astype(np.int64), val_codes.astype(np.int64)]),
            axis=1,
        )
        grp, cnt = np.unique(pairs[0], return_counts=True)
        out[grp] = cnt
    return out, None


# ---- statistical aggregates ----------------------------------------------
# (ref surface: the reference exposes DataFusion's built-in statistical
# aggregates through SQL — stddev/variance/median/approx_* families,
# datafusion/physical-expr aggregates; exact column shapes here since a
# single node aggregates post-scan.)


def _moments(values, valid, codes, n_groups):
    vals = np.asarray(values, dtype=np.float64)
    w = valid.astype(np.float64)
    n = np.bincount(codes, weights=w, minlength=n_groups)
    s1 = np.bincount(codes, weights=np.where(valid, vals, 0.0), minlength=n_groups)
    s2 = np.bincount(codes, weights=np.where(valid, vals * vals, 0.0), minlength=n_groups)
    return n, s1, s2


def _variance(values, valid, codes, n_groups, ddof: int):
    n, s1, s2 = _moments(values, valid, codes, n_groups)
    with np.errstate(divide="ignore", invalid="ignore"):
        mean = s1 / n
        # Centered form: E[x^2] - mean^2 scaled to the ddof denominator;
        # clip the tiny negatives f64 cancellation can produce.
        var = np.maximum(s2 / n - mean * mean, 0.0) * (n / (n - ddof))
    null = n <= ddof
    return np.where(null, np.nan, var), (null if null.any() else None)


def _make_variance(ddof: int, sqrt: bool):
    def agg(values, valid, codes, n_groups):
        var, null = _variance(values, valid, codes, n_groups, ddof)
        return (np.sqrt(var) if sqrt else var), null

    return agg


def _per_group_reduce(values, valid, codes, n_groups, fn):
    """``fn`` maps each group's non-empty f64 slice to a scalar. One
    argsort partitions the rows so total cost is O(n log n + n_groups),
    not O(n_groups * n) full-array masks per group."""
    vals = np.asarray(values, dtype=np.float64)
    out = np.full(n_groups, np.nan)
    null = np.ones(n_groups, dtype=bool)
    idx = np.nonzero(valid)[0]
    if len(idx):
        c = codes[idx]
        order = np.argsort(c, kind="stable")
        sv = vals[idx][order]
        sc = c[order]
        gids = np.arange(n_groups)
        starts = np.searchsorted(sc, gids)
        ends = np.searchsorted(sc, gids, side="right")
        for g in gids:
            if ends[g] > starts[g]:
                out[g] = fn(sv[starts[g]:ends[g]])
                null[g] = False
    return out, (null if null.any() else None)


def _median(values, valid, codes, n_groups):
    return _per_group_reduce(values, valid, codes, n_groups, np.median)


def _make_percentile():
    def agg(values, valid, codes, n_groups, q=0.5):
        q = float(q)
        if not 0.0 <= q <= 1.0:
            raise FunctionError("percentile must be in [0, 1]")
        return _per_group_reduce(
            values, valid, codes, n_groups, lambda gv: np.quantile(gv, q)
        )

    return agg


def _covar(v1, valid1, v2, valid2, codes, n_groups, ddof: int):
    both = valid1 & valid2
    x = np.asarray(v1, dtype=np.float64)
    y = np.asarray(v2, dtype=np.float64)
    w = both.astype(np.float64)
    n = np.bincount(codes, weights=w, minlength=n_groups)
    sx = np.bincount(codes, weights=np.where(both, x, 0.0), minlength=n_groups)
    sy = np.bincount(codes, weights=np.where(both, y, 0.0), minlength=n_groups)
    sxy = np.bincount(codes, weights=np.where(both, x * y, 0.0), minlength=n_groups)
    with np.errstate(divide="ignore", invalid="ignore"):
        cov = (sxy / n - (sx / n) * (sy / n)) * (n / (n - ddof))
    null = n <= ddof
    return np.where(null, np.nan, cov), null, (n, sx, sy, sxy, both, x, y)


def _make_covar(ddof: int):
    def agg(v1, valid1, v2, valid2, codes, n_groups):
        cov, null, _ = _covar(v1, valid1, v2, valid2, codes, n_groups, ddof)
        return cov, (null if null.any() else None)

    return agg


def _corr(v1, valid1, v2, valid2, codes, n_groups):
    cov, null, (n, sx, sy, sxy, both, x, y) = _covar(
        v1, valid1, v2, valid2, codes, n_groups, 0
    )
    sx2 = np.bincount(codes, weights=np.where(both, x * x, 0.0), minlength=n_groups)
    sy2 = np.bincount(codes, weights=np.where(both, y * y, 0.0), minlength=n_groups)
    with np.errstate(divide="ignore", invalid="ignore"):
        vx = np.maximum(sx2 / n - (sx / n) ** 2, 0.0)
        vy = np.maximum(sy2 / n - (sy / n) ** 2, 0.0)
        out = cov / np.sqrt(vx * vy)
    null = null | ~np.isfinite(out)
    return np.where(null, np.nan, out), (null if null.any() else None)


def default_registry() -> FunctionRegistry:
    reg = FunctionRegistry()
    reg.register_scalar("time_bucket", _time_bucket, raw_args=True)
    reg.register_scalar("date_trunc", _date_trunc, raw_args=True)
    reg.register_scalar("abs", _abs)
    reg.register_scalar("coalesce", _coalesce)
    reg.register_scalar("upper", _make_str_fn(str.upper))
    reg.register_scalar("lower", _make_str_fn(str.lower))
    reg.register_scalar("trim", _make_str_fn(str.strip))
    reg.register_scalar("length", _length)
    reg.register_scalar("char_length", _length)
    reg.register_scalar("concat", _concat)
    reg.register_scalar("round", _round, raw_args=True)
    reg.register_scalar("floor", _make_math_fn(np.floor))
    reg.register_scalar("ceil", _make_math_fn(np.ceil))
    reg.register_scalar("ceiling", _make_math_fn(np.ceil))
    reg.register_scalar("sqrt", _make_math_fn(np.sqrt, domain=lambda v: v >= 0))
    reg.register_scalar("exp", _make_math_fn(np.exp))
    reg.register_scalar("ln", _make_math_fn(np.log, domain=lambda v: v > 0))
    reg.register_scalar("log10", _make_math_fn(np.log10, domain=lambda v: v > 0))
    reg.register_scalar("log2", _make_math_fn(np.log2, domain=lambda v: v > 0))
    reg.register_scalar("power", _power)
    reg.register_scalar("pow", _power)
    reg.register_scalar("now", _now)
    reg.register_aggregate("thetasketch_distinct", _thetasketch_distinct)
    # approx_distinct: same exact-count analog (see _thetasketch_distinct
    # docstring for why exact is the right trade at post-scan scale).
    reg.register_aggregate("approx_distinct", _thetasketch_distinct)
    reg.register_aggregate("stddev", _make_variance(1, sqrt=True), numeric_only=True)
    reg.register_aggregate("stddev_samp", _make_variance(1, sqrt=True), numeric_only=True)
    reg.register_aggregate("stddev_pop", _make_variance(0, sqrt=True), numeric_only=True)
    reg.register_aggregate("variance", _make_variance(1, sqrt=False), numeric_only=True)
    reg.register_aggregate("var_samp", _make_variance(1, sqrt=False), numeric_only=True)
    reg.register_aggregate("var_pop", _make_variance(0, sqrt=False), numeric_only=True)
    reg.register_aggregate("median", _median, numeric_only=True)
    reg.register_aggregate("approx_median", _median, numeric_only=True)
    reg.register_aggregate("approx_percentile_cont", _make_percentile(), numeric_only=True)
    reg.register_binary_aggregate("corr", _corr)
    reg.register_binary_aggregate("covar", _make_covar(1))
    reg.register_binary_aggregate("covar_samp", _make_covar(1))
    reg.register_binary_aggregate("covar_pop", _make_covar(0))
    return reg


REGISTRY = default_registry()
