"""Frontend: SQL text -> Plan (ref: query_frontend/src/frontend.rs:110-214).

``parse_sql`` and ``statement_to_plan`` mirror the reference's two-step
surface; PromQL/InfluxQL/OpenTSDB translators land beside this in later
rounds (same Plan target, different grammars).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..common_types.schema import Schema
from . import ast
from .parser import parse_many, parse_sql
from .plan import Plan
from .planner import Planner


class Frontend:
    def __init__(self, schema_of: Callable[[str], Optional[Schema]]) -> None:
        self.planner = Planner(schema_of)

    def parse_sql(self, sql: str) -> ast.Statement:
        return parse_sql(sql)

    def parse_sql_many(self, sql: str) -> list[ast.Statement]:
        return parse_many(sql)

    def statement_to_plan(self, stmt: ast.Statement) -> Plan:
        return self.planner.plan(stmt)

    def sql_to_plan(self, sql: str) -> Plan:
        return self.statement_to_plan(self.parse_sql(sql))
