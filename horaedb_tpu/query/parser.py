"""SQL tokenizer + recursive-descent parser.

Covers the reference's extended SQL dialect (ref: query_frontend/src/
parser.rs:140-363 — standard SQL plus ``TAG`` column modifiers,
``TIMESTAMP KEY``, ``ENGINE = Analytic``, ``WITH (k='v')`` table options,
``PARTITION BY KEY(...) PARTITIONS n``). Hand-rolled because the image has
no SQL parsing library — and the dialect is small enough that a tight
tokenizer + precedence-climbing expression parser is clearer than bending
a general parser around the extensions.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Optional

from . import ast


class ParseError(ValueError):
    def __init__(self, msg: str, pos: int = -1, sql: str = "") -> None:
        ctx = ""
        if sql and pos >= 0:
            ctx = f" near: {sql[max(0, pos - 10):pos + 20]!r}"
        super().__init__(f"{msg}{ctx}")


# ---- tokenizer ---------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>--[^\n]*)
  | (?P<number>\d+(\.\d+)?([eE][+-]?\d+)?)
  | (?P<qident>"[^"]*"|`[^`]*`)
  | (?P<string>'(?:[^']|'')*')
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><=|>=|!=|<>|==|[-+*/%(),.=<>;])
    """,
    re.VERBOSE,
)


class Token:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind: str, text: str, pos: int) -> None:
        self.kind = kind  # number|string|name|op|qident
        self.text = text
        self.pos = pos

    def __repr__(self) -> str:
        return f"Token({self.kind}, {self.text!r})"


def tokenize(sql: str) -> list[Token]:
    out: list[Token] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise ParseError(f"unexpected character {sql[i]!r}", i, sql)
        kind = m.lastgroup
        if kind not in ("ws", "comment"):
            out.append(Token(kind, m.group(), i))
        i = m.end()
    return out


_PRECEDENCE = {
    "OR": 1,
    "AND": 2,
    "=": 4, "!=": 4, "<>": 4, "<": 4, "<=": 4, ">": 4, ">=": 4,
    "+": 5, "-": 5,
    "*": 6, "/": 6, "%": 6,
}


class Parser:
    """One statement per parse() call; parse_many() splits on ';'."""

    def __init__(self, sql: str) -> None:
        self.sql = sql
        self.tokens = tokenize(sql)
        self.i = 0

    # ---- cursor helpers -------------------------------------------------
    def _peek(self) -> Optional[Token]:
        return self.tokens[self.i] if self.i < len(self.tokens) else None

    def _next(self) -> Token:
        t = self._peek()
        if t is None:
            raise ParseError("unexpected end of input", len(self.sql), self.sql)
        self.i += 1
        return t

    def _peek_ahead_is(self, kw: str) -> bool:
        nxt = self.i + 1
        return nxt < len(self.tokens) and self.tokens[nxt].text.upper() == kw

    def _at_kw(self, *kws: str) -> bool:
        t = self._peek()
        return t is not None and t.kind == "name" and t.text.upper() in kws

    def _eat_kw(self, *kws: str) -> bool:
        if self._at_kw(*kws):
            self.i += 1
            return True
        return False

    def _expect_kw(self, kw: str) -> None:
        if not self._eat_kw(kw):
            t = self._peek()
            raise ParseError(
                f"expected {kw}, found {t.text if t else 'end of input'}",
                t.pos if t else len(self.sql),
                self.sql,
            )

    def _at_op(self, op: str) -> bool:
        t = self._peek()
        return t is not None and t.kind == "op" and t.text == op

    def _eat_op(self, op: str) -> bool:
        if self._at_op(op):
            self.i += 1
            return True
        return False

    def _expect_op(self, op: str) -> None:
        if not self._eat_op(op):
            t = self._peek()
            raise ParseError(
                f"expected {op!r}, found {t.text if t else 'end of input'}",
                t.pos if t else len(self.sql),
                self.sql,
            )

    def _ident(self) -> str:
        t = self._next()
        if t.kind == "name":
            return t.text
        if t.kind == "qident":
            return t.text[1:-1]
        raise ParseError(f"expected identifier, found {t.text!r}", t.pos, self.sql)

    # ---- entry points ---------------------------------------------------
    def parse(self) -> ast.Statement:
        stmt = self._statement()
        self._eat_op(";")
        t = self._peek()
        if t is not None:
            raise ParseError(f"unexpected trailing input {t.text!r}", t.pos, self.sql)
        return stmt

    def parse_many(self) -> list[ast.Statement]:
        out = []
        while self._peek() is not None:
            out.append(self._statement())
            if not self._eat_op(";"):
                break
        t = self._peek()
        if t is not None:
            raise ParseError(f"unexpected trailing input {t.text!r}", t.pos, self.sql)
        return out

    # ---- statements ------------------------------------------------------
    def _statement(self) -> ast.Statement:
        if self._peek() is None:
            raise ParseError("empty statement", 0, self.sql)
        if self._at_kw("EXPLAIN"):
            self.i += 1
            analyze = self._eat_kw("ANALYZE")
            if self._at_kw("WITH"):
                t = self._peek()
                raise ParseError(
                    "EXPLAIN over WITH is not supported; EXPLAIN the "
                    "outer statement against materialized tables instead",
                    t.pos, self.sql,
                )
            inner = self._select_or_union()
            if analyze and isinstance(inner, ast.UnionSelect):
                t = self._peek()
                raise ParseError(
                    "EXPLAIN ANALYZE over UNION is not supported",
                    t.pos if t else -1, self.sql,
                )
            return ast.Explain(inner, analyze=analyze)
        if self._at_kw("WITH"):
            return self._with_statement()
        if self._at_kw("SELECT"):
            return self._select_or_union()
        if self._at_kw("CREATE"):
            return self._create_table()
        if self._at_kw("INSERT"):
            return self._insert()
        if self._at_kw("DROP"):
            return self._drop()
        if self._at_kw("DESCRIBE", "DESC"):
            self.i += 1
            self._eat_kw("TABLE")
            return ast.Describe(self._ident())
        if self._at_kw("SHOW"):
            return self._show()
        if self._at_kw("EXISTS"):
            self.i += 1
            self._eat_kw("TABLE")
            return ast.ExistsTable(self._ident())
        if self._at_kw("ALTER"):
            return self._alter()
        if self._at_kw("KILL"):
            # KILL [QUERY] <id> — cooperative cancellation; the id comes
            # from system.public.queries (utils/deadline registry)
            self.i += 1
            self._eat_kw("QUERY")
            t = self._next()
            if t.kind != "number" or "." in t.text:
                raise ParseError(
                    "KILL QUERY expects an integer query id", t.pos, self.sql
                )
            return ast.KillQuery(int(t.text))
        t = self._peek()
        raise ParseError(f"unsupported statement start {t.text!r}", t.pos, self.sql)

    def _with_statement(self) -> ast.Statement:
        """WITH a AS (select), b AS (select) <select-or-union> — each cte
        body may itself be a union; later ctes may reference earlier ones
        (resolved by the interpreter's overlay)."""
        self._expect_kw("WITH")
        ctes: list[tuple[str, ast.Select | ast.UnionSelect]] = []
        while True:
            name = self._ident()
            self._expect_kw("AS")
            self._expect_op("(")
            body = self._select_or_union()
            self._expect_op(")")
            ctes.append((name, body))
            if not self._eat_op(","):
                break
        outer = self._select_or_union()
        return dataclasses.replace(outer, ctes=tuple(ctes))

    def _select_or_union(self) -> ast.Select | ast.UnionSelect:
        """SELECT ... [UNION [ALL] SELECT ...]*; a trailing ORDER BY/LIMIT
        (which ``_select`` greedily attaches to the last branch — the only
        place SQL allows them un-parenthesized) lifts to the union."""
        first = self._select()
        if not self._at_kw("UNION"):
            return first
        selects = [first]
        all_flags: list[bool] = []
        while self._eat_kw("UNION"):
            branch_all = bool(self._eat_kw("ALL"))
            self._eat_kw("DISTINCT")
            all_flags.append(branch_all)
            selects.append(self._select())
        last = selects[-1]
        order_by, limit, offset = last.order_by, last.limit, last.offset
        if order_by or limit is not None or offset:
            selects[-1] = dataclasses.replace(
                last, order_by=(), limit=None, offset=0
            )
        n_cols = {len(s.items) for s in selects}
        if len(n_cols) > 1 and not any(
            isinstance(i.expr, ast.Star) for s in selects for i in s.items
        ):
            raise ParseError("UNION branches have different column counts", -1, self.sql)
        return ast.UnionSelect(
            selects=tuple(selects),
            all_flags=tuple(all_flags),
            order_by=order_by,
            limit=limit,
            offset=offset,
        )

    def _select(self) -> ast.Select:
        self._expect_kw("SELECT")
        distinct = bool(self._eat_kw("DISTINCT"))
        items = [self._select_item()]
        while self._eat_op(","):
            items.append(self._select_item())
        table = None
        joins: list[ast.Join] = []
        if self._eat_kw("FROM"):
            table = self._table_name()
            prev_tables = [table]
            while True:
                if self._eat_kw("INNER"):
                    self._expect_kw("JOIN")
                    kind = "inner"
                elif self._eat_kw("LEFT"):
                    self._eat_kw("OUTER")
                    self._expect_kw("JOIN")
                    kind = "left"
                elif self._eat_kw("RIGHT"):
                    self._eat_kw("OUTER")
                    self._expect_kw("JOIN")
                    kind = "right"
                elif self._eat_kw("FULL"):
                    self._eat_kw("OUTER")
                    self._expect_kw("JOIN")
                    kind = "full"
                elif self._eat_kw("JOIN"):
                    kind = "inner"
                else:
                    break
                j = self._join_clause(prev_tables, kind=kind)
                joins.append(j)
                prev_tables.append(j.table)
        where = None
        if self._eat_kw("WHERE"):
            where = self._expr()
        group_by: tuple = ()
        having = None
        if self._eat_kw("GROUP"):
            self._expect_kw("BY")
            gb = [self._expr()]
            while self._eat_op(","):
                gb.append(self._expr())
            group_by = tuple(gb)
        if self._eat_kw("HAVING"):
            having = self._expr()
        order_by: list[ast.OrderItem] = []
        if self._eat_kw("ORDER"):
            self._expect_kw("BY")
            while True:
                e = self._expr()
                asc = True
                if self._eat_kw("DESC"):
                    asc = False
                elif self._eat_kw("ASC"):
                    pass
                nulls_last = None
                if self._eat_kw("NULLS"):
                    if self._eat_kw("LAST"):
                        nulls_last = True
                    elif self._eat_kw("FIRST"):
                        nulls_last = False
                    else:
                        t = self._peek()
                        raise ParseError(
                            "expected FIRST or LAST after NULLS",
                            t.pos if t else -1, self.sql,
                        )
                order_by.append(ast.OrderItem(e, asc, nulls_last))
                if not self._eat_op(","):
                    break
        limit = None
        if self._eat_kw("LIMIT"):
            t = self._next()
            if t.kind != "number":
                raise ParseError("LIMIT expects a number", t.pos, self.sql)
            limit = int(t.text)
        offset = 0
        if self._eat_kw("OFFSET"):
            t = self._next()
            if t.kind != "number":
                raise ParseError("OFFSET expects a number", t.pos, self.sql)
            offset = int(t.text)
        return ast.Select(
            items=tuple(items),
            table=table,
            where=where,
            group_by=group_by,
            order_by=tuple(order_by),
            limit=limit,
            offset=offset,
            having=having,
            distinct=distinct,
            join=joins[0] if joins else None,
            joins=tuple(joins[1:]),
        )

    def _join_clause(self, prev_tables: list[str], kind: str = "inner") -> ast.Join:
        """JOIN t2 ON a.k1 = b.k1 [AND a.k2 = b.k2 ...] — equi-key join
        (the reference gets richer joins from DataFusion; this is the
        host-path equi-join subset). In a chain the left side of each
        equality may reference ANY earlier table."""
        right = self._table_name()
        self._expect_kw("ON")
        left_cols: list[str] = []
        right_cols: list[str] = []

        def names_table(tab: Optional[str], full: str) -> bool:
            """ON qualifiers may use the full dotted name or its last
            component (JOIN public.t2 ... ON t1.k = t2.k)."""
            return tab is None or tab == full or tab == full.rsplit(".", 1)[-1]

        def names_any_prev(tab: Optional[str]) -> bool:
            return any(names_table(tab, p) for p in prev_tables)

        while True:
            l_tab, l_col = self._qualified()
            self._expect_op("=")
            r_tab, r_col = self._qualified()
            # normalize sides: an earlier table's column first
            if (l_tab is not None and names_table(l_tab, right)
                    and r_tab is not None and names_any_prev(r_tab)):
                l_col, r_col = r_col, l_col
            elif not (names_any_prev(l_tab) and names_table(r_tab, right)):
                raise ParseError(
                    f"JOIN ON must reference an earlier table "
                    f"({', '.join(prev_tables)}) and {right}", -1, self.sql
                )
            left_cols.append(l_col)
            right_cols.append(r_col)
            if not self._eat_kw("AND"):
                break
        return ast.Join(right, tuple(left_cols), tuple(right_cols), kind=kind)

    def _table_name(self) -> str:
        """A possibly-qualified table reference. Qualified names
        (system.public.tables — the system catalog's virtual tables,
        ref: system_catalog/src/tables.rs; or public.demo) join into one
        dotted identifier; regular tables stay single-part. Shared by
        FROM and JOIN targets."""
        name = self._ident()
        while self._eat_op("."):
            name = f"{name}.{self._ident()}"
        return name

    def _qualified(self) -> tuple[Optional[str], str]:
        name = self._ident()
        if self._eat_op("."):
            return name, self._ident()
        return None, name

    def _select_item(self) -> ast.SelectItem:
        if self._at_op("*"):
            self.i += 1
            return ast.SelectItem(ast.Star())
        e = self._expr()
        alias = None
        if self._eat_kw("AS"):
            alias = self._ident()
        elif (t := self._peek()) is not None and t.kind in ("name", "qident") and t.text.upper() not in (
            "FROM", "WHERE", "GROUP", "ORDER", "LIMIT", "AS",
            "HAVING", "JOIN", "INNER", "ON", "LEFT", "OUTER",
            "RIGHT", "FULL", "UNION", "OVER",
        ):
            alias = self._ident()
        return ast.SelectItem(e, alias)

    def _create_table(self) -> ast.CreateTable:
        self._expect_kw("CREATE")
        self._expect_kw("TABLE")
        if_not_exists = False
        if self._eat_kw("IF"):
            self._expect_kw("NOT")
            self._expect_kw("EXISTS")
            if_not_exists = True
        name = self._ident()
        self._expect_op("(")
        columns: list[ast.ColumnDef] = []
        timestamp_key: Optional[str] = None
        primary_key: Optional[tuple[str, ...]] = None
        while True:
            if self._at_kw("TIMESTAMP") and self._peek_ahead_is("KEY"):
                self.i += 2
                self._expect_op("(")
                timestamp_key = self._ident()
                self._expect_op(")")
            elif self._at_kw("PRIMARY"):
                self.i += 1
                self._expect_kw("KEY")
                self._expect_op("(")
                pk = [self._ident()]
                while self._eat_op(","):
                    pk.append(self._ident())
                self._expect_op(")")
                primary_key = tuple(pk)
            else:
                columns.append(self._column_def())
                if columns[-1].is_timestamp_key:
                    timestamp_key = columns[-1].name
            if not self._eat_op(","):
                break
        self._expect_op(")")
        engine = "Analytic"
        partition_by = None
        options: dict[str, str] = {}
        while True:
            if self._eat_kw("ENGINE"):
                self._expect_op("=")
                engine = self._ident()
            elif self._at_kw("PARTITION"):
                partition_by = self._partition_by()
            elif self._eat_kw("WITH"):
                self._expect_op("(")
                while True:
                    k = self._ident()
                    self._expect_op("=")
                    v = self._next()
                    options[k] = v.text[1:-1].replace("''", "'") if v.kind == "string" else v.text
                    if not self._eat_op(","):
                        break
                self._expect_op(")")
            else:
                break
        return ast.CreateTable(
            table=name,
            columns=tuple(columns),
            timestamp_key=timestamp_key,
            primary_key=primary_key,
            engine=engine,
            options=options,
            if_not_exists=if_not_exists,
            partition_by=partition_by,
        )

    def _partition_by(self) -> ast.PartitionBy:
        self._expect_kw("PARTITION")
        self._expect_kw("BY")
        method = self._ident().lower()
        if method not in ("key", "hash"):
            raise ParseError(f"unsupported partition method {method!r}")
        self._expect_op("(")
        cols = [self._ident()]
        while self._eat_op(","):
            cols.append(self._ident())
        self._expect_op(")")
        self._expect_kw("PARTITIONS")
        t = self._next()
        if t.kind != "number":
            raise ParseError("PARTITIONS expects a number", t.pos, self.sql)
        return ast.PartitionBy(method, tuple(cols), int(t.text))

    def _column_def(self) -> ast.ColumnDef:
        name = self._ident()
        type_name = self._ident()
        is_tag = False
        is_ts_key = False
        not_null = False
        comment = ""
        while True:
            if self._eat_kw("TAG"):
                is_tag = True
            elif self._eat_kw("KEY"):
                is_ts_key = True
            elif self._at_kw("TIMESTAMP") and self._peek_ahead_is("KEY"):
                self.i += 2
                is_ts_key = True
            elif self._eat_kw("NOT"):
                self._expect_kw("NULL")
                not_null = True
            elif self._eat_kw("NULL"):
                pass
            elif self._eat_kw("COMMENT"):
                t = self._next()
                if t.kind != "string":
                    raise ParseError("COMMENT expects a string", t.pos, self.sql)
                comment = t.text[1:-1].replace("''", "'")
            else:
                break
        return ast.ColumnDef(name, type_name, is_tag, is_ts_key, not_null, comment)

    def _insert(self) -> ast.Insert:
        self._expect_kw("INSERT")
        self._expect_kw("INTO")
        table = self._ident()
        columns: tuple[str, ...] = ()
        if self._eat_op("("):
            cols = [self._ident()]
            while self._eat_op(","):
                cols.append(self._ident())
            self._expect_op(")")
            columns = tuple(cols)
        self._expect_kw("VALUES")
        rows = []
        while True:
            self._expect_op("(")
            vals = [self._literal_value()]
            while self._eat_op(","):
                vals.append(self._literal_value())
            self._expect_op(")")
            rows.append(tuple(vals))
            if not self._eat_op(","):
                break
        return ast.Insert(table, columns, tuple(rows))

    def _literal_value(self) -> Any:
        e = self._expr()
        return _fold_literal(e, self.sql)

    def _drop(self) -> ast.DropTable:
        self._expect_kw("DROP")
        self._expect_kw("TABLE")
        if_exists = False
        if self._eat_kw("IF"):
            self._expect_kw("EXISTS")
            if_exists = True
        return ast.DropTable(self._ident(), if_exists)

    def _show(self) -> ast.Statement:
        self._expect_kw("SHOW")
        if self._eat_kw("TABLES"):
            return ast.ShowTables()
        if self._eat_kw("CREATE"):
            self._expect_kw("TABLE")
            return ast.ShowCreateTable(self._ident())
        t = self._peek()
        raise ParseError(
            f"unsupported SHOW {t.text if t else ''}", t.pos if t else -1, self.sql
        )

    def _alter(self) -> ast.Statement:
        self._expect_kw("ALTER")
        self._expect_kw("TABLE")
        table = self._ident()
        if self._eat_kw("ADD"):
            self._eat_kw("COLUMN")
            cols = [self._column_def()]
            while self._eat_op(","):
                self._eat_kw("COLUMN")
                cols.append(self._column_def())
            return ast.AlterTableAddColumn(table, tuple(cols))
        if self._eat_kw("MODIFY"):
            self._expect_kw("SETTING")
            opts: dict[str, str] = {}
            while True:
                k = self._ident()
                self._expect_op("=")
                v = self._next()
                opts[k] = v.text[1:-1].replace("''", "'") if v.kind == "string" else v.text
                if not self._eat_op(","):
                    break
            return ast.AlterTableSetOptions(table, opts)
        t = self._peek()
        raise ParseError(
            f"unsupported ALTER action {t.text if t else ''}", t.pos if t else -1, self.sql
        )

    # ---- expressions ------------------------------------------------------
    def _expr(self, min_prec: int = 0) -> ast.Expr:
        left = self._unary()
        while True:
            t = self._peek()
            if t is None:
                return left
            op = t.text.upper() if t.kind == "name" else t.text
            # NOT IN / NOT BETWEEN / IS [NOT] NULL / IN / BETWEEN / [NOT] [I]LIKE
            if t.kind == "name" and op in ("IN", "BETWEEN", "IS", "NOT", "LIKE", "ILIKE"):
                left = self._postfix_predicate(left)
                continue
            prec = _PRECEDENCE.get(op)
            if prec is None or prec < min_prec:
                return left
            self.i += 1
            if op == "<>":
                op = "!="
            right = self._expr(prec + 1)
            left = ast.BinaryOp(op, left, right)

    def _postfix_predicate(self, left: ast.Expr) -> ast.Expr:
        negated = self._eat_kw("NOT")
        if self._eat_kw("IN"):
            self._expect_op("(")
            if self._at_kw("SELECT"):
                inner = self._select()
                self._expect_op(")")
                return ast.InSubquery(left, inner, negated)
            vals = [self._expr()]
            while self._eat_op(","):
                vals.append(self._expr())
            self._expect_op(")")
            return ast.InList(left, tuple(vals), negated)
        if self._eat_kw("BETWEEN"):
            low = self._expr(_PRECEDENCE["AND"] + 1)
            self._expect_kw("AND")
            high = self._expr(_PRECEDENCE["AND"] + 1)
            return ast.Between(left, low, high, negated)
        for kw, ci in (("LIKE", False), ("ILIKE", True)):
            if self._eat_kw(kw):
                t = self._next()
                if t.kind != "string":
                    raise ParseError(
                        f"{kw} expects a string pattern", t.pos, self.sql
                    )
                pattern = t.text[1:-1].replace("''", "'")
                return ast.Like(left, pattern, negated, case_insensitive=ci)
        if not negated and self._eat_kw("IS"):
            neg = self._eat_kw("NOT")
            self._expect_kw("NULL")
            return ast.IsNull(left, neg)
        t = self._peek()
        raise ParseError(
            f"unexpected token {t.text if t else ''}", t.pos if t else -1, self.sql
        )

    def _unary(self) -> ast.Expr:
        if self._eat_kw("NOT"):
            return ast.UnaryOp("NOT", self._unary())
        if self._eat_op("-"):
            inner = self._unary()
            # Fold negative number literals so every downstream consumer
            # (predicate extraction, residual filters) sees plain Literals.
            if isinstance(inner, ast.Literal) and isinstance(inner.value, (int, float)):
                return ast.Literal(-inner.value)
            return ast.UnaryOp("-", inner)
        if self._eat_op("+"):
            return self._unary()
        return self._primary()

    def _primary(self) -> ast.Expr:
        t = self._next()
        if t.kind == "number":
            text = t.text
            if "." in text or "e" in text or "E" in text:
                return ast.Literal(float(text))
            return ast.Literal(int(text))
        if t.kind == "string":
            return ast.Literal(t.text[1:-1].replace("''", "'"))
        if t.kind == "op" and t.text == "(":
            if self._at_kw("SELECT"):
                # scalar subquery: (SELECT max(v) FROM t) — must be
                # uncorrelated; the interpreter evaluates it first
                inner = self._select()
                self._expect_op(")")
                return ast.Subquery(inner)
            e = self._expr()
            self._expect_op(")")
            return e
        if t.kind == "op" and t.text == "*":
            return ast.Star()
        if t.kind in ("name", "qident"):
            upper = t.text.upper()
            if upper == "TRUE":
                return ast.Literal(True)
            if upper == "FALSE":
                return ast.Literal(False)
            if upper == "NULL":
                return ast.Literal(None)
            if upper == "EXISTS" and self._at_op("("):
                # EXISTS (SELECT ...): semi-join probe; NOT EXISTS arrives
                # via _unary's NOT wrapping.
                self._expect_op("(")
                inner = self._select()
                self._expect_op(")")
                return ast.Exists(inner)
            if upper == "CASE":
                return self._case()
            if upper == "CAST" and self._at_op("("):
                self.i += 1
                inner = self._expr()
                self._expect_kw("AS")
                ty = self._next()
                if ty.kind != "name":
                    raise ParseError("CAST expects a type name", ty.pos, self.sql)
                self._expect_op(")")
                return ast.Cast(inner, ty.text.lower())
            name = t.text if t.kind == "name" else t.text[1:-1]
            if self._at_op("("):
                self.i += 1
                distinct = self._eat_kw("DISTINCT")
                args: list[ast.Expr] = []
                if not self._at_op(")"):
                    args.append(self._expr())
                    while self._eat_op(","):
                        args.append(self._expr())
                self._expect_op(")")
                call = ast.FuncCall(name.lower(), tuple(args), distinct)
                if self._eat_kw("FILTER"):
                    # standard SQL: agg(col) FILTER (WHERE cond)
                    self._expect_op("(")
                    self._expect_kw("WHERE")
                    cond = self._expr()
                    self._expect_op(")")
                    call = ast.FuncCall(
                        call.name, call.args, call.distinct, filter_where=cond
                    )
                if self._eat_kw("OVER"):
                    return self._window(call)
                return call
            if self._at_op("."):
                # qualified column (t.col) — resolution is by column name;
                # the planner validates the qualifier
                self.i += 1
                return ast.Column(self._ident(), qualifier=name)
            return ast.Column(name)
        raise ParseError(f"unexpected token {t.text!r}", t.pos, self.sql)

    def _case(self) -> ast.Case:
        """CASE [operand] WHEN w THEN t ... [ELSE e] END; the simple form
        (with operand) normalizes to searched conditions (operand = w)."""
        operand = None
        if not self._at_kw("WHEN"):
            operand = self._expr()
        whens: list[tuple[ast.Expr, ast.Expr]] = []
        while self._eat_kw("WHEN"):
            w = self._expr()
            self._expect_kw("THEN")
            t = self._expr()
            if operand is not None:
                w = ast.BinaryOp("=", operand, w)
            whens.append((w, t))
        if not whens:
            tk = self._peek()
            raise ParseError("CASE requires at least one WHEN", tk.pos if tk else -1, self.sql)
        else_ = None
        if self._eat_kw("ELSE"):
            else_ = self._expr()
        self._expect_kw("END")
        return ast.Case(tuple(whens), else_)

    def _window(self, call: ast.FuncCall) -> ast.WindowFunc:
        """fn(...) OVER ( [PARTITION BY e, ...] [ORDER BY e [ASC|DESC], ...] )"""
        if call.distinct:
            raise ParseError("DISTINCT is not allowed in window functions", -1, self.sql)
        if call.filter_where is not None:
            raise ParseError(
                "FILTER is not supported with window functions", -1, self.sql
            )
        self._expect_op("(")
        partition_by: list[ast.Expr] = []
        order_by: list[ast.OrderItem] = []
        if self._eat_kw("PARTITION"):
            self._expect_kw("BY")
            partition_by.append(self._expr())
            while self._eat_op(","):
                partition_by.append(self._expr())
        if self._eat_kw("ORDER"):
            self._expect_kw("BY")
            while True:
                e = self._expr()
                asc = True
                if self._eat_kw("DESC"):
                    asc = False
                elif self._eat_kw("ASC"):
                    pass
                order_by.append(ast.OrderItem(e, asc))
                if not self._eat_op(","):
                    break
        self._expect_op(")")
        return ast.WindowFunc(
            call.name, call.args,
            ast.WindowSpec(tuple(partition_by), tuple(order_by)),
        )


def _fold_literal(e: ast.Expr, sql: str) -> Any:
    """INSERT values must be constants; folds unary minus."""
    if isinstance(e, ast.Literal):
        return e.value
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        v = _fold_literal(e.operand, sql)
        return -v
    raise ParseError(f"expected literal in VALUES, found {e}", -1, sql)


def parse_sql(sql: str) -> ast.Statement:
    return Parser(sql).parse()


def parse_many(sql: str) -> list[ast.Statement]:
    return Parser(sql).parse_many()
