"""Partial aggregation push-down — the distributed query step
(ref: df_engine_extensions/src/dist_sql_query/resolver.rs:76-120 — filter,
projection, and PARTIAL aggregation pushed below the scan to the node that
owns each partition; the coordinator runs only the final combine).

The unit shipped to a partition owner is an ``AggSpecWire`` dict (what the
reference encodes as a protobuf physical subplan): predicate + exact
filters + group tags + time bucket + aggregated columns + device-numeric
filters. The owner scans ONLY its own data, runs the fused scan/agg
kernel (or a NULL-aware host fallback), and returns a tiny partial batch:

    key_0..key_k | __bucket | __count_rows | per field: __count/__sum/__min/__max

Partials from all partitions combine with the aggregation monoid — the
same (count,sum,min,max) algebra the mesh collectives use, so partition
parallelism (DCN) and mesh parallelism (ICI) are the SAME reduction at
different radii.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common_types.dict_column import as_values, unique_inverse
from ..common_types.row_group import RowGroup
from ..common_types.time_range import MAX_TIMESTAMP, MIN_TIMESTAMP
from ..ops import ScanAggSpec, encode_group_codes, scan_aggregate
from ..ops.encoding import build_padded_batch, time_buckets
from ..table_engine.predicate import ColumnFilter, FilterOp, Predicate
from ..remote.codec import predicate_from_dict, predicate_to_dict
from .executor import ResultSet, _plan_needs_minmax
from .plan import QueryPlan

from ..table_engine.predicate import NUMPY_CMP as _CMP


def spec_from_plan(executor, plan: QueryPlan) -> Optional[dict]:
    """AggSpecWire for a pushable aggregate plan, else None.

    Pushable = the device kernel shape fits AND every residual conjunct is
    a simple ``col op literal`` (numeric ones run in the kernel, the rest
    as exact vectorized filters on the owner).
    """
    if not plan.is_aggregate:
        return None
    shape = executor._agg_device_shape(plan)
    if shape is None:
        return None
    tag_keys, bucket_key, agg_cols = shape
    from .planner import _as_simple_cmp

    device_filters, other = executor._split_residual_filters(plan)
    exact_filters: list[list] = []
    for conj in other:
        simple = _as_simple_cmp(conj)
        if simple is None or not plan.schema.has_column(simple[0]):
            return None
        exact_filters.append([simple[0], simple[1], simple[2]])
    return {
        "predicate": predicate_to_dict(plan.predicate),
        "exact_filters": exact_filters,
        "device_filters": [[c, op, float(lit)] for c, op, lit in device_filters],
        "group_tags": [k.column for k in tag_keys],
        "bucket_ms": bucket_key.time_bucket_ms if bucket_key is not None else 0,
        "agg_cols": agg_cols,
        # optional (older peers omit it -> treated as True by consumers)
        "need_minmax": _plan_needs_minmax(plan),
    }


def compute_partial(
    table, spec: dict, m: Optional[dict] = None
) -> tuple[list[str], list[np.ndarray]]:
    """Run the pushed-down partial aggregate against one table/partition.

    Runs wherever the data lives: the executor calls it for local
    partitions, the remote-engine service for shipped ones. ``m`` (when
    given) collects sub-stage spans — scan time, rows scanned, kernel vs
    host path — that ride home to the coordinator's EXPLAIN ANALYZE tree
    (ref: RemoteTaskContext.remote_metrics).
    """
    import time as _time

    pred = predicate_from_dict(spec["predicate"])
    group_tags = list(spec["group_tags"])
    agg_cols = list(spec["agg_cols"])
    bucket_ms = int(spec["bucket_ms"])
    filter_cols = [c for c, _, _ in spec["device_filters"]]
    exact_cols = [c for c, _, _ in spec["exact_filters"]]
    schema = table.schema
    projection = list(
        dict.fromkeys(
            [schema.timestamp_name]
            + ([schema.columns[schema.tsid_index].name] if schema.tsid_index is not None else [])
            + group_tags + agg_cols + filter_cols + exact_cols
        )
    )
    # Memory bound (ref: instance/read.rs:165-190 — the reference streams
    # N record-batch streams instead of one array): when the pruned file
    # metadata says the scan would materialize more than the cap, iterate
    # per-segment-window pieces and CONCATENATE their partial batches —
    # the caller's single monoid combine treats windows exactly like
    # extra partitions, and the whole table never sits in host memory.
    cap_bytes = _agg_memory_cap_bytes()
    # "bounded_hint": the LOCAL executor already walked this table's
    # metadata and decided (plain-table path only — partition scatters
    # leave it unset so each owner estimates its own data).
    if cap_bytes and (
        spec.get("bounded_hint")
        or _scan_estimate_bytes(table, pred, projection) > cap_bytes
    ):
        from ..utils.tracectx import span

        all_names: list[str] | None = None
        parts: list[list[np.ndarray]] = []
        windows = 0
        t_scan = _time.perf_counter()
        rows_seen = 0
        from ..utils.deadline import checkpoint as _deadline_checkpoint

        with span("partial_windowed", table=table.name) as sp:
            for rows in table.read_windows(pred, projection=projection):
                # per-window checkpoint: a long bounded aggregate is
                # exactly the shape a KILL / tight budget must be able
                # to stop mid-flight (the host-fallback chunk loop)
                _deadline_checkpoint("executing")
                windows += 1
                rows_seen += len(rows)
                names, arrays = _partial_on_rows(rows, spec)
                if arrays and len(arrays[0]):
                    all_names = names
                    parts.append(arrays)
            sp.set(windows=windows, rows=rows_seen)
        from ..utils.querystats import record as _qs_record

        _qs_record(scan_rows=rows_seen)
        if m is not None:
            m["scan_ms"] = round((_time.perf_counter() - t_scan) * 1000, 3)
            m["rows_scanned"] = rows_seen
            m["bounded_windows"] = windows
            m["path"] = "kernel-windowed"
        if all_names is None:
            return _partial_on_rows(
                _empty_projected(table, projection), spec
            )
        return all_names, [
            np.concatenate([p[i] for p in parts])
            for i in range(len(all_names))
        ]

    from ..utils.tracectx import span

    t_scan = _time.perf_counter()
    with span("scan", table=table.name) as sp:
        rows = table.read(pred, projection=projection)
        sp.set(rows=len(rows))
    from ..utils.querystats import record as _qs_record

    _qs_record(scan_rows=len(rows))
    if m is not None:
        m["scan_ms"] = round((_time.perf_counter() - t_scan) * 1000, 3)
        m["rows_scanned"] = len(rows)

    t_agg = _time.perf_counter()
    with span("partial") as sp:
        out = _partial_on_rows(rows, spec, m)
        if m is not None and "path" in m:
            sp.set(path=m["path"])
    if m is not None:
        m["agg_ms"] = round((_time.perf_counter() - t_agg) * 1000, 3)
    return out


def _partial_on_rows(
    rows: RowGroup, spec: dict, m: Optional[dict] = None
) -> tuple[list[str], list[np.ndarray]]:
    """The partial aggregate over an already-materialized row set — the
    shared core of the whole-table and per-window (memory-bounded)
    paths. Bucket origins are absolute-aligned (floor to bucket_ms), so
    batches from different windows combine on equal "__bucket" values."""
    agg_cols = list(spec["agg_cols"])
    bucket_ms = int(spec["bucket_ms"])
    n = len(rows)
    mask = np.ones(n, dtype=bool)
    for c, op, v in spec["exact_filters"]:
        col = rows.columns[c]
        valid = rows.valid_mask(c)
        from ..common_types.dict_column import DictColumn

        if isinstance(col, DictColumn):
            hit = col.map_values(lambda vals: _CMP[op](vals, v))
        else:
            hit = _CMP[op](col, v)
        mask &= np.asarray(hit).astype(bool) & valid

    # Exact predicate tag/key filters were already folded into
    # exact_filters by the planner's residual; predicate.filters here only
    # drove pruning. Aggregate inputs:
    all_valid = all(rows.valid_mask(c).all() for c in agg_cols)
    ts = rows.timestamps
    if bucket_ms:
        t0 = int((int(ts.min()) // bucket_ms) * bucket_ms) if n else 0
    else:
        t0 = 0
    if m is not None:
        m["path"] = "kernel" if all_valid else "host"
    if all_valid:
        return _partial_kernel(rows, mask, spec, t0, m)
    return _partial_host(rows, mask, spec, t0)


import functools


@functools.lru_cache(maxsize=None)
def _default_budget_mb(floor_mb: int = 1024) -> int:
    """Default memory budgets scale with the machine: a quarter of
    physical RAM, never below ``floor_mb`` (a 125GB box should not
    refuse a 3GB scan the way a 4GB edge node must)."""
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return max(floor_mb, int(line.split()[1]) // 1024 // 4)
    except OSError:
        pass
    return floor_mb


def _budget_bytes(env_name: str) -> int:
    """Env-or-RAM/4 byte budget (fractional MB allowed; 0 disables) —
    the ONE parse both memory knobs share."""
    import os

    raw = os.environ.get(env_name)
    if raw is None:
        return _default_budget_mb() << 20
    return int(float(raw) * (1 << 20))


def _agg_memory_cap_bytes() -> int:
    """HORAEDB_AGG_MEMORY_MB: cap on the host working set one aggregate
    scan may materialize (0 disables bounding; fractions allowed;
    default: a quarter of physical RAM, min 1GB)."""
    return _budget_bytes("HORAEDB_AGG_MEMORY_MB")


def _scan_estimate_bytes(table, pred, projection) -> int:
    """Pre-read size estimate from pruned SST metadata + memtable bytes
    — no data touched."""
    tr = pred.time_range
    total_rows = 0
    mem_bytes = 0
    n_cols = (
        len(projection)
        if projection is not None
        else len(table.schema.columns)
    )
    for data in table.physical_datas():
        for h in data.version.levels.all_files():
            ftr = h.meta.time_range
            if ftr.inclusive_start < tr.exclusive_end and tr.inclusive_start < ftr.exclusive_end:
                total_rows += h.meta.num_rows
        for mem in [*data.version.immutables(), data.version.mutable]:
            mem_bytes += mem.approx_bytes  # property on both kinds
    return total_rows * 8 * n_cols + mem_bytes


def _empty_projected(table, projection) -> RowGroup:
    from ..common_types.schema import project_schema

    schema = project_schema(table.schema, projection)
    return RowGroup(
        schema,
        {c.name: np.empty(0, dtype=c.kind.numpy_dtype) for c in schema.columns},
    )


def _partial_kernel(
    rows, mask, spec, t0, m: Optional[dict] = None
) -> tuple[list[str], list[np.ndarray]]:
    group_tags = list(spec["group_tags"])
    agg_cols = list(spec["agg_cols"])
    bucket_ms = int(spec["bucket_ms"])
    n = len(rows)
    enc = encode_group_codes(rows, group_tags)
    if bucket_ms and n:
        bucket_ids, n_buckets = time_buckets(rows.timestamps, t0, bucket_ms)
    else:
        bucket_ids, n_buckets = np.zeros(n, dtype=np.int32), 1
    filter_cols = [c for c, _, _ in spec["device_filters"]]
    value_names = list(dict.fromkeys(agg_cols + filter_cols))
    batch = build_padded_batch(
        enc.codes, bucket_ids, mask, [rows.column(c) for c in value_names]
    )
    kspec = ScanAggSpec(
        n_groups=max(enc.num_groups, 1),
        n_buckets=n_buckets,
        n_agg_fields=len(agg_cols),
        numeric_filters=tuple(
            (value_names.index(c), op) for c, op, _ in spec["device_filters"]
        ),
        need_minmax=bool(spec.get("need_minmax", True)),
    ).padded()

    # Learned segment-impl choice (ROADMAP item-3 remainder): the
    # partial path rode the static HORAEDB_MXU_MAX_SEGMENTS heuristic
    # long after the direct/cached/dist paths got the router. Keyed by
    # the WIRE spec's shape (what the owner actually executes — the
    # coordinator's plan never reaches this side of the RPC); group
    # codes are dense here, so groups x buckets is an exact ceiling.
    from .executor import finish_segment_kernel, route_segment_kernel

    shape_key = (
        "partial",
        tuple(group_tags),
        bucket_ms,
        tuple(agg_cols),
        tuple((c, op) for c, op, _ in spec["device_filters"]),
        tuple((c, op) for c, op, _ in spec["exact_filters"]),
    )
    kspec, krec = route_segment_kernel(
        shape_key, kspec, n_rows=batch.n_valid,
        est_distinct=max(enc.num_groups, 1) * n_buckets,
    )

    import time as _time

    from ..parallel.mesh import dist_min_rows, serving_mesh

    mesh = serving_mesh()
    t_kernel = _time.perf_counter()
    if mesh is not None and batch.n_valid >= dist_min_rows():
        from ..parallel.dist_agg import dist_scan_aggregate

        state = dist_scan_aggregate(
            mesh, batch, kspec, [lit for _, _, lit in spec["device_filters"]]
        )
    else:
        state = scan_aggregate(batch, kspec, [lit for _, _, lit in spec["device_filters"]])
    finish_segment_kernel(
        krec, kspec, m if m is not None else {}, state,
        _time.perf_counter() - t_kernel, n_valid=batch.n_valid,
    )

    G, B = max(enc.num_groups, 1), n_buckets
    counts = state.counts[:G, :B]
    live_g, live_b = np.nonzero(counts > 0)
    names = [f"__k{i}" for i in range(len(group_tags))] + ["__bucket", "__count_rows"]
    arrays: list[np.ndarray] = [
        np.asarray(enc.key_values[i])[live_g] for i in range(len(group_tags))
    ]
    arrays.append(t0 + live_b.astype(np.int64) * (bucket_ms or 1))
    arrays.append(counts[live_g, live_b].astype(np.int64))
    need_minmax = bool(spec.get("need_minmax", True))
    n_live = len(live_g)
    for fi, _col in enumerate(agg_cols):
        names += [f"__count_{fi}", f"__sum_{fi}", f"__min_{fi}", f"__max_{fi}"]
        arrays += [
            counts[live_g, live_b].astype(np.int64),  # full validity ⇒ same
            state.sums[fi, :G, :B][live_g, live_b],
            # identity elements when the kernel skipped min/max: the
            # monoid fold in combine_partials leaves them inert
            state.mins[fi, :G, :B][live_g, live_b]
            if need_minmax else np.full(n_live, np.inf),
            state.maxs[fi, :G, :B][live_g, live_b]
            if need_minmax else np.full(n_live, -np.inf),
        ]
    return names, arrays


def _partial_host(rows, mask, spec, t0) -> tuple[list[str], list[np.ndarray]]:
    """NULL-aware numpy fallback with identical output shape."""
    group_tags = list(spec["group_tags"])
    agg_cols = list(spec["agg_cols"])
    bucket_ms = int(spec["bucket_ms"])
    for c, op, lit in spec["device_filters"]:
        mask &= _CMP[op](as_values(rows.column(c)), lit) & rows.valid_mask(c)
    idx = np.nonzero(mask)[0]
    rows = rows.take(idx)
    n = len(rows)
    key_arrays = [rows.column(c) for c in group_tags]
    if bucket_ms:
        bucket = ((rows.timestamps // bucket_ms) * bucket_ms).astype(np.int64)
    else:
        bucket = np.zeros(n, dtype=np.int64)
    combined = np.zeros(n, dtype=np.int64)
    uniqs = []
    for arr in [*key_arrays, bucket]:
        u, inv = unique_inverse(arr)
        uniqs.append(u)
        combined = combined * (len(u) + 1) + inv
    uc, first, codes = np.unique(combined, return_index=True, return_inverse=True)
    G = len(uc)
    names = [f"__k{i}" for i in range(len(group_tags))] + ["__bucket", "__count_rows"]
    arrays: list[np.ndarray] = [as_values(a[first]) for a in key_arrays]
    arrays.append(bucket[first])
    arrays.append(np.bincount(codes, minlength=G).astype(np.int64))
    for fi, col_name in enumerate(agg_cols):
        v = as_values(rows.column(col_name)).astype(np.float64)
        valid = rows.valid_mask(col_name)
        vv = np.where(valid, v, 0.0)
        cnt = np.bincount(codes, weights=valid.astype(np.float64), minlength=G)
        sums = np.bincount(codes, weights=vv, minlength=G)
        mins = np.full(G, np.inf)
        maxs = np.full(G, -np.inf)
        np.minimum.at(mins, codes[valid], v[valid])
        np.maximum.at(maxs, codes[valid], v[valid])
        names += [f"__count_{fi}", f"__sum_{fi}", f"__min_{fi}", f"__max_{fi}"]
        arrays += [cnt.astype(np.int64), sums, mins, maxs]
    return names, arrays


def combine_partials(
    parts: list[tuple[list[str], list[np.ndarray]]], spec: dict
) -> tuple[dict[str, np.ndarray], int]:
    """Concatenate partial batches and fold the monoid per (keys, bucket)."""
    n_keys = len(spec["group_tags"])
    n_fields = len(spec["agg_cols"])
    parts = [p for p in parts if len(p[1]) and len(p[1][0])]
    if not parts:
        return {}, 0
    by_name = {}
    for names, arrays in parts:
        for nm, arr in zip(names, arrays):
            by_name.setdefault(nm, []).append(arr)
    cat = {nm: np.concatenate(arrs) for nm, arrs in by_name.items()}

    combined = np.zeros(len(cat["__bucket"]), dtype=np.int64)
    uniq_per_key = []
    for i in range(n_keys):
        u, inv = unique_inverse(cat[f"__k{i}"])
        uniq_per_key.append(u)
        combined = combined * (len(u) + 1) + inv
    u, inv = unique_inverse(cat["__bucket"])
    combined = combined * (len(u) + 1) + inv
    uc, first, codes = np.unique(combined, return_index=True, return_inverse=True)
    G = len(uc)
    out: dict[str, np.ndarray] = {}
    for i in range(n_keys):
        out[f"__k{i}"] = as_values(cat[f"__k{i}"][first])
    out["__bucket"] = cat["__bucket"][first]
    out["__count_rows"] = np.bincount(
        codes, weights=cat["__count_rows"].astype(np.float64), minlength=G
    ).astype(np.int64)
    for fi in range(n_fields):
        out[f"__count_{fi}"] = np.bincount(
            codes, weights=cat[f"__count_{fi}"].astype(np.float64), minlength=G
        ).astype(np.int64)
        out[f"__sum_{fi}"] = np.bincount(
            codes, weights=cat[f"__sum_{fi}"], minlength=G
        )
        mins = np.full(G, np.inf)
        maxs = np.full(G, -np.inf)
        np.minimum.at(mins, codes, cat[f"__min_{fi}"])
        np.maximum.at(maxs, codes, cat[f"__max_{fi}"])
        out[f"__min_{fi}"] = mins
        out[f"__max_{fi}"] = maxs
    return out, G


def assemble_result(plan: QueryPlan, combined: dict, n_groups: int, spec: dict) -> ResultSet:
    from . import ast
    from .executor import _empty_ungrouped_agg_row, _order_and_limit

    if n_groups == 0:
        if not plan.group_keys:
            return _order_and_limit(_empty_ungrouped_agg_row(plan), plan)
        names = [item.output_name for item in plan.select.items]
        return _order_and_limit(ResultSet.empty(names), plan)
    group_tags = list(spec["group_tags"])
    agg_cols = list(spec["agg_cols"])

    def agg_column(a) -> tuple[np.ndarray, np.ndarray | None]:
        if a.column is None:  # count(*)
            return combined["__count_rows"], None
        fi = agg_cols.index(a.column)
        cnt = combined[f"__count_{fi}"]
        empty = cnt == 0
        null = empty if empty.any() else None
        if a.func == "count":
            return cnt, None
        if a.func == "sum":
            return combined[f"__sum_{fi}"], null
        if a.func == "avg":
            with np.errstate(divide="ignore", invalid="ignore"):
                return combined[f"__sum_{fi}"] / np.maximum(cnt, 1), null
        if a.func == "min":
            return combined[f"__min_{fi}"], null
        if a.func == "max":
            return combined[f"__max_{fi}"], null
        # unreachable: shape check restricts the func set
        raise ValueError(f"unsupported agg {a.func}")

    names: list[str] = []
    columns: list[np.ndarray] = []
    nulls: dict[str, np.ndarray] = {}
    agg_expr_map = dict(plan.agg_exprs)
    computed = None
    if agg_expr_map:
        from .executor import eval_agg_exprs

        base = {
            tag: (combined[f"__k{ki}"], None)
            for ki, tag in enumerate(group_tags)
        }
        for a in plan.aggs:
            base[a.output_name] = agg_column(a)
        computed = eval_agg_exprs(plan, base)
    for item in plan.select.items:
        out_name = item.output_name
        e = item.expr
        if out_name in agg_expr_map:
            v, nm = computed[out_name]
            columns.append(v)
            if nm is not None:
                nulls[out_name] = nm
        elif isinstance(e, ast.Column):
            ki = group_tags.index(e.name)
            columns.append(combined[f"__k{ki}"])
        elif isinstance(e, ast.FuncCall) and e.name in ("time_bucket", "date_trunc"):
            columns.append(combined["__bucket"])
        else:
            agg_i = [a.output_name for a in plan.aggs].index(out_name)
            col, null = agg_column(plan.aggs[agg_i])
            columns.append(col)
            if null is not None:
                nulls[out_name] = null
        names.append(out_name)
    return _order_and_limit(ResultSet(names, columns, nulls or None), plan)
