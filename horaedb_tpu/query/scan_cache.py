"""Device-resident scan cache — the HBM-fed serving path.

The reference keeps hot SST pages in a memory cache (mem_cache.rs) so
repeated scans skip object storage. The TPU-native equivalent goes
further: after the first scan of a table state, the dense scan inputs live
in device HBM —

    per-row series codes (int32), relative timestamps (int32),
    value columns (f32)

— and every subsequent aggregate query ships only O(series)+O(1) data:
a series->group map, a series allow-list (tag filters evaluated per
series on host), time-range scalars, and filter literals. The fused
kernel (ops.scan_agg.cached_scan_agg) does the rest on device.

Invalidation: entries key on the table's BASE fingerprint — schema
version, flushed sequence, SST file set. Plain ingest (memtable appends)
does NOT invalidate: the cache serves base state from HBM and the
executor folds the small unflushed DELTA (memtable rows with sequence
above the entry's build point) into the aggregate on the side, so the
steady state of a TSDB — continuous writes — stays on the device path.
Flush/compaction/ALTER change the base fingerprint and rebuild.

Eligibility: aggregate plans whose residual filters decompose into tag
EQ/IN (series-level) + numeric field comparisons (device literals), and
whose data span fits int32 relative milliseconds (~24 days).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..common_types.dict_column import as_values
from ..common_types.row_group import RowGroup
from ..ops.encoding import pad_to_bucket, shape_bucket
from ..table_engine.predicate import Predicate

_I32_MAX = 2**31 - 1


def _cache_dtype_mode() -> str:
    """HORAEDB_CACHE_DTYPE: f32 (default, exact), bf16 (every value
    column halved), or auto — the learned per-column mode: a column is
    stored bf16 only while every query shape that touched it needs just
    count/min/max of it (sums accumulate rounding; filters compare
    against the resident values), and promotes back to f32 the moment a
    sum/avg/filter usage appears (ScanCache.note_usage)."""
    import os

    v = os.environ.get("HORAEDB_CACHE_DTYPE", "f32")
    return v if v in ("f32", "bf16", "auto") else "f32"


def _cache_layout_mode() -> str:
    """HORAEDB_CACHE_LAYOUT: auto (default — per-column compressed
    layouts chosen from observed cardinality + the usage map) or raw
    (every column dense, the pre-ISSUE-19 behavior; also the bench A/B
    control). Read per call so operators can flip it live; entries built
    under the old mode keep their layout until rebuilt/invalidated."""
    import os

    v = os.environ.get("HORAEDB_CACHE_LAYOUT", "auto")
    return v if v in ("auto", "raw") else "auto"


def _dict_max_cardinality() -> int:
    """HORAEDB_CACHE_DICT_MAX: cardinality cap for dictionary-encoding a
    value/timestamp column (codes stay <= 16 bits regardless)."""
    from ..utils.env import env_int

    return env_int("HORAEDB_CACHE_DICT_MAX", 4096)


def _delta_max_bits() -> int:
    """HORAEDB_CACHE_DELTA_MAX_BITS: widest per-block offset the
    delta/FOR timestamp codec accepts before falling back to dict/raw."""
    from ..utils.env import env_int

    return env_int("HORAEDB_CACHE_DELTA_MAX_BITS", 16)


@dataclass
class EncodedColumn:
    """A dictionary-encoded device value column (ISSUE 19).

    Duck-types the accounting surface of a plain device array — ``nbytes``
    is the ENCODED footprint (what the byte budget and LRU price),
    ``dtype`` the LOGICAL dtype the column decodes to — while carrying
    the device parts the encoded-domain kernels consume and the sorted
    host dictionary the executor translates filter literals against."""

    words: object  # device uint32 packed codes (+ safety word)
    dictionary: object  # device f32/int32 dictionary, pow2-padded
    dict_host: np.ndarray  # unpadded sorted dictionary (host)
    width: int  # bits per code
    encoding: str  # "dict8" | "dict16"

    @property
    def nbytes(self) -> int:
        return int(self.words.nbytes + self.dictionary.nbytes)

    @property
    def dtype(self):
        return np.dtype(np.float32)

    @property
    def parts(self) -> tuple:
        return (self.words, self.dictionary)

    def layout(self, full_decode: bool = True) -> tuple:
        return ("dict", self.width, full_decode)


def _parts_nbytes(parts) -> int:
    return int(sum(p.nbytes for p in parts)) if parts else 0


def _layout_encoding(layout: tuple) -> str:
    """Inventory label of a series/ts layout descriptor."""
    if layout[0] == "dict":
        return "dict8" if layout[1] <= 8 else "dict16"
    return layout[0]  # "raw" | "delta"


@dataclass
class CachedTableScan:
    """Device-resident state for one table fingerprint."""

    fingerprint: tuple
    # merged host rows. None once dropped under the host-bytes budget —
    # everything the serving path needs lives in the small derived fields
    # below (series_rows, ts_rel_host, all_valid); only extending the
    # entry with a NEW value column needs a re-read (ScanCache._extend).
    rows: Optional[RowGroup]
    n_valid: int
    min_ts: int
    max_ts: int
    # per-series (small, host): unique tsids + first-row index
    series_first_idx: np.ndarray
    n_series: int
    # device arrays (padded): series codes, relative ts. None when the
    # layout tuner stored the column ENCODED — the decoded form then
    # never occupies HBM; ``series_parts``/``ts_parts`` hold the streams.
    series_codes_dev: "jnp.ndarray"
    ts_rel_dev: "jnp.ndarray"
    # device value columns by name, shape (padded,): plain f32/bf16
    # arrays or EncodedColumn wrappers (dictionary layouts)
    value_cols_dev: dict
    # the mesh the big arrays are sharded over (None = single device);
    # queries on a sharded entry MUST use the shard_map cached kernel.
    mesh: object = None
    # owning table name — keys the cache's per-column usage map (dtype
    # auto-tuning) from extend paths that only hold the entry.
    table_name: str = ""
    # stacked (F, padded) value arrays per column tuple — stacking is a
    # device op, so reuse the result across steady-state queries.
    _stacks: dict = None
    # sorted unique tsid values — maps delta rows onto series codes
    series_tsids: np.ndarray = None
    # per physical table id: last sequence INCLUDED in this entry; newer
    # memtable rows are the query-time delta
    built_seqs: dict = None
    # rows are SORTED by (series, ts): series i occupies
    # [series_offsets[i], series_offsets[i+1]) — selective queries gather
    # just those ranges instead of scanning the whole table
    series_offsets: np.ndarray = None
    # compressed layouts (ISSUE 19): the device part tuples the kernels
    # consume (raw -> the dense array itself) + their static descriptors
    # (the jit-key fragments); padded_rows is the logical padded length
    # (len() of the dense arrays, which may not exist when encoded)
    series_parts: tuple = None
    ts_parts: tuple = None
    series_layout: tuple = ("raw",)
    ts_layout: tuple = ("raw",)
    padded_rows: int = 0
    # value columns dropped for f32/dict promotion whose re-upload hasn't
    # happened yet — an LRU eviction of this entry must resolve their
    # journaled layout_tuner decisions as outcome="evicted" (ISSUE 19
    # satellite: pending-until-expiry leak)
    pending_promotions: set = None

    # per-(group map, allow list) content -> device-resident upload; a
    # dashboard re-issuing the same query shape skips the upload entirely
    # (see ops.scan_agg packed serving path)
    _sessions: dict = None
    # raw (non-aggregate) reads ship only the allow-list — their own
    # content-keyed session cache (ops.scan_topk packed serving path)
    _raw_sessions: dict = None
    # Derived host state that SURVIVES dropping ``rows`` (ref analog: the
    # reference's MemCacheStore keeps bounded bytes, mem_cache.rs:64-158):
    # one row per series (tags for group maps/filters), the int32
    # relative timestamps (selective range gathers), per-column
    # no-NULLs flags, and a 0-row schema carrier for empty deltas.
    series_rows: Optional[RowGroup] = None
    ts_rel_host: Optional[np.ndarray] = None
    all_valid: dict = None
    empty_rows: Optional[RowGroup] = None
    # per-series (min, max) of each resident value column — the cached
    # path's analog of parquet row-group statistics: a numeric filter no
    # row of a series can pass excludes the series BEFORE the kernel
    # (ref: row_group_pruner.rs:240-288 value-stat pruning)
    series_value_stats: dict = None
    # resident-size accounting for the cache's byte budget
    device_bytes: int = 0
    host_bytes: int = 0
    # last serve time (hit or build) — the device telemetry plane's
    # "last-hit age" column; the usage recency the future livewindow
    # eviction policy (ROADMAP item 2) reads
    last_hit_at: float = 0.0
    # Serializes _extend against itself for THIS entry only: two hit-path
    # queries needing a missing value column must not both upload it and
    # double-count device_bytes. Per-entry, so unrelated tables' extends
    # never contend (the cache's stated no-cross-table-serialization
    # design constraint).
    ext_lock: threading.Lock = field(default_factory=threading.Lock)

    def total_bytes(self) -> int:
        return self.device_bytes + self.host_bytes

    def any_encoded(self, names: list[str]) -> bool:
        return (
            self.series_layout[0] != "raw"
            or self.ts_layout[0] != "raw"
            or any(
                isinstance(self.value_cols_dev.get(n), EncodedColumn)
                for n in names
            )
        )

    def value_layout(self, name: str, full_decode: bool = True) -> tuple:
        """Static layout descriptor of one resident value column."""
        dev = self.value_cols_dev[name]
        if isinstance(dev, EncodedColumn):
            return dev.layout(full_decode)
        return ("bf16",) if dev.dtype == jnp.bfloat16 else ("raw",)

    def values_for(self, names: list[str]):
        key = tuple(names)
        if any(isinstance(self.value_cols_dev.get(n), EncodedColumn) for n in names):
            # Mixed/encoded layouts ship as a tuple of per-field part
            # tuples (a jit pytree). No stack cache: assembling the tuple
            # is a host-side pointer shuffle, not a device op.
            return tuple(
                dev.parts if isinstance(dev, EncodedColumn) else (dev,)
                for dev in (self.value_cols_dev[n] for n in names)
            )
        # Work on a LOCAL reference: a concurrent _extend invalidates by
        # setting self._stacks = None (it holds only ext_lock, which this
        # hit path deliberately does not take), so re-reading the
        # attribute between the None-check and the store below can crash
        # a select. Stacks are per-name-tuple over add-only columns, so
        # storing into a just-discarded dict is merely a lost cache fill.
        stacks = self._stacks
        if stacks is None:
            stacks = self._stacks = {}
        out = stacks.get(key)
        if out is None:
            if not names:
                out = jnp.zeros((0, self.padded_rows), dtype=jnp.float32)
            else:
                out = jnp.stack([self.value_cols_dev[n] for n in names])
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                import jax

                out = jax.device_put(out, NamedSharding(self.mesh, P(None, "shard")))
            stacks[key] = out
        return out

    def _session_lru(self, attr: str, key: bytes, build):
        """Content-keyed bounded-LRU get-or-build shared by both session
        caches; benign races just upload twice."""
        cache = getattr(self, attr)
        if cache is None:
            cache = {}
            setattr(self, attr, cache)
        dev = cache.pop(key, None)
        if dev is None:
            if len(cache) >= 32:
                try:  # racing evictors may target the same oldest key
                    cache.pop(next(iter(cache)), None)
                except (StopIteration, RuntimeError):
                    pass
            dev = build()
        cache[key] = dev
        return dev

    def session_for(self, gos: np.ndarray, allow: np.ndarray):
        """Device handle for the packed [group map | allow list] upload,
        keyed by CONTENT — repeats of a query shape (the dashboard steady
        state) reuse the resident buffer and ship zero series-level bytes."""
        from ..ops.scan_agg import pack_session

        return self._session_lru(
            "_sessions",
            gos.tobytes() + allow.tobytes(),
            lambda: jnp.asarray(pack_session(gos, allow)),
        )

    def raw_session_for(self, allow: np.ndarray):
        """Device handle for a raw read's allow-list upload (raw reads
        ship no group map), content-keyed like the aggregate sessions."""
        return self._session_lru(
            "_raw_sessions",
            allow.tobytes(),
            lambda: jnp.asarray(allow.astype(np.int32)),
        )


def _rowgroup_bytes(rows: RowGroup) -> int:
    """Approximate resident bytes of a RowGroup's host columns."""
    from ..common_types.dict_column import DictColumn

    total = 0
    for arr in rows.columns.values():
        if isinstance(arr, DictColumn):
            total += arr.codes.nbytes
            total += sum(len(str(v)) + 49 for v in arr.values)  # str overhead
        elif isinstance(arr, np.ndarray) and arr.dtype == object:
            total += arr.nbytes + 56 * len(arr)  # pointer + str objects
        else:
            total += arr.nbytes
    for mask in rows.validity.values():
        total += mask.nbytes
    return total


class ScanCache:
    """Bounded by BYTES, not entry count (ref: mem_cache.rs:64-158 — the
    reference budgets its partitioned LRU by capacity): entries are
    evicted least-recently-used until resident device+host bytes fit
    ``max_bytes`` (HORAEDB_SCAN_CACHE_MB, default RAM/4). A single table
    whose resident state alone exceeds the budget is never built — the
    host path serves it instead of failing a giant device_put. Entries
    whose HOST rows exceed HORAEDB_CACHE_HOST_ROWS_MB (default 256) drop
    the host copy after deriving the small serving-side state; a later
    query needing a NEW value column re-reads from the SSTs."""

    def __init__(
        self,
        max_entries: int = 4,
        max_bytes: Optional[int] = None,
        max_host_rows_bytes: Optional[int] = None,
    ) -> None:
        import os

        self._entries: dict[str, CachedTableScan] = {}
        # fingerprint last seen per table: a cache build is only worth the
        # full-table read once the data has been STABLE across two
        # consecutive eligible queries (a write-heavy table would otherwise
        # rebuild — full read + upload — on every single query).
        self._candidate: dict[str, tuple] = {}
        # per table -> per value column: how query shapes have USED it
        # ({"sum": bool, "filter": bool}) — drives the auto dtype choice.
        # Sticky by design: one sum/filter usage pins the column f32 for
        # the cache's lifetime (a later min/max-only query must not
        # demote a column some dashboard still sums).
        self._usage: dict[str, dict[str, dict]] = {}
        self._lock = threading.Lock()
        self.max_entries = max_entries
        if max_bytes is not None:
            self.max_bytes = max_bytes
        else:
            from .partial import _budget_bytes

            self.max_bytes = _budget_bytes("HORAEDB_SCAN_CACHE_MB")
        from ..utils.env import env_int

        self.max_host_rows_bytes = (
            max_host_rows_bytes
            if max_host_rows_bytes is not None
            else env_int("HORAEDB_CACHE_HOST_ROWS_MB", 256) << 20
        )
        self.hits = 0
        self.misses = 0
        # per-table budget-eviction counts (survive the entry — the
        # device telemetry plane reports them; bounded LRU-style)
        self._evictions: dict[str, int] = {}
        # the cache IS the HBM residency source: the device telemetry
        # plane walks registered caches for system.public.device
        from ..obs.device import register_occupancy_provider

        register_occupancy_provider(self)

    def resident_bytes(self) -> int:
        with self._lock:
            return sum(e.total_bytes() for e in self._entries.values())

    def occupancy_bytes(self) -> dict:
        """Cheap component byte sums (no row materialization) — the
        hot-path gauge refresh (obs/device.refresh_occupancy) reads this
        instead of snapshot_device()."""
        with self._lock:
            entries = list(self._entries.values())
        col = sess = stack = 0
        for e in entries:
            try:
                col += e.device_bytes
                for attr in ("_sessions", "_raw_sessions"):
                    c = getattr(e, attr)
                    if c:
                        sess += sum(v.nbytes for v in list(c.values()))
                s = e._stacks
                if s:
                    stack += sum(v.nbytes for v in list(s.values()))
            except Exception:
                continue  # a racing extend/evict: best-effort sums
        return {"column": col, "session": sess, "stack": stack}

    def snapshot_device(self) -> list[dict]:
        """Per-(table, column, dtype) HBM residency rows for the device
        telemetry plane (obs/device.device_inventory). ``component=
        "column"`` rows sum EXACTLY to the entries' ``device_bytes``
        accounting (the acceptance invariant); sessions/stacks — the
        content-keyed query-shape uploads and stacked value views — are
        reported beside them; evicted tables keep a zero-byte row
        carrying their eviction count."""
        with self._lock:
            entries = list(self._entries.items())
            evictions = dict(self._evictions)
        now = time.time()
        rows: list[dict] = []

        def row(table: str, column: str, component: str, dtype: str,
                nbytes: int, nrows: int, age_ms: int,
                encoding: str = "", logical_rows: int = 0) -> dict:
            return {
                "table_name": table,
                "column_name": column,
                "component": component,
                "dtype": dtype,
                "bytes": int(nbytes),
                "rows": int(nrows),
                "last_hit_age_ms": age_ms,
                "evictions": int(evictions.get(table, 0)),
                # compressed-layout inventory (ISSUE 19): what form the
                # bytes are in, and how many LOGICAL rows they serve —
                # rows-per-HBM-byte is logical_rows / bytes
                "encoding": encoding,
                "logical_rows": int(logical_rows),
            }

        for name, e in entries:
            try:
                age = (
                    int((now - e.last_hit_at) * 1000)
                    if e.last_hit_at else -1
                )
                sc_bytes = (
                    _parts_nbytes(e.series_parts)
                    if e.series_parts is not None
                    else e.series_codes_dev.nbytes
                )
                ts_bytes = (
                    _parts_nbytes(e.ts_parts)
                    if e.ts_parts is not None
                    else e.ts_rel_dev.nbytes
                )
                rows.append(row(name, "__series_codes__", "column", "int32",
                                sc_bytes, e.n_valid, age,
                                encoding=_layout_encoding(e.series_layout),
                                logical_rows=e.n_valid))
                rows.append(row(name, "__ts_rel__", "column", "int32",
                                ts_bytes, e.n_valid, age,
                                encoding=_layout_encoding(e.ts_layout),
                                logical_rows=e.n_valid))
                for col, dev in list(e.value_cols_dev.items()):
                    if isinstance(dev, EncodedColumn):
                        enc = dev.encoding
                    elif dev.dtype == jnp.bfloat16:
                        enc = "bf16"
                    else:
                        enc = "raw"
                    rows.append(row(name, col, "column", str(dev.dtype),
                                    dev.nbytes, e.n_valid, age,
                                    encoding=enc, logical_rows=e.n_valid))
                for attr, label in (("_sessions", "__sessions__"),
                                    ("_raw_sessions", "__raw_sessions__")):
                    cache = getattr(e, attr)
                    if cache:
                        vals = list(cache.values())
                        rows.append(row(
                            name, label, "session", "int32",
                            sum(v.nbytes for v in vals), len(vals), age,
                        ))
                stacks = e._stacks
                if stacks:
                    vals = list(stacks.values())
                    rows.append(row(
                        name, "__stacks__", "stack",
                        str(vals[0].dtype) if vals else "float32",
                        sum(v.nbytes for v in vals), len(vals), age,
                    ))
            except Exception:
                continue  # a racing extend/evict: skip this entry's rows
        resident = {name for name, _ in entries}
        for table, n in evictions.items():
            if table not in resident and n:
                rows.append(row(table, "", "evicted", "", 0, 0, -1))
        return rows

    # ---- learned per-column dtype ---------------------------------------
    def note_usage(
        self,
        table_name: str,
        value_columns: list[str],
        sum_cols=(),
        filter_cols=(),
    ) -> None:
        """Record how this query shape touches each value column — the
        feedback the HORAEDB_CACHE_DTYPE=auto mode tunes dtypes from
        ("fine-tune the data structure to the workload", arXiv
        2112.13099). Called by the executor BEFORE the cache lookup, so
        the very first build of an entry already stores min/max-only
        columns as bf16. A column already resident as bf16 whose usage
        GROWS a sum/filter is promoted: its device copy is dropped here
        and the ordinary extend path re-uploads it f32."""
        promote: list[str] = []
        with self._lock:
            usage = self._usage.get(table_name)
            if usage is None:
                # bound tracked tables LRU-style (dict order = recency)
                if len(self._usage) >= 512:
                    self._usage.pop(next(iter(self._usage)))
                usage = self._usage[table_name] = {}
            else:
                self._usage[table_name] = self._usage.pop(table_name)
            for c in value_columns:
                u = usage.setdefault(c, {"sum": False, "filter": False})
                was_exact = u["sum"] or u["filter"]
                u["sum"] |= c in sum_cols
                u["filter"] |= c in filter_cols
                if (u["sum"] or u["filter"]) and not was_exact:
                    promote.append(c)
            entry = self._entries.get(table_name)
        if promote and entry is not None and _cache_dtype_mode() == "auto":
            self._drop_bf16_columns(entry, promote)
            from ..obs.device import refresh_occupancy

            refresh_occupancy(force=True)  # bf16 drop freed device bytes

    def _column_dtype(self, table_name: str, column: str):
        """Resident dtype for one value column under the current mode."""
        mode = _cache_dtype_mode()
        if mode == "bf16":
            return jnp.bfloat16
        if mode == "auto":
            with self._lock:
                u = self._usage.get(table_name, {}).get(column)
            # unknown usage -> exact: auto must never guess lossy
            if u is not None and not (u["sum"] or u["filter"]):
                return jnp.bfloat16
        return jnp.float32

    @staticmethod
    def _drop_bf16_columns(entry: CachedTableScan, columns) -> None:
        """Evict now-stale bf16 device copies so the extend path
        re-uploads them at f32 (may force an SST re-read if the host
        rows were dropped — correctness over residency)."""
        from ..obs.decisions import record_decision

        with entry.ext_lock:
            for c in columns:
                dev = entry.value_cols_dev.get(c)
                if dev is None or dev.dtype != jnp.bfloat16:
                    continue
                entry.value_cols_dev.pop(c)
                entry.device_bytes -= dev.nbytes
                entry._stacks = None
                if entry.series_value_stats is not None:
                    entry.series_value_stats.pop(c, None)
                # Decision plane: the tuner chose to spend HBM for
                # exactness. Predicted: the f32 re-upload doubles the
                # dropped bf16 bytes; the extend path resolves with the
                # bytes ACTUALLY uploaded (a grown pad bucket, a raced
                # rebuild, or a dictionary re-encode beating f32 shows
                # up as calibration error).
                record_decision(
                    "layout_tuner",
                    key=f"{entry.table_name}:{c}",
                    choice="promote_f32",
                    features={"bf16_bytes": int(dev.nbytes)},
                    predicted=float(dev.nbytes) * 2.0,
                )
                # An LRU eviction of the whole entry before the re-upload
                # must resolve this decision (outcome=evicted), not leak
                # it to TTL expiry.
                if entry.pending_promotions is None:
                    entry.pending_promotions = set()
                entry.pending_promotions.add(c)

    @staticmethod
    def _resolve_pending_evicted(entry: CachedTableScan) -> None:
        """Resolve still-pending promotion decisions of a dying entry as
        ``outcome=evicted`` — the re-upload they predicted will never
        happen, so without this they sit pending until TTL expiry and
        the tenantsim accounting shows them as leaks. No calibration:
        there is no realized-bytes ground truth for an upload that never
        ran."""
        pending = entry.pending_promotions
        if not pending:
            return
        from ..obs.decisions import DECISION_JOURNAL

        for c in list(pending):
            DECISION_JOURNAL.resolve_matching(
                "layout_tuner",
                f"{entry.table_name}:{c}",
                actual=0.0,
                outcome="evicted",
                calibrate=False,
            )
        pending.clear()

    def _evict_over_budget_locked(self, keep: str) -> int:
        """Evict least-recently-used entries (never ``keep``) until both
        the entry-count and byte budgets hold — the ONE eviction policy;
        the insert path and the hit path (whose _extend uploads grow
        entries) both call it. Returns how many entries were evicted so
        callers can force the occupancy-gauge refresh on mutation."""
        evicted = 0
        while len(self._entries) > 1 and (
            len(self._entries) > self.max_entries
            or sum(e.total_bytes() for e in self._entries.values())
            > self.max_bytes
        ):
            victim = next(
                (k for k in self._entries if k != keep), None
            )
            if victim is None:
                return evicted
            self._resolve_pending_evicted(self._entries.pop(victim))
            evicted += 1
            # accounted eviction: the device plane reports per-table
            # counts (the usage-map signal the layout tuner reads)
            if len(self._evictions) >= 512 and victim not in self._evictions:
                self._evictions.pop(next(iter(self._evictions)))
            self._evictions[victim] = self._evictions.get(victim, 0) + 1
            from ..obs.device import note_eviction

            note_eviction()
        return evicted

    def get(
        self,
        table,
        value_columns: list[str],
        read_rows,
    ) -> tuple[Optional[CachedTableScan], bool, Optional["RowGroup"]]:
        """(cached scan state, was_built_this_call, delta_rows).

        ``read_rows()`` materializes the full-table merged rows on miss.
        ``delta_rows`` (possibly empty) are memtable rows written AFTER the
        entry was built — the executor folds them into the aggregate so
        ingest doesn't evict the HBM state. Entry is None when the table's
        shape doesn't fit the cached-kernel contract (span overflow, empty
        table), or when the base state hasn't been stable long enough.
        """
        base_fp = _base_fingerprint(table)
        from ..parallel.mesh import serving_mesh

        mesh_now = serving_mesh()
        with self._lock:
            entry = self._entries.get(table.name)
            if entry is not None and entry.mesh is not None and entry.mesh is not mesh_now:
                # Device set changed (mesh rebuilt): sharded arrays are
                # placed on the old mesh — rebuild from scratch.
                self._resolve_pending_evicted(self._entries.pop(table.name))
                entry = None
            hit = entry is not None and entry.fingerprint == base_fp
            if not hit and self._candidate.get(table.name) != base_fp:
                # first sighting of this base state: don't build yet
                self._candidate[table.name] = base_fp
                self.misses += 1
                return None, False, None
        if hit:
            # Delta materialization and column upload run OUTSIDE the
            # cache lock — they do O(memtable) / O(rows) work and must not
            # serialize unrelated tables' queries. Entry mutation during
            # _extend is per-entry idempotent; the fingerprint re-check
            # catches a racing flush.
            if not all(c in entry.value_cols_dev for c in value_columns):
                if not self._extend(
                    entry, value_columns, read_rows=read_rows, table=table
                ):
                    # host rows were dropped and the re-read raced a
                    # write: serve this query from the host path
                    self.misses += 1
                    return None, False, None
            delta = _read_delta(table, entry)
            with self._lock:
                if delta is not None and _base_fingerprint(table) == base_fp:
                    self.hits += 1
                    entry.last_hit_at = time.time()
                    # LRU touch: reinsert at the tail
                    e = self._entries.pop(table.name, None)
                    if e is not None:
                        self._entries[table.name] = e
                    # _extend above may have grown this entry's device
                    # bytes — the budget holds on the hit path too.
                    evicted = self._evict_over_budget_locked(keep=table.name)
                else:
                    # A flush raced the delta read (or the delta predates
                    # the entry inconsistently): serve nothing from cache.
                    self.misses += 1
                    entry = None
                    evicted = 0
            # gauge refresh OUTSIDE the cache lock (snapshot_device
            # re-takes it); _extend above may have changed residency.
            # An eviction forces through the throttle — it may be the
            # last touch for a while and must not park the gauge.
            from ..obs.device import refresh_occupancy

            refresh_occupancy(force=bool(evicted))
            if entry is None:
                return None, False, None
            return entry, False, delta
        seq_before = {d.table_id: d.last_sequence for d in table.physical_datas()}
        rows = read_rows()
        seq_after = {d.table_id: d.last_sequence for d in table.physical_datas()}
        if seq_before != seq_after or _base_fingerprint(table) != base_fp:
            # Writes or a flush raced the build read: the entry's exact
            # row set would be ambiguous (delta double/under-count) —
            # skip building this time.
            return None, False, None
        n = len(rows)
        if n == 0:
            return None, False, None
        ts = rows.timestamps
        min_ts, max_ts = int(ts.min()), int(ts.max())
        if max_ts - min_ts >= _I32_MAX:
            return None, False, None
        # A table whose resident state ALONE busts the byte budget never
        # builds — the host path serves it instead of a failing (or
        # budget-starving) giant device_put. Under the layout tuner the
        # raw estimate may overstate the encoded footprint by the codec
        # ratio, so auto mode admits down to a best-case 8x and the
        # post-build check below enforces the REAL bytes.
        est = shape_bucket(n + 1) * 4 * (2 + len(value_columns))
        if _cache_layout_mode() == "auto":
            est //= 8
        host_est = min(_rowgroup_bytes(rows), self.max_host_rows_bytes)
        if est + host_est > self.max_bytes:
            return None, False, None
        entry = self._build(
            base_fp, rows, min_ts, max_ts, value_columns, table.name
        )
        if entry.total_bytes() > self.max_bytes:
            # the codecs didn't deliver the admitted ratio: the realized
            # entry alone busts the budget — never insert it
            self._resolve_pending_evicted(entry)
            return None, False, None
        entry.built_seqs = seq_after
        entry.last_hit_at = time.time()
        with self._lock:
            self.misses += 1
            self._entries.pop(table.name, None)
            self._entries[table.name] = entry
            self._evict_over_budget_locked(keep=table.name)
        from ..obs.device import refresh_occupancy

        refresh_occupancy(force=True)  # a build is a residency mutation
        empty = entry.empty_rows
        return entry, True, empty

    @staticmethod
    def _resident_layout(rows: RowGroup):
        """THE resident layout: rows sorted by (series, ts). One
        definition — _build derives it and _extend's re-read (after a
        host-rows drop) must reproduce it bit-for-bit.

        Selective queries (a handful of series out of thousands — the
        TSBS single-groupby shape) become contiguous-range gathers
        instead of full scans because of this sort."""
        schema = rows.schema
        tsid = rows.columns[schema.columns[schema.tsid_index].name]
        uniq, _, inverse = np.unique(tsid, return_index=True, return_inverse=True)
        order = np.lexsort((rows.timestamps, inverse))
        return rows.take(order), uniq, inverse[order]

    def _build(
        self,
        fp,
        rows: RowGroup,
        min_ts: int,
        max_ts: int,
        value_columns: list[str],
        table_name: str = "",
    ) -> CachedTableScan:
        n = len(rows)
        schema = rows.schema
        rows, uniq, inverse = self._resident_layout(rows)
        n_series = len(uniq)
        counts = np.bincount(inverse, minlength=n_series)
        offsets = np.zeros(n_series + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        first_idx = offsets[:-1].copy()
        # One explicit pad row at index n (series code n_series, allow
        # masked): selective gathers point their padding here even when n
        # itself is a power of two.
        codes = pad_to_bucket(
            np.append(inverse.astype(np.int32), np.int32(n_series)), n + 1,
            fill=n_series,
        )
        ts_rel = pad_to_bucket(
            np.append((rows.timestamps - min_ts).astype(np.int32), np.int32(-1)),
            n + 1,
            fill=np.int32(-1),
        )
        # Multi-device: the big row arrays live SHARDED across the mesh so
        # steady-state serving is itself distributed (each chip holds and
        # scans 1/Nth of the table; combine rides the collectives). Small
        # tables stay single-device — same threshold as the uncached path
        # (collective dispatch would dominate).
        from ..parallel.mesh import dist_min_rows, serving_mesh

        mesh = serving_mesh() if n >= dist_min_rows() else None
        place = None
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            if len(codes) % n_dev:
                extra = n_dev - len(codes) % n_dev
                codes = np.pad(codes, (0, extra), constant_values=n_series)
                ts_rel = np.pad(ts_rel, (0, extra), constant_values=-1)
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            place = NamedSharding(mesh, P("shard"))
            codes_dev = jax.device_put(codes, place)
            ts_dev = jax.device_put(ts_rel, place)
            series_parts, ts_parts = (codes_dev,), (ts_dev,)
            series_layout = ts_layout = ("raw",)
        else:
            # Compressed layouts (ISSUE 19) — single-device entries only
            # (the shard_map kernels scan raw streams). Both codecs are
            # lossless and roundtrip-verified; any rejection falls back
            # to the dense array, bit-identical to the pre-layout path.
            series_layout = ts_layout = ("raw",)
            series_parts = ts_parts = None
            if _cache_layout_mode() == "auto":
                from ..obs.decisions import DECISION_JOURNAL, record_decision
                from ..ops.encoding import delta_for_encode, dict_encode

                def _journal(col, choice, predicted, actual, **features):
                    record_decision(
                        "layout_tuner",
                        key=f"{table_name}:{col}",
                        choice=choice,
                        features=features,
                        predicted=predicted,
                    )
                    DECISION_JOURNAL.resolve_matching(
                        "layout_tuner",
                        f"{table_name}:{col}",
                        actual=actual,
                        outcome="encoded",
                    )

                # Series codes are sorted consecutive np.unique inverses:
                # any 128-row block spans <= 128 distinct codes, so
                # delta/FOR at width <= 8 succeeds whenever the padded
                # bucket is block-aligned (tiny tables stay raw).
                d = delta_for_encode(codes, 8)
                if d is not None:
                    series_layout = ("delta", d.width)
                    series_parts = (jnp.asarray(d.words), jnp.asarray(d.base))
                    _journal(
                        "__series_codes__", "delta",
                        predicted=len(codes) * d.width / 8.0 + d.base.nbytes,
                        actual=float(_parts_nbytes(series_parts)),
                        width=d.width,
                    )
                # The -1 pad fill would blow the FOR width at the tail;
                # pad rows are series-masked in every kernel (the allow
                # list's last entry is always False), so the encoded
                # stream may carry any value there — reuse the last real
                # timestamp. ts_rel_host keeps the true values.
                ts_src = ts_rel.copy()
                ts_src[n:] = ts_src[n - 1] if n else 0
                dt = delta_for_encode(ts_src, _delta_max_bits())
                if dt is not None:
                    ts_layout = ("delta", dt.width)
                    ts_parts = (jnp.asarray(dt.words), jnp.asarray(dt.base))
                    _journal(
                        "__ts_rel__", "delta",
                        predicted=len(ts_src) * dt.width / 8.0 + dt.base.nbytes,
                        actual=float(_parts_nbytes(ts_parts)),
                        width=dt.width,
                    )
                else:
                    # aligned multi-series timestamps: few distinct
                    # relative values — a dictionary beats raw even when
                    # per-block ranges are wide
                    de = dict_encode(ts_src, _dict_max_cardinality())
                    if de is not None:
                        ts_layout = ("dict", de.width)
                        ts_parts = (
                            jnp.asarray(de.words), jnp.asarray(de.dictionary),
                        )
                        _journal(
                            "__ts_rel__", de.encoding,
                            predicted=len(ts_src) * de.width / 8.0
                            + de.dict_host.nbytes,
                            actual=float(_parts_nbytes(ts_parts)),
                            width=de.width,
                            cardinality=len(de.dict_host),
                        )
            codes_dev = jnp.asarray(codes) if series_parts is None else None
            ts_dev = jnp.asarray(ts_rel) if ts_parts is None else None
            if series_parts is None:
                series_parts = (codes_dev,)
            if ts_parts is None:
                ts_parts = (ts_dev,)
        entry = CachedTableScan(
            fingerprint=fp,
            rows=rows,
            n_valid=n,
            min_ts=min_ts,
            max_ts=max_ts,
            series_first_idx=first_idx,
            n_series=n_series,
            series_codes_dev=codes_dev,
            ts_rel_dev=ts_dev,
            value_cols_dev={},
            mesh=mesh,
            table_name=table_name,
            series_tsids=uniq,
            series_offsets=offsets,
            series_parts=series_parts,
            ts_parts=ts_parts,
            series_layout=series_layout,
            ts_layout=ts_layout,
            padded_rows=len(codes),
        )
        # Serving-side state that outlives the host rows: per-series tag
        # rows, int32 relative timestamps, no-NULL flags, schema carrier.
        entry.series_rows = RowGroup(
            schema,
            {c.name: rows.columns[c.name][first_idx] for c in schema.columns},
            {name: mask[first_idx] for name, mask in rows.validity.items()},
        )
        entry.ts_rel_host = (rows.timestamps - min_ts).astype(np.int32)
        entry.all_valid = {
            c.name: bool(rows.valid_mask(c.name).all()) for c in schema.columns
        }
        entry.empty_rows = rows.slice(0, 0)
        entry.device_bytes = _parts_nbytes(series_parts) + _parts_nbytes(ts_parts)
        entry.host_bytes = (
            _rowgroup_bytes(rows)
            + entry.ts_rel_host.nbytes
            + _rowgroup_bytes(entry.series_rows)
        )
        # _extend uploads the value columns and then applies the host
        # budget: an oversized full host copy is dropped (the derived
        # state above keeps the device path serving; _extend re-reads
        # from the SSTs should a new value column ever be requested).
        self._extend(entry, value_columns)
        return entry

    def _extend(
        self,
        entry: CachedTableScan,
        value_columns: list[str],
        read_rows=None,
        table=None,
    ) -> bool:
        """Upload any missing value columns; False when the entry's host
        rows were dropped and the re-read couldn't reproduce the build
        state (caller serves from the host path).

        Runs OUTSIDE the cache-wide lock (O(rows) work must not serialize
        unrelated tables) but UNDER the entry's own lock: concurrent
        hit-path extends re-check ``value_cols_dev`` after acquiring it,
        so a column uploads once and ``device_bytes`` counts once."""
        with entry.ext_lock:
            return self._extend_locked(entry, value_columns, read_rows, table)

    def _extend_locked(
        self,
        entry: CachedTableScan,
        value_columns: list[str],
        read_rows=None,
        table=None,
    ) -> bool:
        import jax

        missing = [c for c in value_columns if c not in entry.value_cols_dev]
        if missing and entry.rows is None:
            if read_rows is None or table is None:
                return False
            # The re-read must reproduce EXACTLY the build-time row set.
            # Any write since the build — including an OVERWRITE of an
            # existing (tsid, ts) key, which changes neither the row
            # count nor the timestamps — would leak into the uploaded
            # column AND be re-counted by the delta fold. Same guard the
            # build path uses: sequences must still equal the build point.
            def _seqs():
                return {
                    d.table_id: d.last_sequence for d in table.physical_datas()
                }

            if entry.built_seqs is None or _seqs() != entry.built_seqs:
                return False
            # Re-derive the EXACT resident layout (the ONE definition in
            # _resident_layout) — deterministic for an unchanged base.
            rows = read_rows()
            if _seqs() != entry.built_seqs:
                return False  # a write raced the re-read
            if len(rows) != entry.n_valid:
                return False
            rows, _, _ = self._resident_layout(rows)
            if not np.array_equal(
                (rows.timestamps - entry.min_ts).astype(np.int32),
                entry.ts_rel_host,
            ):
                return False
            entry.rows = rows  # keep until the next budget sweep

        target = entry.padded_rows  # includes any mesh padding
        place = None
        if entry.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            place = NamedSharding(entry.mesh, P("shard"))
        # HORAEDB_CACHE_DTYPE: bf16 halves resident HBM for value columns
        # (the kernels upcast to f32 for accumulation — on TPU the cast is
        # free on the vector units, the win is bandwidth/capacity). Costs
        # ~3 significant digits on stored samples, INCLUDING values that
        # numeric filters compare against — rows within bf16 rounding of
        # a filter threshold may classify differently than the host path.
        # Default stays f32; "bf16" opts every column in; "auto" tunes
        # per column from observed usage (_column_dtype: min/max-only
        # columns shrink, summed/filtered columns stay exact).
        for c in value_columns:
            if c not in entry.value_cols_dev:
                dtype = self._column_dtype(entry.table_name, c)
                # entry.rows is already in the sorted resident layout;
                # dtype conversion happens on HOST so the sharded
                # device_put transfers straight to each shard (no staging
                # of the full column on one device)
                arr = as_values(entry.rows.column(c)).astype(np.float32, copy=False)
                padded = np.pad(arr, (0, target - len(arr))).astype(
                    np.dtype(dtype), copy=False
                )
                # Layout tuner (ISSUE 19): a low-cardinality exact column
                # stores as bit-packed dictionary codes + a small sorted
                # f32 dictionary — lossless (bit-verified in dict_encode)
                # and 4-8x smaller. bf16 columns keep the lossy half-size
                # layout the dtype mode chose; mesh entries stay raw.
                enc = None
                if (
                    place is None
                    and _cache_layout_mode() == "auto"
                    and padded.dtype == np.float32
                ):
                    from ..ops.encoding import dict_encode

                    enc = dict_encode(padded, _dict_max_cardinality())
                if enc is not None:
                    from ..obs.decisions import record_decision

                    record_decision(
                        "layout_tuner",
                        key=f"{entry.table_name}:{c}",
                        choice=enc.encoding,
                        features={
                            "cardinality": len(enc.dict_host),
                            "width": enc.width,
                            "raw_bytes": int(padded.nbytes),
                        },
                        predicted=target * enc.width / 8.0
                        + enc.dict_host.nbytes,
                    )
                    dev = EncodedColumn(
                        words=jnp.asarray(enc.words),
                        dictionary=jnp.asarray(enc.dictionary),
                        dict_host=enc.dict_host,
                        width=enc.width,
                        encoding=enc.encoding,
                    )
                    # memtable ride-along: remember this column arrives
                    # low-cardinality so freezes dictionary-code it early
                    from ..common_types.layout_hints import note_low_cardinality

                    note_low_cardinality(
                        entry.table_name, c, len(enc.dict_host)
                    )
                else:
                    dev = (
                        jax.device_put(padded, place)
                        if place is not None
                        else jnp.asarray(padded)
                    )
                entry.value_cols_dev[c] = dev
                entry.device_bytes += dev.nbytes
                entry._stacks = None  # stale stacked views
                if padded.dtype != np.dtype(jnp.bfloat16):
                    # an exact upload closes any pending promote_f32
                    # decision for this column — and, one call, the
                    # just-recorded encode decision (no match -> no-op:
                    # a plain first raw upload decided nothing)
                    from ..obs.decisions import DECISION_JOURNAL

                    outcome = (
                        "promoted"
                        if entry.pending_promotions
                        and c in entry.pending_promotions
                        else "encoded"
                    )
                    DECISION_JOURNAL.resolve_matching(
                        "layout_tuner",
                        f"{entry.table_name}:{c}",
                        actual=float(dev.nbytes),
                        outcome=outcome,
                    )
                    if entry.pending_promotions:
                        entry.pending_promotions.discard(c)
                # Per-series min/max over the SAME values the kernel sees
                # — the dtype-CAST values (bf16-resident columns compare
                # rounded), with fills included and NaN samples ignored
                # (np.fmin/fmax: a NaN passes no numeric filter, so it
                # must not poison a series' stats; an all-NaN series
                # yields NaN stats and correctly prunes). Every series is
                # non-empty by construction (offsets from bincount of
                # present rows), so reduceat is well-defined.
                if entry.series_value_stats is None:
                    entry.series_value_stats = {}
                seg = entry.series_offsets[:-1]
                stat_src = padded[: len(arr)].astype(np.float64)
                entry.series_value_stats[c] = (
                    np.fmin.reduceat(stat_src, seg),
                    np.fmax.reduceat(stat_src, seg),
                )
        self._apply_host_budget(entry)
        return True

    def _apply_host_budget(self, entry: CachedTableScan) -> None:
        """Drop the full host rows copy when it exceeds the per-entry
        budget; the derived serving state stays."""
        if (
            entry.rows is not None
            and _rowgroup_bytes(entry.rows) > self.max_host_rows_bytes
        ):
            entry.rows = None
            entry.host_bytes = entry.ts_rel_host.nbytes + _rowgroup_bytes(
                entry.series_rows
            )

    def invalidate(self, table_name: str) -> None:
        with self._lock:
            entry = self._entries.pop(table_name, None)
            if entry is not None:
                self._resolve_pending_evicted(entry)
        from ..obs.device import refresh_occupancy

        # forced: an invalidation (DROP/ALTER) may be the last cache
        # touch for a long time — a throttled skip would leave the
        # resident-bytes gauges reporting the freed bytes until the
        # next query, and the recorder would persist the stale value
        refresh_occupancy(force=True)


def _base_fingerprint(table) -> tuple:
    """The FLUSHED state only: schema + flushed sequence + SST file set.

    Plain memtable appends deliberately do NOT change it — they are
    served as a delta on top of the cached base."""
    parts = []
    for data in table.physical_datas():
        files = tuple(
            (h.level, h.file_id) for h in data.version.levels.all_files()
        )
        parts.append(
            (
                data.table_id,
                data.schema.version,  # ALTER invalidates even with no writes
                data.version.flushed_sequence,
                files,
            )
        )
    return tuple(parts)


def _append_newer(parts: list, rows, seqs, built: int) -> None:
    """Append the sub-slice of rows written after the build point."""
    if len(rows) == 0:
        return
    keep = seqs > built
    if keep.any():
        parts.append(rows if keep.all() else rows.filter(keep))


def _read_delta(table, entry: CachedTableScan):
    """Memtable rows with sequence above the entry's build point, or None
    when the delta cannot be trusted (entry predates unknown state)."""
    if entry.built_seqs is None:
        return None
    parts = []
    for data in table.physical_datas():
        built = entry.built_seqs.get(data.table_id)
        if built is None:
            return None  # physical set changed (e.g. partition added)
        version = data.version
        for mem in [*version.immutables(), version.mutable]:
            # snapshot() is uniform across memtable kinds: frozen segments
            # (layered only) + the mutable head. Whole segments at or
            # below the build point are skipped on their scalar max_seq —
            # the delta never touches rows older than the cache entry.
            segments, head_rows, head_seqs = mem.snapshot()
            for seg in segments:
                if seg.max_seq <= built:
                    continue
                _append_newer(parts, seg.rows, seg.seqs, built)
            _append_newer(parts, head_rows, head_seqs, built)
    if not parts:
        # verified clean: an empty RowGroup with the table schema
        return entry.empty_rows
    from ..common_types.row_group import RowGroup

    return RowGroup.concat(parts) if len(parts) > 1 else parts[0]
