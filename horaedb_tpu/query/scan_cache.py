"""Device-resident scan cache — the HBM-fed serving path.

The reference keeps hot SST pages in a memory cache (mem_cache.rs) so
repeated scans skip object storage. The TPU-native equivalent goes
further: after the first scan of a table state, the dense scan inputs live
in device HBM —

    per-row series codes (int32), relative timestamps (int32),
    value columns (f32)

— and every subsequent aggregate query ships only O(series)+O(1) data:
a series->group map, a series allow-list (tag filters evaluated per
series on host), time-range scalars, and filter literals. The fused
kernel (ops.scan_agg.cached_scan_agg) does the rest on device.

Invalidation: entries key on a table fingerprint (last/flushed sequence +
SST file ids per physical table); any write or compaction changes it.
Eligibility: aggregate plans whose residual filters decompose into tag
EQ/IN (series-level) + numeric field comparisons (device literals), and
whose data span fits int32 relative milliseconds (~24 days).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..common_types.dict_column import as_values
from ..common_types.row_group import RowGroup
from ..ops.encoding import pad_to_bucket, shape_bucket
from ..table_engine.predicate import Predicate

_I32_MAX = 2**31 - 1


@dataclass
class CachedTableScan:
    """Device-resident state for one table fingerprint."""

    fingerprint: tuple
    rows: RowGroup  # merged host rows (kept for fallbacks/series lookups)
    n_valid: int
    min_ts: int
    max_ts: int
    # per-series (small, host): unique tsids + first-row index
    series_first_idx: np.ndarray
    n_series: int
    # device arrays (padded): series codes, relative ts
    series_codes_dev: "jnp.ndarray"
    ts_rel_dev: "jnp.ndarray"
    # device value columns by name, shape (padded,)
    value_cols_dev: dict
    # the mesh the big arrays are sharded over (None = single device);
    # queries on a sharded entry MUST use the shard_map cached kernel.
    mesh: object = None
    # stacked (F, padded) value arrays per column tuple — stacking is a
    # device op, so reuse the result across steady-state queries.
    _stacks: dict = None

    def values_for(self, names: list[str]):
        key = tuple(names)
        if self._stacks is None:
            self._stacks = {}
        out = self._stacks.get(key)
        if out is None:
            out = jnp.stack([self.value_cols_dev[n] for n in names])
            if self.mesh is not None:
                from jax.sharding import NamedSharding, PartitionSpec as P
                import jax

                out = jax.device_put(out, NamedSharding(self.mesh, P(None, "shard")))
            self._stacks[key] = out
        return out


class ScanCache:
    def __init__(self, max_entries: int = 4) -> None:
        self._entries: dict[str, CachedTableScan] = {}
        # fingerprint last seen per table: a cache build is only worth the
        # full-table read once the data has been STABLE across two
        # consecutive eligible queries (a write-heavy table would otherwise
        # rebuild — full read + upload — on every single query).
        self._candidate: dict[str, tuple] = {}
        self._lock = threading.Lock()
        self.max_entries = max_entries
        self.hits = 0
        self.misses = 0

    def get(
        self,
        table,
        value_columns: list[str],
        read_rows,
    ) -> tuple[Optional[CachedTableScan], bool]:
        """(cached scan state, was_built_this_call) for ``table``.

        ``read_rows()`` materializes the full-table merged rows on miss.
        Entry is None when the table's shape doesn't fit the cached-kernel
        contract (span overflow, empty table), or when the data hasn't been
        stable long enough to justify a build.
        """
        fp = _fingerprint(table)
        from ..parallel.mesh import serving_mesh

        mesh_now = serving_mesh()
        with self._lock:
            entry = self._entries.get(table.name)
            if entry is not None and entry.mesh is not None and entry.mesh is not mesh_now:
                # Device set changed (mesh rebuilt): sharded arrays are
                # placed on the old mesh — rebuild from scratch.
                self._entries.pop(table.name, None)
                entry = None
            if entry is not None and entry.fingerprint == fp:
                if all(c in entry.value_cols_dev for c in value_columns):
                    self.hits += 1
                    return entry, False
                # same data, new columns: extend the entry in place
                self._extend(entry, value_columns)
                self.hits += 1
                return entry, False
            if self._candidate.get(table.name) != fp:
                # first sighting of this table state: don't build yet
                self._candidate[table.name] = fp
                self.misses += 1
                return None, False
        rows = read_rows()
        n = len(rows)
        if n == 0:
            return None, False
        ts = rows.timestamps
        min_ts, max_ts = int(ts.min()), int(ts.max())
        if max_ts - min_ts >= _I32_MAX:
            return None, False
        entry = self._build(fp, rows, min_ts, max_ts, value_columns)
        with self._lock:
            self.misses += 1
            if table.name not in self._entries and len(self._entries) >= self.max_entries:
                self._entries.pop(next(iter(self._entries)))
            self._entries[table.name] = entry
        return entry, True

    def _build(
        self, fp, rows: RowGroup, min_ts: int, max_ts: int, value_columns: list[str]
    ) -> CachedTableScan:
        n = len(rows)
        schema = rows.schema
        tsid = rows.columns[schema.columns[schema.tsid_index].name]
        uniq, first_idx, inverse = np.unique(tsid, return_index=True, return_inverse=True)
        n_series = len(uniq)
        # pad rows carry series code n_series -> masked out by the kernel
        codes = pad_to_bucket(inverse.astype(np.int32), n, fill=n_series)
        ts_rel = pad_to_bucket(
            (rows.timestamps - min_ts).astype(np.int32), n, fill=np.int32(-1)
        )
        # Multi-device: the big row arrays live SHARDED across the mesh so
        # steady-state serving is itself distributed (each chip holds and
        # scans 1/Nth of the table; combine rides the collectives). Small
        # tables stay single-device — same threshold as the uncached path
        # (collective dispatch would dominate).
        from ..parallel.mesh import dist_min_rows, serving_mesh

        mesh = serving_mesh() if n >= dist_min_rows() else None
        place = None
        if mesh is not None:
            n_dev = int(mesh.devices.size)
            if len(codes) % n_dev:
                extra = n_dev - len(codes) % n_dev
                codes = np.pad(codes, (0, extra), constant_values=n_series)
                ts_rel = np.pad(ts_rel, (0, extra), constant_values=-1)
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            place = NamedSharding(mesh, P("shard"))
            codes_dev = jax.device_put(codes, place)
            ts_dev = jax.device_put(ts_rel, place)
        else:
            codes_dev = jnp.asarray(codes)
            ts_dev = jnp.asarray(ts_rel)
        entry = CachedTableScan(
            fingerprint=fp,
            rows=rows,
            n_valid=n,
            min_ts=min_ts,
            max_ts=max_ts,
            series_first_idx=first_idx,
            n_series=n_series,
            series_codes_dev=codes_dev,
            ts_rel_dev=ts_dev,
            value_cols_dev={},
            mesh=mesh,
        )
        self._extend(entry, value_columns)
        return entry

    def _extend(self, entry: CachedTableScan, value_columns: list[str]) -> None:
        target = len(entry.series_codes_dev)  # includes any mesh padding
        place = None
        if entry.mesh is not None:
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            place = NamedSharding(entry.mesh, P("shard"))
        for c in value_columns:
            if c not in entry.value_cols_dev:
                arr = as_values(entry.rows.column(c)).astype(np.float32, copy=False)
                padded = np.pad(arr, (0, target - len(arr)))
                if place is not None:
                    entry.value_cols_dev[c] = jax.device_put(padded, place)
                else:
                    entry.value_cols_dev[c] = jnp.asarray(padded)
                entry._stacks = None  # stale stacked views

    def invalidate(self, table_name: str) -> None:
        with self._lock:
            self._entries.pop(table_name, None)


def _fingerprint(table) -> tuple:
    parts = []
    for data in table.physical_datas():
        files = tuple(
            (h.level, h.file_id) for h in data.version.levels.all_files()
        )
        parts.append(
            (
                data.table_id,
                data.schema.version,  # ALTER invalidates even with no writes
                data.last_sequence,
                data.version.flushed_sequence,
                files,
            )
        )
    return tuple(parts)
