"""Query layer: SQL front end -> Plan -> interpreters -> executor.

Mirrors the reference's query_frontend / interpreters / query_engine split
(SURVEY §2.1): a hand-rolled SQL parser with the time-series extensions
(TAG columns, TIMESTAMP KEY, ENGINE=, WITH options — ref: parser.rs:140-363
extends sqlparser-rs the same way), a ``Plan`` sum type (ref: plan.rs:67),
interpreters dispatching per plan variant (ref: factory.rs:70), and an
executor that compiles scan+filter+group-by+aggregate plans into the fused
TPU kernel with a vectorized-numpy fallback for everything else.
"""

from .frontend import Frontend
from .plan import (
    AlterTablePlan,
    CreateTablePlan,
    DescribePlan,
    DropTablePlan,
    ExistsPlan,
    InsertPlan,
    Plan,
    QueryPlan,
    ShowCreatePlan,
    ShowTablesPlan,
)

__all__ = [
    "Frontend",
    "Plan",
    "QueryPlan",
    "InsertPlan",
    "CreateTablePlan",
    "DropTablePlan",
    "DescribePlan",
    "AlterTablePlan",
    "ShowTablesPlan",
    "ShowCreatePlan",
    "ExistsPlan",
]
