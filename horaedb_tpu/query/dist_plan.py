"""Distributed plan shipping — execute plan subtrees on partition owners
(ref: df_engine_extensions/src/dist_sql_query/resolver.rs:105-120 — the
reference resolves an UnresolvedPartitionedScan into per-partition remote
plan executions; remote_engine_client/src/client.rs:484
``execute_physical_plan``).

Before this module, only *partial aggregates* shipped (query/partial.py);
every other distributed query pulled raw rows across the DCN and computed
at the coordinator. Here whole plan shapes execute where the data lives:

- ``window``  — window functions whose every PARTITION BY covers the
  table's partition rule columns: rows of one window partition share the
  rule hash, so per-owner execution is exact; the coordinator just
  concatenates and re-applies the outer ORDER BY/LIMIT.
- ``agg``     — non-kernel aggregates (FILTER clauses, approx/statistical
  functions) whose GROUP BY covers the rule columns: every group lives in
  exactly one partition, so owners run the FULL aggregate (HAVING
  included) and the coordinator concatenates — no combine step at all.
- ``topk``    — ORDER BY + LIMIT: owners return their local top
  limit+offset rows, the coordinator merges and re-limits.
- ``distinct``— owners dedup locally, the coordinator dedups the union.
- ``filter``  — residual WHERE the storage predicate could not express
  (e.g. ``a + b > 3``): owners evaluate it exactly and return only
  matching rows instead of the whole partition.

The modes share one correctness obligation: the coordinator's combine
(concat [+ dedup] + outer ORDER BY/LIMIT/OFFSET) must be expressible over
the shipped results' OUTPUT columns — checked up front, falling back to
the raw-row path when it isn't.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ..remote.plan_codec import PlanNotShippable, select_to_wire
from . import ast
from .plan import QueryPlan


def dist_plan_mode(executor, plan: QueryPlan, table) -> Optional[str]:
    """Which shipping mode (if any) this plan takes over this partitioned
    table. Pure analysis — EXPLAIN calls it too."""
    rule = getattr(table, "rule", None)
    if rule is None or not hasattr(table, "sub_tables"):
        return None
    stmt = plan.select
    if stmt is None or stmt.join is not None or stmt.ctes:
        return None
    # Embedded runtime state (correlated lookups) or pre-materialization
    # subqueries can't ship; planner/interpreter substitutions happen
    # before the executor, so anything left is a refusal.
    from .planner import _walk

    for src in _expr_sources(stmt):
        for e in _walk(src):
            if isinstance(
                e,
                (ast.Subquery, ast.InSubquery, ast.Exists, ast.CorrelatedLookup),
            ):
                return None

    windows = [
        e
        for item in stmt.items
        for e in _walk(item.expr)
        if isinstance(e, ast.WindowFunc)
    ]
    rule_cols = set(rule.columns)

    if plan.is_aggregate:
        if windows:
            return None
        # The partial-agg path (device kernel + combine) is preferred and
        # runs first; full-agg shipping handles the shapes it refuses,
        # provided groups are partition-local.
        if executor is not None and executor._agg_device_shape(plan) is not None:
            from .partial import spec_from_plan

            if spec_from_plan(executor, plan) is not None:
                return None
        group_cols = {k.column for k in plan.group_keys if k.column is not None}
        if not rule_cols or not rule_cols <= group_cols:
            return None
        if not _order_resolvable(stmt, plan):
            return None
        return "agg"

    if windows:
        for w in windows:
            part_cols = {
                p.name for p in w.spec.partition_by if isinstance(p, ast.Column)
            }
            if not rule_cols or not rule_cols <= part_cols:
                return None
        if not _order_resolvable(stmt, plan):
            return None
        return "window"

    if stmt.distinct:
        if not _order_resolvable(stmt, plan):
            return None
        return "distinct"

    if stmt.order_by and stmt.limit is not None:
        if not _order_resolvable(stmt, plan):
            return None
        return "topk"

    # Residual WHERE: filter on the owner instead of pulling every row.
    if (
        not stmt.order_by
        and executor is not None
        and executor._residual_where(plan) is not None
    ):
        return "filter"
    return None


def try_dist_plan(executor, plan: QueryPlan, table, m: dict):
    """Execute ``plan`` by shipping it to partition owners; None when the
    shape doesn't ship (caller falls back to the raw-row scan path)."""
    mode = dist_plan_mode(executor, plan, table)
    if mode is None:
        return None

    keep = table.rule.prune(plan.predicate)
    subs = (
        table.sub_tables
        if keep is None
        else [table.sub_tables[i] for i in keep]
    )
    sub_select = _sub_select(plan.select, mode)
    try:
        # Validate encodability ONCE before fanning out.
        select_to_wire(dataclasses.replace(sub_select, table="_"))
    except PlanNotShippable:
        return None

    import contextvars

    from ..utils.runtime import scatter_pool
    from ..utils.tracectx import span, wire_context

    def run_one(sub):
        # Runs inside a COPY of the coordinator's context: the partition
        # span lands under the dist_fanout span, and the wire context's
        # parent_span_id points at THIS partition's span — the owner's
        # subtree grafts back exactly where it belongs.
        with span("partition", partition=sub.name):
            wire = select_to_wire(
                dataclasses.replace(sub_select, table=sub.name)
            )
            shipped = getattr(sub, "execute_plan", None)
            if shipped is not None:
                out = shipped(
                    {"plan": wire, "trace": wire_context() or {"request_id": None}}
                )
                if out is not None:
                    return out  # (names, columns, nulls, metrics)
            sub_plan = dataclasses.replace(
                plan,
                table=sub.name,
                select=dataclasses.replace(sub_select, table=sub.name),
            )
            rs = executor.execute(sub_plan, sub)
            return rs.names, rs.columns, rs.nulls, {
                "partition": sub.name,
                "local": True,
                **{k: v for k, v in (rs.metrics or {}).items()
                   if k in ("path", "scan_ms", "rows_scanned", "total_ms")},
            }

    from ..utils.querystats import record as _qs_record

    _qs_record(fanout=len(subs))
    with span("dist_fanout", mode=mode, partitions=len(subs)):
        if len(subs) == 1:
            parts = [run_one(subs[0])]
        else:
            # one context copy per task — a single Context can't be
            # entered by two pool threads at once
            ctxs = [contextvars.copy_context() for _ in subs]
            parts = list(
                scatter_pool().map(
                    lambda cs: cs[0].run(run_one, cs[1]), zip(ctxs, subs)
                )
            )

    from .executor import ResultSet, _order_and_limit

    names = None
    col_parts: list[list[np.ndarray]] = []
    null_parts: list[dict] = []
    stage_metrics = []
    for p_names, p_cols, p_nulls, p_metrics in parts:
        stage_metrics.append(p_metrics)
        if names is None:
            names = p_names
        if p_cols and len(p_cols[0]):
            col_parts.append(p_cols)
            null_parts.append(p_nulls or {})
    m["dist_plan"] = mode
    m["partitions"] = len(subs)
    m["dist_stages"] = stage_metrics
    if names is None:
        # Every partition pruned away or returned empty: derive the output
        # shape from the select list, expanding ``*`` against the table
        # schema exactly as a sub-execution would have — the empty result
        # must not grow a column literally named "*".
        names = []
        for item in plan.select.items:
            if isinstance(item.expr, ast.Star):
                names.extend(
                    c.name for c in plan.schema.columns
                    if not c.name.startswith("__hidden_")
                )
            else:
                names.append(item.output_name)
    if not col_parts:
        result = ResultSet.empty(list(names))
    else:
        cols = [
            _concat_aligned([p[i] for p in col_parts])
            for i in range(len(names))
        ]
        nulls: dict[str, np.ndarray] = {}
        for i, name in enumerate(names):
            if any(name in np_ for np_ in null_parts):
                nulls[name] = np.concatenate(
                    [
                        np_.get(name, np.zeros(len(p[i]), dtype=bool))
                        for np_, p in zip(null_parts, col_parts)
                    ]
                )
        result = ResultSet(list(names), cols, nulls or None)

    # Owners already applied HAVING (mode "agg") — the coordinator only
    # dedups (stmt.distinct, handled once inside _order_and_limit: the
    # union of per-owner DISTINCT sets can repeat across partitions) and
    # re-sorts/limits over output columns.
    coord_plan = dataclasses.replace(
        plan, select=dataclasses.replace(plan.select, having=None)
    )
    return _order_and_limit(result, coord_plan)


def _concat_aligned(arrays: list[np.ndarray]) -> np.ndarray:
    """Concat per-partition result columns, unifying dtypes (an empty or
    all-NULL partition may have produced a narrower dtype)."""
    if len(arrays) == 1:
        return arrays[0]
    kinds = {a.dtype.kind for a in arrays}
    if len({a.dtype for a in arrays}) == 1:
        return np.concatenate(arrays)
    if kinds <= {"i", "u", "b"}:
        # Pure integer/bool mixes stay exact: routing them through
        # float64 would corrupt int64 values above 2^53. A uint64 value
        # past int64's range can't stay exact in EITHER fixed dtype next
        # to signed values — object preserves it instead of wrapping.
        if any(
            a.dtype == np.uint64 and len(a) and a.max() > np.iinfo(np.int64).max
            for a in arrays
        ):
            return np.concatenate([a.astype(object) for a in arrays])
        return np.concatenate([a.astype(np.int64) for a in arrays])
    if kinds <= {"i", "u", "f", "b"}:
        return np.concatenate([a.astype(np.float64) for a in arrays])
    return np.concatenate([a.astype(object) for a in arrays])


def _sub_select(stmt: ast.Select, mode: str) -> ast.Select:
    """The per-owner Select for a shipping mode (table patched later)."""
    if mode in ("window", "agg", "distinct"):
        # Coordinator re-applies ordering; owners need the full set (but
        # a DISTINCT owner without ordering can stop at limit+offset).
        limit = None
        if mode == "distinct" and not stmt.order_by and stmt.limit is not None:
            limit = stmt.limit + stmt.offset
        return dataclasses.replace(
            stmt, order_by=(), limit=limit, offset=0
        )
    if mode == "topk":
        return dataclasses.replace(
            stmt, limit=stmt.limit + stmt.offset, offset=0
        )
    # mode == "filter": push LIMIT when nothing else needs the full set.
    limit = None
    if stmt.limit is not None and not stmt.order_by:
        limit = stmt.limit + stmt.offset
    return dataclasses.replace(stmt, limit=limit, offset=0)


def _order_resolvable(stmt: ast.Select, plan: QueryPlan) -> bool:
    """Can the coordinator re-sort the combined output rows? Mirrors
    executor._order_and_limit's resolution: each ORDER BY key must name an
    output column (directly, by rendered expression, or by alias)."""
    if not stmt.order_by:
        return True
    outputs = set()
    star = False
    for item in stmt.items:
        if isinstance(item.expr, ast.Star):
            star = True
        else:
            outputs.add(item.output_name)
            if item.alias:
                outputs.add(item.alias)
    if star:
        outputs |= {c.name for c in plan.schema.columns}
    for o in stmt.order_by:
        if isinstance(o.expr, ast.Column) and o.expr.name in outputs:
            continue
        if str(o.expr) in outputs:
            continue
        return False
    return True


def _expr_sources(select: ast.Select) -> list:
    out = [item.expr for item in select.items]
    out += [
        e
        for e in (select.where, select.having, *select.group_by)
        if e is not None
    ]
    out += [o.expr for o in select.order_by]
    return out
