"""Interpreters: Plan -> effect (ref: src/interpreters, factory.rs:70).

One interpreter per plan variant, dispatched by ``InterpreterFactory``;
outputs are either a ``ResultSet`` (queries, SHOW/DESCRIBE) or an affected
row count (writes, DDL) — mirroring the reference's ``Output`` enum.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Union

import numpy as np

from ..catalog import Catalog
from ..common_types.row_group import RowGroup
from ..engine.options import format_duration
from . import ast
from .executor import Executor, ResultSet
from .plan import (
    AlterTablePlan,
    CTEPlan,
    CreateTablePlan,
    DescribePlan,
    DropTablePlan,
    ExistsPlan,
    ExplainPlan,
    InsertPlan,
    Plan,
    QueryPlan,
    ShowCreatePlan,
    ShowTablesPlan,
    UnionPlan,
)


def _walk_all(e):
    """Generic expression walker that also SEES subquery nodes (does not
    descend into their inner selects — those are separate scopes)."""
    yield e
    for name in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, name)
        if isinstance(v, ast.Expr):
            yield from _walk_all(v)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, ast.Expr):
                    yield from _walk_all(x)


def _flatten_and(e: ast.Expr) -> list:
    if isinstance(e, ast.BinaryOp) and e.op == "AND":
        return _flatten_and(e.left) + _flatten_and(e.right)
    return [e]


def _inner_tables_of(select: ast.Select) -> set:
    # NOTE: keep in sync with the scope computation in
    # _materialize_subqueries — both must cover the full join chain.
    return {
        t
        for t in (select.table, select.join.table if select.join else None)
        if t
    } | {j.table for j in select.joins}


def _correlated_cols(exprs, scope, inner_tables) -> list:
    """Columns qualified by an OUTER-scope table (the correlation refs).
    Unqualified names always resolve inner — outer references must be
    qualified (documented restriction)."""
    return [
        x
        for src in exprs
        if src is not None
        for x in _walk_all(src)
        if isinstance(x, ast.Column)
        and x.qualifier
        and x.qualifier in scope
        and x.qualifier not in inner_tables
    ]


def _has_correlated_refs(select: ast.Select, scope) -> bool:
    inner = _inner_tables_of(select)
    sources = InterpreterFactory._expr_sources(select)
    return bool(_correlated_cols(sources, scope, inner))


@dataclass(frozen=True)
class AffectedRows:
    count: int


Output = Union[ResultSet, AffectedRows]


class InterpreterError(ValueError):
    pass


class InterpreterFactory:
    def __init__(self, catalog: Catalog) -> None:
        self.catalog = catalog
        self.executor = Executor()

    def execute(self, plan: Plan) -> Output:
        if isinstance(plan, QueryPlan):
            return self._select(plan)
        if isinstance(plan, InsertPlan):
            return self._insert(plan)
        if isinstance(plan, CreateTablePlan):
            return self._create(plan)
        if isinstance(plan, DropTablePlan):
            dropped = self.catalog.drop_table(plan.table, plan.if_exists)
            return AffectedRows(1 if dropped else 0)
        if isinstance(plan, DescribePlan):
            return self._describe(plan)
        if isinstance(plan, ShowTablesPlan):
            names = self.catalog.table_names()
            return ResultSet(["Tables"], [np.array(names, dtype=object)])
        if isinstance(plan, ShowCreatePlan):
            return self._show_create(plan)
        if isinstance(plan, ExistsPlan):
            return ResultSet(
                ["result"], [np.array([1 if self.catalog.exists(plan.table) else 0])]
            )
        if isinstance(plan, AlterTablePlan):
            return self._alter(plan)
        if isinstance(plan, ExplainPlan):
            return self._explain(plan)
        if isinstance(plan, UnionPlan):
            return self._union(plan)
        if isinstance(plan, CTEPlan):
            return self._cte(plan)
        from .plan import KillQueryPlan

        if isinstance(plan, KillQueryPlan):
            # cooperative kill: flip the cancel flag; the victim unwinds
            # at its next checkpoint and releases every slot it holds
            from ..utils.deadline import QUERY_REGISTRY

            if not QUERY_REGISTRY.kill(plan.query_id, source="kill"):
                raise InterpreterError(
                    f"no live query with id {plan.query_id} "
                    "(see system.public.queries)"
                )
            return AffectedRows(1)
        raise InterpreterError(f"no interpreter for {type(plan).__name__}")

    # ---- UNION / CTE -----------------------------------------------------
    def _union(self, plan: UnionPlan) -> ResultSet:
        """Branches execute independently (each on its own best path);
        results align by position, names from the first branch, folded
        left-to-right: each distinct UNION dedups everything accumulated
        so far, each UNION ALL appends (standard left-associative
        semantics — `a UNION b UNION ALL c` keeps c's duplicates)."""
        from .executor import _distinct_result

        results = [self._select(b) for b in plan.branches]
        combined = results[0]
        for i, res in enumerate(results[1:]):
            combined = _concat_results([combined, res])
            if not plan.all_flags[i]:
                combined = _distinct_result(combined)
        return _order_limit_result(combined, plan.order_by, plan.limit, plan.offset)

    def _cte(self, plan: CTEPlan) -> Output:
        """WITH bindings materialize in order into an overlay of in-memory
        tables (later ctes and the outer statement see earlier ones); the
        outer statement then plans + executes against the overlay (ref:
        DataFusion CTEs via LogicalPlan inlining; materialization keeps
        each cte single-execution like DataFusion's cte work-table)."""
        from .planner import Planner

        overlay: dict = {}

        def schema_of(name: str):
            t = overlay.get(name)
            if t is not None:
                return t.schema
            return self.catalog.schema_of(name)

        planner = Planner(schema_of)
        sub = self._overlay_factory(overlay)
        for name, stmt in plan.ctes:
            if name in overlay or self.catalog.exists(name):
                raise InterpreterError(f"cte name {name!r} shadows an existing table")
            p = planner.plan(stmt)
            res = sub.execute(p)
            overlay[name] = _result_to_table(name, res, p)
        return sub.execute(planner.plan(plan.inner))

    def _overlay_factory(self, overlay: dict) -> "InterpreterFactory":
        f = object.__new__(InterpreterFactory)
        f.catalog = _OverlayCatalog(self.catalog, overlay)
        f.executor = self.executor  # share scan cache / router state
        return f

    def _explain(self, plan: ExplainPlan) -> ResultSet:
        """Textual plan tree (ref: EXPLAIN over DataFusion plans)."""
        q = plan.inner
        if isinstance(q, UnionPlan):
            if plan.analyze:
                # guard HERE, where the capability gap lives (the parser
                # also rejects, but programmatic AST producers bypass it)
                raise InterpreterError("EXPLAIN ANALYZE over UNION is not supported")
            order = ", ".join(
                f"{o.expr}{'' if o.ascending else ' DESC'}" for o in q.order_by
            )
            lines = [
                f"Union: branches={len(q.branches)} "
                f"all_flags={list(q.all_flags)}"
                + (f" order_by=[{order}]" if order else "")
                + f" limit={q.limit} offset={q.offset}"
            ]
            for i, b in enumerate(q.branches):
                lines.append(f"  Branch {i}:")
                lines.extend(
                    "    " + l for l in self._explain_query_lines(b, analyze=False)
                )
            return ResultSet(["plan"], [np.array(lines, dtype=object)])
        return ResultSet(
            ["plan"],
            [np.array(self._explain_query_lines(q, plan.analyze), dtype=object)],
        )

    def _explain_query_lines(self, q: QueryPlan, analyze: bool) -> list[str]:
        table = self.catalog.open(q.table)
        lines = []
        tr = q.predicate.time_range
        lines.append(f"Query: table={q.table} priority={q.priority.value}")
        # the workload manager's verdict for this plan shape (wlm/admission)
        from ..wlm.admission import classify_plan, lane_for

        adm_class, est_ms = classify_plan(q)
        lines.append(
            f"  Admission: class={adm_class} lane={lane_for(adm_class)}"
            + (f" est_ms={est_ms:.1f}" if est_ms is not None else "")
        )
        lines.append(
            f"  TimeRange: [{tr.inclusive_start}, {tr.exclusive_end})"
        )
        # Follower-served EXPLAIN (gateway replica path): say so — the
        # plan below describes LOCAL read-only state, not the leader's.
        from ..cluster.replica import replica_context

        _rc = replica_context()
        if _rc is not None:
            lines.append(
                f"  Replica: route=follower epoch={_rc['epoch']} "
                f"watermark_lag_ms={_rc['lag_ms']}"
            )
        if q.predicate.filters:
            fs = ", ".join(
                f"{f.column} {f.op.value} {f.value!r}" for f in q.predicate.filters
            )
            lines.append(f"  PushedFilters: {fs}")
        if q.is_aggregate:
            keys = ", ".join(k.output_name for k in q.group_keys) or "(none)"
            aggs = ", ".join(
                f"{a.func}({a.column or '*'})"
                + (f" FILTER (WHERE {a.filter_where})" if a.filter_where is not None else "")
                for a in q.aggs
            )
            lines.append(f"  Aggregate: keys=[{keys}] aggs=[{aggs}]")
            # same shared predicate the executor hook serves from — what
            # this line promises is what execution does (route=rollup)
            from ..rules.rewrite import rollup_decision_for

            dec = rollup_decision_for(self.catalog, q)
            if dec is not None:
                lines.append(
                    f"  Rollup: table={dec.rollup_table} tier={dec.suffix} "
                    f"buckets<[{dec.cut}] served pre-aggregated, raw tail "
                    f"[{dec.cut}, {dec.end}) from {q.table} (route=rollup)"
                )
            # live window state: again the ONE executor predicate, so the
            # promise and the serve cannot drift (route=livewindow)
            from ..state.livewindow import livewindow_decision_for

            lw = livewindow_decision_for(self.catalog, q)
            if lw is not None:
                lines.append(
                    f"  LiveWindow: window={lw.step_ms}ms "
                    f"[{lw.s_lo}, {lw.s_hi}) served from device ring state "
                    f"({lw.n_buckets} buckets), raw head [{lw.start}, "
                    f"{lw.s_lo}) (route=livewindow)"
                )
            shape = self.executor._agg_device_shape(q)
            if shape is not None:
                path = "device (fused kernel; HBM-cached when table state is stable)"
                nullable_aggs = [
                    a.column
                    for a in q.aggs
                    if a.column is not None and q.schema.column(a.column).is_nullable
                ]
                if nullable_aggs:
                    path += f" [host fallback if NULLs in {nullable_aggs}]"
            else:
                path = "host"
            lines.append(f"  Execution: {path}")
        else:
            import os as _os

            from ..ops.scan_topk import raw_device_enabled

            # same gate as the executor: plain engine tables only
            # (partitioned plans ship subtrees; raw serving happens on
            # the owners), the scan cache + kill switch open, and never
            # on limit-pushdown-safe plans (the host early-stop scan is
            # unbeatable by construction)
            raw_shape = (
                self.executor._raw_device_shape(q)
                if raw_device_enabled()
                and _os.environ.get("HORAEDB_SCAN_CACHE", "1") != "0"
                and not hasattr(table, "sub_tables")
                and table.physical_datas()
                and not self.executor._limit_pushdown_safe(q)
                else None
            )
            if raw_shape is not None:
                kind = "top-k" if raw_shape["topk_ok"] else "bounded selection"
                lines.append(
                    f"  Execution: raw device ({kind} over the HBM scan "
                    "cache; host fallback when the cache or the "
                    "HORAEDB_RAW_MAX_ROWS budget refuses)"
                )
            else:
                lines.append("  Execution: projection scan (host)")
        from ..table_engine.partition import PartitionedTable

        if isinstance(table, PartitionedTable):
            keep = table.rule.prune(q.predicate)
            shown = "all" if keep is None else str(keep)
            lines.append(
                f"  Partitions: {table.rule.num_partitions} "
                f"({table.rule.method}) scan={shown}"
            )
            from .dist_plan import dist_plan_mode

            mode = dist_plan_mode(self.executor, q, table)
            if mode is not None:
                lines.append(
                    f"  Distributed: ship plan subtree to partition owners "
                    f"(mode={mode}; remote partitions execute via "
                    f"/horaedb.remote_engine/ExecutePlan, coordinator "
                    f"combines + re-applies ORDER/LIMIT)"
                )
        if analyze:
            # EXPLAIN ANALYZE: actually run the query and report observed
            # execution (ref: EXPLAIN ANALYZE carrying runtime metrics +
            # the formatted trace_metric span tree).
            import time as _time

            from ..utils.tracectx import (
                current_trace,
                finish_trace,
                render_tree,
                span,
                start_trace,
            )

            from ..utils.querystats import (
                current_ledger,
                finish_ledger,
                render_ledger,
                start_ledger,
            )

            trace = current_trace()
            handle = None
            if trace is None:
                # direct embedded call (no proxy): own the trace so the
                # tree still lands in TRACE_STORE / /debug/trace
                trace, handle = start_trace(
                    f"explain-{id(q):x}", "explain_analyze", table=q.table
                )
            # A NESTED ledger scoped to the analyzed execution: what this
            # one query cost, untangled from the proxy's statement-wide
            # ledger — then folded back so query_stats stays whole.
            outer_ledger = current_ledger()
            qledger, qtoken = start_ledger(trace.trace_id, "explain analyze")
            try:
                t0 = _time.perf_counter()
                with span("analyze", table=q.table):
                    out = self._execute_query(q, table)
                elapsed = (_time.perf_counter() - t0) * 1000
                lines.append(
                    f"  Analyzed: path={self.executor.last_path} "
                    f"rows={out.num_rows} elapsed={elapsed:.2f}ms"
                )
                m = out.metrics or {}
                detail = ", ".join(
                    f"{k}={v}" for k, v in m.items() if k not in ("table", "path")
                )
                if detail:
                    lines.append(f"  Metrics: {detail}")
                lines.append(f"  Ledger: {render_ledger(qledger)}")
                # Device plane: EXPLAIN ANALYZE dispatches are always
                # timed (obs/device forces sampling for explain runs),
                # so device_ms is present whenever a kernel ran; compile
                # events this run journaled render inline.
                dd = int(qledger.counts.get("device_dispatches", 0))
                if dd:
                    lines.append(
                        f"  Device: dispatches={dd} "
                        f"device_ms={qledger.counts.get('device_ms', 0.0):.3f} "
                        f"compile_hit={int(qledger.counts.get('compile_hit', 0))}"
                    )
                    from ..utils.events import EVENT_STORE

                    for ev in EVENT_STORE.list(kind="kernel_compile"):
                        if ev.get("trace_id") == trace.trace_id:
                            a = ev.get("attrs", {})
                            lines.append(
                                f"  Compile: kernel={a.get('kernel')} "
                                f"wall_ms={a.get('wall_ms')} "
                                f"shape={a.get('shape')}"
                            )
                # Decision plane: any adaptive decision journaled under
                # THIS run's trace (the kernel router's impl pick for a
                # routed aggregation) renders with its prediction and —
                # the run just finished, so the resolve landed — the
                # realized seconds and relative error.
                from ..obs.decisions import DECISION_JOURNAL

                for de in DECISION_JOURNAL.list():
                    if de.get("trace_id") == trace.trace_id:
                        parts = [
                            f"  Decision: loop={de['loop']} "
                            f"choice={de['choice']}"
                        ]
                        if de["predicted"] is not None:
                            parts.append(f"predicted={de['predicted']:.6f}")
                        if de["actual"] is not None:
                            parts.append(f"actual={de['actual']:.6f}")
                        if de["error"] is not None:
                            parts.append(f"error={de['error']:+.3f}")
                        if de["outcome"]:
                            parts.append(f"outcome={de['outcome']}")
                        lines.append(" ".join(parts))
                if handle is not None:
                    trace.root.finish()  # owned: closed before rendering
                tree = trace.to_dict()["root"]
                lines.append(f"  Trace: request_id={trace.trace_id}")
                lines.extend("    " + l for l in render_tree(tree, 0))
                # Profile plane: the max-time chain through this run's
                # tree — the hop where inclusive≈self is where the
                # wall-clock actually went.
                from ..obs.profile import render_critical_path

                cp = render_critical_path(tree)
                if cp:
                    lines.append(f"  Critical path: {cp}")
            finally:
                # an execute error must still reset the ContextVars — a
                # leaked trace would swallow every later query's spans
                finish_ledger(qledger, qtoken, 0.0, record_stats=False)
                if outer_ledger is not None:
                    outer_ledger.merge_remote(qledger.to_dict())
                    if qledger.route:
                        outer_ledger.set_route(qledger.route)
                if handle is not None:
                    finish_trace(handle)
        return lines

    # ---- variants -----------------------------------------------------------
    def _select(self, plan: QueryPlan) -> ResultSet:
        rewritten = self._materialize_subqueries(plan)
        if rewritten is not None:
            plan = rewritten
        if plan.select.join is not None:
            from .join import execute_join

            return execute_join(self.catalog, self.executor, plan.select)
        table = self.catalog.open(plan.table)
        if table is None:
            raise InterpreterError(f"table not found: {plan.table}")
        return self._execute_query(plan, table)

    def execute_cohort(self, plans: list) -> list:
        """Execute a cohort of shape-identical SELECT plans, fusing as
        many as possible into single batched device dispatches
        (wlm/batch hands cohorts here through the proxy). Returns one
        Output-or-exception per plan, positionally — a member whose
        execution fails poisons only its own slot. Members needing
        machinery the fused path cannot serve (subqueries, joins,
        rollup rewrites, unknown tables) execute solo in place."""
        outcomes: list = [None] * len(plans)
        by_table: dict[str, list] = {}
        for i, plan in enumerate(plans):
            try:
                rewritten = self._materialize_subqueries(plan)
                p = rewritten if rewritten is not None else plan
                if p.select.join is not None:
                    outcomes[i] = self._select(p)
                    continue
                from ..rules.rewrite import try_rollup_serve
                from ..state.livewindow import try_livewindow_serve

                out = try_livewindow_serve(self, p)
                if out is None:
                    out = try_rollup_serve(self, p)
                if out is not None:
                    outcomes[i] = out
                    continue
                by_table.setdefault(p.table, []).append((i, p))
            except BaseException as e:
                outcomes[i] = e
        for table_name, grp in by_table.items():
            table = self.catalog.open(table_name)
            if table is None:
                err = InterpreterError(f"table not found: {table_name}")
                for i, _ in grp:
                    outcomes[i] = err
                continue
            results = self.executor.execute_cohort([p for _, p in grp], table)
            for (i, _), r in zip(grp, results):
                outcomes[i] = r
        return outcomes

    def _execute_query(self, plan: QueryPlan, table) -> ResultSet:
        """One door to query execution (SELECT and EXPLAIN ANALYZE both
        pass through): a step-compatible dashboard aggregate over a
        rollup-maintained table serves from the tier tables
        (rules/rewrite, ``route=rollup``); an eligible open-tail window
        aggregate serves head-from-rollup + tail-from-state
        (state/livewindow, ``route=livewindow``); everything else takes
        the executor's normal paths."""
        from ..rules.rewrite import try_rollup_serve
        from ..state.livewindow import try_livewindow_serve

        out = try_livewindow_serve(self, plan)
        if out is not None:
            return out
        out = try_rollup_serve(self, plan)
        if out is not None:
            return out
        return self.executor.execute(plan, table)

    @staticmethod
    def _expr_sources(select: ast.Select) -> list:
        """Every expression-bearing position of a Select — the ONE list
        subquery materialization and correlation checking both walk (a
        new expr-bearing clause must be added here once, not N times)."""
        out = [item.expr for item in select.items]
        out += [
            e
            for e in (select.where, select.having, *select.group_by)
            if e is not None
        ]
        out += [o.expr for o in select.order_by]
        return out

    def _materialize_subqueries(self, plan: QueryPlan, outer_scope=frozenset()):
        """Uncorrelated subqueries run FIRST and substitute as literals
        (ref: the reference gets subqueries from DataFusion; this is the
        uncorrelated subset): ``IN (SELECT ...)`` becomes an InList of the
        inner result's values, a scalar ``(SELECT ...)`` becomes one
        Literal. Returns a re-planned QueryPlan, or None if the statement
        has no subqueries."""
        stmt = plan.select
        sources = self._expr_sources(stmt)
        if not any(
            isinstance(e, (ast.InSubquery, ast.Subquery, ast.Exists))
            for src in sources
            for e in _walk_all(src)
        ):
            return None

        from .planner import Planner

        planner = Planner(self.catalog.schema_of)
        # the full outer scope: every enclosing query's tables, so nested
        # subqueries still get the clear correlation error
        scope = set(outer_scope) | {
            t for t in (stmt.table, stmt.join.table if stmt.join else None) if t
        } | {j.table for j in stmt.joins}

        def run_inner(select: ast.Select) -> list:
            # A qualifier naming an OUTER-scope table means the subquery
            # is correlated — say so directly instead of letting the inner
            # planner report a baffling "unknown qualifier".
            inner_tables = _inner_tables_of(select)
            for src in self._expr_sources(select):
                for e in _walk_all(src):
                    if (
                        isinstance(e, ast.Column)
                        and e.qualifier
                        and e.qualifier in scope
                        and e.qualifier not in inner_tables
                    ):
                        raise InterpreterError(
                            f"correlated subqueries are not supported: "
                            f"{e.qualifier}.{e.name} references the outer "
                            f"query's table {e.qualifier!r}"
                        )
            inner_plan = planner.plan(select)
            nested = self._materialize_subqueries(inner_plan, outer_scope=scope)
            inner = self.execute(nested if nested is not None else inner_plan)
            if not isinstance(inner, ResultSet):
                raise InterpreterError("subquery must be a SELECT")
            if len(inner.names) != 1:
                raise InterpreterError(
                    f"subquery must return one column, got {inner.names}"
                )
            nulls = (inner.nulls or {}).get(inner.names[0])
            col = inner.columns[0]
            return [
                v.item() if isinstance(v, np.generic) else v
                for i, v in enumerate(col)
                if nulls is None or not nulls[i]
            ]

        import dataclasses

        def subst(e: ast.Expr) -> ast.Expr:
            if isinstance(e, ast.InSubquery):
                vals = run_inner(e.select)
                return ast.InList(
                    subst(e.expr), tuple(ast.Literal(v) for v in vals), e.negated
                )
            if isinstance(e, ast.Exists):
                if _has_correlated_refs(e.select, scope):
                    # Equality-correlated semi-join: decorrelate into a
                    # distinct-key inner query + boolean membership lookup.
                    return self._decorrelate_exists(e.select, scope, planner)
                # Uncorrelated: EXISTS is a constant — one row after the
                # subquery's own LIMIT/OFFSET decides it (LIMIT 0 stays
                # empty; OFFSET is honored by the probe).
                import dataclasses as _dc

                probe = _dc.replace(
                    e.select,
                    limit=1 if e.select.limit is None else min(e.select.limit, 1),
                )
                inner_plan = planner.plan(probe)
                nested = self._materialize_subqueries(
                    inner_plan, outer_scope=scope
                )
                inner = self.execute(
                    nested if nested is not None else inner_plan
                )
                if not isinstance(inner, ResultSet):
                    raise InterpreterError("EXISTS subquery must be a SELECT")
                return ast.Literal(inner.num_rows > 0)
            if isinstance(e, ast.Subquery):
                if _has_correlated_refs(e.select, scope):
                    # Equality-correlated scalar aggregate: decorrelate
                    # into one grouped inner query + per-row lookup.
                    return self._decorrelate_scalar(e.select, scope, planner)
                vals = run_inner(e.select)
                if len(vals) > 1:
                    raise InterpreterError(
                        f"scalar subquery returned {len(vals)} rows"
                    )
                return ast.Literal(vals[0] if vals else None)
            # Generic rebuild mirroring _walk_all: any Expr-typed field
            # (or tuple of them) may hide a subquery — FuncCall args,
            # InList values, IsNull, everything current and future.
            if dataclasses.is_dataclass(e):
                changes = {}
                for name in e.__dataclass_fields__:
                    v = getattr(e, name)
                    if isinstance(v, ast.Expr):
                        nv = subst(v)
                        if nv is not v:
                            changes[name] = nv
                    elif isinstance(v, tuple) and any(
                        isinstance(x, ast.Expr) for x in v
                    ):
                        nv = tuple(
                            subst(x) if isinstance(x, ast.Expr) else x for x in v
                        )
                        if nv != v:
                            changes[name] = nv
                if changes:
                    return dataclasses.replace(e, **changes)
            return e

        new_stmt = dataclasses.replace(
            stmt,
            items=tuple(
                dataclasses.replace(item, expr=subst(item.expr))
                for item in stmt.items
            ),
            where=subst(stmt.where) if stmt.where is not None else None,
            having=subst(stmt.having) if stmt.having is not None else None,
            group_by=tuple(subst(g) for g in stmt.group_by),
            order_by=tuple(
                dataclasses.replace(o, expr=subst(o.expr)) for o in stmt.order_by
            ),
        )
        return planner.plan(new_stmt)

    def _decorrelate_scalar(
        self, select: ast.Select, scope, planner
    ) -> ast.CorrelatedLookup:
        """Rewrite an equality-correlated scalar aggregate subquery
        (ref: DataFusion's scalar-subquery decorrelation; the classic
        Kim/Neumann unnesting for the equality case):

            (SELECT agg(x) FROM inner
              WHERE inner.k = outer.k [AND uncorrelated...])

        becomes one grouped inner query ``SELECT k, agg(x) ... GROUP BY
        k`` run ONCE, substituted as a per-outer-row lookup on the
        correlation columns. Anything beyond ANDed equality correlation
        raises the established clear error."""
        import dataclasses

        inner_tables = _inner_tables_of(select)

        def unsupported(why: str):
            return InterpreterError(
                f"correlated subquery not supported: {why} (only a single "
                "scalar aggregate with ANDed `inner_col = outer.col` "
                "correlation is decorrelated)"
            )

        if len(select.items) != 1:
            raise unsupported("subquery must select exactly one expression")
        if (
            select.group_by
            or select.having is not None
            or select.order_by
            or select.limit is not None
            or select.offset
            or select.distinct
            or select.join is not None
        ):
            raise unsupported(
                "GROUP BY/HAVING/ORDER BY/LIMIT/OFFSET/DISTINCT/JOIN in the subquery"
            )
        item = select.items[0]
        non_where = [item.expr, *select.group_by]
        if _correlated_cols(non_where, scope, inner_tables):
            raise unsupported("outer reference outside the WHERE clause")

        pairs: list[tuple[str, ast.Column]] = []  # (inner col, outer Column)
        residual: list[ast.Expr] = []
        for conj in _flatten_and(select.where) if select.where is not None else []:
            corr = _correlated_cols([conj], scope, inner_tables)
            if not corr:
                residual.append(conj)
                continue
            ok = (
                isinstance(conj, ast.BinaryOp)
                and conj.op == "="
                and isinstance(conj.left, ast.Column)
                and isinstance(conj.right, ast.Column)
            )
            if not ok:
                raise unsupported(f"non-equality outer reference: {conj}")
            sides = {True: None, False: None}
            for col in (conj.left, conj.right):
                is_outer = bool(
                    col.qualifier
                    and col.qualifier in scope
                    and col.qualifier not in inner_tables
                )
                sides[is_outer] = col
            if sides[True] is None or sides[False] is None:
                raise unsupported(f"both sides of {conj} bind to one scope")
            pairs.append((sides[False].name, sides[True]))

        # One grouped query: correlation keys become GROUP BY columns.
        key_items = tuple(
            ast.SelectItem(ast.Column(inner_col), alias=f"__ck{i}")
            for i, (inner_col, _) in enumerate(pairs)
        )
        where = None
        for conj in residual:
            where = conj if where is None else ast.BinaryOp("AND", where, conj)
        value_item = dataclasses.replace(item, alias="__cv")
        grouped = True
        try:
            inner_plan = planner.plan(
                dataclasses.replace(
                    select,
                    items=(*key_items, value_item),
                    where=where,
                    group_by=tuple(ast.Column(c) for c, _ in pairs),
                )
            )
            grouped = bool(getattr(inner_plan, "is_aggregate", False))
        except Exception:
            grouped = False
        if not grouped:
            # Non-aggregate correlated scalar (SELECT col FROM ... WHERE
            # k = outer.k): legal SQL — fails only if some correlated
            # group yields more than one row (checked below).
            inner_plan = planner.plan(
                dataclasses.replace(
                    select,
                    items=(*key_items, value_item),
                    where=where,
                    group_by=(),
                )
            )
        nested = self._materialize_subqueries(inner_plan, outer_scope=scope)
        res = self.execute(nested if nested is not None else inner_plan)
        if not isinstance(res, ResultSet):
            raise unsupported("subquery must be a SELECT")

        def py(v):
            return v.item() if isinstance(v, np.generic) else v

        nulls = res.nulls or {}
        k = len(pairs)
        key_cols = res.columns[:k]
        val_col = res.columns[k]
        val_null = nulls.get(res.names[k])
        key_nulls = [nulls.get(res.names[i]) for i in range(k)]
        keys, values = [], []
        keyed: dict = {}
        for i in range(len(val_col)):
            if any(kn is not None and kn[i] for kn in key_nulls):
                # `inner.k = outer.k` is NULL (not true) when the inner
                # key is NULL — such rows can never match any outer row,
                # and must not surface as their column's fill value.
                continue
            key = tuple(py(col[i]) for col in key_cols)
            if not grouped and key in keyed:
                # SQL errors only when this key is actually probed by an
                # outer row — mark it and let the lookup raise then.
                values[keyed[key]] = ast.CORRELATED_DUP
                continue
            keyed[key] = len(keys)
            keys.append(key)
            values.append(
                None if (val_null is not None and val_null[i]) else py(val_col[i])
            )
        # SQL empty-group semantics: COUNT over no rows is 0, any other
        # aggregate is NULL.
        is_count = (
            isinstance(item.expr, ast.FuncCall) and item.expr.name == "count"
        )
        return ast.CorrelatedLookup(
            outer_cols=tuple(outer for _, outer in pairs),  # Column nodes
            keys=tuple(keys),
            values=tuple(values),
            default=0 if is_count else None,
        )

    def _decorrelate_exists(
        self, select: ast.Select, scope, planner
    ) -> ast.CorrelatedLookup:
        """Rewrite an equality-correlated EXISTS (the semi-join analog of
        _decorrelate_scalar): ``EXISTS (SELECT ... WHERE inner.k =
        outer.k [AND uncorrelated...])`` runs ONE distinct-key inner
        query and substitutes a per-outer-row boolean membership lookup
        (present -> True; missing or NULL outer key -> False, which NOT
        then flips for anti-join semantics)."""
        import dataclasses

        inner_tables = _inner_tables_of(select)

        def unsupported(why: str):
            return InterpreterError(
                f"correlated EXISTS not supported: {why} (only ANDed "
                "`inner_col = outer.col` correlation in the WHERE is "
                "decorrelated)"
            )

        if (
            select.group_by
            or select.having is not None
            or select.join is not None
            or select.offset
        ):
            raise unsupported("GROUP BY/HAVING/JOIN/OFFSET in the subquery")
        if select.limit is not None and select.limit <= 0:
            return ast.CorrelatedLookup(
                outer_cols=(), keys=(), values=(), default=False
            )
        from .planner import _is_agg_name, _walk

        if any(
            isinstance(x, ast.FuncCall) and _is_agg_name(x.name)
            for item in select.items
            for x in _walk(item.expr)
        ):
            # An ungrouped aggregate subquery yields EXACTLY one row for
            # every outer row (NULL aggregate over the empty group
            # included) — EXISTS is unconditionally TRUE.
            return ast.Literal(True)
        # The select items are irrelevant to EXISTS; only the WHERE's
        # correlation matters (outer refs anywhere else are unsupported).
        if _correlated_cols(
            [i.expr for i in select.items] + [o.expr for o in select.order_by],
            scope,
            inner_tables,
        ):
            raise unsupported("outer reference outside the WHERE clause")

        pairs: list[tuple[str, ast.Column]] = []  # (inner col, outer Column)
        residual: list[ast.Expr] = []
        for conj in _flatten_and(select.where) if select.where is not None else []:
            corr = _correlated_cols([conj], scope, inner_tables)
            if not corr:
                residual.append(conj)
                continue
            ok = (
                isinstance(conj, ast.BinaryOp)
                and conj.op == "="
                and isinstance(conj.left, ast.Column)
                and isinstance(conj.right, ast.Column)
            )
            if not ok:
                raise unsupported(f"non-equality outer reference: {conj}")
            sides = {True: None, False: None}
            for col in (conj.left, conj.right):
                is_outer = bool(
                    col.qualifier
                    and col.qualifier in scope
                    and col.qualifier not in inner_tables
                )
                sides[is_outer] = col
            if sides[True] is None or sides[False] is None:
                raise unsupported(f"both sides of {conj} bind to one scope")
            pairs.append((sides[False].name, sides[True]))
        if not pairs:
            raise unsupported("no equality correlation found")

        where = None
        for conj in residual:
            where = conj if where is None else ast.BinaryOp("AND", where, conj)
        inner_plan = planner.plan(
            dataclasses.replace(
                select,
                items=tuple(
                    ast.SelectItem(ast.Column(c), alias=f"__ek{i}")
                    for i, (c, _) in enumerate(pairs)
                ),
                where=where,
                group_by=(),
                order_by=(),
                limit=None,
                distinct=True,  # membership needs each key once
            )
        )
        nested = self._materialize_subqueries(inner_plan, outer_scope=scope)
        res = self.execute(nested if nested is not None else inner_plan)
        if not isinstance(res, ResultSet):
            raise unsupported("subquery must be a SELECT")

        def py(v):
            return v.item() if isinstance(v, np.generic) else v

        nulls = res.nulls or {}
        key_nulls = [nulls.get(n) for n in res.names]
        keys = []
        for i in range(res.num_rows):
            if any(kn is not None and kn[i] for kn in key_nulls):
                continue  # NULL inner key matches no outer row
            keys.append(tuple(py(col[i]) for col in res.columns))
        return ast.CorrelatedLookup(
            outer_cols=tuple(outer for _, outer in pairs),
            keys=tuple(keys),
            values=(True,) * len(keys),
            default=False,
        )

    def _insert(self, plan: InsertPlan) -> AffectedRows:
        table = self.catalog.open(plan.table)
        if table is None:
            raise InterpreterError(f"table not found: {plan.table}")
        rows = RowGroup.from_rows(table.schema, list(plan.rows))
        table.write(rows)
        return AffectedRows(len(rows))

    def _create(self, plan: CreateTablePlan) -> AffectedRows:
        partition_info = None
        if plan.partition_by is not None:
            partition_info = {
                "method": plan.partition_by.method,
                "columns": list(plan.partition_by.columns),
                "num_partitions": plan.partition_by.num_partitions,
            }
        self.catalog.create_table(
            plan.table,
            plan.schema,
            plan.options,
            if_not_exists=plan.if_not_exists,
            partition_info=partition_info,
        )
        return AffectedRows(0)

    def _describe(self, plan: DescribePlan) -> ResultSet:
        table = self.catalog.open(plan.table)
        if table is None:
            raise InterpreterError(f"table not found: {plan.table}")
        schema = table.schema
        names, types, keys, tags, nullables = [], [], [], [], []
        for i, c in enumerate(schema.columns):
            names.append(c.name)
            types.append(c.kind.value)
            keys.append(i in schema.primary_key_indexes)
            tags.append(c.is_tag)
            nullables.append(c.is_nullable)
        return ResultSet(
            ["name", "type", "is_primary", "is_nullable", "is_tag"],
            [
                np.array(names, dtype=object),
                np.array(types, dtype=object),
                np.array(keys),
                np.array(nullables),
                np.array(tags),
            ],
        )

    def _show_create(self, plan: ShowCreatePlan) -> ResultSet:
        table = self.catalog.open(plan.table)
        if table is None:
            raise InterpreterError(f"table not found: {plan.table}")
        schema = table.schema
        cols = []
        for i, c in enumerate(schema.columns):
            parts = [f"`{c.name}` {c.kind.value}"]
            if c.is_tag:
                parts.append("TAG")
            if not c.is_nullable:
                parts.append("NOT NULL")
            if c.comment:
                parts.append(f"COMMENT '{c.comment}'")
            cols.append(" ".join(parts))
        cols.append(f"TIMESTAMP KEY({schema.timestamp_name})")
        opts = table.options
        with_parts = [
            f"update_mode='{opts.update_mode.value.upper()}'",
            f"enable_ttl='{str(opts.enable_ttl).lower()}'",
        ]
        if opts.enable_ttl and opts.ttl_ms:
            with_parts.append(f"ttl='{format_duration(opts.ttl_ms)}'")
        if opts.memtable_type != "columnar":
            with_parts.append(f"memtable_type='{opts.memtable_type}'")
        if opts.segment_duration_ms:
            with_parts.insert(0, f"segment_duration='{format_duration(opts.segment_duration_ms)}'")
        sql = (
            f"CREATE TABLE `{plan.table}` ({', '.join(cols)}) "
            f"ENGINE=Analytic WITH ({', '.join(with_parts)})"
        )
        return ResultSet(
            ["Table", "Create Table"],
            [np.array([plan.table], dtype=object), np.array([sql], dtype=object)],
        )

    def _alter(self, plan: AlterTablePlan) -> AffectedRows:
        table = self.catalog.open(plan.table)
        if table is None:
            raise InterpreterError(f"table not found: {plan.table}")
        if plan.add_columns:
            schema = table.schema
            for c in plan.add_columns:
                schema = schema.with_added_column(c)
            table.alter_schema(schema)
        if plan.set_options:
            from ..engine.options import TableOptions

            merged = {**table.options.to_dict()}
            new = TableOptions.from_kv(plan.set_options).to_dict()
            for k in plan.set_options:
                key = {
                    "segment_duration": "segment_duration_ms",
                    "ttl": "ttl_ms",
                }.get(k.lower(), k.lower())
                if key in new:
                    merged[key] = new[key]
            table.alter_options(TableOptions.from_dict(merged))
        from ..utils.events import record_event

        record_event(
            "ddl_alter_table", table=plan.table,
            added_columns=len(plan.add_columns or ()),
            set_options=sorted(plan.set_options or ()),
        )
        return AffectedRows(0)


# ---- UNION / CTE helpers --------------------------------------------------


def _concat_results(results: list[ResultSet]) -> ResultSet:
    """Positional concatenation; names from the first result. Mismatched
    column dtypes widen to object (SQL's union type coercion, minus the
    numeric-promotion lattice DataFusion has)."""
    first = results[0]
    n_cols = len(first.names)
    for r in results[1:]:
        if len(r.names) != n_cols:
            raise InterpreterError("UNION branches produced different column counts")
    names = list(first.names)
    columns: list[np.ndarray] = []
    nulls: dict[str, np.ndarray] = {}
    for i in range(n_cols):
        parts = []
        mask_parts = []
        for r in results:
            col = r.columns[i]
            parts.append(col)
            m = (r.nulls or {}).get(r.names[i])
            mask_parts.append(
                m if m is not None else np.zeros(len(col), dtype=bool)
            )
        try:
            col = np.concatenate(parts)
        except (ValueError, TypeError):
            col = np.concatenate([p.astype(object) for p in parts])
        if col.dtype.kind not in "OUSb" and any(
            p.dtype.kind == "f" for p in parts
        ) and any(p.dtype.kind in "iu" for p in parts):
            col = col.astype(np.float64)
        columns.append(col)
        mask = np.concatenate(mask_parts)
        if mask.any():
            nulls[names[i]] = mask
    return ResultSet(names, columns, nulls or None)


def _order_limit_result(result: ResultSet, order_by, limit, offset: int = 0) -> ResultSet:
    """ORDER BY/LIMIT/OFFSET over a bare ResultSet (union output): order
    keys must name output columns of the first branch."""
    from .executor import _desc_key, _null_rank, _slice_result

    if order_by and result.num_rows:
        keys = []
        for o in reversed(order_by):
            name = o.expr.name if isinstance(o.expr, ast.Column) else str(o.expr)
            if name not in result.names:
                raise InterpreterError(
                    f"ORDER BY column {name!r} is not in the UNION output"
                )
            col = result.column(name)
            null_mask = (result.nulls or {}).get(name)
            valid = (
                np.ones(len(col), dtype=bool) if null_mask is None else ~null_mask
            )
            keys.append(col if o.ascending else _desc_key(col))
            keys.append(_null_rank(valid, o))
        order = np.lexsort(tuple(keys))
        result = ResultSet(
            result.names,
            [c[order] for c in result.columns],
            {k: v[order] for k, v in (result.nulls or {}).items()} or None,
        )
    if limit is not None or offset:
        result = _slice_result(result, offset, limit)
    return result


_HIDDEN_TS = "__hidden_ts"


def _result_to_table(name: str, res: ResultSet, plan):
    """Materialize a cte's ResultSet as an in-memory table.

    Column kinds come from the source schema when the output column is a
    plain (possibly aliased) source column, else from the numpy dtype.
    Derived columns are all plain fields (no tags/tsid — a cte output has
    no series identity), so queries over it take the host path. A result
    with no TIMESTAMP column gets a hidden zero timestamp column
    (schemas require one); SELECT * skips hidden columns.
    """
    from ..common_types.datum import DatumKind
    from ..common_types.dict_column import DictColumn, as_values
    from ..common_types.schema import ColumnSchema, Schema
    from ..table_engine.table import MemoryTable

    src_schema = plan.schema if isinstance(plan, QueryPlan) else None
    src_items: dict[str, ast.Expr] = {}
    if isinstance(plan, QueryPlan):
        for item in plan.select.items:
            if not isinstance(item.expr, ast.Star):
                src_items[item.output_name] = item.expr

    _DTYPE_KIND = {
        "f": DatumKind.DOUBLE,
        "i": DatumKind.INT64,
        "u": DatumKind.UINT64,
        "b": DatumKind.BOOLEAN,
    }
    cols: list[ColumnSchema] = []
    data: dict[str, np.ndarray] = {}
    validity: dict[str, np.ndarray] = {}
    seen = set()
    for out_name, col in zip(res.names, res.columns):
        if out_name in seen:
            raise InterpreterError(
                f"cte {name!r} has duplicate output column {out_name!r} "
                "(alias the expressions uniquely)"
            )
        seen.add(out_name)
        kind = None
        src = src_items.get(out_name)
        if (
            isinstance(src, ast.Column)
            and src_schema is not None
            and src_schema.has_column(src.name)
        ):
            kind = src_schema.column(src.name).kind
        elif src_schema is not None and src_schema.has_column(out_name):
            kind = src_schema.column(out_name).kind
        elif (
            isinstance(src, ast.FuncCall)
            and src.name == "time_bucket"
        ):
            kind = DatumKind.TIMESTAMP
        if kind is None:
            if isinstance(col, DictColumn):
                kind = DatumKind.STRING
            else:
                kind = _DTYPE_KIND.get(np.asarray(col).dtype.kind, DatumKind.STRING)
        cols.append(ColumnSchema(out_name, kind, is_nullable=True))
        data[out_name] = col
        m = (res.nulls or {}).get(out_name)
        if m is not None:
            validity[out_name] = ~m
    ts_name = next(
        (c.name for c in cols if c.kind is DatumKind.TIMESTAMP), None
    )
    n = res.num_rows
    if ts_name is None:
        ts_name = _HIDDEN_TS
        cols.append(ColumnSchema(ts_name, DatumKind.TIMESTAMP, is_nullable=False))
        data[ts_name] = np.zeros(n, dtype=np.int64)
    else:
        # a NULL timestamp row would break time filtering; coerce to 0
        vm = validity.get(ts_name)
        if vm is not None and not vm.all():
            vals = as_values(data[ts_name]).copy()
            vals[~vm] = 0
            data[ts_name] = vals
    schema = Schema.build(cols, timestamp_column=ts_name, primary_key=(ts_name,))
    table = MemoryTable(name, schema)
    if n:
        table.write(RowGroup(schema, data, validity))
    return table


class _OverlayCatalog:
    """Catalog view layering cte temp tables over the real catalog —
    reads resolve overlay-first; everything else passes through."""

    def __init__(self, base, overlay: dict) -> None:
        self._base = base
        self._overlay = overlay

    def open(self, name: str):
        t = self._overlay.get(name)
        return t if t is not None else self._base.open(name)

    def schema_of(self, name: str):
        t = self._overlay.get(name)
        return t.schema if t is not None else self._base.schema_of(name)

    def exists(self, name: str) -> bool:
        return name in self._overlay or self._base.exists(name)

    def table_names(self) -> list[str]:
        return sorted(set(self._base.table_names()) | set(self._overlay))

    def __getattr__(self, item):
        return getattr(self._base, item)
