"""Planner: AST -> Plan (ref: query_frontend/src/planner.rs).

Besides shape-checking against the schema, the planner does the two
analyses the TPU executor depends on:

- predicate extraction: WHERE conjuncts on the timestamp column become the
  scan ``TimeRange``; ``col op literal`` conjuncts become pushable filters
  (ref: table_engine/src/predicate.rs time-range extraction);
- aggregation shape: aggregate calls + group keys (plain columns or
  ``time_bucket``) are lifted out of the select list so the executor can
  route the query to the fused device kernel.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

from ..common_types.datum import DatumKind
from ..common_types.schema import ColumnSchema, Schema
from ..common_types.time_range import MAX_TIMESTAMP, MIN_TIMESTAMP, TimeRange
from ..engine.options import TableOptions, parse_duration_ms
from ..table_engine.predicate import ColumnFilter, FilterOp, Predicate
from . import ast
from .plan import (
    AggCall,
    AlterTablePlan,
    CreateTablePlan,
    DescribePlan,
    DropTablePlan,
    EXPENSIVE_QUERY_RANGE_MS,
    ExistsPlan,
    GroupKey,
    InsertPlan,
    Plan,
    QueryPlan,
    QueryPriority,
    ShowCreatePlan,
    ShowTablesPlan,
)

AGG_FUNCS = {"count", "sum", "min", "max", "avg"}


def _is_agg_name(name: str) -> bool:
    """Core aggregates plus anything in the function registry (UDAFs —
    ref: df_operator registry.rs; e.g. thetasketch_distinct)."""
    if name in AGG_FUNCS:
        return True
    from .functions import REGISTRY

    return (
        REGISTRY.aggregate(name) is not None
        or REGISTRY.binary_aggregate(name) is not None
    )


class PlanError(ValueError):
    pass


class Planner:
    """``schema_of(table) -> Schema | None`` is the MetaProvider analog
    (ref: query_frontend/src/provider.rs)."""

    def __init__(self, schema_of: Callable[[str], Optional[Schema]]) -> None:
        self.schema_of = schema_of

    def plan(self, stmt: ast.Statement) -> Plan:
        if isinstance(stmt, ast.Explain):
            from .plan import ExplainPlan

            return ExplainPlan(self.plan(stmt.inner), analyze=stmt.analyze)
        if isinstance(stmt, (ast.Select, ast.UnionSelect)) and stmt.ctes:
            # CTE bodies and the outer statement plan lazily at execution:
            # each cte's output schema exists only once it materializes
            # (interpreters._cte).
            from .plan import CTEPlan
            import dataclasses as _dc

            return CTEPlan(ctes=stmt.ctes, inner=_dc.replace(stmt, ctes=()))
        if isinstance(stmt, ast.UnionSelect):
            from .plan import UnionPlan

            branches = tuple(self._plan_select(s) for s in stmt.selects)
            if not any(
                isinstance(i.expr, ast.Star)
                for b in branches
                for i in b.select.items
            ):
                if len({len(b.select.items) for b in branches}) > 1:
                    raise PlanError("UNION branches have different column counts")
            return UnionPlan(
                branches=branches,
                all_flags=stmt.all_flags,
                order_by=stmt.order_by,
                limit=stmt.limit,
                offset=stmt.offset,
            )
        if isinstance(stmt, ast.Select):
            return self._plan_select(stmt)
        if isinstance(stmt, ast.CreateTable):
            return self._plan_create(stmt)
        if isinstance(stmt, ast.Insert):
            return self._plan_insert(stmt)
        if isinstance(stmt, ast.DropTable):
            return DropTablePlan(stmt.table, stmt.if_exists)
        if isinstance(stmt, ast.Describe):
            self._require_schema(stmt.table)
            return DescribePlan(stmt.table)
        if isinstance(stmt, ast.ShowTables):
            return ShowTablesPlan()
        if isinstance(stmt, ast.ShowCreateTable):
            self._require_schema(stmt.table)
            return ShowCreatePlan(stmt.table)
        if isinstance(stmt, ast.ExistsTable):
            return ExistsPlan(stmt.table)
        if isinstance(stmt, ast.KillQuery):
            from .plan import KillQueryPlan

            return KillQueryPlan(stmt.query_id)
        if isinstance(stmt, ast.AlterTableAddColumn):
            schema = self._require_schema(stmt.table)
            cols = tuple(
                ColumnSchema(
                    c.name,
                    DatumKind.from_sql_type(c.type_name),
                    is_nullable=not c.not_null,
                    is_tag=c.is_tag,
                    comment=c.comment,
                )
                for c in stmt.columns
            )
            for c in cols:
                if c.is_tag:
                    raise PlanError("cannot ADD a TAG column")
                if not c.is_nullable:
                    # Existing rows can only surface NULL for the new column.
                    raise PlanError("added columns must be nullable")
                if schema.has_column(c.name):
                    raise PlanError(f"column {c.name!r} already exists")
            return AlterTablePlan(stmt.table, add_columns=cols)
        if isinstance(stmt, ast.AlterTableSetOptions):
            self._require_schema(stmt.table)
            return AlterTablePlan(stmt.table, set_options=dict(stmt.options))
        raise PlanError(f"unsupported statement: {type(stmt).__name__}")

    def _require_schema(self, table: str) -> Schema:
        schema = self.schema_of(table)
        if schema is None:
            raise PlanError(f"table not found: {table}")
        return schema

    # ---- CREATE ----------------------------------------------------------
    def _plan_create(self, stmt: ast.CreateTable) -> CreateTablePlan:
        if stmt.engine.lower() != "analytic":
            raise PlanError(f"unsupported engine {stmt.engine!r}")
        if stmt.timestamp_key is None:
            raise PlanError("CREATE TABLE requires a TIMESTAMP KEY column")
        cols = []
        for c in stmt.columns:
            kind = DatumKind.from_sql_type(c.type_name)
            if c.is_tag and not kind.is_key_kind:
                raise PlanError(f"column {c.name}: {c.type_name} cannot be TAG")
            cols.append(
                ColumnSchema(
                    c.name,
                    kind,
                    is_nullable=not c.not_null,
                    is_tag=c.is_tag,
                    comment=c.comment,
                )
            )
        schema = Schema.build(
            cols,
            timestamp_column=stmt.timestamp_key,
            primary_key=list(stmt.primary_key) if stmt.primary_key else None,
        )
        if stmt.partition_by is not None:
            for c in stmt.partition_by.columns:
                if not schema.has_column(c):
                    raise PlanError(f"partition column {c!r} not defined")
                if not schema.column(c).kind.is_key_kind:
                    raise PlanError(f"partition column {c!r} must be a key kind")
            if stmt.partition_by.method == "hash":
                if len(stmt.partition_by.columns) != 1:
                    raise PlanError("PARTITION BY HASH takes exactly one column")
                kind = schema.column(stmt.partition_by.columns[0]).kind
                if not kind.is_integer:
                    raise PlanError(
                        "PARTITION BY HASH requires an integer column; "
                        "use PARTITION BY KEY for strings"
                    )
            if stmt.partition_by.num_partitions < 1:
                raise PlanError("PARTITIONS must be >= 1")
        options = TableOptions.from_kv(stmt.options)
        return CreateTablePlan(
            table=stmt.table,
            schema=schema,
            options=options,
            raw_options=dict(stmt.options),
            if_not_exists=stmt.if_not_exists,
            partition_by=stmt.partition_by,
        )

    # ---- INSERT ----------------------------------------------------------
    def _plan_insert(self, stmt: ast.Insert) -> InsertPlan:
        schema = self._require_schema(stmt.table)
        columns = stmt.columns
        if not columns:
            # positional: all non-generated columns in schema order
            columns = tuple(
                c.name
                for c in schema.columns
                if schema.tsid_index is None or c.name != schema.columns[schema.tsid_index].name
            )
        for c in columns:
            if not schema.has_column(c):
                raise PlanError(f"unknown column {c!r} in INSERT")
        rows = []
        for vals in stmt.values:
            if len(vals) != len(columns):
                raise PlanError(
                    f"INSERT arity mismatch: {len(columns)} columns, {len(vals)} values"
                )
            rows.append(dict(zip(columns, vals)))
        return InsertPlan(stmt.table, schema, tuple(rows))

    # ---- SELECT ----------------------------------------------------------
    def _plan_select(self, stmt: ast.Select) -> QueryPlan:
        if stmt.table is None:
            raise PlanError("SELECT without FROM is not supported")
        if stmt.having is not None and not stmt.group_by:
            raise PlanError("HAVING requires GROUP BY (use WHERE for row filters)")
        self._check_qualifiers(stmt)
        if stmt.join is not None:
            # Joined queries validate against the COMBINED schema at
            # execution (query/join.py); the plan is a thin carrier.
            if stmt.group_by or any(
                isinstance(e, ast.FuncCall) and _is_agg_name(e.name)
                for item in stmt.items
                for e in _walk(item.expr)
            ):
                raise PlanError("aggregates over JOIN are not supported yet")
            schema = self._require_schema(stmt.table)
            from ..table_engine.predicate import Predicate

            return QueryPlan(
                table=stmt.table,
                schema=schema,
                select=stmt,
                predicate=Predicate.all_time(),
                aggs=(),
                group_keys=(),
                is_aggregate=False,
                priority=QueryPriority.HIGH,
            )
        schema = self._require_schema(stmt.table)
        stmt = self._resolve_group_by_aliases(stmt, schema)
        self._check_columns(stmt, schema)
        self._check_windows(stmt)

        predicate = extract_predicate(stmt.where, schema)
        aggs, group_keys, is_agg, agg_exprs = self._agg_shape(stmt, schema)

        tr = predicate.time_range
        span = tr.exclusive_end - tr.inclusive_start
        priority = (
            QueryPriority.LOW if span > EXPENSIVE_QUERY_RANGE_MS else QueryPriority.HIGH
        )
        return QueryPlan(
            table=stmt.table,
            schema=schema,
            select=stmt,
            predicate=predicate,
            aggs=aggs,
            group_keys=group_keys,
            is_aggregate=is_agg,
            priority=priority,
            agg_exprs=agg_exprs,
        )

    def _resolve_group_by_aliases(self, stmt: ast.Select, schema: Schema) -> ast.Select:
        """``GROUP BY b`` where ``b`` is a SELECT alias of an expression
        (``SELECT time_bucket(ts, '1m') AS b ... GROUP BY b``) substitutes
        the aliased expression — standard SQL/DataFusion behavior. A real
        schema column of the same name takes precedence (the standard's
        resolution order), so existing queries never change meaning."""
        if not stmt.group_by:
            return stmt
        alias_map = {
            item.alias: item.expr for item in stmt.items if item.alias
        }
        if not alias_map:
            return stmt
        new_gb = tuple(
            alias_map[g.name]
            if (
                isinstance(g, ast.Column)
                and g.qualifier is None
                and not schema.has_column(g.name)
                and g.name in alias_map
            )
            else g
            for g in stmt.group_by
        )
        if new_gb == stmt.group_by:
            return stmt
        return dataclasses.replace(stmt, group_by=new_gb)

    def _check_qualifiers(self, stmt: ast.Select) -> None:
        """``t.col`` qualifiers must name a table in the query — a silent
        wrong-table binding would mask user errors."""
        known = {stmt.table}
        if stmt.join is not None:
            known.add(stmt.join.table)
        for j in stmt.joins:
            known.add(j.table)
        # Qualified table names may be referenced by their last component
        # (FROM public.cpu ... WHERE cpu.usage > 0).
        for full in list(known):
            if "." in full:
                known.add(full.rsplit(".", 1)[-1])
        sources = [item.expr for item in stmt.items]
        sources += [e for e in (stmt.where, stmt.having, *stmt.group_by) if e is not None]
        sources += [o.expr for o in stmt.order_by]
        for src in sources:
            for e in _walk(src):
                if (
                    isinstance(e, ast.Column)
                    and e.qualifier is not None
                    and e.qualifier not in known
                ):
                    raise PlanError(
                        f"unknown table qualifier {e.qualifier!r} for column "
                        f"{e.name!r}"
                    )

    def _check_columns(self, stmt: ast.Select, schema: Schema) -> None:
        aliases = {item.alias for item in stmt.items if item.alias}
        for item in stmt.items:
            for e in _walk(item.expr):
                if isinstance(e, ast.Column) and not schema.has_column(e.name):
                    raise PlanError(f"unknown column {e.name!r}")
        for src in (stmt.where, *stmt.group_by):
            if src is None:
                continue
            for e in _walk(src):
                if isinstance(e, ast.Column) and not schema.has_column(e.name):
                    raise PlanError(f"unknown column {e.name!r}")
        # ORDER BY may reference select aliases as well as table columns.
        for o in stmt.order_by:
            for e in _walk(o.expr):
                if (
                    isinstance(e, ast.Column)
                    and not schema.has_column(e.name)
                    and e.name not in aliases
                ):
                    raise PlanError(f"unknown column {e.name!r}")

    _WINDOW_FUNCS = {
        "row_number", "rank", "dense_rank", "lag", "lead",
        "first_value", "last_value", "count", "sum", "avg", "min", "max",
    }

    def _check_windows(self, stmt: ast.Select) -> None:
        """Window functions may appear only in the select list (possibly
        inside larger expressions) — never in WHERE/GROUP BY/HAVING, and
        not mixed with grouped aggregation (windows run over scan rows)."""
        for src, where in (
            (stmt.where, "WHERE"),
            (stmt.having, "HAVING"),
            *((g, "GROUP BY") for g in stmt.group_by),
        ):
            if src is None:
                continue
            if any(isinstance(e, ast.WindowFunc) for e in _walk(src)):
                raise PlanError(f"window functions are not allowed in {where}")
        wfs = [
            e
            for item in stmt.items
            for e in _walk(item.expr)
            if isinstance(e, ast.WindowFunc)
        ]
        if not wfs:
            return
        if stmt.group_by or any(
            isinstance(e, ast.FuncCall) and _is_agg_name(e.name)
            for item in stmt.items
            for e in _walk(item.expr)
        ):
            raise PlanError(
                "window functions cannot be mixed with GROUP BY aggregation "
                "(wrap the aggregate in a WITH cte and window over it)"
            )
        for w in wfs:
            if w.name not in self._WINDOW_FUNCS:
                raise PlanError(f"unknown window function {w.name!r}")
            if w.name in ("row_number", "rank", "dense_rank"):
                if w.args:
                    raise PlanError(f"{w.name}() takes no arguments")
                if not w.spec.order_by:
                    raise PlanError(f"{w.name}() requires ORDER BY in OVER()")
            elif w.name in ("lag", "lead"):
                if not 1 <= len(w.args) <= 3:
                    raise PlanError(f"{w.name}(value[, offset[, default]])")
                if len(w.args) >= 2 and not (
                    isinstance(w.args[1], ast.Literal)
                    and isinstance(w.args[1].value, int)
                ):
                    raise PlanError(f"{w.name} offset must be an integer literal")
                if not w.spec.order_by:
                    raise PlanError(f"{w.name}() requires ORDER BY in OVER()")
            elif w.name in ("first_value", "last_value"):
                if len(w.args) != 1:
                    raise PlanError(f"{w.name}(value) expects one argument")
            elif w.name == "count":
                if len(w.args) > 1:
                    raise PlanError("count([value]) window expects <= 1 argument")
            else:  # sum/avg/min/max
                if len(w.args) != 1:
                    raise PlanError(f"{w.name}(value) window expects one argument")

    def _make_agg_call(
        self, e: ast.FuncCall, output_name: str, schema: Schema
    ) -> AggCall:
        from .functions import REGISTRY as _FN

        col = None
        col2 = None
        params: tuple = ()
        is_binary = _FN.binary_aggregate(e.name) is not None
        if e.args and not isinstance(e.args[0], ast.Star):
            if (
                e.name == "count"
                and isinstance(e.args[0], ast.Literal)
                and e.args[0].value is not None
            ):
                pass  # count(1) == count(*)
            elif not isinstance(e.args[0], ast.Column):
                raise PlanError(
                    f"aggregate over expression not supported: {e}"
                )
            else:
                col = e.args[0].name
        if e.name != "count" and col is None:
            raise PlanError(f"{e.name} requires a column argument")
        if is_binary:
            if len(e.args) != 2 or not isinstance(e.args[1], ast.Column):
                raise PlanError(
                    f"{e.name}(x, y) expects two column arguments"
                )
            col2 = e.args[1].name
        elif len(e.args) > 1:
            # Trailing literal parameters (approx_percentile_cont).
            extra = e.args[1:]
            if not all(isinstance(a, ast.Literal) for a in extra):
                raise PlanError(
                    f"extra arguments of {e.name} must be literals"
                )
            params = tuple(a.value for a in extra)
        numeric_required = e.name in ("sum", "avg") or _FN.numeric_only(e.name)
        if numeric_required:
            for c in (col, col2):
                if c is not None and not schema.column(c).kind.is_numeric:
                    raise PlanError(
                        f"{e.name}({c}) requires a numeric column"
                    )
        return AggCall(
            e.name, col, output_name, e.distinct,
            column2=col2, params=params, filter_where=e.filter_where,
        )

    def _agg_shape(
        self, stmt: ast.Select, schema: Schema
    ) -> tuple[tuple[AggCall, ...], tuple[GroupKey, ...], bool, tuple]:
        aggs: list[AggCall] = []
        has_agg = any(
            isinstance(e, ast.FuncCall) and _is_agg_name(e.name)
            for item in stmt.items
            for e in _walk(item.expr)
        )
        if not has_agg:
            if stmt.group_by:
                raise PlanError("GROUP BY without aggregates is not supported")
            return (), (), False, ()

        group_keys: list[GroupKey] = []
        for g in stmt.group_by:
            group_keys.append(_group_key(g, schema))
        group_names = {k.output_name for k in group_keys}

        # Hidden aggregates lifted out of arithmetic-over-aggregate select
        # items (sum(v) / count(*)); deduped by their SQL rendering, and
        # against identical SELECT-level aggregates (computed once). Names
        # must not collide with user aliases — '__aggN' is not reserved
        # syntax, so probe for a free name instead of assuming.
        hidden: dict[str, AggCall] = {}
        agg_exprs: list[tuple[str, ast.Expr]] = []
        plain_by_render: dict[str, str] = {
            str(item.expr): item.output_name
            for item in stmt.items
            if isinstance(item.expr, ast.FuncCall) and _is_agg_name(item.expr.name)
        }
        used_names = {item.output_name for item in stmt.items}

        def hidden_name() -> str:
            i = len(hidden)
            while f"__agg{i}" in used_names:
                i += 1
            name = f"__agg{i}"
            used_names.add(name)
            return name

        def lift(expr: ast.Expr) -> ast.Expr:
            """Replace aggregate calls with hidden result columns; validate
            the remaining leaves resolve per-group."""
            if isinstance(expr, ast.FuncCall) and _is_agg_name(expr.name):
                key = str(expr)
                if key in plain_by_render:
                    # The same aggregate is already a SELECT item — read
                    # its result column instead of computing it twice.
                    return ast.Column(plain_by_render[key])
                if key not in hidden:
                    hidden[key] = self._make_agg_call(expr, hidden_name(), schema)
                return ast.Column(hidden[key].output_name)
            if isinstance(expr, ast.Column):
                if expr.name not in group_names:
                    raise PlanError(
                        f"column {expr.name!r} must appear in GROUP BY "
                        f"or an aggregate"
                    )
                return expr
            if isinstance(expr, ast.Literal):
                return expr
            if isinstance(expr, ast.BinaryOp):
                return ast.BinaryOp(expr.op, lift(expr.left), lift(expr.right))
            if isinstance(expr, ast.UnaryOp):
                return ast.UnaryOp(expr.op, lift(expr.operand))
            if isinstance(expr, ast.Cast):
                return ast.Cast(lift(expr.expr), expr.type_name)
            if isinstance(expr, ast.Case):
                return ast.Case(
                    tuple((lift(w), lift(t)) for w, t in expr.whens),
                    lift(expr.else_) if expr.else_ is not None else None,
                )
            if isinstance(expr, ast.FuncCall):
                return ast.FuncCall(
                    expr.name, tuple(lift(a) for a in expr.args), expr.distinct
                )
            if isinstance(expr, ast.IsNull):
                return ast.IsNull(lift(expr.expr), expr.negated)
            if isinstance(expr, ast.Between):
                return ast.Between(
                    lift(expr.expr), lift(expr.low), lift(expr.high), expr.negated
                )
            if isinstance(expr, ast.InList):
                return ast.InList(
                    lift(expr.expr),
                    tuple(lift(v) for v in expr.values),
                    expr.negated,
                )
            if isinstance(expr, ast.Like):
                return ast.Like(
                    lift(expr.expr), expr.pattern, expr.negated,
                    expr.case_insensitive,
                )
            raise PlanError(
                f"unsupported expression over aggregates: {expr}"
            )

        for item in stmt.items:
            e = item.expr
            if isinstance(e, ast.FuncCall) and _is_agg_name(e.name):
                aggs.append(self._make_agg_call(e, item.output_name, schema))
            elif isinstance(e, ast.Column):
                if e.name not in group_names:
                    raise PlanError(
                        f"column {e.name!r} must appear in GROUP BY or an aggregate"
                    )
            elif isinstance(e, ast.FuncCall) and e.name in ("time_bucket", "date_trunc"):
                if e.filter_where is not None:
                    raise PlanError(
                        f"FILTER is only valid on aggregate functions, not {e.name}"
                    )
                key = _group_key(e, schema)
                if key.output_name not in {k.output_name for k in group_keys}:
                    raise PlanError(f"{e.name} in SELECT must also be in GROUP BY")
            elif any(
                isinstance(x, ast.FuncCall) and _is_agg_name(x.name)
                for x in _walk(e)
            ):
                # Arithmetic (or CASE/CAST/scalar calls) over aggregates:
                # evaluate per group AFTER aggregation.
                agg_exprs.append((item.output_name, lift(e)))
            else:
                raise PlanError(f"unsupported select item in aggregate query: {e}")
        aggs.extend(hidden.values())
        return tuple(aggs), tuple(group_keys), True, tuple(agg_exprs)


# Fixed-width date_trunc units map onto the bucket kernel; month/year are
# calendar-variable and stay unsupported (clear error beats wrong buckets).
_DATE_TRUNC_MS = {
    "millisecond": 1, "second": 1_000, "minute": 60_000, "hour": 3_600_000,
    "day": 86_400_000, "week": 7 * 86_400_000,
}


def _group_key(e: ast.Expr, schema: Schema) -> GroupKey:
    if isinstance(e, ast.Column):
        return GroupKey(column=e.name, output_name=e.name)
    if isinstance(e, ast.FuncCall) and e.name == "time_bucket":
        if len(e.args) != 2:
            raise PlanError("time_bucket(timestamp_col, 'interval') expects 2 args")
        col, interval = e.args
        if not isinstance(col, ast.Column) or col.name != schema.timestamp_name:
            raise PlanError("time_bucket must be applied to the timestamp key column")
        if isinstance(interval, ast.Literal) and isinstance(interval.value, str):
            width = parse_duration_ms(interval.value)
        elif (
            isinstance(interval, ast.Literal)
            and isinstance(interval.value, (int, float))
            and not isinstance(interval.value, bool)
            and interval.value > 0
            and int(interval.value) == interval.value
        ):
            width = int(interval.value)  # milliseconds (whole ms only —
            # a fractional width would truncate to a 0-width bucket)
        else:
            raise PlanError(
                "time_bucket interval must be a duration string like '1h' "
                "or a positive millisecond count"
            )
        return GroupKey(time_bucket_ms=width, output_name=str(e))
    if isinstance(e, ast.FuncCall) and e.name == "date_trunc":
        if len(e.args) != 2:
            raise PlanError("date_trunc('unit', timestamp_col) expects 2 args")
        unit, col = e.args
        if not isinstance(col, ast.Column) or col.name != schema.timestamp_name:
            raise PlanError("date_trunc must be applied to the timestamp key column")
        if not isinstance(unit, ast.Literal) or not isinstance(unit.value, str):
            raise PlanError("date_trunc unit must be a string literal")
        width = _DATE_TRUNC_MS.get(unit.value.lower())
        if width is None:
            raise PlanError(
                f"unsupported date_trunc unit {unit.value!r} "
                f"(supported: {', '.join(sorted(_DATE_TRUNC_MS))})"
            )
        return GroupKey(time_bucket_ms=width, output_name=str(e))
    raise PlanError(f"unsupported GROUP BY expression: {e}")


# ---- predicate extraction ----------------------------------------------

_CMP_TO_FILTER = {
    "=": FilterOp.EQ,
    "!=": FilterOp.NE,
    "<": FilterOp.LT,
    "<=": FilterOp.LE,
    ">": FilterOp.GT,
    ">=": FilterOp.GE,
}
_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}


def extract_predicate(where: Optional[ast.Expr], schema: Schema) -> Predicate:
    """Time range + pushable filters from the WHERE conjunction.

    Only top-level AND conjuncts are pushable (a disjunct can't narrow the
    scan). Conjuncts that don't fit ``col op literal`` remain in the
    executor's exact post-filter — extraction here is sound, not complete.
    """
    if where is None:
        return Predicate.all_time()
    ts_name = schema.timestamp_name
    lo, hi = MIN_TIMESTAMP, MAX_TIMESTAMP
    filters: list[ColumnFilter] = []
    for conj in _conjuncts(where):
        simple = _as_simple_cmp(conj)
        if simple is None:
            if isinstance(conj, ast.Between) and not conj.negated:
                col = conj.expr
                if (
                    isinstance(col, ast.Column)
                    and isinstance(conj.low, ast.Literal)
                    and isinstance(conj.high, ast.Literal)
                ):
                    if col.name == ts_name:
                        lo = max(lo, int(conj.low.value))
                        hi = min(hi, int(conj.high.value) + 1)
                    else:
                        filters.append(ColumnFilter(col.name, FilterOp.GE, conj.low.value))
                        filters.append(ColumnFilter(col.name, FilterOp.LE, conj.high.value))
            elif isinstance(conj, ast.InList) and not conj.negated:
                col = conj.expr
                if isinstance(col, ast.Column) and all(
                    isinstance(v, ast.Literal) for v in conj.values
                ):
                    filters.append(
                        ColumnFilter(
                            col.name,
                            FilterOp.IN,
                            tuple(v.value for v in conj.values),
                        )
                    )
            continue
        col, op, value = simple
        if col == ts_name:
            v = int(value)
            if op == "=":
                lo, hi = max(lo, v), min(hi, v + 1)
            elif op == "<":
                hi = min(hi, v)
            elif op == "<=":
                hi = min(hi, v + 1)
            elif op == ">":
                lo = max(lo, v + 1)
            elif op == ">=":
                lo = max(lo, v)
            else:  # != — not range-expressible; leave to post-filter
                filters.append(ColumnFilter(col, FilterOp.NE, v))
        else:
            filters.append(ColumnFilter(col, _CMP_TO_FILTER[op], value))
    if hi < lo:
        return Predicate(TimeRange.empty(), tuple(filters))
    return Predicate(TimeRange(lo, hi), tuple(filters))


def _conjuncts(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.BinaryOp) and e.op.upper() == "AND":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _as_simple_cmp(e: ast.Expr) -> Optional[tuple[str, str, Any]]:
    if not isinstance(e, ast.BinaryOp) or e.op not in _CMP_TO_FILTER:
        return None
    l, r = e.left, e.right
    if isinstance(l, ast.Column) and isinstance(r, ast.Literal):
        return l.name, e.op, r.value
    if isinstance(l, ast.Literal) and isinstance(r, ast.Column):
        return r.name, _FLIP[e.op], l.value
    # fold unary minus literals
    if isinstance(l, ast.Column) and isinstance(r, ast.UnaryOp) and r.op == "-" and isinstance(r.operand, ast.Literal):
        return l.name, e.op, -r.operand.value
    return None


def _walk(e: ast.Expr):
    yield e
    if isinstance(e, ast.BinaryOp):
        yield from _walk(e.left)
        yield from _walk(e.right)
    elif isinstance(e, ast.UnaryOp):
        yield from _walk(e.operand)
    elif isinstance(e, ast.FuncCall):
        for a in e.args:
            yield from _walk(a)
        if e.filter_where is not None:
            yield from _walk(e.filter_where)
    elif isinstance(e, ast.Case):
        for w, t in e.whens:
            yield from _walk(w)
            yield from _walk(t)
        if e.else_ is not None:
            yield from _walk(e.else_)
    elif isinstance(e, ast.Cast):
        yield from _walk(e.expr)
    elif isinstance(e, ast.Like):
        yield from _walk(e.expr)
    elif isinstance(e, ast.InList):
        yield from _walk(e.expr)
        for v in e.values:
            yield from _walk(v)
    elif isinstance(e, ast.InSubquery):
        # the LEFT side lives in the outer scope; the inner select has its
        # own table scope and is validated when it is planned
        yield from _walk(e.expr)
    elif isinstance(e, ast.Between):
        yield from _walk(e.expr)
        yield from _walk(e.low)
        yield from _walk(e.high)
    elif isinstance(e, ast.IsNull):
        yield from _walk(e.expr)
    elif isinstance(e, ast.CorrelatedLookup):
        # the correlation columns are outer-scope references — scan
        # pruning and qualifier validation must see them
        for c in e.outer_cols:
            yield from _walk(c)
    elif isinstance(e, ast.WindowFunc):
        for a in e.args:
            yield from _walk(a)
        for p in e.spec.partition_by:
            yield from _walk(p)
        for o in e.spec.order_by:
            yield from _walk(o.expr)


def _walk_exprs(stmt: ast.Select):
    for item in stmt.items:
        yield from _walk(item.expr)
    if stmt.where is not None:
        yield from _walk(stmt.where)
    for g in stmt.group_by:
        yield from _walk(g)
    for o in stmt.order_by:
        yield from _walk(o.expr)
