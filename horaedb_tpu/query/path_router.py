"""Adaptive device/host path routing for aggregate queries.

The reference picks execution resources per query with a static rule
(expensive-query classification by time range -> priority runtime,
query_frontend/src/plan.rs:105, components/runtime/src/priority_runtime.rs);
this is the TPU-native generalization: the profitable path depends on the
accelerator's dispatch latency, which varies by deployment (PCIe-attached
~us; a tunneled/remote chip ~tens of ms). Instead of a static threshold,
the router MEASURES both paths per query shape and serves from the winner,
re-probing the loser on a fixed cadence so it adapts when conditions change
(scan cache finishes building, data grows, tunnel latency shifts).

Keyed by (table, select-statement shape): repeated dashboard/TSBS-style
queries converge after one probe of each path. Latencies fold into an EWMA
so a single GC hiccup or retuned tunnel doesn't flip the decision.

Enabled when the JAX backend is not ``cpu`` (override with
HORAEDB_ADAPTIVE_PATH=0/1): on the host backend "device" dispatch is
in-process and the device path's own thresholds already apply.
"""

from __future__ import annotations

import dataclasses
import os
import threading

PROBE_EVERY = 16  # serve the winner; re-probe the loser every Nth call
MAX_KEYS = 512  # LRU bound on tracked query shapes


def plan_shape_key(plan) -> tuple:
    """(table, normalized-select) with literal VALUES masked out.

    Rolling-window dashboards re-issue the same query with fresh time/
    filter literals every refresh; masking literals makes those one shape,
    so the router's samples accumulate instead of restarting (and the
    stats table stays bounded)."""
    return (plan.table, _shape(plan.select))


def _shape(node):
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        if type(node).__name__ == "Literal":
            return ("?",)  # value masked; shape only
        return (
            type(node).__name__,
            *(
                (f.name, _shape(getattr(node, f.name)))
                for f in dataclasses.fields(node)
            ),
        )
    if isinstance(node, (tuple, list)):
        return tuple(_shape(x) for x in node)
    return node


class PathRouter:
    def __init__(self) -> None:
        # key -> {"device": s, "host": s, "device_n": int, "calls": int}
        self._stats: dict = {}
        self._lock = threading.Lock()

    def _touch(self, key) -> dict:
        """stats entry for key, LRU-bumped; evicts the oldest past MAX_KEYS
        (dicts preserve insertion order — re-inserting moves to the back)."""
        st = self._stats.pop(key, None)
        if st is None:
            st = {"calls": 0}
            if len(self._stats) >= MAX_KEYS:
                self._stats.pop(next(iter(self._stats)))
        self._stats[key] = st
        return st

    def choose(self, key) -> str:
        """"device" or "host".

        Collects TWO device samples before judging: the first device
        execution of a query shape pays jit trace+compile, and the second
        typically absorbs the scan cache's deferred build (scan_cache
        builds on the second sighting of a stable base state) — neither
        reflects steady-state serving. Then one host sample, then the
        measured winner with periodic probes of the loser.
        """
        with self._lock:
            st = self._touch(key)
            if st.get("device_n", 0) < 2:
                return "device"
            if "host" not in st:
                return "host"
            st["calls"] += 1
            winner = "device" if st["device"] <= st["host"] else "host"
            if st["calls"] % PROBE_EVERY == 0:
                return "host" if winner == "device" else "device"
            return winner

    def record(self, key, kind: str, seconds: float) -> None:
        """Fold a sample in: adapt DOWN instantly (a faster time is proof
        the path can go that fast), creep UP by 10% per sample (one GC
        pause or tunnel hiccup must not flip the route)."""
        with self._lock:
            st = self._touch(key)
            prev = st.get(kind)
            if kind == "device":
                n = st.get("device_n", 0) + 1
                st["device_n"] = n
                if n == 2:
                    prev = None  # drop the compile-tainted first sample
            st[kind] = seconds if prev is None else min(seconds, prev * 1.1)

    def stats(self, key) -> dict:
        with self._lock:
            return dict(self._stats.get(key, {}))


def adaptive_enabled() -> bool:
    v = os.environ.get("HORAEDB_ADAPTIVE_PATH", "auto")
    if v in ("0", "off", "false"):
        return False
    if v in ("1", "on", "true"):
        return True
    import jax

    return jax.default_backend() != "cpu"


def raw_adaptive_enabled() -> bool:
    """Adaptive routing for RAW (non-aggregate) reads. Defaults ON for
    every backend — unlike the aggregate kernels (where device wins and
    "auto" only worries about dispatch latency), raw device-vs-host
    genuinely flips with table size/selectivity on XLA-CPU too.
    HORAEDB_ADAPTIVE_PATH=0 still pins routing off (device-first)."""
    v = os.environ.get("HORAEDB_ADAPTIVE_PATH", "auto")
    return v not in ("0", "off", "false")


# ---- learned segment-kernel routing ---------------------------------------
#
# The device group-by has three segment-reduction impls (ops/scan_agg.py:
# mxu one-hot matmul, scatter segment_* ops, hash slot table) and the
# winner flips with group cardinality and skew (arXiv 2411.13245) — a
# static import-time threshold leaves a regime on the table on every
# deployment. Same EWMA + periodic-reprobe machinery as PathRouter, one
# level down: keyed by (plan shape, segment-count bucket), choosing the
# IMPL the jitted kernel branches on instead of the device/host path.
# The first call of a shape is seeded from estimated group cardinality
# (sampler/exact group encoding + observed query_stats history), so it
# already starts near the winner instead of probing blind.


def kernel_routing_enabled() -> bool:
    """Learned impl choice (default on — it matters on every backend;
    scatter-vs-hash flips on CPU too). HORAEDB_SEGMENT_IMPL pinning
    bypasses the router entirely regardless of this switch."""
    return os.environ.get("HORAEDB_KERNEL_ROUTER", "1") not in (
        "0", "off", "false",
    )


def candidate_kernels(n_seg: int, n_rows: int, est_distinct=None) -> tuple:
    """Impls worth PROBING for this shape. Routing must never schedule a
    probe that is catastrophically wrong by construction: the MXU one-hot
    is O(N * n_seg) — beyond a bounded extrapolation of the static
    crossover a single probe could cost seconds — and the hash table
    cannot beat the direct impls when the domain is already tiny or the
    live cardinality fills most of it (a near-full table just routes
    everything through the overflow fallback)."""
    import jax

    from ..ops.scan_agg import mxu_max_segments

    cands = ["scatter"]
    if n_seg <= (
        # the 4x extrapolation is MXU-calibrated; without a matrix unit
        # the one-hot's O(N * n_seg) bites orders of magnitude sooner
        4 * mxu_max_segments() if jax.default_backend() == "tpu" else 256
    ):
        cands.append("mxu")
    if n_seg > 64 and (est_distinct is None or est_distinct * 4 <= n_seg):
        cands.append("hash")
    return tuple(cands)


def seed_kernel(n_seg: int, est_distinct, backend: str) -> str:
    """Cardinality-seeded starting impl for a never-measured shape."""
    if (
        est_distinct is not None
        and n_seg > 512
        and est_distinct * 8 <= n_seg
    ):
        # Sparse domain: most segments provably empty — hash territory.
        return "hash"
    from ..ops.scan_agg import mxu_max_segments

    if backend == "tpu":
        return "mxu" if n_seg <= mxu_max_segments() else "scatter"
    return "scatter"


class KernelRouter:
    """Per-(plan-shape, segment-bucket) EWMA over the segment impls.

    Same discipline as PathRouter: warm each candidate (dropping its
    compile-tainted first sample), serve the measured winner, re-probe
    the losers round-robin every PROBE_EVERY-th call so the choice
    adapts when conditions change. Also remembers the observed live
    segment count per key — the feedback that sizes the hash slot table
    and corrects a bad seed estimate."""

    def __init__(self) -> None:
        self._stats: dict = {}
        self._lock = threading.Lock()

    def _touch(self, key) -> dict:
        st = self._stats.pop(key, None)
        if st is None:
            st = {"calls": 0, "n": {}, "t": {}}
            if len(self._stats) >= MAX_KEYS:
                self._stats.pop(next(iter(self._stats)))
        self._stats[key] = st
        return st

    def choose(self, key, seed: str, candidates: tuple) -> str:
        """The impl to dispatch this call with."""
        with self._lock:
            st = self._touch(key)
            st["calls"] += 1
            samples, times = st["n"], st["t"]
            order = [seed] + [k for k in candidates if k != seed]
            for k in order:
                # two samples each: the first pays jit trace+compile and
                # is dropped by record() — judging needs a clean one
                if k in candidates and samples.get(k, 0) < 2:
                    return k
            measured = {k: times[k] for k in candidates if k in times}
            if not measured:
                return seed if seed in candidates else candidates[0]
            winner = min(measured, key=measured.get)
            if st["calls"] % PROBE_EVERY == 0:
                losers = [k for k in candidates if k != winner]
                if losers:
                    return losers[(st["calls"] // PROBE_EVERY) % len(losers)]
            return winner

    def record(self, key, kernel: str, seconds: float) -> None:
        """Fold a dispatch latency in: adapt DOWN instantly, creep UP by
        10% per sample; the first sample of each impl (compile-tainted)
        only counts, never judges."""
        with self._lock:
            st = self._touch(key)
            n = st["n"][kernel] = st["n"].get(kernel, 0) + 1
            if n == 1:
                return  # compile-tainted
            prev = st["t"].get(kernel)
            st["t"][kernel] = (
                seconds if prev is None else min(seconds, prev * 1.1)
            )

    def note_segments(self, key, live: int) -> None:
        """Observed live (group x bucket) cells — EWMA'd so the hash
        slot table is sized from what the shape actually produces."""
        with self._lock:
            st = self._touch(key)
            prev = st.get("segments")
            st["segments"] = (
                int(live) if prev is None else int(0.7 * prev + 0.3 * live)
            )

    def observed_segments(self, key):
        with self._lock:
            st = self._stats.get(key)
            return None if st is None else st.get("segments")

    def stats(self, key) -> dict:
        with self._lock:
            st = self._stats.get(key, {})
            return {
                k: (dict(v) if isinstance(v, dict) else v)
                for k, v in st.items()
            }

    def reset(self) -> None:
        with self._lock:
            self._stats.clear()


# One process-wide router: kernel latency is a property of the hardware
# and the shape, not of any particular executor instance — every
# consumer (direct device path, cached path, dist-agg step) folds into
# and serves from the same history.
KERNEL_ROUTER = KernelRouter()


def bootstrap_observed_segments(sql: str):
    """Seed a never-seen router key from query_stats history: the most
    recent finalized ledger of the same normalized SQL shape carries the
    live segment count its aggregation produced (``agg_segments``)."""
    if not sql:
        return None
    from ..utils.querystats import STATS_STORE
    from ..wlm.admission import normalize_shape

    shape = normalize_shape(sql)
    for row in reversed(STATS_STORE.list()):
        segs = row.get("agg_segments")
        if segs and normalize_shape(str(row.get("sql", ""))) == shape:
            return int(segs)
    return None
