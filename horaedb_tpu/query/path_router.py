"""Adaptive device/host path routing for aggregate queries.

The reference picks execution resources per query with a static rule
(expensive-query classification by time range -> priority runtime,
query_frontend/src/plan.rs:105, components/runtime/src/priority_runtime.rs);
this is the TPU-native generalization: the profitable path depends on the
accelerator's dispatch latency, which varies by deployment (PCIe-attached
~us; a tunneled/remote chip ~tens of ms). Instead of a static threshold,
the router MEASURES both paths per query shape and serves from the winner,
re-probing the loser on a fixed cadence so it adapts when conditions change
(scan cache finishes building, data grows, tunnel latency shifts).

Keyed by (table, select-statement shape): repeated dashboard/TSBS-style
queries converge after one probe of each path. Latencies fold into an EWMA
so a single GC hiccup or retuned tunnel doesn't flip the decision.

Enabled when the JAX backend is not ``cpu`` (override with
HORAEDB_ADAPTIVE_PATH=0/1): on the host backend "device" dispatch is
in-process and the device path's own thresholds already apply.
"""

from __future__ import annotations

import dataclasses
import os
import threading

PROBE_EVERY = 16  # serve the winner; re-probe the loser every Nth call
MAX_KEYS = 512  # LRU bound on tracked query shapes


def plan_shape_key(plan) -> tuple:
    """(table, normalized-select) with literal VALUES masked out.

    Rolling-window dashboards re-issue the same query with fresh time/
    filter literals every refresh; masking literals makes those one shape,
    so the router's samples accumulate instead of restarting (and the
    stats table stays bounded)."""
    return (plan.table, _shape(plan.select))


def _shape(node):
    if dataclasses.is_dataclass(node) and not isinstance(node, type):
        if type(node).__name__ == "Literal":
            return ("?",)  # value masked; shape only
        return (
            type(node).__name__,
            *(
                (f.name, _shape(getattr(node, f.name)))
                for f in dataclasses.fields(node)
            ),
        )
    if isinstance(node, (tuple, list)):
        return tuple(_shape(x) for x in node)
    return node


class PathRouter:
    def __init__(self) -> None:
        # key -> {"device": s, "host": s, "device_n": int, "calls": int}
        self._stats: dict = {}
        self._lock = threading.Lock()

    def _touch(self, key) -> dict:
        """stats entry for key, LRU-bumped; evicts the oldest past MAX_KEYS
        (dicts preserve insertion order — re-inserting moves to the back)."""
        st = self._stats.pop(key, None)
        if st is None:
            st = {"calls": 0}
            if len(self._stats) >= MAX_KEYS:
                self._stats.pop(next(iter(self._stats)))
        self._stats[key] = st
        return st

    def choose(self, key) -> str:
        """"device" or "host".

        Collects TWO device samples before judging: the first device
        execution of a query shape pays jit trace+compile, and the second
        typically absorbs the scan cache's deferred build (scan_cache
        builds on the second sighting of a stable base state) — neither
        reflects steady-state serving. Then one host sample, then the
        measured winner with periodic probes of the loser.
        """
        with self._lock:
            st = self._touch(key)
            if st.get("device_n", 0) < 2:
                return "device"
            if "host" not in st:
                return "host"
            st["calls"] += 1
            winner = "device" if st["device"] <= st["host"] else "host"
            if st["calls"] % PROBE_EVERY == 0:
                return "host" if winner == "device" else "device"
            return winner

    def record(self, key, kind: str, seconds: float) -> None:
        """Fold a sample in: adapt DOWN instantly (a faster time is proof
        the path can go that fast), creep UP by 10% per sample (one GC
        pause or tunnel hiccup must not flip the route)."""
        with self._lock:
            st = self._touch(key)
            prev = st.get(kind)
            if kind == "device":
                n = st.get("device_n", 0) + 1
                st["device_n"] = n
                if n == 2:
                    prev = None  # drop the compile-tainted first sample
            st[kind] = seconds if prev is None else min(seconds, prev * 1.1)

    def stats(self, key) -> dict:
        with self._lock:
            return dict(self._stats.get(key, {}))


def adaptive_enabled() -> bool:
    v = os.environ.get("HORAEDB_ADAPTIVE_PATH", "auto")
    if v in ("0", "off", "false"):
        return False
    if v in ("1", "on", "true"):
        return True
    import jax

    return jax.default_backend() != "cpu"
