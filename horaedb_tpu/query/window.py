"""Window function evaluation (host path).

The reference inherits window functions from DataFusion
(`query_engine/src/datafusion_impl/mod.rs:54` — the whole engine is a
DataFusion impl, so `OVER (PARTITION BY .. ORDER BY ..)` works there).
This is the vectorized-numpy equivalent, shaped for the TSDB access
pattern: partition by tags, order by time, shift/rank/accumulate within
each series.

Semantics match the SQL standard (and DataFusion's defaults):

- no explicit frames; with an ORDER BY, aggregate windows use the default
  running frame RANGE UNBOUNDED PRECEDING .. CURRENT ROW — peers (rows
  tied on all order keys) share the frame end; without ORDER BY the frame
  is the whole partition;
- `last_value` with an ORDER BY therefore returns the current peer
  group's last row (the standard surprise), the partition's last row
  without one;
- NULL ordering: NULLS LAST for ASC, NULLS FIRST for DESC (postgres
  defaults);
- ranking is computed over the sort the OVER clause declares, never the
  output order.

Everything is O(n log n) vectorized: one lexsort, then cumsum/bincount
arithmetic; running min/max uses a Hillis-Steele segmented scan (log n
doubling passes) instead of a per-partition Python loop.
"""

from __future__ import annotations

import numpy as np

from ..common_types.dict_column import as_values
from . import ast


class WindowError(ValueError):
    pass


def _factorize(values: np.ndarray, valid: np.ndarray) -> tuple[np.ndarray, int]:
    """Dense int64 codes in VALUE-SORTED order; NULLs share one code just
    past the valid range (callers re-map per null-placement rule)."""
    v = as_values(values)
    if valid.all():
        u, inv = np.unique(v, return_inverse=True)
        return inv.astype(np.int64), len(u)
    u, inv = np.unique(v[valid], return_inverse=True)
    codes = np.full(len(v), len(u), dtype=np.int64)
    codes[valid] = inv
    return codes, len(u)


def _segmented_scan(values: np.ndarray, offset: np.ndarray, op) -> np.ndarray:
    """Inclusive prefix-``op`` within segments (Hillis-Steele doubling).

    ``offset[i]`` is i's distance from its segment start; ``op`` must be
    an associative ufunc (np.minimum / np.maximum).
    """
    out = values.copy()
    n = len(out)
    shift = 1
    while shift < n:
        take = offset >= shift
        if not take.any():
            break
        prev = np.empty_like(out)
        prev[shift:] = out[:-shift]
        out[take] = op(out[take], prev[take])
        shift *= 2
    return out


def eval_window(
    wf: ast.WindowFunc, rows, eval_expr
) -> tuple[np.ndarray, np.ndarray]:
    """-> (values, valid mask) aligned with ``rows``.

    ``eval_expr`` is executor.eval_expr, passed in to avoid a circular
    import (window args/keys are ordinary expressions).
    """
    n = len(rows)
    if n == 0:
        return np.empty(0), np.empty(0, dtype=bool)

    # ---- partition codes -------------------------------------------------
    part = np.zeros(n, dtype=np.int64)
    for e in wf.spec.partition_by:
        v, m = eval_expr(e, rows)
        codes, k = _factorize(v, m)
        part = part * (k + 1) + codes

    # ---- order keys (factorized: NaN-safe ties, NULL placement) ---------
    sort_keys: list[np.ndarray] = []  # in lexsort order (primary LAST)
    tie_keys: list[np.ndarray] = []
    for o in wf.spec.order_by:
        v, m = eval_expr(o.expr, rows)
        codes, k = _factorize(v, m)
        if o.ascending:
            key = codes  # NULL code k -> last
        else:
            key = -codes  # NULL -> -k -> first
        sort_keys.append(key)
        tie_keys.append(codes)
    perm = np.lexsort(tuple(reversed(sort_keys)) + (part,))

    part_s = part[perm]
    new_seg = np.empty(n, dtype=bool)
    new_seg[0] = True
    new_seg[1:] = part_s[1:] != part_s[:-1]
    idx = np.arange(n, dtype=np.int64)
    start = np.maximum.accumulate(np.where(new_seg, idx, 0))
    seg_id = np.cumsum(new_seg) - 1
    seg_counts = np.bincount(seg_id)
    end = np.cumsum(seg_counts)[seg_id]  # exclusive per-row segment end

    new_peer = new_seg.copy()
    for tk in tie_keys:
        tks = tk[perm]
        new_peer[1:] |= tks[1:] != tks[:-1]
    has_order = bool(wf.spec.order_by)

    def arg_sorted(i: int):
        v, m = eval_expr(wf.args[i], rows)
        return as_values(v)[perm], m[perm]

    name = wf.name
    out_v: np.ndarray
    out_m = np.ones(n, dtype=bool)

    if name == "row_number":
        out_v = idx - start + 1
    elif name == "rank":
        peer_start = np.maximum.accumulate(np.where(new_peer, idx, 0))
        out_v = peer_start - start + 1
    elif name == "dense_rank":
        c = np.cumsum(new_peer)
        out_v = c - c[start] + 1
    elif name in ("lag", "lead"):
        v_s, m_s = arg_sorted(0)
        off = wf.args[1].value if len(wf.args) >= 2 else 1
        default = wf.args[2].value if len(wf.args) >= 3 else None
        if name == "lag":
            src = idx - off
            ok = src >= start
        else:
            src = idx + off
            ok = src < end
        src_c = np.clip(src, 0, n - 1)
        out_v = np.where(ok, v_s[src_c], v_s[0])
        out_m = np.where(ok, m_s[src_c], False)
        if default is not None:
            fill = ~ok
            out_v = _fill_default(out_v, fill, default)
            out_m = out_m | fill
    elif name == "first_value":
        v_s, m_s = arg_sorted(0)
        out_v = v_s[start]
        out_m = m_s[start]
    elif name == "last_value":
        v_s, m_s = arg_sorted(0)
        last = _peer_end(new_peer, n) - 1 if has_order else end - 1
        out_v = v_s[last]
        out_m = m_s[last]
    else:  # count / sum / avg / min / max
        if name == "count" and (
            not wf.args or isinstance(wf.args[0], ast.Star)
        ):
            v_s = np.ones(n)
            m_s = np.ones(n, dtype=bool)
        else:
            v_s, m_s = arg_sorted(0)
        if name == "count":
            # count needs only validity — never touch the values (they
            # may be strings)
            v_f = np.zeros(n)
        else:
            if np.asarray(v_s).dtype.kind not in "fiub":
                raise WindowError(
                    f"{name}() window over a non-numeric column is not "
                    "supported"
                )
            v_f = np.where(m_s, v_s.astype(np.float64, copy=False), 0.0)
        cnt_inc = m_s.astype(np.int64)
        csum = np.cumsum(v_f)
        ccnt = np.cumsum(cnt_inc)
        base_sum = csum[start] - v_f[start]
        base_cnt = ccnt[start] - cnt_inc[start]
        if has_order:
            at = _peer_end(new_peer, n) - 1
            run_sum = csum[at] - base_sum
            run_cnt = ccnt[at] - base_cnt
        else:
            at = end - 1
            run_sum = (csum[at] - base_sum)
            run_cnt = (ccnt[at] - base_cnt)
        if name == "count":
            out_v = run_cnt
        elif name == "sum":
            out_v = run_sum
            out_m = run_cnt > 0
        elif name == "avg":
            with np.errstate(divide="ignore", invalid="ignore"):
                out_v = run_sum / run_cnt
            out_m = run_cnt > 0
        else:  # min / max
            op = np.minimum if name == "min" else np.maximum
            fill = np.inf if name == "min" else -np.inf
            masked = np.where(m_s, v_s.astype(np.float64, copy=False), fill)
            scanned = _segmented_scan(masked, idx - start, op)
            at_mm = _peer_end(new_peer, n) - 1 if has_order else end - 1
            out_v = scanned[at_mm]
            out_m = run_cnt > 0
    res_v = np.empty_like(out_v)
    res_v[perm] = out_v
    res_m = np.empty(n, dtype=bool)
    res_m[perm] = out_m
    return res_v, res_m


def _peer_end(new_peer: np.ndarray, n: int) -> np.ndarray:
    """Exclusive end index of each row's peer group (sorted domain)."""
    peer_id = np.cumsum(new_peer) - 1
    counts = np.bincount(peer_id)
    return np.cumsum(counts)[peer_id]


def _fill_default(out_v: np.ndarray, fill: np.ndarray, default) -> np.ndarray:
    """Write ``default`` into ``fill`` slots, widening dtype if needed."""
    if not fill.any():
        return out_v
    try:
        out_v = out_v.copy()
        out_v[fill] = default
        return out_v
    except (ValueError, TypeError):
        widened = out_v.astype(object)
        widened[fill] = default
        return widened
