"""Equi-key inner/left join on the host path
(ref: the reference gets JOIN from DataFusion, query_engine/src/
datafusion_impl/mod.rs:54 — this is the host-path subset: one or more
equi-keys ANDed, inner/left, two tables).

Vectorized hash-join shape: factorize each key-column pair into one code
space, fold multiple keys into a composite code (re-compacted per key so
the product never overflows), sort the right side by code, then expand
match pairs with repeat/cumsum arithmetic — no per-row Python. Joined
rows feed the existing projection/WHERE/ORDER BY/LIMIT machinery over a
synthesized combined schema.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common_types.dict_column import as_values, unique_inverse
from ..common_types.row_group import RowGroup
from ..common_types.schema import ColumnSchema, Schema
from . import ast
from .executor import ResultSet


class JoinError(ValueError):
    pass


def execute_join(catalog, executor, stmt: ast.Select) -> ResultSet:
    join = stmt.join
    left_t = catalog.open(stmt.table)
    right_t = catalog.open(join.table)
    if left_t is None:
        raise JoinError(f"table not found: {stmt.table}")
    if right_t is None:
        raise JoinError(f"table not found: {join.table}")
    ls, rs = left_t.schema, right_t.schema
    for col in join.left_cols:
        if not ls.has_column(col):
            raise JoinError(f"join key {col!r} not in {stmt.table}")
    for col in join.right_cols:
        if not rs.has_column(col):
            raise JoinError(f"join key {col!r} not in {join.table}")

    # Push the WHERE's time range + simple filters into the LEFT scan
    # (the output timestamp IS the left one, so its conjuncts are left's;
    # exact WHERE still evaluates post-join). The right side is typically
    # a small dimension table — full read.
    from .planner import extract_predicate

    left = left_t.read(extract_predicate(stmt.where, ls))
    right = right_t.read(None)

    lk, rk = _composite_codes(
        [as_values(left.column(c)) for c in join.left_cols],
        [as_values(right.column(c)) for c in join.right_cols],
    )
    li_idx, ri_idx = _inner_match(lk, rk)
    if join.kind == "left":
        # unmatched left rows survive with NULL right columns
        matched = np.zeros(len(lk), dtype=bool)
        matched[li_idx] = True
        unmatched = np.nonzero(~matched)[0]
        li_idx = np.concatenate([li_idx, unmatched])
        ri_idx = np.concatenate(
            [ri_idx, np.full(len(unmatched), -1, dtype=np.int64)]
        )

    # Combined schema: left columns + right non-key columns; internal tsid
    # columns stay out; name clashes (other than the key) are an error the
    # user resolves by renaming — qualified output names are not modeled.
    def visible(s: Schema) -> list[ColumnSchema]:
        tsid = s.columns[s.tsid_index].name if s.tsid_index is not None else None
        return [c for c in s.columns if c.name != tsid]

    cols: list[ColumnSchema] = list(visible(ls))
    names = {c.name for c in cols}
    for c in visible(rs):
        if c.name in join.right_cols:
            continue  # equal to the left keys by construction
        if c.name == rs.timestamp_name:
            # Every table carries a timestamp; the joined row keeps the
            # LEFT one (dimension-table joins don't want the right's).
            continue
        if c.name in names:
            raise JoinError(
                f"ambiguous column {c.name!r} on both sides of the join"
            )
        cols.append(c)

    combined_schema = Schema.build(
        [ColumnSchema(c.name, c.kind, is_tag=c.is_tag) for c in cols],
        timestamp_column=ls.timestamp_name,
        primary_key=[*join.left_cols, ls.timestamp_name],
    )
    data = {}
    validity = {}
    for c in visible(ls):
        data[c.name] = as_values(left.column(c.name))[li_idx]
        m = left.valid_mask(c.name)
        if not m.all():
            validity[c.name] = m[li_idx]
    null_right = ri_idx < 0  # LEFT JOIN: rows with no right-side match
    ri_safe = np.where(null_right, 0, ri_idx)
    for c in visible(rs):
        if c.name in join.right_cols or c.name == rs.timestamp_name:
            continue
        vals = as_values(right.column(c.name))
        # NULL slots carry the column kind's default fill (the engine-wide
        # convention — see RowGroup) so downstream comparisons/sorts see a
        # well-typed value, never an arbitrary row-0 leak.
        fill = np.full(len(ri_idx), c.kind.default_value(), dtype=c.kind.numpy_dtype)
        if len(vals) == 0:
            data[c.name] = fill
            validity[c.name] = np.zeros(len(ri_idx), dtype=bool)
            continue
        data[c.name] = np.where(null_right, fill, vals[ri_safe])
        m = right.valid_mask(c.name)[ri_safe] & ~null_right
        if not m.all():
            validity[c.name] = m
    # Schema.build may prepend a tsid column; fill it (unused downstream).
    if combined_schema.tsid_index is not None:
        tsid_name = combined_schema.columns[combined_schema.tsid_index].name
        if tsid_name not in data:
            data[tsid_name] = np.zeros(len(li_idx), dtype=np.uint64)
    rows = RowGroup(combined_schema, data, validity)

    # Reuse the projection pipeline: WHERE/ORDER/LIMIT over joined rows.
    from .plan import QueryPlan
    from ..table_engine.predicate import Predicate

    plan = QueryPlan(
        table=f"{stmt.table}⋈{join.table}",
        schema=combined_schema,
        select=stmt,
        predicate=Predicate.all_time(),
        aggs=(),
        group_keys=(),
        is_aggregate=False,
    )
    # WHERE evaluates here exactly (the storage predicate never saw the
    # join): hand the projection a where-less statement so the residual
    # logic can't drop time conjuncts it assumes storage applied.
    if stmt.where is not None and len(rows):
        from .executor import eval_expr

        v, m = eval_expr(stmt.where, rows)
        rows = rows.filter(np.asarray(as_values(v)).astype(bool) & m)
    import dataclasses

    plan = dataclasses.replace(plan, select=dataclasses.replace(stmt, where=None))
    return executor._execute_projection(plan, rows)


def _composite_codes(
    l_cols: list[np.ndarray], r_cols: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """Fold N key-column pairs into one integer code per row such that
    composite codes are equal iff every key column is equal.

    Per key: factorize left+right jointly, then composite = prior * card
    + code. The composite is RE-COMPACTED after each key (unique over at
    most n_l + n_r values), so the running product stays bounded by
    (n_l + n_r) * card and cannot overflow int64 for any realistic input.
    """
    n_l = len(l_cols[0])
    comp: Optional[np.ndarray] = None
    for lk, rk in zip(l_cols, r_cols):
        _, codes = unique_inverse(np.concatenate([lk, rk]))
        codes = codes.astype(np.int64)
        if comp is None:
            comp = codes
            continue
        card = int(codes.max()) + 1 if len(codes) else 1
        comp = comp * card + codes
        _, comp = np.unique(comp, return_inverse=True)
    assert comp is not None
    return comp[:n_l], comp[n_l:]


def _inner_match(lk: np.ndarray, rk: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Index pairs (li, ri) of every equal-code combination; ``lk``/``rk``
    are already in one shared code space (see _composite_codes)."""
    n_l = len(lk)
    lc, rc = lk, rk
    order_r = np.argsort(rc, kind="stable")
    rc_sorted = rc[order_r]
    # for each left row: the contiguous run of matching right rows
    starts = np.searchsorted(rc_sorted, lc, side="left")
    ends = np.searchsorted(rc_sorted, lc, side="right")
    counts = ends - starts
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    li = np.repeat(np.arange(n_l, dtype=np.int64), counts)
    # within-run offsets: global arange minus each row's run start
    run_starts = np.repeat(np.cumsum(counts) - counts, counts)
    offsets = np.arange(total, dtype=np.int64) - run_starts
    ri = order_r[np.repeat(starts, counts) + offsets]
    return li, ri
