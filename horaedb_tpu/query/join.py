"""Equi-key joins on the host path
(ref: the reference gets JOIN from DataFusion, query_engine/src/
datafusion_impl/mod.rs:54 — this is the host-path subset: one or more
equi-keys ANDed; INNER / LEFT / RIGHT / FULL OUTER; arbitrary-length
chains folded left-to-right).

Vectorized hash-join shape: factorize each key-column pair into one code
space, fold multiple keys into a composite code (re-compacted per key so
the product never overflows), sort the right side by code, then expand
match pairs with repeat/cumsum arithmetic — no per-row Python. NULL keys
match nothing (SQL equality), including other NULLs. Outer variants are
the same match mirrored: unmatched-left rows ride with NULL right
columns, unmatched-right with NULL left columns, FULL with both; merged
key columns COALESCE(left, right) so an unmatched-right row still shows
its key. Joined rows feed the existing projection/WHERE/ORDER BY/LIMIT
machinery over a synthesized combined schema.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common_types.dict_column import as_values, unique_inverse
from ..common_types.row_group import RowGroup
from ..common_types.schema import ColumnSchema, Schema
from . import ast
from .executor import ResultSet


class JoinError(ValueError):
    pass


def execute_join(catalog, executor, stmt: ast.Select) -> ResultSet:
    joins = [stmt.join, *stmt.joins]
    left_t = catalog.open(stmt.table)
    if left_t is None:
        raise JoinError(f"table not found: {stmt.table}")

    # Push the WHERE's time range + simple filters into the BASE scan
    # (the output timestamp IS the left one, so its conjuncts are left's;
    # exact WHERE still evaluates post-join). Sound only when no step is
    # RIGHT/FULL: dropping a base row early would turn its matches into
    # unmatched-right rows, changing which NULL-padded rows exist before
    # the exact WHERE runs.
    from .planner import extract_predicate

    push = all(j.kind in ("inner", "left") for j in joins)
    pred = extract_predicate(stmt.where, left_t.schema) if push else None
    rows = left_t.read(pred)

    for join in joins:
        right_t = catalog.open(join.table)
        if right_t is None:
            raise JoinError(f"table not found: {join.table}")
        rows = _join_step(rows, join, right_t.read(None), right_t.schema)

    # Reuse the projection pipeline: WHERE/ORDER/LIMIT over joined rows.
    from ..table_engine.predicate import Predicate
    from .plan import QueryPlan

    plan = QueryPlan(
        table="⋈".join([stmt.table, *(j.table for j in joins)]),
        schema=rows.schema,
        select=stmt,
        predicate=Predicate.all_time(),
        aggs=(),
        group_keys=(),
        is_aggregate=False,
    )
    # WHERE evaluates here exactly (the storage predicate never saw the
    # join): hand the projection a where-less statement so the residual
    # logic can't drop time conjuncts it assumes storage applied.
    if stmt.where is not None and len(rows):
        from .executor import eval_expr

        v, m = eval_expr(stmt.where, rows)
        rows = rows.filter(np.asarray(as_values(v)).astype(bool) & m)
    import dataclasses

    plan = dataclasses.replace(plan, select=dataclasses.replace(stmt, where=None))
    return executor._execute_projection(plan, rows)


def _visible(s: Schema) -> list[ColumnSchema]:
    tsid = s.columns[s.tsid_index].name if s.tsid_index is not None else None
    return [c for c in s.columns if c.name != tsid]


def _join_step(
    left: RowGroup, join: ast.Join, right: RowGroup, rs: Schema
) -> RowGroup:
    """One fold step: the combined rows so far ⋈ the next table."""
    ls = left.schema
    for col in join.left_cols:
        if not ls.has_column(col):
            raise JoinError(f"join key {col!r} not found on the left side")
    for col in join.right_cols:
        if not rs.has_column(col):
            raise JoinError(f"join key {col!r} not in {join.table}")

    lk, rk = _composite_codes(
        [as_values(left.column(c)) for c in join.left_cols],
        [as_values(right.column(c)) for c in join.right_cols],
    )
    # SQL equality: a NULL key matches NOTHING (not even another NULL) —
    # give each NULL-keyed row a unique code outside the shared space.
    l_valid = np.ones(len(lk), dtype=bool)
    for c in join.left_cols:
        l_valid &= left.valid_mask(c)
    r_valid = np.ones(len(rk), dtype=bool)
    for c in join.right_cols:
        r_valid &= right.valid_mask(c)
    if not l_valid.all() or not r_valid.all():
        base = int(max(lk.max(initial=0), rk.max(initial=0))) + 1
        lk = lk.copy()
        rk = rk.copy()
        l_bad = np.nonzero(~l_valid)[0]
        r_bad = np.nonzero(~r_valid)[0]
        lk[l_bad] = base + np.arange(len(l_bad))
        rk[r_bad] = base + len(l_bad) + np.arange(len(r_bad))

    li_idx, ri_idx = _inner_match(lk, rk)
    if join.kind in ("left", "full"):
        matched = np.zeros(len(lk), dtype=bool)
        matched[li_idx] = True
        unmatched = np.nonzero(~matched)[0]
        li_idx = np.concatenate([li_idx, unmatched])
        ri_idx = np.concatenate(
            [ri_idx, np.full(len(unmatched), -1, dtype=np.int64)]
        )
    if join.kind in ("right", "full"):
        # the mirrored mask: right rows no left row matched
        matched_r = np.zeros(len(rk), dtype=bool)
        matched_r[ri_idx[ri_idx >= 0]] = True
        unmatched_r = np.nonzero(~matched_r)[0]
        li_idx = np.concatenate(
            [li_idx, np.full(len(unmatched_r), -1, dtype=np.int64)]
        )
        ri_idx = np.concatenate([ri_idx, unmatched_r])

    # Combined schema: left columns + right non-key columns; internal tsid
    # columns stay out; name clashes (other than the key) are an error the
    # user resolves by renaming — qualified output names are not modeled.
    cols: list[ColumnSchema] = list(_visible(ls))
    names = {c.name for c in cols}
    for c in _visible(rs):
        if c.name in join.right_cols:
            continue  # merged into the left key columns (COALESCE)
        if c.name == rs.timestamp_name:
            # Every table carries a timestamp; the joined row keeps the
            # LEFT one (dimension-table joins don't want the right's).
            continue
        if c.name in names:
            raise JoinError(
                f"ambiguous column {c.name!r} on both sides of the join"
            )
        cols.append(c)

    combined_schema = Schema.build(
        [ColumnSchema(c.name, c.kind, is_tag=c.is_tag) for c in cols],
        timestamp_column=ls.timestamp_name,
        primary_key=[*join.left_cols, ls.timestamp_name],
    )
    n_out = len(li_idx)
    null_left = li_idx < 0  # RIGHT/FULL: rows with no left-side match
    null_right = ri_idx < 0  # LEFT/FULL: rows with no right-side match
    li_safe = np.where(null_left, 0, li_idx)
    ri_safe = np.where(null_right, 0, ri_idx)
    key_merge = dict(zip(join.left_cols, join.right_cols))

    data = {}
    validity = {}
    for c in _visible(ls):
        fill = np.full(n_out, c.kind.default_value(), dtype=c.kind.numpy_dtype)
        lvals = as_values(left.column(c.name))
        taken = fill if len(lvals) == 0 else np.where(
            null_left, fill, lvals[li_safe]
        )
        lm = (
            np.zeros(n_out, dtype=bool)
            if len(lvals) == 0
            else left.valid_mask(c.name)[li_safe] & ~null_left
        )
        if c.name in key_merge:
            # merged key column: COALESCE(left, right) — an unmatched
            # right row still shows the key it joined on.
            rvals = as_values(right.column(key_merge[c.name]))
            if len(rvals):
                rtaken = rvals[ri_safe]
                rm = right.valid_mask(key_merge[c.name])[ri_safe] & ~null_right
                taken = np.where(null_left, rtaken, taken)
                lm = np.where(null_left, rm, lm)
        data[c.name] = taken
        if not lm.all():
            validity[c.name] = lm
    for c in _visible(rs):
        if c.name in join.right_cols or c.name == rs.timestamp_name:
            continue
        vals = as_values(right.column(c.name))
        # NULL slots carry the column kind's default fill (the engine-wide
        # convention — see RowGroup) so downstream comparisons/sorts see a
        # well-typed value, never an arbitrary row-0 leak.
        fill = np.full(n_out, c.kind.default_value(), dtype=c.kind.numpy_dtype)
        if len(vals) == 0:
            data[c.name] = fill
            validity[c.name] = np.zeros(n_out, dtype=bool)
            continue
        data[c.name] = np.where(null_right, fill, vals[ri_safe])
        m = right.valid_mask(c.name)[ri_safe] & ~null_right
        if not m.all():
            validity[c.name] = m
    # Schema.build may prepend a tsid column; fill it (unused downstream).
    if combined_schema.tsid_index is not None:
        tsid_name = combined_schema.columns[combined_schema.tsid_index].name
        if tsid_name not in data:
            data[tsid_name] = np.zeros(n_out, dtype=np.uint64)
    return RowGroup(combined_schema, data, validity)


def _composite_codes(
    l_cols: list[np.ndarray], r_cols: list[np.ndarray]
) -> tuple[np.ndarray, np.ndarray]:
    """Fold N key-column pairs into one integer code per row such that
    composite codes are equal iff every key column is equal.

    Per key: factorize left+right jointly, then composite = prior * card
    + code. The composite is RE-COMPACTED after each key (unique over at
    most n_l + n_r values), so the running product stays bounded by
    (n_l + n_r) * card and cannot overflow int64 for any realistic input.
    """
    n_l = len(l_cols[0])
    comp: Optional[np.ndarray] = None
    for lk, rk in zip(l_cols, r_cols):
        _, codes = unique_inverse(np.concatenate([lk, rk]))
        codes = codes.astype(np.int64)
        if comp is None:
            comp = codes
            continue
        card = int(codes.max()) + 1 if len(codes) else 1
        comp = comp * card + codes
        _, comp = np.unique(comp, return_inverse=True)
    assert comp is not None
    return comp[:n_l], comp[n_l:]


def _inner_match(lk: np.ndarray, rk: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Index pairs (li, ri) of every equal-code combination; ``lk``/``rk``
    are already in one shared code space (see _composite_codes)."""
    n_l = len(lk)
    lc, rc = lk, rk
    order_r = np.argsort(rc, kind="stable")
    rc_sorted = rc[order_r]
    # for each left row: the contiguous run of matching right rows
    starts = np.searchsorted(rc_sorted, lc, side="left")
    ends = np.searchsorted(rc_sorted, lc, side="right")
    counts = ends - starts
    total = int(counts.sum())
    if total == 0:
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)
    li = np.repeat(np.arange(n_l, dtype=np.int64), counts)
    # within-run offsets: global arange minus each row's run start
    run_starts = np.repeat(np.cumsum(counts) - counts, counts)
    offsets = np.arange(total, dtype=np.int64) - run_starts
    ri = order_r[np.repeat(starts, counts) + offsets]
    return li, ri
