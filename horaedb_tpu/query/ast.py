"""SQL AST nodes (expressions + statements).

Kept deliberately small: the expression grammar covers what the engine can
execute (arithmetic, comparisons, boolean logic, function calls, literals,
columns); statements cover the reference's Plan surface (plan.rs:67):
query, insert, create/drop/describe/alter/show/exists.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence


# ---- expressions -------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    pass


@dataclass(frozen=True)
class Column(Expr):
    name: str
    # table qualifier from ``t.col`` syntax; resolution is by bare name,
    # but the planner validates the qualifier names a table in the query
    qualifier: Optional[str] = None

    def __str__(self) -> str:
        return self.name


@dataclass(frozen=True)
class Literal(Expr):
    value: Any  # int | float | str | bool | None

    def __str__(self) -> str:
        if isinstance(self.value, str):
            return f"'{self.value}'"
        return str(self.value)


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str  # + - * / % = != < <= > >= AND OR
    left: Expr
    right: Expr

    def __str__(self) -> str:
        return f"({self.left} {self.op} {self.right})"


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # NOT, -
    operand: Expr

    def __str__(self) -> str:
        return f"({self.op} {self.operand})"


@dataclass(frozen=True)
class Case(Expr):
    """CASE [operand] WHEN w THEN t ... [ELSE e] END.

    Simple CASE (with operand) is normalized by the parser into the
    searched form (operand = w -> operand IS NOT DISTINCT FROM w is not
    needed here: SQL simple CASE uses plain equality), so ``whens`` always
    holds boolean conditions."""

    whens: tuple[tuple[Expr, Expr], ...]  # (condition, result)
    else_: Optional[Expr] = None

    def __str__(self) -> str:
        parts = " ".join(f"WHEN {w} THEN {t}" for w, t in self.whens)
        tail = f" ELSE {self.else_}" if self.else_ is not None else ""
        return f"CASE {parts}{tail} END"


@dataclass(frozen=True)
class Cast(Expr):
    """CAST(expr AS type) — type is the SQL name, lowercased."""

    expr: Expr
    type_name: str

    def __str__(self) -> str:
        return f"CAST({self.expr} AS {self.type_name})"


@dataclass(frozen=True)
class Like(Expr):
    """expr [NOT] LIKE 'pattern' — % any run, _ one char; matches are
    case-sensitive (ILIKE relaxes)."""

    expr: Expr
    pattern: str
    negated: bool = False
    case_insensitive: bool = False

    def __str__(self) -> str:
        op = ("NOT " if self.negated else "") + ("ILIKE" if self.case_insensitive else "LIKE")
        return f"({self.expr} {op} '{self.pattern}')"


@dataclass(frozen=True)
class FuncCall(Expr):
    name: str  # lowercased
    args: tuple[Expr, ...]
    distinct: bool = False
    # agg(col) FILTER (WHERE cond) — standard SQL per-aggregate row filter
    filter_where: Optional["Expr"] = None

    def __str__(self) -> str:
        inner = ", ".join(str(a) for a in self.args)
        base = f"{self.name}({'DISTINCT ' if self.distinct else ''}{inner})"
        if self.filter_where is not None:
            base += f" FILTER (WHERE {self.filter_where})"
        return base


@dataclass(frozen=True)
class Star(Expr):
    def __str__(self) -> str:
        return "*"


@dataclass(frozen=True)
class InList(Expr):
    expr: Expr
    values: tuple[Expr, ...]
    negated: bool = False

    def __str__(self) -> str:
        vals = ", ".join(str(v) for v in self.values)
        return f"({self.expr} {'NOT ' if self.negated else ''}IN ({vals}))"


@dataclass(frozen=True)
class Subquery(Expr):
    """Uncorrelated scalar subquery — evaluated once before the outer
    query and replaced with its single value."""

    select: "Select"

    def __str__(self) -> str:
        return f"(subquery:{self.select.table})"


class _CorrelatedDup:
    """Sentinel value for a correlation key whose non-aggregate scalar
    subquery matched more than one inner row. SQL errors only if such a
    key is actually PROBED by an outer row — so the error is raised at
    lookup time, not while materializing the inner result."""

    __slots__ = ()

    def __repr__(self) -> str:
        return "<correlated-dup>"


CORRELATED_DUP = _CorrelatedDup()


@dataclass(frozen=True)
class CorrelatedLookup(Expr):
    """INTERNAL (never parsed): the decorrelated form of an
    equality-correlated scalar subquery. The inner aggregate has been
    computed once, grouped by its correlation keys; evaluation is a
    per-outer-row lookup on ``outer_cols`` (real Column nodes, so every
    expression walker — scan pruning, qualifier validation — sees them).
    ``default`` fills missing keys AND NULL-keyed outer rows (0 for
    COUNT, NULL otherwise — SQL's empty-group semantics)."""

    outer_cols: tuple["Column", ...]
    keys: tuple[tuple, ...]
    values: tuple
    default: object = None

    def __str__(self) -> str:
        return f"(corr-lookup:{','.join(c.name for c in self.outer_cols)})"


@dataclass(frozen=True)
class Exists(Expr):
    """[NOT handled by UnaryOp] EXISTS (SELECT ...) — a boolean semi-join
    probe. Uncorrelated: materializes to a constant. Equality-correlated:
    decorrelates into a distinct-key inner query + per-row membership
    lookup (the semi-join analog of the scalar decorrelation)."""

    select: "Select"

    def __str__(self) -> str:
        return f"EXISTS(subquery:{self.select.table})"


@dataclass(frozen=True)
class InSubquery(Expr):
    """expr [NOT] IN (SELECT col FROM ...) — uncorrelated; materialized
    into an InList before the outer query runs."""

    expr: Expr
    select: "Select"
    negated: bool = False

    def __str__(self) -> str:
        return f"({self.expr} {'NOT ' if self.negated else ''}IN subquery:{self.select.table})"


@dataclass(frozen=True)
class WindowSpec:
    """OVER (PARTITION BY ... ORDER BY ...) — no explicit frames; with an
    ORDER BY, aggregate windows use the SQL-default running frame (RANGE
    UNBOUNDED PRECEDING .. CURRENT ROW, peers included), without one the
    whole partition (the same defaults DataFusion gives the reference,
    query_engine/src/datafusion_impl/mod.rs:54)."""

    partition_by: tuple[Expr, ...] = ()
    order_by: tuple["OrderItem", ...] = ()


@dataclass(frozen=True)
class WindowFunc(Expr):
    """fn(args) OVER (spec). ``name`` is lowercased: row_number, rank,
    dense_rank, lag, lead, first_value, last_value, count, sum, avg,
    min, max."""

    name: str
    args: tuple[Expr, ...]
    spec: WindowSpec

    def __str__(self) -> str:
        inner = ", ".join(str(a) for a in self.args)
        return f"{self.name}({inner}) OVER (...)"


@dataclass(frozen=True)
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr
    negated: bool = False


# ---- statements --------------------------------------------------------


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None

    @property
    def output_name(self) -> str:
        return self.alias if self.alias else str(self.expr)


@dataclass(frozen=True)
class OrderItem:
    expr: Expr
    ascending: bool = True
    # NULLS FIRST/LAST; None = SQL default (LAST when ASC, FIRST when DESC)
    nulls_last: Optional[bool] = None


@dataclass(frozen=True)
class Join:
    """Equi-key join: [LEFT|RIGHT|FULL [OUTER]] JOIN <table> ON
    <l.k1> = <r.k1> [AND ...].

    ``left_cols[i]`` pairs with ``right_cols[i]`` (conjunction of
    equalities; the reference gets arbitrary join conditions from
    DataFusion — this is the host-path equi-join subset). In a chain,
    ``left_cols`` may name columns from ANY earlier table (the combined
    row so far — standard left-to-right join evaluation)."""

    table: str
    left_cols: tuple[str, ...]
    right_cols: tuple[str, ...]
    kind: str = "inner"  # "inner" | "left" | "right" | "full"


@dataclass(frozen=True)
class Select:
    items: tuple[SelectItem, ...]
    table: Optional[str]
    where: Optional[Expr] = None
    group_by: tuple[Expr, ...] = ()
    order_by: tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0
    having: Optional[Expr] = None
    distinct: bool = False
    join: Optional[Join] = None
    # Joins AFTER the first (>2-table chains, folded left-to-right);
    # ``join`` stays the first so every `stmt.join is not None` presence
    # check keeps working.
    joins: tuple[Join, ...] = ()
    # WITH name AS (...) bindings visible to this select (and, through
    # the interpreter's overlay, to later ctes in the same statement)
    ctes: tuple[tuple[str, "Select | UnionSelect"], ...] = ()


@dataclass(frozen=True)
class UnionSelect:
    """s1 UNION [ALL] s2 [UNION ...] — columns align by position, names
    come from the first branch; a trailing ORDER BY/LIMIT applies to the
    combined result (standard SQL placement).

    ``all_flags[i]`` is the ALL-ness of the i-th UNION operator (between
    selects[i] and selects[i+1]); mixed chains evaluate left-to-right —
    a distinct UNION dedups everything accumulated so far, a UNION ALL
    appends (standard left-associative semantics)."""

    selects: tuple[Select, ...]
    all_flags: tuple[bool, ...] = ()
    order_by: tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    offset: int = 0
    ctes: tuple[tuple[str, "Select | UnionSelect"], ...] = ()

    @property
    def all(self) -> bool:
        return all(self.all_flags)


@dataclass(frozen=True)
class ColumnDef:
    name: str
    type_name: str
    is_tag: bool = False
    is_timestamp_key: bool = False
    not_null: bool = False
    comment: str = ""


@dataclass(frozen=True)
class CreateTable:
    table: str
    columns: tuple[ColumnDef, ...]
    timestamp_key: Optional[str]  # from inline `timestamp KEY` or TIMESTAMP KEY(col)
    primary_key: Optional[tuple[str, ...]]
    engine: str = "Analytic"
    options: dict[str, str] = field(default_factory=dict)
    if_not_exists: bool = False
    partition_by: Optional["PartitionBy"] = None


@dataclass(frozen=True)
class PartitionBy:
    """PARTITION BY KEY(cols) PARTITIONS n — ref: parser.rs partition DDL."""

    method: str  # "key" | "hash"
    columns: tuple[str, ...]
    num_partitions: int


@dataclass(frozen=True)
class Insert:
    table: str
    columns: tuple[str, ...]
    values: tuple[tuple[Any, ...], ...]  # literal rows


@dataclass(frozen=True)
class DropTable:
    table: str
    if_exists: bool = False


@dataclass(frozen=True)
class Describe:
    table: str


@dataclass(frozen=True)
class ShowTables:
    pass


@dataclass(frozen=True)
class ShowCreateTable:
    table: str


@dataclass(frozen=True)
class ExistsTable:
    table: str


@dataclass(frozen=True)
class AlterTableAddColumn:
    table: str
    columns: tuple[ColumnDef, ...]


@dataclass(frozen=True)
class AlterTableSetOptions:
    table: str
    options: dict[str, str]


@dataclass(frozen=True)
class Explain:
    inner: "Select | UnionSelect"
    analyze: bool = False


@dataclass(frozen=True)
class KillQuery:
    """KILL QUERY <id> — cooperative cancellation of a live query (the
    id from ``system.public.queries`` / ``/debug/queries?live=1``)."""

    query_id: int


Statement = (
    Select
    | UnionSelect
    | CreateTable
    | Insert
    | DropTable
    | Describe
    | ShowTables
    | ShowCreateTable
    | ExistsTable
    | AlterTableAddColumn
    | AlterTableSetOptions
    | KillQuery
)
