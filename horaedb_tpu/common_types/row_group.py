"""Columnar row groups — the ingest/scan unit.

The reference moves ``Row``/``RowGroup`` row structs through the write path
(src/common_types/src/row/) and converts to Arrow at the engine boundary.
Here the columnar form IS the native form: a ``RowGroup`` is a schema plus
aligned numpy arrays (one per column, plus optional validity masks), so the
path ingest -> memtable -> SST -> device needs no row pivot at all.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Mapping, Optional, Sequence

import numpy as np
import pyarrow as pa

from .datum import DatumKind, arrow_to_kind
from .dict_column import DictColumn, as_values, concat_columns
from .schema import ColumnSchema, Schema, TSID_COLUMN, compute_tsid
from .time_range import TimeRange


class RowGroup:
    def __init__(
        self,
        schema: Schema,
        columns: Mapping[str, np.ndarray],
        validity: Optional[Mapping[str, np.ndarray]] = None,
    ) -> None:
        self.schema = schema
        self.columns: dict[str, np.ndarray] = dict(columns)
        self.validity: dict[str, np.ndarray] = dict(validity or {})
        lengths = {len(a) for a in self.columns.values()}
        if len(lengths) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        self._n = lengths.pop() if lengths else 0
        for c in schema.columns:
            if c.name not in self.columns:
                raise ValueError(f"missing column {c.name!r}")

    # ---- constructors --------------------------------------------------
    @staticmethod
    def from_rows(schema: Schema, rows: Sequence[Mapping[str, Any]]) -> "RowGroup":
        """Build from row dicts (INSERT path). Computes tsid, fills NULLs."""
        n = len(rows)
        columns: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        for col in schema.columns:
            if col.name == TSID_COLUMN and schema.tsid_index is not None:
                continue  # computed below
            dtype = col.kind.numpy_dtype
            arr = np.empty(n, dtype=dtype)
            valid = np.ones(n, dtype=np.bool_)
            default = col.default_value if col.default_value is not None else col.kind.default_value()
            for i, row in enumerate(rows):
                v = row.get(col.name)
                if v is None:
                    if not col.is_nullable:
                        raise ValueError(f"NULL in non-nullable column {col.name!r}")
                    valid[i] = False
                    arr[i] = default
                else:
                    arr[i] = v
            columns[col.name] = arr
            if not valid.all():
                validity[col.name] = valid
        if schema.tsid_index is not None:
            tags = [columns[schema.columns[i].name] for i in schema.tag_indexes]
            columns[TSID_COLUMN] = compute_tsid(tags, num_rows=n)
        return RowGroup(schema, columns, validity)

    @staticmethod
    def from_arrow(schema: Schema, batch: pa.RecordBatch | pa.Table) -> "RowGroup":
        columns: dict[str, np.ndarray] = {}
        validity: dict[str, np.ndarray] = {}
        for col in schema.columns:
            idx = batch.schema.get_field_index(col.name)
            if idx < 0:
                # Column added by ALTER after this batch was written: all-NULL.
                n = batch.num_rows
                fill = col.kind.default_value()
                columns[col.name] = np.full(n, fill, dtype=col.kind.numpy_dtype)
                validity[col.name] = np.zeros(n, dtype=np.bool_)
                continue
            arr = batch.column(idx)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            if pa.types.is_dictionary(arr.type) and col.kind is DatumKind.STRING:
                # String tags stay dictionary-encoded: codes + small
                # vocabulary, never per-row Python objects (the scan hot
                # path). Non-string dictionary inputs fall through to the
                # decode path below.
                vocab = np.asarray(arr.dictionary.to_pylist(), dtype=object)
                default = col.kind.default_value()
                if arr.null_count:
                    validity[col.name] = np.asarray(arr.is_valid())
                    # NULL slots must encode the same default value the
                    # plain-array ingest path fills in, so tsid/partition
                    # hashing is representation-independent.
                    hits = np.nonzero(vocab == default)[0]
                    if len(hits):
                        default_code = int(hits[0])
                    else:
                        vocab = np.append(vocab, default)
                        default_code = len(vocab) - 1
                    codes = np.asarray(arr.indices.fill_null(default_code), dtype=np.int32)
                else:
                    codes = np.asarray(arr.indices.fill_null(0), dtype=np.int32)
                if len(vocab) == 0:
                    vocab = np.array([default], dtype=object)
                columns[col.name] = DictColumn(codes, vocab)
                continue
            if pa.types.is_dictionary(arr.type):
                arr = arr.cast(arr.type.value_type)
            if arr.null_count:
                validity[col.name] = np.asarray(arr.is_valid())
                arr = arr.fill_null(col.kind.default_value())
            if col.kind in (DatumKind.STRING, DatumKind.VARBINARY):
                columns[col.name] = np.asarray(arr.to_pylist(), dtype=object)
            elif col.kind is DatumKind.TIMESTAMP:
                columns[col.name] = np.asarray(arr.cast(pa.int64()))
            else:
                columns[col.name] = np.asarray(arr)
        return RowGroup(schema, columns, validity)

    @staticmethod
    def concat(parts: Sequence["RowGroup"]) -> "RowGroup":
        if not parts:
            raise ValueError("concat of zero row groups")
        schema = parts[0].schema
        columns = {
            name: concat_columns([p.columns[name] for p in parts])
            for name in parts[0].columns
        }
        validity = {}
        names_with_nulls = {n for p in parts for n in p.validity}
        for name in names_with_nulls:
            validity[name] = np.concatenate(
                [p.validity.get(name, np.ones(len(p), dtype=np.bool_)) for p in parts]
            )
        return RowGroup(schema, columns, validity)

    # ---- accessors -----------------------------------------------------
    def __len__(self) -> int:
        return self._n

    @property
    def num_rows(self) -> int:
        return self._n

    def column(self, name: str) -> np.ndarray:
        return self.columns[name]

    def valid_mask(self, name: str) -> np.ndarray:
        m = self.validity.get(name)
        return m if m is not None else np.ones(self._n, dtype=np.bool_)

    @property
    def timestamps(self) -> np.ndarray:
        return self.columns[self.schema.timestamp_name]

    def time_range(self) -> TimeRange:
        if self._n == 0:
            return TimeRange.empty()
        ts = self.timestamps
        return TimeRange(int(ts.min()), int(ts.max()) + 1)

    # ---- transforms ----------------------------------------------------
    def take(self, indices: np.ndarray) -> "RowGroup":
        return RowGroup(
            self.schema,
            {k: v[indices] for k, v in self.columns.items()},
            {k: v[indices] for k, v in self.validity.items()},
        )

    def filter(self, mask: np.ndarray) -> "RowGroup":
        return self.take(np.nonzero(mask)[0])

    def slice(self, start: int, stop: int) -> "RowGroup":
        return RowGroup(
            self.schema,
            {k: v[start:stop] for k, v in self.columns.items()},
            {k: v[start:stop] for k, v in self.validity.items()},
        )

    def key_sort_permutation(self, seq: Optional[np.ndarray] = None) -> np.ndarray:
        """Permutation that sorts rows by primary key columns (ascending).

        With ``seq`` given, later sequence numbers win ties *by coming
        first* — matching the merge-iterator's sequence ordering for
        overwrite tables (ref: row_iter/merge.rs sequence ordering).
        """
        keys: list[np.ndarray] = []
        if seq is not None:
            # Least-significant tiebreak: duplicate keys within ONE write
            # batch share a sequence — later rows win (the reference's
            # memtable applies rows in order, so last-write-wins).
            keys.append(-np.arange(len(self), dtype=np.int64))
            keys.append(-seq.astype(np.int64))
        for i in reversed(self.schema.primary_key_indexes):
            keys.append(self._sortable(self.schema.columns[i].name))
        return np.lexsort(tuple(keys))

    def sorted_by_key(self, seq: Optional[np.ndarray] = None) -> "RowGroup":
        return self.take(self.key_sort_permutation(seq=seq))

    def _sortable(self, name: str) -> np.ndarray:
        arr = self.columns[name]
        if isinstance(arr, DictColumn):
            return arr.sort_ranks()
        return arr

    def to_arrow(self) -> pa.RecordBatch:
        arrays = []
        fields = []
        for col in self.schema.columns:
            f = col.to_arrow_field()
            data = self.columns[col.name]
            mask = self.validity.get(col.name)
            np_mask = None if mask is None else ~mask
            if isinstance(data, DictColumn):
                # non-dictionary fields (e.g. a hinted float column frozen
                # dictionary-coded) keep the FIELD's value type
                arr = pa.DictionaryArray.from_arrays(
                    pa.array(data.codes, type=pa.int32(), mask=np_mask),
                    pa.array(list(data.values), type=f.type.value_type
                             if pa.types.is_dictionary(f.type) else f.type),
                )
                if not pa.types.is_dictionary(f.type):
                    arr = arr.cast(f.type)
            elif pa.types.is_dictionary(f.type):
                arr = pa.array(
                    [None if (np_mask is not None and np_mask[i]) else data[i] for i in range(self._n)]
                    if np_mask is not None
                    else list(data),
                    type=f.type.value_type,
                ).dictionary_encode()
            elif col.kind is DatumKind.TIMESTAMP:
                arr = pa.array(data, type=pa.int64(), mask=np_mask).cast(f.type)
            elif data.dtype == object:
                arr = pa.array(list(data), type=f.type, mask=np_mask)
            else:
                arr = pa.array(data, type=f.type, mask=np_mask)
            arrays.append(arr)
            fields.append(f)
        return pa.RecordBatch.from_arrays(arrays, schema=pa.schema(fields))

    def to_pylist(self) -> list[dict[str, Any]]:
        out = []
        decoded = {
            name: as_values(col) for name, col in self.columns.items()
        }
        for i in range(self._n):
            row = {}
            for col in self.schema.columns:
                if not self.valid_mask(col.name)[i]:
                    row[col.name] = None
                else:
                    v = decoded[col.name][i]
                    row[col.name] = v.item() if isinstance(v, np.generic) else v
            out.append(row)
        return out
