"""Table schema (ref: src/common_types/src/schema.rs).

Model (same as the reference's):

- every table has exactly one TIMESTAMP KEY column;
- columns marked TAG form the series identity; a ``tsid`` uint64 column is
  auto-generated (hash of the tag values) when the user doesn't spell out a
  primary key, and the default primary key is ``(tsid, timestamp)``
  (ref: schema.rs:226,638-722);
- everything else is a field column.

TPU-first difference: tag columns are *dictionary encoded* at ingest time
(string -> int32 code) so that series identity and group-by keys are dense
integers on device; the string dictionary only exists at the edges
(SST metadata, query results).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Iterable, Optional, Sequence

import numpy as np
import pyarrow as pa

from .datum import DatumKind

TSID_COLUMN = "tsid"


@dataclass(frozen=True, slots=True)
class ColumnSchema:
    name: str
    kind: DatumKind
    is_nullable: bool = True
    is_tag: bool = False
    is_dictionary: bool = False
    comment: str = ""
    default_value: Optional[Any] = None

    def to_arrow_field(self) -> pa.Field:
        t = self.kind.arrow_type
        if self.is_tag and self.kind is DatumKind.STRING:
            t = pa.dictionary(pa.int32(), pa.string())
        meta = {}
        if self.is_tag:
            meta[b"horaedb_tpu::tag"] = b"1"
        return pa.field(self.name, t, nullable=self.is_nullable, metadata=meta or None)


class Schema:
    """Immutable table schema with key/tag bookkeeping.

    ``columns`` always start with the primary-key columns:
    ``[tsid, timestamp, ...tags..., ...fields...]`` in the auto-tsid layout.
    """

    def __init__(
        self,
        columns: Sequence[ColumnSchema],
        timestamp_index: int,
        primary_key_indexes: Sequence[int],
        version: int = 1,
    ) -> None:
        names = [c.name for c in columns]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate column names in schema: {names}")
        if not (0 <= timestamp_index < len(columns)):
            raise ValueError("timestamp_index out of range")
        if columns[timestamp_index].kind is not DatumKind.TIMESTAMP:
            raise ValueError("timestamp column must be TIMESTAMP kind")
        for i in primary_key_indexes:
            if not columns[i].kind.is_key_kind:
                raise ValueError(
                    f"column {columns[i].name} ({columns[i].kind}) cannot be a key"
                )
        self.columns: tuple[ColumnSchema, ...] = tuple(columns)
        self.timestamp_index = timestamp_index
        self.primary_key_indexes: tuple[int, ...] = tuple(primary_key_indexes)
        self.version = version
        self._index = {c.name: i for i, c in enumerate(self.columns)}

    # ---- construction --------------------------------------------------
    @staticmethod
    def build(
        columns: Sequence[ColumnSchema],
        timestamp_column: str,
        primary_key: Sequence[str] | None = None,
        version: int = 1,
    ) -> "Schema":
        """Build a schema the way CREATE TABLE does.

        With no explicit primary key, prepends an auto-generated ``tsid``
        column and uses ``(tsid, timestamp)`` (ref: schema.rs enable_tsid
        path). Tag string columns get dictionary encoding.
        """
        cols = [
            ColumnSchema(
                name=c.name,
                kind=c.kind,
                is_nullable=c.is_nullable and c.name != timestamp_column and not c.is_tag,
                is_tag=c.is_tag,
                is_dictionary=c.is_tag and c.kind is DatumKind.STRING,
                comment=c.comment,
                default_value=c.default_value,
            )
            for c in columns
        ]
        names = [c.name for c in cols]
        if timestamp_column not in names:
            raise ValueError(f"timestamp column {timestamp_column!r} not defined")
        if primary_key is None:
            if TSID_COLUMN in names:
                raise ValueError("tsid is a reserved column name")
            cols.insert(
                0,
                ColumnSchema(TSID_COLUMN, DatumKind.UINT64, is_nullable=False),
            )
            # tsid first, then timestamp right after (canonical key order).
            ts_i = [c.name for c in cols].index(timestamp_column)
            if ts_i != 1:
                ts_col = cols.pop(ts_i)
                cols.insert(1, ts_col)
            pk_idx = (0, 1)
        else:
            for k in primary_key:
                if k not in names:
                    raise ValueError(f"primary key column {k!r} not defined")
            pk_idx = tuple([c.name for c in cols].index(k) for k in primary_key)
        ts_index = [c.name for c in cols].index(timestamp_column)
        return Schema(cols, ts_index, pk_idx, version=version)

    # ---- lookups -------------------------------------------------------
    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def timestamp_name(self) -> str:
        return self.columns[self.timestamp_index].name

    @property
    def tsid_index(self) -> Optional[int]:
        return self._index.get(TSID_COLUMN)

    @property
    def tag_indexes(self) -> tuple[int, ...]:
        return tuple(i for i, c in enumerate(self.columns) if c.is_tag)

    @property
    def tag_names(self) -> tuple[str, ...]:
        return tuple(c.name for c in self.columns if c.is_tag)

    @property
    def field_indexes(self) -> tuple[int, ...]:
        """Non-key, non-tag, non-timestamp columns (the measured values)."""
        skip = set(self.primary_key_indexes) | set(self.tag_indexes)
        skip.add(self.timestamp_index)
        return tuple(i for i in range(len(self.columns)) if i not in skip)

    def index_of(self, name: str) -> int:
        try:
            return self._index[name]
        except KeyError:
            raise KeyError(f"no such column: {name!r}") from None

    def column(self, name: str) -> ColumnSchema:
        return self.columns[self.index_of(name)]

    def has_column(self, name: str) -> bool:
        return name in self._index

    def names(self) -> list[str]:
        return [c.name for c in self.columns]

    def same_columns(self, other: "Schema") -> bool:
        """Layout-compatible: identical column tuple and timestamp slot.
        Two such schemas differ only in metadata (primary-key ORDER,
        version) — rows written against one decode under the other
        unchanged (the PK-sampler reorder relies on this)."""
        return (
            self.columns == other.columns
            and self.timestamp_index == other.timestamp_index
        )

    # ---- evolution -----------------------------------------------------
    def with_added_column(self, col: ColumnSchema) -> "Schema":
        """ALTER TABLE ADD COLUMN — appends a nullable field column."""
        if col.name in self._index:
            raise ValueError(f"column {col.name!r} already exists")
        if col.is_tag:
            raise ValueError("cannot add a tag column after table creation")
        return Schema(
            (*self.columns, col),
            self.timestamp_index,
            self.primary_key_indexes,
            version=self.version + 1,
        )

    # ---- interop -------------------------------------------------------
    def to_arrow(self) -> pa.Schema:
        return pa.schema([c.to_arrow_field() for c in self.columns])

    def to_dict(self) -> dict:
        return {
            "version": self.version,
            "timestamp_index": self.timestamp_index,
            "primary_key_indexes": list(self.primary_key_indexes),
            "columns": [
                {
                    "name": c.name,
                    "kind": c.kind.value,
                    "is_nullable": c.is_nullable,
                    "is_tag": c.is_tag,
                    "is_dictionary": c.is_dictionary,
                    "comment": c.comment,
                    "default_value": c.default_value,
                }
                for c in self.columns
            ],
        }

    @staticmethod
    def from_dict(d: dict) -> "Schema":
        cols = [
            ColumnSchema(
                name=c["name"],
                kind=DatumKind(c["kind"]),
                is_nullable=c["is_nullable"],
                is_tag=c["is_tag"],
                is_dictionary=c.get("is_dictionary", False),
                comment=c.get("comment", ""),
                default_value=c.get("default_value"),
            )
            for c in d["columns"]
        ]
        return Schema(
            cols,
            d["timestamp_index"],
            d["primary_key_indexes"],
            version=d["version"],
        )

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, Schema)
            and self.columns == other.columns
            and self.timestamp_index == other.timestamp_index
            and self.primary_key_indexes == other.primary_key_indexes
        )

    def __repr__(self) -> str:
        cols = ", ".join(
            f"{c.name}:{c.kind.value}{'[tag]' if c.is_tag else ''}" for c in self.columns
        )
        return f"Schema(v{self.version}, ts={self.timestamp_name}, [{cols}])"


def project_schema(schema: "Schema", projection: Sequence[str] | None) -> "Schema":
    """Sub-schema for a projected read.

    The timestamp and primary-key columns are always force-included: every
    storage read needs them for time filtering and merge ordering. Shared by
    the SST reader and the memtable/merge path so both sides of a scan agree
    on the projected layout.
    """
    if projection is None:
        return schema
    names = list(dict.fromkeys(projection))
    if schema.timestamp_name not in names:
        names.insert(0, schema.timestamp_name)
    for i in reversed(schema.primary_key_indexes):
        pk = schema.columns[i].name
        if pk not in names:
            names.insert(0, pk)
    cols = [schema.column(n) for n in names]
    ts_index = names.index(schema.timestamp_name)
    pk_indexes = tuple(
        names.index(schema.columns[i].name) for i in schema.primary_key_indexes
    )
    return Schema(cols, ts_index, pk_indexes, version=schema.version)


def compute_tsid(tag_arrays: Sequence[np.ndarray], num_rows: int | None = None) -> np.ndarray:
    """Vectorized series-id hash over tag value columns.

    The reference hashes tag bytes into a u64 ``tsid`` per row
    (schema.rs TSID). Values are CANONICALIZED before hashing so that the
    same logical value hashes identically whether it arrives as a typed
    numpy column (write path), an object array, or a bare Python literal
    (partition-rule locate path): strings -> utf-8, bytes -> raw, bools ->
    one byte, every integer kind -> 8-byte little-endian two's complement.
    Per-column hashes combine with a 64-bit FNV-style mix (order-sensitive,
    stable across processes).
    """
    from ..utils import native

    if not tag_arrays:
        # Tag-less table: every row is the same (only) series, id 0.
        return np.zeros(num_rows or 0, dtype=np.uint64)
    n = len(tag_arrays[0])
    out = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
    for arr in tag_arrays:
        native.fnv_mix(out, _column_hash(arr))
    return out


def _column_hash(arr) -> np.ndarray:
    """Raw per-row XXH64 of one column's canonical bytes.

    Dictionary columns hash the vocabulary once and gather through codes —
    identical results to hashing decoded values, O(|vocab|) work.
    """
    from ..utils import native
    from .dict_column import DictColumn

    if isinstance(arr, DictColumn):
        return _column_hash(arr.values)[arr.codes]
    n = len(arr)
    if arr.dtype == object:
        # Fast path: arrow encodes the whole column into ONE contiguous
        # utf-8 buffer + int64 offsets (C speed), which feeds the native
        # batch hasher directly — no per-value Python. Mixed-type or
        # null-bearing columns fall back to the canonical-bytes loop.
        import pyarrow as pa

        try:
            pa_arr = pa.array(arr, type=pa.large_string())
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError):
            pa_arr = None
        if pa_arr is not None and pa_arr.null_count == 0 and pa_arr.offset == 0:
            offsets = np.frombuffer(pa_arr.buffers()[1], dtype=np.int64)[: n + 1]
            data_buf = pa_arr.buffers()[2]
            data = (
                np.frombuffer(data_buf, dtype=np.uint8)
                if data_buf is not None
                else np.empty(0, dtype=np.uint8)
            )
            return native.hash_var(data, offsets)
        encoded = [_canonical_bytes(v) for v in arr]
        offsets = np.zeros(n + 1, dtype=np.int64)
        np.cumsum(np.fromiter((len(b) for b in encoded), np.int64, count=n), out=offsets[1:])
        return native.hash_var(b"".join(encoded), offsets)
    if arr.dtype == np.bool_:
        return native.hash_fixed(arr.astype(np.uint8))
    if np.issubdtype(arr.dtype, np.integer):
        canon = (
            arr if arr.dtype == np.uint64
            else arr.astype(np.int64, copy=False).view(np.uint64)
        )
        return native.hash_fixed(canon)
    return native.hash_fixed(arr)


def _canonical_bytes(v) -> bytes:
    """Type-canonical byte encoding — must agree with the typed-array
    branches of compute_tsid for every key kind."""
    if isinstance(v, str):
        return v.encode()
    if isinstance(v, bytes):
        return v
    if isinstance(v, (bool, np.bool_)):
        return b"\x01" if v else b"\x00"
    if isinstance(v, (int, np.integer)):
        return (int(v) & 0xFFFFFFFFFFFFFFFF).to_bytes(8, "little")
    return str(v).encode()
