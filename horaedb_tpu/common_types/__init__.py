"""Type & schema core.

TPU-native re-design of the reference's ``src/common_types`` crate
(schema.rs, datum.rs, row/, column_block.rs, time.rs): columnar-first
(numpy/Arrow blocks instead of row structs), with tag columns dictionary
encoded to int32 codes so group-by keys are device-friendly integers.
"""

from .datum import DatumKind, NUMPY_DTYPES, ARROW_TYPES
from .schema import ColumnSchema, Schema, TSID_COLUMN, compute_tsid
from .time_range import TimeRange, TimestampMs
from .row_group import RowGroup

__all__ = [
    "DatumKind",
    "NUMPY_DTYPES",
    "ARROW_TYPES",
    "ColumnSchema",
    "Schema",
    "TSID_COLUMN",
    "compute_tsid",
    "TimeRange",
    "TimestampMs",
    "RowGroup",
]
