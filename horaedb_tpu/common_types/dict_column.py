"""Dictionary-encoded columns: codes + vocabulary, no per-row objects.

The TPU-first answer to string tags (SURVEY §7 hard parts: "TPU kernels
need integer codes -> dictionary-encode tags and group by code"): a tag
column read from an SST stays as ``int32 codes + small value vocabulary``
all the way through scan -> filter -> group-by. Per-row Python strings
exist only at the API edges (INSERT literals, result row dicts).

Any comparison against a literal evaluates on the VOCABULARY (tiny) and
broadcasts through the codes — one vectorized small-op + one index gather
instead of a million string compares.
"""

from __future__ import annotations

from typing import Callable, Sequence

import numpy as np


class DictColumn:
    __slots__ = ("codes", "values")

    def __init__(self, codes: np.ndarray, values: np.ndarray) -> None:
        self.codes = codes  # int32 per row, indexes into values
        self.values = values  # object array, the vocabulary

    # ---- container protocol (what RowGroup needs) -----------------------
    def __len__(self) -> int:
        return len(self.codes)

    def __getitem__(self, idx):
        if isinstance(idx, (int, np.integer)):
            return self.values[self.codes[idx]]
        return DictColumn(self.codes[idx], self.values)

    @property
    def dtype(self):
        return np.dtype(object)

    @property
    def nbytes(self) -> int:
        return self.codes.nbytes + sum(len(str(v)) for v in self.values)

    # ---- conversions ----------------------------------------------------
    def decode(self) -> np.ndarray:
        """Materialize per-row values (the slow path — avoid in hot code)."""
        return self.values[self.codes]

    @staticmethod
    def encode(arr: np.ndarray) -> "DictColumn":
        values, codes = np.unique(arr, return_inverse=True)
        return DictColumn(codes.astype(np.int32), values)

    # ---- vectorized ops on the vocabulary -------------------------------
    def map_values(self, fn: Callable[[np.ndarray], np.ndarray]) -> np.ndarray:
        """Apply a vectorized fn to the vocabulary, gather through codes.

        ``fn(values) -> per-value result``; output is per-row. This is how
        every comparison/predicate over a dict column runs: O(|vocab|)
        compute + O(n) gather.
        """
        per_value = fn(self.values)
        return np.asarray(per_value)[self.codes]

    def sort_ranks(self) -> np.ndarray:
        """Per-row ranks that sort like the decoded values (for ORDER BY)."""
        order = np.argsort(self.values, kind="stable")
        ranks = np.empty(len(self.values), dtype=np.int64)
        ranks[order] = np.arange(len(self.values))
        return ranks[self.codes]

    def min_max(self, mask: np.ndarray | None = None):
        codes = self.codes if mask is None else self.codes[mask]
        if len(codes) == 0:
            return None, None
        used = np.unique(codes)
        vals = self.values[used]
        return min(vals), max(vals)


ColumnData = "np.ndarray | DictColumn"


def as_values(col) -> np.ndarray:
    """Object-array view of any column (decodes DictColumn)."""
    return col.decode() if isinstance(col, DictColumn) else col


def column_take(col, idx):
    return col[idx]


def unique_inverse(col) -> tuple[np.ndarray, np.ndarray]:
    """(unique values, per-row inverse codes) — int-speed for DictColumn."""
    if isinstance(col, DictColumn):
        used, inv = np.unique(col.codes, return_inverse=True)
        return col.values[used], inv
    return np.unique(col, return_inverse=True)


def concat_columns(parts: Sequence) -> "np.ndarray | DictColumn":
    """Concatenate plain and/or dictionary columns.

    If any part is dictionary-encoded the result is dictionary-encoded
    with a UNION vocabulary; code spaces are remapped vectorized.
    """
    if len(parts) == 1:
        return parts[0]
    if not any(isinstance(p, DictColumn) for p in parts):
        return np.concatenate(parts)
    vocabs = []
    for p in parts:
        if isinstance(p, DictColumn):
            vocabs.append(p.values)
        else:
            vocabs.append(np.unique(p))
    # Union vocabulary MUST be sorted: remapping uses searchsorted.
    union = np.unique(np.concatenate(vocabs))
    out_codes = []
    for p in parts:
        if isinstance(p, DictColumn):
            remap = np.searchsorted(union, p.values).astype(np.int32)
            out_codes.append(remap[p.codes])
        else:
            out_codes.append(np.searchsorted(union, p).astype(np.int32))
    return DictColumn(np.concatenate(out_codes), union)
