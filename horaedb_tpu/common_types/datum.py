"""Datum kinds — the scalar type system.

Mirrors the reference's ``DatumKind`` (src/common_types/src/datum.rs) but maps
every kind onto a numpy dtype + Arrow type so that column data lives in
contiguous buffers from ingest to device: there is no per-row boxed value in
the hot path (rows exist only at the API edge).
"""

from __future__ import annotations

import enum
from typing import Any

import numpy as np
import pyarrow as pa


class DatumKind(enum.Enum):
    NULL = "null"
    TIMESTAMP = "timestamp"  # int64 milliseconds since epoch
    DOUBLE = "double"
    FLOAT = "float"
    VARBINARY = "varbinary"
    STRING = "string"
    UINT64 = "uint64"
    UINT32 = "uint32"
    UINT16 = "uint16"
    UINT8 = "uint8"
    INT64 = "bigint"
    INT32 = "int"
    INT16 = "smallint"
    INT8 = "tinyint"
    BOOLEAN = "boolean"
    DATE = "date"  # int32 days since epoch
    TIME = "time"  # int64 nanos within day

    # ---- classification ------------------------------------------------
    @property
    def is_numeric(self) -> bool:
        return self in _NUMERIC

    @property
    def is_integer(self) -> bool:
        return self in _INTEGER

    @property
    def is_float(self) -> bool:
        return self in (DatumKind.DOUBLE, DatumKind.FLOAT)

    @property
    def is_key_kind(self) -> bool:
        """Kinds usable as a primary-key / tag component."""
        return self in _KEY_KINDS

    @property
    def numpy_dtype(self) -> np.dtype:
        return NUMPY_DTYPES[self]

    @property
    def arrow_type(self) -> pa.DataType:
        return ARROW_TYPES[self]

    # ---- parsing -------------------------------------------------------
    @classmethod
    def from_sql_type(cls, name: str) -> "DatumKind":
        """Parse a SQL type name (as used in CREATE TABLE) into a kind."""
        key = name.strip().lower()
        try:
            return _SQL_NAMES[key]
        except KeyError:
            raise ValueError(f"unknown SQL type: {name!r}") from None

    def default_value(self) -> Any:
        """Value used for padding / NULL slots in dense device buffers."""
        if self in (DatumKind.STRING, DatumKind.VARBINARY):
            return b"" if self is DatumKind.VARBINARY else ""
        if self is DatumKind.BOOLEAN:
            return False
        if self is DatumKind.NULL:
            return None
        return self.numpy_dtype.type(0)


_NUMERIC = {
    DatumKind.TIMESTAMP, DatumKind.DOUBLE, DatumKind.FLOAT,
    DatumKind.UINT64, DatumKind.UINT32, DatumKind.UINT16, DatumKind.UINT8,
    DatumKind.INT64, DatumKind.INT32, DatumKind.INT16, DatumKind.INT8,
    DatumKind.DATE, DatumKind.TIME,
}
_INTEGER = {
    DatumKind.TIMESTAMP,
    DatumKind.UINT64, DatumKind.UINT32, DatumKind.UINT16, DatumKind.UINT8,
    DatumKind.INT64, DatumKind.INT32, DatumKind.INT16, DatumKind.INT8,
    DatumKind.DATE, DatumKind.TIME,
}
# Same set the reference accepts for keys/tags (datum.rs is_key_kind):
_KEY_KINDS = {
    DatumKind.TIMESTAMP, DatumKind.STRING, DatumKind.VARBINARY,
    DatumKind.UINT64, DatumKind.UINT32, DatumKind.UINT16, DatumKind.UINT8,
    DatumKind.INT64, DatumKind.INT32, DatumKind.INT16, DatumKind.INT8,
    DatumKind.BOOLEAN, DatumKind.DATE, DatumKind.TIME,
}

NUMPY_DTYPES: dict[DatumKind, np.dtype] = {
    DatumKind.TIMESTAMP: np.dtype(np.int64),
    DatumKind.DOUBLE: np.dtype(np.float64),
    DatumKind.FLOAT: np.dtype(np.float32),
    DatumKind.VARBINARY: np.dtype(object),
    DatumKind.STRING: np.dtype(object),
    DatumKind.UINT64: np.dtype(np.uint64),
    DatumKind.UINT32: np.dtype(np.uint32),
    DatumKind.UINT16: np.dtype(np.uint16),
    DatumKind.UINT8: np.dtype(np.uint8),
    DatumKind.INT64: np.dtype(np.int64),
    DatumKind.INT32: np.dtype(np.int32),
    DatumKind.INT16: np.dtype(np.int16),
    DatumKind.INT8: np.dtype(np.int8),
    DatumKind.BOOLEAN: np.dtype(np.bool_),
    DatumKind.DATE: np.dtype(np.int32),
    DatumKind.TIME: np.dtype(np.int64),
}

ARROW_TYPES: dict[DatumKind, pa.DataType] = {
    DatumKind.NULL: pa.null(),
    DatumKind.TIMESTAMP: pa.timestamp("ms"),
    DatumKind.DOUBLE: pa.float64(),
    DatumKind.FLOAT: pa.float32(),
    DatumKind.VARBINARY: pa.binary(),
    DatumKind.STRING: pa.string(),
    DatumKind.UINT64: pa.uint64(),
    DatumKind.UINT32: pa.uint32(),
    DatumKind.UINT16: pa.uint16(),
    DatumKind.UINT8: pa.uint8(),
    DatumKind.INT64: pa.int64(),
    DatumKind.INT32: pa.int32(),
    DatumKind.INT16: pa.int16(),
    DatumKind.INT8: pa.int8(),
    DatumKind.BOOLEAN: pa.bool_(),
    DatumKind.DATE: pa.date32(),
    DatumKind.TIME: pa.time64("ns"),
}

_SQL_NAMES: dict[str, DatumKind] = {
    "timestamp": DatumKind.TIMESTAMP,
    "double": DatumKind.DOUBLE,
    "float": DatumKind.FLOAT,
    "real": DatumKind.FLOAT,
    "varbinary": DatumKind.VARBINARY,
    "string": DatumKind.STRING,
    "varchar": DatumKind.STRING,
    "text": DatumKind.STRING,
    "uint64": DatumKind.UINT64,
    "uint32": DatumKind.UINT32,
    "uint16": DatumKind.UINT16,
    "uint8": DatumKind.UINT8,
    "bigint": DatumKind.INT64,
    "int64": DatumKind.INT64,
    "int": DatumKind.INT32,
    "int32": DatumKind.INT32,
    "integer": DatumKind.INT32,
    "smallint": DatumKind.INT16,
    "int16": DatumKind.INT16,
    "tinyint": DatumKind.INT8,
    "int8": DatumKind.INT8,
    "boolean": DatumKind.BOOLEAN,
    "bool": DatumKind.BOOLEAN,
    "date": DatumKind.DATE,
    "time": DatumKind.TIME,
}


def arrow_to_kind(t: pa.DataType) -> DatumKind:
    for kind, at in ARROW_TYPES.items():
        if at == t:
            return kind
    # Dictionary-encoded string columns round-trip to STRING.
    if pa.types.is_dictionary(t) and pa.types.is_string(t.value_type):
        return DatumKind.STRING
    if pa.types.is_timestamp(t):
        return DatumKind.TIMESTAMP
    raise ValueError(f"unsupported arrow type: {t}")
