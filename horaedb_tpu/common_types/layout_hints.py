"""Cross-layer column-layout hints (ISSUE 19, the PR-6 remainder).

The scan cache's layout tuner learns, per (table, column), that a value
column is low-cardinality enough to dictionary-encode. That knowledge is
useful BELOW the cache too: if the memtable freezes such a column as a
DictColumn, every downstream consumer — freeze concat, SST write, the
cache build's host read — moves codes instead of repeated values, and
the column arrives at the cache already in the layout the tuner would
pick.

This module is the (deliberately tiny) channel: a bounded process-global
map written by the cache at encode time and read by the memtable at
freeze time. Hints are advisory — a column that stopped being
low-cardinality simply fails the next dictionary attempt and freezes
dense; nothing downstream may *require* a hint to hold.

Lives in common_types because both engine.memtable and query.scan_cache
import it (either direction between those two would cycle).
"""

from __future__ import annotations

import threading

_lock = threading.Lock()
# (table, column) -> last observed dictionary cardinality; dict order is
# recency (LRU-style bound like ScanCache._usage)
_hints: dict[tuple[str, str], int] = {}
_MAX_HINTS = 4096


def note_low_cardinality(table: str, column: str, cardinality: int) -> None:
    """Record that ``table.column`` dictionary-encoded at ``cardinality``
    distinct values (called by the cache's layout tuner on encode)."""
    key = (table, column)
    with _lock:
        _hints.pop(key, None)
        if len(_hints) >= _MAX_HINTS:
            _hints.pop(next(iter(_hints)))
        _hints[key] = int(cardinality)


def low_cardinality_hint(table: str, column: str) -> int:
    """Last observed dictionary cardinality for ``table.column``, or 0
    when the tuner has never dictionary-encoded it."""
    with _lock:
        return _hints.get((table, column), 0)


def clear_hints() -> None:
    """Test isolation helper."""
    with _lock:
        _hints.clear()
