"""Timestamps and time ranges (ref: src/common_types/src/time.rs).

Timestamps are int64 milliseconds since the Unix epoch throughout the
framework. A ``TimeRange`` is half-open ``[inclusive_start, exclusive_end)``,
exactly like the reference's ``TimeRange`` — range math here must agree with
SST pruning and segment bucketing everywhere else.
"""

from __future__ import annotations

from dataclasses import dataclass

TimestampMs = int

MIN_TIMESTAMP: TimestampMs = -(2**63)
MAX_TIMESTAMP: TimestampMs = 2**63 - 1


@dataclass(frozen=True, slots=True)
class TimeRange:
    inclusive_start: TimestampMs
    exclusive_end: TimestampMs

    def __post_init__(self) -> None:
        if self.exclusive_end < self.inclusive_start:
            raise ValueError(
                f"invalid TimeRange [{self.inclusive_start}, {self.exclusive_end})"
            )

    # ---- constructors --------------------------------------------------
    @staticmethod
    def min_to_max() -> "TimeRange":
        return TimeRange(MIN_TIMESTAMP, MAX_TIMESTAMP)

    @staticmethod
    def empty() -> "TimeRange":
        return TimeRange(0, 0)

    @staticmethod
    def bucket_of(ts: TimestampMs, bucket_ms: int) -> "TimeRange":
        """The aligned bucket of width ``bucket_ms`` containing ``ts``.

        Floor-division alignment (correct for negative timestamps too) — the
        same alignment flush uses to split memtable rows into time-bucketed
        SSTs (ref: instance/flush_compaction.rs preprocess_flush).
        """
        start = (ts // bucket_ms) * bucket_ms
        return TimeRange(start, start + bucket_ms)

    # ---- predicates ----------------------------------------------------
    def is_empty(self) -> bool:
        return self.exclusive_end <= self.inclusive_start

    def contains(self, ts: TimestampMs) -> bool:
        return self.inclusive_start <= ts < self.exclusive_end

    def overlaps(self, other: "TimeRange") -> bool:
        return (
            self.inclusive_start < other.exclusive_end
            and other.inclusive_start < self.exclusive_end
        )

    def covers(self, other: "TimeRange") -> bool:
        return (
            self.inclusive_start <= other.inclusive_start
            and other.exclusive_end <= self.exclusive_end
        )

    # ---- combinators ---------------------------------------------------
    def intersect(self, other: "TimeRange") -> "TimeRange":
        start = max(self.inclusive_start, other.inclusive_start)
        end = min(self.exclusive_end, other.exclusive_end)
        return TimeRange(start, end) if start < end else TimeRange.empty()

    def union_merge(self, other: "TimeRange") -> "TimeRange":
        """Smallest range covering both (used for SST meta aggregation)."""
        if self.is_empty():
            return other
        if other.is_empty():
            return self
        return TimeRange(
            min(self.inclusive_start, other.inclusive_start),
            max(self.exclusive_end, other.exclusive_end),
        )

    def buckets(self, bucket_ms: int) -> list["TimeRange"]:
        """Aligned buckets of width ``bucket_ms`` overlapping this range."""
        if self.is_empty():
            return []
        out = []
        cur = (self.inclusive_start // bucket_ms) * bucket_ms
        while cur < self.exclusive_end:
            out.append(TimeRange(cur, cur + bucket_ms))
            cur += bucket_ms
        return out
