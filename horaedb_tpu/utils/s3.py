"""S3-compatible object store backend
(ref: components/object_store/src/{s3.rs,multipart.rs} — the reference's
cloud backends via the Rust object_store crate; this is a from-scratch
AWS Signature V4 client over urllib, so any S3-compatible service (AWS,
MinIO, OSS S3 gateway) works with zero extra dependencies).

Supports: GET (+ Range), PUT, HEAD, DELETE, ListObjectsV2 with
continuation, and multipart upload above a size threshold (multipart.rs
analog — SSTs larger than one part stream up in chunks).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import urllib.error
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET
from typing import Iterator, Optional

from .object_store import ObjectStore

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _sign(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    url: str,
    region: str,
    access_key: str,
    secret_key: str,
    payload_sha256: str,
    amz_date: Optional[str] = None,
    extra_headers: Optional[dict] = None,
) -> dict:
    """AWS Signature Version 4 headers for one request (public algorithm).

    Exposed as a function (not a method) so the test fake can RE-COMPUTE
    the expected signature — the round trip proves the signing, not just
    the plumbing."""
    parsed = urllib.parse.urlsplit(url)
    if amz_date is None:
        amz_date = datetime.datetime.now(datetime.timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    host = parsed.netloc
    headers = {"host": host, "x-amz-content-sha256": payload_sha256, "x-amz-date": amz_date}
    if extra_headers:
        headers.update({k.lower(): v for k, v in extra_headers.items()})
    signed_names = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    # canonical query: sorted by key, values URI-encoded
    q = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='')}={urllib.parse.quote(v, safe='')}"
        for k, v in sorted(q)
    )
    canonical = "\n".join(
        [
            method,
            # S3 canonical URI = the (already percent-encoded) request
            # path used ONCE — re-quoting would double-encode '%20' etc.
            # and real services would reject the signature.
            parsed.path or "/",
            canonical_query,
            canonical_headers,
            signed_names,
            payload_sha256,
        ]
    )
    scope = f"{date}/{region}/s3/aws4_request"
    to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical.encode()).hexdigest(),
        ]
    )
    k = _sign(("AWS4" + secret_key).encode(), date)
    k = _sign(k, region)
    k = _sign(k, "s3")
    k = _sign(k, "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    return out


class S3Error(IOError):
    pass


class S3Store(ObjectStore):
    def __init__(
        self,
        bucket: str,
        endpoint: str,  # e.g. "http://127.0.0.1:9000" or "https://s3.us-east-1.amazonaws.com"
        access_key: str,
        secret_key: str,
        region: str = "us-east-1",
        prefix: str = "",
        multipart_threshold: int = 64 << 20,
        multipart_part_size: int = 16 << 20,
        timeout_s: float = 30.0,
    ) -> None:
        self.bucket = bucket
        self.endpoint = endpoint.rstrip("/")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region
        self.prefix = prefix.strip("/")
        self.multipart_threshold = multipart_threshold
        self.multipart_part_size = multipart_part_size
        self.timeout_s = timeout_s

    # ---- plumbing --------------------------------------------------------
    def _key(self, path: str) -> str:
        return f"{self.prefix}/{path}" if self.prefix else path

    def _url(self, key: str, query: str = "") -> str:
        q = f"?{query}" if query else ""
        return f"{self.endpoint}/{self.bucket}/{urllib.parse.quote(key)}{q}"

    def _request(
        self,
        method: str,
        url: str,
        body: bytes = b"",
        extra_headers: Optional[dict] = None,
    ):
        payload_hash = hashlib.sha256(body).hexdigest() if body else _EMPTY_SHA256
        headers = sigv4_headers(
            method, url, self.region, self.access_key, self.secret_key,
            payload_hash, extra_headers=extra_headers,
        )
        req = urllib.request.Request(url, data=body or None, headers=headers, method=method)
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    # ---- ObjectStore -----------------------------------------------------
    def put(self, path: str, data: bytes) -> None:
        key = self._key(path)
        if len(data) > self.multipart_threshold:
            self._multipart_put(key, data)
            return
        with self._request("PUT", self._url(key), body=data):
            pass

    def _multipart_put(self, key: str, data: bytes) -> None:
        """Multipart upload (ref: multipart.rs) — big SSTs go up in parts."""
        with self._request("POST", self._url(key, "uploads=")) as r:
            upload_id = ET.fromstring(r.read()).findtext(
                "{*}UploadId"
            ) or ""
        if not upload_id:
            raise S3Error(f"multipart initiate failed for {key}")
        etags = []
        try:
            part = 1
            for off in range(0, len(data), self.multipart_part_size):
                chunk = data[off : off + self.multipart_part_size]
                q = f"partNumber={part}&uploadId={urllib.parse.quote(upload_id)}"
                with self._request("PUT", self._url(key, q), body=chunk) as r:
                    etags.append((part, r.headers.get("ETag", "")))
                part += 1
            parts_xml = "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
                for n, e in etags
            )
            body = f"<CompleteMultipartUpload>{parts_xml}</CompleteMultipartUpload>".encode()
            q = f"uploadId={urllib.parse.quote(upload_id)}"
            with self._request("POST", self._url(key, q), body=body):
                pass
        except Exception:
            try:
                q = f"uploadId={urllib.parse.quote(upload_id)}"
                with self._request("DELETE", self._url(key, q)):
                    pass
            except Exception:
                pass
            raise

    def get(self, path: str) -> bytes:
        try:
            with self._request("GET", self._url(self._key(path))) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(path) from None
            raise S3Error(f"GET {path}: {e}") from None

    def get_range(self, path: str, start: int, end: int) -> bytes:
        try:
            with self._request(
                "GET",
                self._url(self._key(path)),
                extra_headers={"range": f"bytes={start}-{end - 1}"},
            ) as r:
                return r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(path) from None
            raise S3Error(f"GET range {path}: {e}") from None

    def head(self, path: str) -> int:
        try:
            with self._request("HEAD", self._url(self._key(path))) as r:
                return int(r.headers.get("Content-Length", 0))
        except urllib.error.HTTPError as e:
            if e.code == 404:
                raise FileNotFoundError(path) from None
            raise S3Error(f"HEAD {path}: {e}") from None

    def delete(self, path: str) -> None:
        try:
            with self._request("DELETE", self._url(self._key(path))):
                pass
        except urllib.error.HTTPError as e:
            if e.code != 404:
                raise S3Error(f"DELETE {path}: {e}") from None

    def list(self, prefix: str = "") -> Iterator[str]:
        full_prefix = self._key(prefix)
        token: Optional[str] = None
        out = []
        while True:
            q = "list-type=2&prefix=" + urllib.parse.quote(full_prefix, safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token, safe="")
            url = f"{self.endpoint}/{self.bucket}?{q}"
            try:
                with self._request("GET", url) as r:
                    root = ET.fromstring(r.read())
            except urllib.error.HTTPError as e:
                raise S3Error(f"LIST {prefix}: {e}") from None
            for c in root.findall("{*}Contents"):
                key = c.findtext("{*}Key") or ""
                if self.prefix and key.startswith(self.prefix + "/"):
                    key = key[len(self.prefix) + 1 :]
                out.append(key)
            if (root.findtext("{*}IsTruncated") or "").lower() == "true":
                token = root.findtext("{*}NextContinuationToken")
                if not token:
                    break
            else:
                break
        return iter(sorted(out))
