"""Object store abstraction (ref: src/components/object_store).

The reference re-exports the Rust ``object_store`` crate and layers caches on
top (mem_cache.rs, disk_cache.rs). Here the trait is a small ABC with the
operations the engine actually needs — whole/range reads, atomic-ish puts,
listing, delete — with three impls:

- ``MemoryStore``      — tests / ephemeral
- ``LocalDiskStore``   — standalone deployments (write-to-temp + rename)
- ``MemCacheStore``    — sharded-LRU read-through page cache wrapper
                         (ref: mem_cache.rs partitioned LRU)

S3/OSS-style remote backends slot in behind the same ABC in a later round
(zero-egress image: nothing to talk to here).
"""

from __future__ import annotations

import os
import threading
from abc import ABC, abstractmethod
from collections import OrderedDict
from typing import Iterator, Optional, Sequence


class ObjectStore(ABC):
    @abstractmethod
    def put(self, path: str, data: bytes) -> None: ...

    @abstractmethod
    def get(self, path: str) -> bytes: ...

    @abstractmethod
    def get_range(self, path: str, start: int, end: int) -> bytes:
        """Bytes in [start, end) — the SST reader's footer/page reads."""

    @abstractmethod
    def head(self, path: str) -> int:
        """Size in bytes; raises FileNotFoundError if absent."""

    @abstractmethod
    def delete(self, path: str) -> None: ...

    @abstractmethod
    def list(self, prefix: str = "") -> Iterator[str]: ...

    def prefetch(self, paths: Sequence[str]) -> None:
        """Hint: these objects will be read soon — start pulling them into
        whatever cache this store has, in the background, without blocking
        the caller. Default: no cache, nothing to do (the prefetchable-
        stream analog, ref: analytic_engine/src/prefetchable_stream.rs +
        num_streams_to_prefetch, lib.rs:109)."""

    def exists(self, path: str) -> bool:
        try:
            self.head(path)
            return True
        except FileNotFoundError:
            return False


class MemoryStore(ObjectStore):
    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, path: str, data: bytes) -> None:
        with self._lock:
            self._objects[path] = bytes(data)

    def get(self, path: str) -> bytes:
        try:
            return self._objects[path]
        except KeyError:
            raise FileNotFoundError(path) from None

    def get_range(self, path: str, start: int, end: int) -> bytes:
        return self.get(path)[start:end]

    def head(self, path: str) -> int:
        return len(self.get(path))

    def delete(self, path: str) -> None:
        with self._lock:
            self._objects.pop(path, None)

    def list(self, prefix: str = "") -> Iterator[str]:
        with self._lock:
            keys = sorted(self._objects)
        return iter([k for k in keys if k.startswith(prefix)])


class LocalDiskStore(ObjectStore):
    """Filesystem-backed store; puts are atomic via temp-file + rename."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path))
        if not p.startswith(self.root):
            raise ValueError(f"path escapes store root: {path!r}")
        return p

    def put(self, path: str, data: bytes) -> None:
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def get(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def get_range(self, path: str, start: int, end: int) -> bytes:
        with open(self._abs(path), "rb") as f:
            f.seek(start)
            return f.read(end - start)

    def head(self, path: str) -> int:
        return os.path.getsize(self._abs(path))

    def delete(self, path: str) -> None:
        try:
            os.remove(self._abs(path))
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> Iterator[str]:
        # Start the walk at the deepest directory the prefix pins down —
        # a per-table prefix must not traverse the whole store.
        base_rel = prefix if prefix.endswith("/") else os.path.dirname(prefix)
        start = os.path.join(self.root, base_rel.rstrip("/")) if base_rel else self.root
        if not os.path.isdir(start):
            return iter([])
        out = []
        for dirpath, _dirs, files in os.walk(start):
            for name in files:
                if name.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return iter(sorted(out))

    def local_path(self, path: str) -> str:
        """Direct filesystem path — lets pyarrow mmap SSTs instead of
        round-tripping bytes through Python."""
        return self._abs(path)


class DiskCacheStore(ObjectStore):
    """Paged on-disk read cache over a (remote) store
    (ref: components/object_store/src/disk_cache.rs — page-granular
    caching with CRC integrity, LRU eviction, and request dedup so a cold
    page is fetched once even under concurrent readers).

    ``get_range`` reads fetch whole aligned PAGES from the inner store and
    serve slices from disk afterwards; ``get`` caches the whole object as
    its pages. Each cache file is ``[u32 crc][payload]`` — a torn or
    corrupted page re-fetches instead of serving garbage.
    """

    def __init__(
        self,
        inner: ObjectStore,
        cache_dir: str,
        capacity_bytes: int = 1 << 30,
        page_size: int = 1 << 20,
    ) -> None:
        import zlib

        self._zlib = zlib
        self.inner = inner
        self.cache_dir = os.path.abspath(cache_dir)
        os.makedirs(self.cache_dir, exist_ok=True)
        self.page_size = page_size
        self.capacity_bytes = capacity_bytes
        self._lock = threading.Lock()
        self._lru: "OrderedDict[str, int]" = OrderedDict()  # cache file -> bytes
        self._bytes = 0
        self._inflight: dict[str, threading.Event] = {}
        # object sizes cached too: a warm read must not pay a remote HEAD
        self._sizes: dict[str, int] = {}
        # lazy pools: most stores never see a cold multi-page read
        self._pool = None
        self._bg_pool = None
        self.hits = 0
        self.misses = 0
        # /metrics visibility: prefetch effectiveness is invisible from
        # timings alone (a useless prefetch just wastes inner-store IO).
        from .metrics import REGISTRY

        self._m_hits = REGISTRY.counter(
            "horaedb_object_store_page_cache_hits_total",
            "disk page cache hits (all DiskCacheStore instances)",
        )
        self._m_misses = REGISTRY.counter(
            "horaedb_object_store_page_cache_misses_total",
            "disk page cache misses (cold fetches from the inner store)",
        )
        self._m_prefetch = REGISTRY.counter(
            "horaedb_object_store_prefetch_objects_total",
            "objects queued for background prefetch",
        )
        self._load_index()

    # ---- index -----------------------------------------------------------
    def _load_index(self) -> None:
        for name in sorted(os.listdir(self.cache_dir)):
            p = os.path.join(self.cache_dir, name)
            if name.endswith(".tmp"):
                # torn write from a crash mid-_write_cached: reclaim now
                try:
                    os.remove(p)
                except FileNotFoundError:
                    pass
                continue
            if os.path.isfile(p):
                size = os.path.getsize(p)
                self._lru[name] = size
                self._bytes += size

    def _cache_name(self, path: str, page: int) -> str:
        import hashlib

        digest = hashlib.sha256(path.encode()).hexdigest()[:24]
        return f"{digest}.{page:06d}"

    # ---- page IO ---------------------------------------------------------
    def _read_cached(self, name: str) -> Optional[bytes]:
        p = os.path.join(self.cache_dir, name)
        try:
            with open(p, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return None
        if len(raw) < 4:
            return None
        crc = int.from_bytes(raw[:4], "little")
        payload = raw[4:]
        if self._zlib.crc32(payload) & 0xFFFFFFFF != crc:
            # torn/corrupt page: drop it, caller re-fetches
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
            with self._lock:
                size = self._lru.pop(name, 0)
                self._bytes -= size
            return None
        with self._lock:
            if name in self._lru:
                self._lru.move_to_end(name)
        return payload

    def _write_cached(self, name: str, payload: bytes) -> None:
        p = os.path.join(self.cache_dir, name)
        tmp = p + ".tmp"
        crc = (self._zlib.crc32(payload) & 0xFFFFFFFF).to_bytes(4, "little")
        with open(tmp, "wb") as f:
            f.write(crc + payload)
        os.replace(tmp, p)
        size = len(payload) + 4
        evict = []
        with self._lock:
            self._lru[name] = size
            self._lru.move_to_end(name)
            self._bytes += size
            while self._bytes > self.capacity_bytes and len(self._lru) > 1:
                evicted, esize = self._lru.popitem(last=False)
                self._bytes -= esize
                evict.append(evicted)
        for name_ in evict:
            try:
                os.remove(os.path.join(self.cache_dir, name_))
            except FileNotFoundError:
                pass

    def _fetch_page(self, path: str, page: int, obj_size: int) -> bytes:
        """One page, cached; concurrent requests for a cold page dedup.

        Followers wait on the current leader's event and retry the cache;
        a follower whose leader failed loops back and may become the NEXT
        leader — it never touches an event it didn't register."""
        name = self._cache_name(path, page)
        while True:
            cached = self._read_cached(name)
            if cached is not None:
                self.hits += 1
                self._m_hits.inc()
                return cached
            with self._lock:
                ev = self._inflight.get(name)
                if ev is None:
                    my_event = threading.Event()
                    self._inflight[name] = my_event
                    break  # we are the leader
            # follower wait caps at min(op_cap, remaining budget): a
            # query out of time observes it at the next checkpoint
            # instead of riding a slow leader fetch to the 60s bound
            from .deadline import cap_timeout, checkpoint

            ev.wait(timeout=cap_timeout(60))
            checkpoint("store")
        try:
            # Double-check as leader: our first cache miss may predate a
            # previous leader's write (we raced past its event) — a
            # redundant remote fetch is wasted inner-store traffic.
            cached = self._read_cached(name)
            if cached is not None:
                self.hits += 1
                self._m_hits.inc()
                return cached
            self.misses += 1
            self._m_misses.inc()
            start = page * self.page_size
            end = min(start + self.page_size, obj_size)
            payload = self.inner.get_range(path, start, end)
            self._write_cached(name, payload)
            return payload
        finally:
            with self._lock:
                if self._inflight.get(name) is my_event:
                    del self._inflight[name]
            my_event.set()

    # ---- ObjectStore -----------------------------------------------------
    def _fetch_pool(self, background: bool = False):
        """Store-OWNED pools for cold-page fan-out. Deliberately not the
        shared io_pool: get_range is often called FROM io_pool tasks
        (scan_sources overlaps SST reads there), and a bounded pool whose
        tasks submit to itself and wait deadlocks. Nothing running on
        these pools ever re-enters them — page fetches call
        ``inner.get_range`` directly.

        TWO pools, not one: prefetch() queues whole-object pulls on the
        BACKGROUND pool only, so a foreground read's cold pages never
        wait behind the hint backlog (the priority inversion a shared
        FIFO queue would reintroduce). The inflight leader/follower
        protocol dedups fetches across both pools."""
        with self._lock:
            if self._pool is None:
                from concurrent.futures import ThreadPoolExecutor

                from .env import env_int

                n = env_int("HORAEDB_CACHE_FETCH_THREADS", 8)
                self._pool = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix="diskcache-fetch",
                )
                self._bg_pool = ThreadPoolExecutor(
                    max_workers=max(1, n // 2),
                    thread_name_prefix="diskcache-prefetch",
                )
            return self._bg_pool if background else self._pool

    def get_range(self, path: str, start: int, end: int) -> bytes:
        size = self.head(path)
        end = min(end, size)
        if start >= end:
            return b""
        first, last = start // self.page_size, (end - 1) // self.page_size
        pages = range(first, last + 1)
        # Warm pages are served INLINE from disk — never through the
        # fetch pool, whose FIFO queue may hold a backlog of whole-object
        # prefetch pulls that a foreground read must not wait behind.
        byp: dict[int, bytes] = {}
        cold: list[int] = []
        for pg in pages:
            cached = self._read_cached(self._cache_name(path, pg))
            if cached is not None:
                self.hits += 1
                self._m_hits.inc()
                byp[pg] = cached
            else:
                cold.append(pg)
        if len(cold) > 1:
            # Cold pages fan out: a 64MB object at 1MB pages would
            # otherwise serialize 64 round trips to the inner store
            # (first-read prefetch pipeline); the inflight leader/follower
            # protocol dedups against concurrent readers and prefetchers.
            for pg, payload in zip(
                cold,
                self._fetch_pool().map(
                    lambda p: self._fetch_page(path, p, size), cold
                ),
            ):
                byp[pg] = payload
        else:
            for pg in cold:
                byp[pg] = self._fetch_page(path, pg, size)
        blob = b"".join(byp[pg] for pg in pages)
        base = first * self.page_size
        return blob[start - base : end - base]

    def get(self, path: str) -> bytes:
        return self.get_range(path, 0, self.head(path))

    def prefetch(self, paths: Sequence[str]) -> None:
        """Queue background whole-object pulls into the page cache; the
        decode loop that follows finds pages warm instead of paying one
        round trip per page. Bounded by the fetch pool's worker count and
        the cache's LRU capacity; failures are swallowed (a prefetch is a
        hint, the read path re-fetches on miss)."""

        def pull(path: str) -> None:
            try:
                size = self.head(path)
                for page in range((size + self.page_size - 1) // self.page_size):
                    self._fetch_page(path, page, size)
            except Exception:
                pass

        self._m_prefetch.inc(len(paths))
        for p in paths:
            self._fetch_pool(background=True).submit(pull, p)

    def head(self, path: str) -> int:
        with self._lock:
            size = self._sizes.get(path)
        if size is not None:
            return size
        size = self.inner.head(path)
        with self._lock:
            self._sizes[path] = size
        return size

    def put(self, path: str, data: bytes) -> None:
        self.inner.put(path, data)
        self._invalidate(path)

    def delete(self, path: str) -> None:
        self.inner.delete(path)
        self._invalidate(path)

    def _invalidate(self, path: str) -> None:
        import hashlib

        digest = hashlib.sha256(path.encode()).hexdigest()[:24]
        with self._lock:
            self._sizes.pop(path, None)
            stale = [n for n in self._lru if n.startswith(digest + ".")]
            for n in stale:
                self._bytes -= self._lru.pop(n)
        for n in stale:
            try:
                os.remove(os.path.join(self.cache_dir, n))
            except FileNotFoundError:
                pass

    def list(self, prefix: str = "") -> Iterator[str]:
        return self.inner.list(prefix)


class InjectedFaultError(OSError):
    """A fault the FaultInjectingStore raised on purpose — typed so test
    assertions can tell injected chaos from real store failures."""


class FaultInjectingStore(ObjectStore):
    """Deterministic fault-injection wrapper over any store — the shared
    chaos layer for bench (ingest A/B), chipbench, and the tenant-scale
    production simulator (tools/tenantsim), promoted from bench.py's
    ad-hoc latency-injected SST store.

    Injection points:

    - ``put_latency_s``   — synthetic upload delay per matching put (the
      remote-store shape the pipelined flush exists for)
    - ``get_latency_s``   — synthetic fetch delay per matching get/range
    - ``error_rate``      — probability in [0, 1] that a matching op
      raises ``InjectedFaultError`` (an OSError: the engine's retry/
      backoff paths see exactly what a flaky store would produce)
    - ``suffix``          — only paths ending with it are injected
      (default ``".sst"``: manifest/WAL appends stay fast — the point is
      the data-object cost); ``""`` injects everything

    All knobs are plain attributes, adjustable mid-run under ``_lock``
    (the simulator's fault schedule flips them live). The RNG is seeded
    (``seed``) so a failing schedule replays identically. ``head``/
    ``list``/``delete`` are never injected: they back bookkeeping the
    engine must not lose, and the interesting failure shapes are data
    reads/writes. ``local_path`` (mmap fast path) intentionally does NOT
    pass through: a wrapped store must not let readers bypass injection.
    """

    def __init__(
        self,
        inner: ObjectStore,
        put_latency_s: float = 0.0,
        get_latency_s: float = 0.0,
        error_rate: float = 0.0,
        seed: int = 0,
        suffix: str = ".sst",
    ) -> None:
        import random

        self.inner = inner
        self.put_latency_s = float(put_latency_s)
        self.get_latency_s = float(get_latency_s)
        self.error_rate = float(error_rate)
        self.suffix = suffix
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected_errors = 0
        self.delayed_ops = 0
        # /metrics visibility: the simulator's SLO objectives and alert
        # rules observe the chaos through the DATABASE's own telemetry
        # (rate over the samples history), not harness-side bookkeeping
        from .metrics import REGISTRY

        self._m_errors = REGISTRY.counter(
            "horaedb_object_store_injected_faults_total",
            "operations failed on purpose by FaultInjectingStore",
        )
        self._m_delays = REGISTRY.counter(
            "horaedb_object_store_injected_delays_total",
            "operations delayed on purpose by FaultInjectingStore",
        )

    def _maybe_inject(self, path: str, latency_s: float, op: str) -> None:
        if self.suffix and not path.endswith(self.suffix):
            return
        with self._lock:
            rate = self.error_rate
            fail = rate > 0 and self._rng.random() < rate
            if fail:
                self.injected_errors += 1
                self._m_errors.inc()
            elif latency_s > 0:
                self.delayed_ops += 1
                self._m_delays.inc()
        if fail:
            raise InjectedFaultError(f"injected {op} fault: {path}")
        if latency_s > 0:
            import time

            time.sleep(latency_s)

    def put(self, path: str, data: bytes) -> None:
        self._maybe_inject(path, self.put_latency_s, "put")
        self.inner.put(path, data)

    def get(self, path: str) -> bytes:
        self._maybe_inject(path, self.get_latency_s, "get")
        return self.inner.get(path)

    def get_range(self, path: str, start: int, end: int) -> bytes:
        self._maybe_inject(path, self.get_latency_s, "get_range")
        return self.inner.get_range(path, start, end)

    def head(self, path: str) -> int:
        return self.inner.head(path)

    def delete(self, path: str) -> None:
        self.inner.delete(path)

    def list(self, prefix: str = "") -> Iterator[str]:
        return self.inner.list(prefix)

    def prefetch(self, paths: Sequence[str]) -> None:
        self.inner.prefetch(paths)

    def __getattr__(self, name: str):
        # Forward everything else to the inner store (``root`` places the
        # state files — rules_state.json / wlm_state.json — so hiding it
        # would silently disable persistence on wrapped nodes). EXCEPT
        # ``local_path``: the mmap fast path would let readers bypass
        # injection entirely.
        if name == "local_path":
            raise AttributeError(
                "FaultInjectingStore hides local_path (mmap would bypass "
                "fault injection)"
            )
        inner = self.__dict__.get("inner")
        if inner is None:  # mid-__init__ lookup: nothing to forward yet
            raise AttributeError(name)
        return getattr(inner, name)


class MemCacheStore(ObjectStore):
    """Read-through whole-object LRU cache over another store.

    Sharded like the reference's partitioned LRU (mem_cache.rs:64-158) to
    keep lock contention off the scan path.
    """

    SHARDS = 16

    def __init__(self, inner: ObjectStore, capacity_bytes: int) -> None:
        self.inner = inner
        self._shard_cap = max(1, capacity_bytes // self.SHARDS)
        self._shards = [OrderedDict() for _ in range(self.SHARDS)]
        self._sizes = [0] * self.SHARDS
        self._locks = [threading.Lock() for _ in range(self.SHARDS)]
        self.hits = 0
        self.misses = 0

    def _shard(self, path: str) -> int:
        return hash(path) % self.SHARDS

    def get(self, path: str) -> bytes:
        i = self._shard(path)
        with self._locks[i]:
            cached = self._shards[i].get(path)
            if cached is not None:
                self._shards[i].move_to_end(path)
                self.hits += 1
                return cached
        self.misses += 1
        data = self.inner.get(path)
        with self._locks[i]:
            if path not in self._shards[i]:
                self._shards[i][path] = data
                self._sizes[i] += len(data)
                while self._sizes[i] > self._shard_cap and len(self._shards[i]) > 1:
                    _, evicted = self._shards[i].popitem(last=False)
                    self._sizes[i] -= len(evicted)
        return data

    def get_range(self, path: str, start: int, end: int) -> bytes:
        return self.get(path)[start:end]

    def put(self, path: str, data: bytes) -> None:
        self.inner.put(path, data)
        self._invalidate(path)

    def delete(self, path: str) -> None:
        self.inner.delete(path)
        self._invalidate(path)

    def _invalidate(self, path: str) -> None:
        i = self._shard(path)
        with self._locks[i]:
            old = self._shards[i].pop(path, None)
            if old is not None:
                self._sizes[i] -= len(old)

    def head(self, path: str) -> int:
        return self.inner.head(path)

    def prefetch(self, paths: Sequence[str]) -> None:
        # Forward to the inner (disk) cache: pulling whole objects into
        # THIS cache on a hint could evict the working set from RAM; the
        # page cache below is disk-backed and LRU-bounded.
        self.inner.prefetch(paths)

    def list(self, prefix: str = "") -> Iterator[str]:
        return self.inner.list(prefix)
