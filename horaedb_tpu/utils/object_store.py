"""Object store abstraction (ref: src/components/object_store).

The reference re-exports the Rust ``object_store`` crate and layers caches on
top (mem_cache.rs, disk_cache.rs). Here the trait is a small ABC with the
operations the engine actually needs — whole/range reads, atomic-ish puts,
listing, delete — with three impls:

- ``MemoryStore``      — tests / ephemeral
- ``LocalDiskStore``   — standalone deployments (write-to-temp + rename)
- ``MemCacheStore``    — sharded-LRU read-through page cache wrapper
                         (ref: mem_cache.rs partitioned LRU)

S3/OSS-style remote backends slot in behind the same ABC in a later round
(zero-egress image: nothing to talk to here).
"""

from __future__ import annotations

import os
import threading
from abc import ABC, abstractmethod
from collections import OrderedDict
from typing import Iterator, Optional


class ObjectStore(ABC):
    @abstractmethod
    def put(self, path: str, data: bytes) -> None: ...

    @abstractmethod
    def get(self, path: str) -> bytes: ...

    @abstractmethod
    def get_range(self, path: str, start: int, end: int) -> bytes:
        """Bytes in [start, end) — the SST reader's footer/page reads."""

    @abstractmethod
    def head(self, path: str) -> int:
        """Size in bytes; raises FileNotFoundError if absent."""

    @abstractmethod
    def delete(self, path: str) -> None: ...

    @abstractmethod
    def list(self, prefix: str = "") -> Iterator[str]: ...

    def exists(self, path: str) -> bool:
        try:
            self.head(path)
            return True
        except FileNotFoundError:
            return False


class MemoryStore(ObjectStore):
    def __init__(self) -> None:
        self._objects: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def put(self, path: str, data: bytes) -> None:
        with self._lock:
            self._objects[path] = bytes(data)

    def get(self, path: str) -> bytes:
        try:
            return self._objects[path]
        except KeyError:
            raise FileNotFoundError(path) from None

    def get_range(self, path: str, start: int, end: int) -> bytes:
        return self.get(path)[start:end]

    def head(self, path: str) -> int:
        return len(self.get(path))

    def delete(self, path: str) -> None:
        with self._lock:
            self._objects.pop(path, None)

    def list(self, prefix: str = "") -> Iterator[str]:
        with self._lock:
            keys = sorted(self._objects)
        return iter([k for k in keys if k.startswith(prefix)])


class LocalDiskStore(ObjectStore):
    """Filesystem-backed store; puts are atomic via temp-file + rename."""

    def __init__(self, root: str) -> None:
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _abs(self, path: str) -> str:
        p = os.path.normpath(os.path.join(self.root, path))
        if not p.startswith(self.root):
            raise ValueError(f"path escapes store root: {path!r}")
        return p

    def put(self, path: str, data: bytes) -> None:
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def get(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def get_range(self, path: str, start: int, end: int) -> bytes:
        with open(self._abs(path), "rb") as f:
            f.seek(start)
            return f.read(end - start)

    def head(self, path: str) -> int:
        return os.path.getsize(self._abs(path))

    def delete(self, path: str) -> None:
        try:
            os.remove(self._abs(path))
        except FileNotFoundError:
            pass

    def list(self, prefix: str = "") -> Iterator[str]:
        # Start the walk at the deepest directory the prefix pins down —
        # a per-table prefix must not traverse the whole store.
        base_rel = prefix if prefix.endswith("/") else os.path.dirname(prefix)
        start = os.path.join(self.root, base_rel.rstrip("/")) if base_rel else self.root
        if not os.path.isdir(start):
            return iter([])
        out = []
        for dirpath, _dirs, files in os.walk(start):
            for name in files:
                if name.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return iter(sorted(out))

    def local_path(self, path: str) -> str:
        """Direct filesystem path — lets pyarrow mmap SSTs instead of
        round-tripping bytes through Python."""
        return self._abs(path)


class MemCacheStore(ObjectStore):
    """Read-through whole-object LRU cache over another store.

    Sharded like the reference's partitioned LRU (mem_cache.rs:64-158) to
    keep lock contention off the scan path.
    """

    SHARDS = 16

    def __init__(self, inner: ObjectStore, capacity_bytes: int) -> None:
        self.inner = inner
        self._shard_cap = max(1, capacity_bytes // self.SHARDS)
        self._shards = [OrderedDict() for _ in range(self.SHARDS)]
        self._sizes = [0] * self.SHARDS
        self._locks = [threading.Lock() for _ in range(self.SHARDS)]
        self.hits = 0
        self.misses = 0

    def _shard(self, path: str) -> int:
        return hash(path) % self.SHARDS

    def get(self, path: str) -> bytes:
        i = self._shard(path)
        with self._locks[i]:
            cached = self._shards[i].get(path)
            if cached is not None:
                self._shards[i].move_to_end(path)
                self.hits += 1
                return cached
        self.misses += 1
        data = self.inner.get(path)
        with self._locks[i]:
            if path not in self._shards[i]:
                self._shards[i][path] = data
                self._sizes[i] += len(data)
                while self._sizes[i] > self._shard_cap and len(self._shards[i]) > 1:
                    _, evicted = self._shards[i].popitem(last=False)
                    self._sizes[i] -= len(evicted)
        return data

    def get_range(self, path: str, start: int, end: int) -> bytes:
        return self.get(path)[start:end]

    def put(self, path: str, data: bytes) -> None:
        self.inner.put(path, data)
        self._invalidate(path)

    def delete(self, path: str) -> None:
        self.inner.delete(path)
        self._invalidate(path)

    def _invalidate(self, path: str) -> None:
        i = self._shard(path)
        with self._locks[i]:
            old = self._shards[i].pop(path, None)
            if old is not None:
                self._sizes[i] -= len(old)

    def head(self, path: str) -> int:
        return self.inner.head(path)

    def list(self, prefix: str = "") -> Iterator[str]:
        return self.inner.list(prefix)
