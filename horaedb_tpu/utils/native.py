"""Native (C++) acceleration loader.

The reference's write path is native Rust end-to-end; here the Python
orchestration calls into ``libhoraedb_native.so`` (built from ``native/``)
for the batch-hashing hot path, with a pure-Python fallback when the
library isn't built. The library is compiled on demand with g++ the first
time it's needed (cached next to the sources).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional

import numpy as np

logger = logging.getLogger("horaedb_tpu.native")

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libhoraedb_native.so")
_SRC_PATH = os.path.join(_NATIVE_DIR, "xxhash64.cpp")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Compile to a private temp name then rename atomically: concurrent
    # builders in other processes never expose a half-written .so, and a
    # live process that already dlopen'd the old file keeps its mapping
    # (rename unlinks, not truncates).
    tmp = f"{_LIB_PATH}.{os.getpid()}.tmp"
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC_PATH],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, _LIB_PATH)
        return True
    except Exception as e:  # no g++, compile error, read-only fs...
        logger.info("native build unavailable (%s); using pure-Python path", e)
        try:
            os.remove(tmp)
        except OSError:
            pass
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) or (
            os.path.exists(_SRC_PATH)
            and os.path.getmtime(_SRC_PATH) > os.path.getmtime(_LIB_PATH)
        ):
            if not os.path.exists(_SRC_PATH) or not _build():
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError as e:
            logger.info("native load failed (%s); using pure-Python path", e)
            return None
        lib.hash_var_xx64.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.hash_fixed_xx64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.fnv_mix.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        _lib = lib
        return _lib


def _ptr(a: np.ndarray) -> ctypes.c_void_p:
    return ctypes.c_void_p(a.ctypes.data)


def hash_var(data, offsets: np.ndarray) -> np.ndarray:
    """XXH64 of each [offsets[i], offsets[i+1]) slice of ``data``.

    ``data`` may be bytes or any uint8 buffer (e.g. a zero-copy view of
    an arrow string column's data buffer)."""
    lib = load()
    n = len(offsets) - 1
    out = np.empty(n, dtype=np.uint64)
    if n == 0:
        return out
    if lib is None:
        import xxhash

        for i in range(n):
            out[i] = xxhash.xxh64_intdigest(data[offsets[i]:offsets[i + 1]])
        return out
    buf = (
        data
        if isinstance(data, np.ndarray)
        else np.frombuffer(data, dtype=np.uint8)
    )
    offs = np.ascontiguousarray(offsets, dtype=np.int64)
    lib.hash_var_xx64(_ptr(buf), _ptr(offs), n, _ptr(out))
    return out


def hash_fixed(data: np.ndarray) -> np.ndarray:
    """XXH64 of each row of a contiguous fixed-width array."""
    lib = load()
    data = np.ascontiguousarray(data)
    n = len(data)
    out = np.empty(n, dtype=np.uint64)
    if n == 0:
        return out
    if lib is None:
        import xxhash

        raw = data.tobytes()
        k = data.dtype.itemsize
        for i in range(n):
            out[i] = xxhash.xxh64_intdigest(raw[i * k:(i + 1) * k])
        return out
    lib.hash_fixed_xx64(_ptr(data), data.dtype.itemsize, n, _ptr(out))
    return out


def fnv_mix(acc: np.ndarray, col: np.ndarray) -> None:
    """In-place ``acc = (acc ^ col) * FNV_PRIME`` (wrapping u64)."""
    lib = load()
    if lib is None or len(acc) == 0:
        prime = np.uint64(0x100000001B3)
        np.multiply(np.bitwise_xor(acc, col), prime, out=acc)
        return
    # Keep the (possibly copied) array referenced until the call returns.
    col_c = np.ascontiguousarray(col, dtype=np.uint64)
    lib.fnv_mix(_ptr(acc), _ptr(col_c), len(acc))
