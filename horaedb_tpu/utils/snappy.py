"""Minimal snappy BLOCK format codec (pure Python).

Prometheus remote read/write bodies are snappy block-compressed protobuf;
no snappy library ships in this image, and the format is small enough to
implement directly (it is a public format: a varint uncompressed length
followed by literal/copy tagged elements).

- ``decompress`` handles the full tag set real compressors emit
  (literals + 1/2/4-byte-offset copies).
- ``compress`` emits ALL-LITERAL output — valid snappy any decoder
  accepts; we trade compression ratio for zero complexity on the encode
  side (responses are small aggregates anyway).
"""

from __future__ import annotations


class SnappyError(ValueError):
    pass


def _read_uvarint(buf: bytes, i: int) -> tuple[int, int]:
    shift = result = 0
    while True:
        if i >= len(buf):
            raise SnappyError("truncated varint")
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7
        if shift > 63:
            raise SnappyError("varint too long")


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def decompress(buf: bytes) -> bytes:
    total, i = _read_uvarint(buf, 0)
    out = bytearray()
    n = len(buf)
    while i < n:
        tag = buf[i]
        i += 1
        elem_type = tag & 0x03
        if elem_type == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if i + extra > n:
                    raise SnappyError("truncated literal length")
                length = int.from_bytes(buf[i : i + extra], "little") + 1
                i += extra
            if i + length > n:
                raise SnappyError("truncated literal")
            out += buf[i : i + length]
            i += length
            continue
        if elem_type == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x07) + 4
            if i >= n:
                raise SnappyError("truncated copy-1")
            offset = ((tag >> 5) << 8) | buf[i]
            i += 1
        elif elem_type == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if i + 2 > n:
                raise SnappyError("truncated copy-2")
            offset = int.from_bytes(buf[i : i + 2], "little")
            i += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if i + 4 > n:
                raise SnappyError("truncated copy-4")
            offset = int.from_bytes(buf[i : i + 4], "little")
            i += 4
        if offset == 0 or offset > len(out):
            raise SnappyError(f"bad copy offset {offset}")
        # Copies may overlap themselves (run-length style): byte-at-a-time
        # when the length exceeds the back-reference distance.
        start = len(out) - offset
        if length <= offset:
            out += out[start : start + length]
        else:
            for k in range(length):
                out.append(out[start + k])
    if len(out) != total:
        raise SnappyError(f"decompressed size {len(out)} != header {total}")
    return bytes(out)


def compress(data: bytes) -> bytes:
    out = bytearray(_write_uvarint(len(data)))
    i = 0
    n = len(data)
    while i < n:
        chunk = min(n - i, 0x10000)  # literal length fits in 2 extra bytes
        if chunk <= 60:
            out.append((chunk - 1) << 2)
        elif chunk <= 0x100:
            out.append(60 << 2)
            out += (chunk - 1).to_bytes(1, "little")
        else:
            out.append(61 << 2)
            out += (chunk - 1).to_bytes(2, "little")
        out += data[i : i + chunk]
        i += chunk
    return bytes(out)
