"""Minimal Prometheus-style metrics registry
(ref: lazy-static prometheus registries in nearly every reference crate,
exposed at /metrics — server/src/http.rs:532).

Counters, gauges and histograms (what the serving path needs); text
exposition format compatible with Prometheus scraping. Counters and
gauges take optional labels — one HELP/TYPE header per family, one
sample line per label set (how prometheus-client renders families).
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Optional, Sequence


def _escape_label_value(v: str) -> str:
    # Prometheus text format: backslash, double-quote and newline must be
    # escaped or one bad label value fails the ENTIRE scrape.
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _render_labels(labels: dict[str, str] | None) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    TYPE = "counter"

    def __init__(self, name: str, help_: str, labels: dict[str, str] | None = None) -> None:
        self.name = name
        self.help = help_
        self.labels = dict(labels) if labels else {}
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def snapshot(self) -> float:
        """The value, read under the lock — the one way scrapes and the
        self-monitoring recorder read a counter/gauge, so a read racing
        ``inc()`` can never observe a torn update."""
        with self._lock:
            return self._value

    @property
    def value(self) -> float:
        return self.snapshot()

    def expose_parts(self) -> tuple[str, str]:
        header = (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} {self.TYPE}\n"
        )
        body = f"{self.name}{_render_labels(self.labels)} {self.snapshot()}\n"
        return header, body

    def expose(self) -> str:
        header, body = self.expose_parts()
        return header + body


class Gauge(Counter):
    """A value that can go down (queue depths, in-flight work)."""

    TYPE = "gauge"

    def set(self, value: float) -> None:
        with self._lock:
            self._value = value

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0)


class Histogram:
    def __init__(self, name: str, help_: str, buckets: Sequence[float] = DEFAULT_BUCKETS,
                 labels: dict[str, str] | None = None):
        self.name = name
        self.help = help_
        self.labels = dict(labels) if labels else {}
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._total += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """``(bucket_counts, sum, count)`` under the lock — a consistent
        view (buckets sum to count) for scrapes, the system tables, and
        the self-monitoring recorder."""
        with self._lock:
            return list(self._counts), self._sum, self._total

    @property
    def count(self) -> int:
        with self._lock:
            return self._total

    def expose_parts(self) -> tuple[str, str]:
        # consistent snapshot: buckets must sum to count
        counts, sum_, total = self.snapshot()
        header = (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} histogram\n"
        )
        # Per-labelset samples: the labels merge INTO the bucket braces
        # alongside ``le`` (one family header, many labelsets — same
        # rendering rule the Counter/Gauge families follow).
        base = sorted(self.labels.items())
        suffix = _render_labels(self.labels)
        out = []
        acc = 0
        for le, c in zip(self.buckets, counts):
            acc += c
            inner = ",".join(
                f'{k}="{_escape_label_value(v)}"'
                for k, v in (*base, ("le", str(le)))
            )
            out.append(f"{self.name}_bucket{{{inner}}} {acc}")
        inner = ",".join(
            f'{k}="{_escape_label_value(v)}"'
            for k, v in (*base, ("le", "+Inf"))
        )
        out.append(f"{self.name}_bucket{{{inner}}} {total}")
        out.append(f"{self.name}_sum{suffix} {sum_}")
        out.append(f"{self.name}_count{suffix} {total}")
        return header, "\n".join(out) + "\n"

    def expose(self) -> str:
        header, body = self.expose_parts()
        return header + body


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Histogram] = {}
        self._lock = threading.Lock()

    def _get(self, key: str, factory, cls):
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = factory()
                self._metrics[key] = m
            elif type(m) is not cls:
                # A name registered as one kind silently returned as
                # another would blow up far from the registration site
                # (.set on a Counter, .observe on a Gauge) — fail HERE.
                raise TypeError(
                    f"metric {key!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}"
                )
            return m

    def counter(self, name: str, help_: str = "",
                labels: dict[str, str] | None = None) -> Counter:
        key = name + _render_labels(labels)
        return self._get(key, lambda: Counter(name, help_, labels), Counter)

    def gauge(self, name: str, help_: str = "",
              labels: dict[str, str] | None = None) -> Gauge:
        key = name + _render_labels(labels)
        return self._get(key, lambda: Gauge(name, help_, labels), Gauge)

    def histogram(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS,
                  labels: dict[str, str] | None = None) -> Histogram:
        key = name + _render_labels(labels)
        return self._get(key, lambda: Histogram(name, help_, buckets, labels), Histogram)

    def remove(self, name: str, labels: dict[str, str] | None = None) -> None:
        """Unregister one labelset (per-entity series — e.g. a dropped
        table's memtable gauge — must not pin the registry forever)."""
        with self._lock:
            self._metrics.pop(name + _render_labels(labels), None)

    def families(self) -> dict[str, list]:
        """Live family name -> member metrics (the metrics-name lint and
        other introspection walk this instead of parsing exposition)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict[str, list] = {}
        for m in metrics:
            out.setdefault(m.name, []).append(m)
        return out

    def expose(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        # Group samples by family: labeled children may have registered
        # interleaved with other metrics, but the exposition format wants
        # one HELP/TYPE header followed by ALL of that family's samples.
        order: list[str] = []
        families: dict[str, list] = {}
        for m in metrics:
            if m.name not in families:
                families[m.name] = []
                order.append(m.name)
            families[m.name].append(m)
        out: list[str] = []
        for name in order:
            members = families[name]
            out.append(members[0].expose_parts()[0])
            out.extend(m.expose_parts()[1] for m in members)
        return "".join(out)


REGISTRY = Registry()
