"""Minimal Prometheus-style metrics registry
(ref: lazy-static prometheus registries in nearly every reference crate,
exposed at /metrics — server/src/http.rs:532).

Counters and histograms only (what the serving path needs); text
exposition format compatible with Prometheus scraping.
"""

from __future__ import annotations

import threading
from bisect import bisect_right
from typing import Optional, Sequence


class Counter:
    def __init__(self, name: str, help_: str) -> None:
        self.name = name
        self.help = help_
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def expose(self) -> str:
        return (
            f"# HELP {self.name} {self.help}\n"
            f"# TYPE {self.name} counter\n"
            f"{self.name} {self._value}\n"
        )


DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0)


class Histogram:
    def __init__(self, name: str, help_: str, buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._total += 1

    @property
    def count(self) -> int:
        return self._total

    def expose(self) -> str:
        with self._lock:  # consistent snapshot: buckets must sum to count
            counts = list(self._counts)
            total = self._total
            sum_ = self._sum
        out = [
            f"# HELP {self.name} {self.help}",
            f"# TYPE {self.name} histogram",
        ]
        acc = 0
        for le, c in zip(self.buckets, counts):
            acc += c
            out.append(f'{self.name}_bucket{{le="{le}"}} {acc}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {sum_}")
        out.append(f"{self.name}_count {total}")
        return "\n".join(out) + "\n"


class Registry:
    def __init__(self) -> None:
        self._metrics: dict[str, Counter | Histogram] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Counter(name, help_)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def histogram(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = Histogram(name, help_, buckets)
                self._metrics[name] = m
            return m  # type: ignore[return-value]

    def expose(self) -> str:
        with self._lock:
            return "".join(m.expose() for m in self._metrics.values())


REGISTRY = Registry()
