"""Priority runtimes (ref: components/runtime priority_runtime.rs:57-100).

The reference runs expensive (long-time-range) queries on a separate,
smaller tokio runtime so they can't starve cheap queries. Same shape here:
two thread pools; the planner's priority decision picks the pool. The low
pool is intentionally small — expensive queries queue among themselves.
"""

from __future__ import annotations

import concurrent.futures as cf
import threading
from typing import Callable, TypeVar

T = TypeVar("T")


class PriorityRuntime:
    def __init__(self, high_workers: int = 4, low_workers: int = 2) -> None:
        self._high = cf.ThreadPoolExecutor(
            max_workers=high_workers, thread_name_prefix="query-high"
        )
        self._low = cf.ThreadPoolExecutor(
            max_workers=low_workers, thread_name_prefix="query-low"
        )
        self.submitted_high = 0
        self.submitted_low = 0
        self._lock = threading.Lock()

    def submit(self, priority: str, fn: Callable[[], T]) -> "cf.Future[T]":
        pool = self._low if priority == "low" else self._high
        with self._lock:
            if priority == "low":
                self.submitted_low += 1
            else:
                self.submitted_high += 1
        return pool.submit(fn)

    def run(self, priority: str, fn: Callable[[], T]) -> T:
        """Run on the priority pool, blocking the caller until done.

        When the caller already sits on the TARGET pool's own thread,
        run inline instead — submitting would deadlock once the pool is
        saturated with blocked callers.
        """
        name = threading.current_thread().name
        target_prefix = "query-low" if priority == "low" else "query-high"
        if name.startswith(target_prefix):
            return fn()
        return self.submit(priority, fn).result()

    def shutdown(self) -> None:
        self._high.shutdown(wait=False, cancel_futures=True)
        self._low.shutdown(wait=False, cancel_futures=True)


_scatter_pool = None
_scatter_lock = threading.Lock()


def scatter_pool() -> "cf.ThreadPoolExecutor":
    """Shared pool for partition scatter/gather (partial-agg fan-out,
    remote reads). One long-lived pool instead of per-query spawn/join —
    the fan-out sits on the hot serving path."""
    global _scatter_pool
    with _scatter_lock:
        if _scatter_pool is None:
            _scatter_pool = cf.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="scatter"
            )
        return _scatter_pool


_io_pool = None
_io_lock = threading.Lock()


def io_pool() -> "cf.ThreadPoolExecutor":
    """Dedicated pool for storage IO fan-out (concurrent SST fetches from
    remote stores). SEPARATE from scatter_pool: partition scatter tasks
    trigger SST reads, and nesting both on one bounded pool would
    deadlock under load."""
    global _io_pool
    with _io_lock:
        if _io_pool is None:
            _io_pool = cf.ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="sst-io"
            )
        return _io_pool
