"""Guarded environment-variable parsing.

An operator typo (``HORAEDB_MXU_MAX_SEGMENTS=8k``) must degrade to the
default, not abort the process — several of these are read at module
import, where an unguarded ``int()`` kills the whole server before it can
log anything. The guarded pattern existed ad hoc (merge.py, mesh.py);
this is the one shared helper every env-int read routes through.
"""

from __future__ import annotations

import os
from typing import Optional


def env_int(name: str, default: Optional[int]) -> Optional[int]:
    """``int(os.environ[name])`` with the malformed/missing cases folded
    to ``default``. Never raises. ``default=None`` lets a caller
    distinguish unset/malformed from any explicit value (including
    negatives) instead of burning a sentinel in the value space."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return int(raw)
    except (TypeError, ValueError):
        return default


def env_float(name: str, default: float) -> float:
    """Float twin of :func:`env_int`. Never raises."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    try:
        return float(raw)
    except (TypeError, ValueError):
        return default
