"""Per-query resource accounting — the cost ledger beside the span tree
(ref: trace_metric's per-operator cost counters; Fine-Tuning Data
Structures for Analytical Query Processing argues route/layout decisions
are only tunable when per-operator cost counters are first-class).

A ``QueryLedger`` rides a ContextVar next to the PR-1 trace: the proxy
opens one per SQL statement, every stage the request touches adds its
costs (rows scanned, SSTs pruned vs read, object-store bytes, scan-cache
hits, kernel compiles, remote RPCs, ...), and finalization feeds three
sinks at once:

- the bounded ``STATS_STORE`` ring, served as the SQL-queryable virtual
  table ``system.public.query_stats`` (joinable on request_id);
- the ``horaedb_query_*`` Prometheus families (one counter per ledger
  field, plus ``horaedb_query_route_total{route=...}``);
- EXPLAIN ANALYZE and the slow-query log, which render the ledger
  inline with the span tree.

Cross-node: partition owners account their share in a detached serving
ledger (``serving_ledger``) and ship it home in the RPC response's
``ledger`` field; the remote client merges it into the coordinator's
ledger (``merge_remote``), so the coordinator's row is the CLUSTER-wide
cost of the query. Everything is a cheap no-op outside a request
(background flush/compaction pays one ContextVar read).

Field registry discipline: ``NUMERIC_FIELDS`` is the single source of
truth — the query_stats schema, the metric families, and the docs lint
all derive from (or are checked against) it, so a new field cannot land
without a column, a metric, and documentation.
"""

from __future__ import annotations

import contextvars
import threading
import time
from typing import Any, Optional

from .metrics import REGISTRY

# ---- field registry -------------------------------------------------------

# field -> one-line meaning (becomes metric HELP and the docs table).
# Names must keep the metrics lint happy once prefixed/suffixed into
# ``horaedb_query_<field>_total``.
NUMERIC_FIELDS: dict[str, str] = {
    "scan_rows": "rows materialized by storage scans for the query",
    "memtable_rows": "rows of those served from memtables",
    "sst_read": "SST files opened by the query's scans",
    "sst_pruned": "SST files skipped by time-range pruning",
    "store_read_bytes": "object-store bytes fetched (compressed row groups)",
    "cache_hits": "scan-cache (HBM) hits serving the query",
    "cache_misses": "scan-cache misses/bypasses on eligible paths",
    "cache_bytes": "device-resident bytes the cache served from",
    "jit_compiles": "kernel shapes compiled for the first time",
    "jit_cache_hits": "kernel dispatches served by the compile cache",
    "fanout": "partition fan-out width (scattered sub-queries)",
    "remote_rpcs": "remote-engine RPCs issued",
    "remote_bytes": "request+response bytes over the remote engine",
    "retries": "stale-route retries during execution",
    # workload-management roles (wlm/dedup): single-flight reads record
    # which side of the coalescing they were on
    "dedup_followers": "identical in-flight twins this leader execution served",
    "dedup_follower": "1 when this query awaited an identical in-flight leader",
    # cohort batching (wlm/batch): shape-identical in-flight queries
    # served by one fused device dispatch record which side of the
    # cohort they were on, and how wide it was
    "batch_leader": "cohort size when this query led a fused cohort dispatch",
    "batch_member": "1 when this query was served by a cohort leader's fused dispatch",
    "batch_cohort": "fused cohort size for batch-served queries (leader and members)",
    # kernel-routing feedback: how many (group x bucket) cells the device
    # aggregation actually produced — the cardinality truth the kernel
    # router seeds from on the next sighting of the shape
    "agg_segments": "live segment cells the device aggregation produced",
    # raw (non-aggregate) device reads: result rows the fused
    # filter+top-k/selection path returned (0 for host-served raw reads)
    "raw_rows_returned": "rows the device raw-read path returned",
    # replicated follower reads (route=follower): how far the serving
    # follower's freshness watermark trailed "now" at serve time
    "replica_lag_ms": "follower watermark lag (ms) on replica-served reads",
    # deadline propagation / cooperative cancellation (utils/deadline):
    # the budget the request carried and how it ended
    "deadline_ms": "time budget (ms) the request carried at ingress (0 = unbounded)",
    "timed_out": "1 when the query died to its deadline (DeadlineExceeded)",
    "cancelled": "1 when the query was cooperatively cancelled (KILL/disconnect)",
    # device telemetry plane (obs/device): how much device work the
    # query issued and whether it paid a compile stall
    "device_dispatches": "device kernel dispatches the query issued",
    "compile_hit": "device dispatches that paid a first-time XLA compile (compile-stall marker)",
    # live window state (state/livewindow, route=livewindow): how many
    # ring buckets the state-served tail of the query read
    "state_buckets": "device ring buckets served from live window state",
}

# wall-time costs; seconds, float.
FLOAT_FIELDS: dict[str, str] = {
    "jit_compile_seconds": "wall seconds spent compiling new kernel shapes",
    "admission_wait_seconds": "wall seconds waiting for an admission slot",
    # sampled on-device dispatch wall (obs/device timed_dispatch):
    # milliseconds for render friendliness — tiny kernels are sub-ms
    "device_ms": "sampled on-device dispatch wall milliseconds (block_until_ready timing)",
}

LEDGER_FIELDS: dict[str, str] = {**NUMERIC_FIELDS, **FLOAT_FIELDS}


def metric_name(field: str) -> str:
    """The Prometheus family a ledger field feeds at finalization."""
    return f"horaedb_query_{field}_total"


# Eager registration: the families exist from the first scrape (and the
# registry lint sees them) even before any query finalizes.
_FIELD_COUNTERS = {
    field: REGISTRY.counter(metric_name(field), help_)
    for field, help_ in LEDGER_FIELDS.items()
}


def _route_counter(route: str):
    return REGISTRY.counter(
        "horaedb_query_route_total",
        "queries by executor route (which of the six paths ran)",
        labels={"route": route},
    )


# ---- aggregation-kernel accounting ----------------------------------------

# Which segment-reduction impl served a device aggregation (the learned
# kernel router's choice, or the static heuristic's). "single" is the
# n_seg == 1 pure-reduction shape; "host" the tiny-input hash fallback.
SEGMENT_KERNEL_LABELS = ("mxu", "scatter", "hash", "single", "host")

# Registry discipline (lint-enforced like the admission/flush families):
# declared here, registered eagerly, documented in docs/OBSERVABILITY.md,
# and no stray horaedb_agg_* family may exist outside this tuple.
AGG_KERNEL_METRIC_FAMILIES = ("horaedb_agg_kernel_total",)

_AGG_KERNEL_COUNTERS = {
    k: REGISTRY.counter(
        "horaedb_agg_kernel_total",
        "device aggregation dispatches by segment-reduction kernel",
        labels={"kernel": k},
    )
    for k in SEGMENT_KERNEL_LABELS
}


# ---- raw-read accounting ---------------------------------------------------

# Which serving shape a raw (non-aggregate) read took. "topk"/"select"
# are the device kernels ("_dist" variants when the entry is sharded
# over the mesh), "host" an ELIGIBLE query deliberately routed to the
# host path (router choice, kill switch, selectivity over budget), and
# "fallback" a device attempt the cache or eligibility checks bounced.
RAW_SCAN_PATHS = (
    "topk", "select", "topk_dist", "select_dist", "host", "fallback",
)

# Registry discipline (lint-enforced like the agg-kernel family):
# declared here, registered eagerly, documented in docs/OBSERVABILITY.md,
# and no stray horaedb_raw_* family may exist outside this tuple.
RAW_SCAN_METRIC_FAMILIES = ("horaedb_raw_scan_total",)

_RAW_SCAN_COUNTERS = {
    p: REGISTRY.counter(
        "horaedb_raw_scan_total",
        "raw (non-aggregate) reads by serving path",
        labels={"path": p},
    )
    for p in RAW_SCAN_PATHS
}


def note_raw_scan(path: str, kernel: str = "", rows=None) -> None:
    """Account one raw read: bump the per-path family and — on the
    device paths — stamp the ledger's ``kernel`` field and the
    ``raw_rows_returned`` count, so ``system.public.query_stats`` covers
    raw serving on every wire."""
    counter = _RAW_SCAN_COUNTERS.get(path)
    if counter is not None:
        counter.inc()
    ledger = _current_ledger.get()
    if ledger is not None:
        if kernel:
            ledger.set_kernel(kernel)
        if rows is not None:
            ledger.add(raw_rows_returned=rows)


def note_agg_kernel(kernel: str, segments: int = 0) -> None:
    """Account one aggregation dispatch: bump the per-kernel family,
    stamp the ledger's ``kernel`` field, and record the live segment
    count the kernel router learns cardinality from."""
    counter = _AGG_KERNEL_COUNTERS.get(kernel)
    if counter is not None:
        counter.inc()
    ledger = _current_ledger.get()
    if ledger is not None:
        ledger.set_kernel(kernel)
        if segments:
            ledger.add(agg_segments=segments)


# ---- ledger ---------------------------------------------------------------


class QueryLedger:
    """One request's accumulating cost counters. Thread-safe: the scatter
    pool and gRPC client callbacks add from several threads at once."""

    __slots__ = ("request_id", "sql", "route", "kernel", "table_name",
                 "counts", "started_at", "_lock")

    def __init__(self, request_id=None, sql: str = "") -> None:
        self.request_id = request_id
        self.sql = sql
        self.route = ""  # last executor path taken (one of the six)
        self.kernel = ""  # last segment-reduction impl dispatched
        # primary table the statement targeted — the elastic control
        # loop's load signal (meta/elastic reads per-table query counts
        # from system.public.query_stats over the distributed read path)
        self.table_name = ""
        self.counts: dict[str, float] = dict.fromkeys(LEDGER_FIELDS, 0)
        self.started_at = time.time()
        self._lock = threading.Lock()

    def add(self, **fields: float) -> None:
        with self._lock:
            for k, v in fields.items():
                if k in self.counts:
                    self.counts[k] += v

    def set_route(self, route: str) -> None:
        self.route = route

    def set_kernel(self, kernel: str) -> None:
        self.kernel = kernel

    def set_table(self, table: Optional[str]) -> None:
        if table:
            self.table_name = table

    def merge_remote(self, remote: Optional[dict]) -> None:
        """Fold a partition owner's shipped ledger into this one (numeric
        fields only — the owner's route is a sub-plan detail)."""
        if not isinstance(remote, dict):
            return
        counts = remote.get("counts")
        if not isinstance(counts, dict):
            return
        if not self.kernel and isinstance(remote.get("kernel"), str):
            # partition owners ran the kernels; the coordinator did not
            self.kernel = remote["kernel"]
        with self._lock:
            for k, v in counts.items():
                if k in self.counts and isinstance(v, (int, float)):
                    self.counts[k] += v

    def to_dict(self) -> dict:
        with self._lock:
            counts = dict(self.counts)
        return {"route": self.route, "kernel": self.kernel, "counts": counts}

    def nonzero(self) -> dict[str, float]:
        """Fields with activity — what EXPLAIN ANALYZE / slow log print."""
        with self._lock:
            return {k: v for k, v in self.counts.items() if v}


_current_ledger: contextvars.ContextVar[Optional[QueryLedger]] = (
    contextvars.ContextVar("horaedb_query_ledger", default=None)
)


def current_ledger() -> Optional[QueryLedger]:
    return _current_ledger.get()


def record(**fields: float) -> None:
    """Add costs to the current request's ledger (no-op outside one)."""
    ledger = _current_ledger.get()
    if ledger is not None:
        ledger.add(**fields)


def set_route(route: str) -> None:
    ledger = _current_ledger.get()
    if ledger is not None:
        ledger.set_route(route)


def merge_remote(remote: Optional[dict]) -> None:
    """Fold a remote owner's response ledger into the current one."""
    ledger = _current_ledger.get()
    if ledger is not None:
        ledger.merge_remote(remote)


def start_ledger(request_id=None, sql: str = "") -> tuple[QueryLedger, Any]:
    """Open a ledger in the current context; pass the handle (and the
    ledger) to ``finish_ledger``."""
    ledger = QueryLedger(request_id, sql)
    token = _current_ledger.set(ledger)
    return ledger, token


def finish_ledger(ledger: QueryLedger, token, duration_s: float,
                  record_stats: bool = True) -> None:
    """Close the request's ledger: reset the ContextVar and (by default)
    record the row in STATS_STORE + feed the horaedb_query_* families."""
    _current_ledger.reset(token)
    if not record_stats:
        return
    snapshot = {
        "timestamp": int(time.time() * 1000),
        "request_id": ledger.request_id,
        "sql": ledger.sql[:200],
        "route": ledger.route,
        "kernel": ledger.kernel,
        "table_name": ledger.table_name,
        "duration_ms": round(duration_s * 1000, 3),
        **ledger.counts,
    }
    STATS_STORE.record(snapshot)
    if ledger.route:
        _route_counter(ledger.route).inc()
    for field, counter in _FIELD_COUNTERS.items():
        v = ledger.counts.get(field, 0)
        if v:
            counter.inc(v)


class _ServingLedger:
    """Context manager serving an RPC under a detached ledger: the owner's
    costs ship home in the response (``wire`` attribute) instead of
    landing in this node's query_stats ring — the coordinator's merged
    row is the one source of per-query truth."""

    def __init__(self, request_id=None) -> None:
        self.request_id = request_id
        self.ledger: Optional[QueryLedger] = None
        self._token = None

    def __enter__(self) -> QueryLedger:
        self.ledger, self._token = start_ledger(self.request_id)
        return self.ledger

    def __exit__(self, *exc) -> None:
        finish_ledger(self.ledger, self._token, 0.0, record_stats=False)

    @property
    def wire(self) -> dict:
        return self.ledger.to_dict()


def serving_ledger(request_id=None) -> _ServingLedger:
    return _ServingLedger(request_id)


# ---- kernel compile-cache accounting --------------------------------------

# Static kernel shapes seen by THIS process. First dispatch of a shape
# pays the XLA compile; the wall time of that first call is an honest
# upper bound on the compile cost and is what operators need to explain a
# latency cliff ("this query shape compiled").
_seen_kernel_keys: set = set()
_kernel_lock = threading.Lock()


def note_kernel_dispatch(key, elapsed_s: float, kind: str = "",
                         cost_fn=None) -> None:
    """Account one device-kernel dispatch: a never-seen static ``key``
    counts as a compile (with its wall seconds); a seen one as a
    compile-cache hit.

    ``kind`` (a DEVICE_KERNEL_KINDS label) routes the outcome into the
    device telemetry plane too: a first sighting journals a typed
    ``kernel_compile`` event and marks the ledger's ``compile_hit``; a
    repeat ticks the per-kernel compile-cache-hit counter. ``cost_fn``
    (only called on a compile) may return an XLA cost_analysis dict to
    ride the event (obs/device.cost_analysis)."""
    with _kernel_lock:
        first = key not in _seen_kernel_keys
        if first:
            _seen_kernel_keys.add(key)
    if first:
        record(jit_compiles=1, jit_compile_seconds=elapsed_s)
        if kind:
            from ..obs.device import note_compile

            cost = None
            if cost_fn is not None:
                try:
                    cost = cost_fn()
                except Exception:
                    cost = None
            note_compile(kind, key, elapsed_s, cost)
    else:
        record(jit_cache_hits=1)
        if kind:
            from ..obs.device import note_compile_cache_hit

            note_compile_cache_hit(kind)


# ---- stats store ----------------------------------------------------------


class StatsStore:
    """Bounded ring of finalized per-query ledgers — the rows behind
    ``system.public.query_stats``. Snapshots (plain dicts), so readers
    never race a live request."""

    def __init__(self, maxlen: int = 256) -> None:
        from collections import deque

        self._ring: "deque[dict]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()

    def record(self, snapshot: dict) -> None:
        with self._lock:
            self._ring.append(snapshot)

    def list(self) -> list[dict]:
        """Oldest-first snapshot of the ring."""
        with self._lock:
            return list(self._ring)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


STATS_STORE = StatsStore()


def render_ledger(ledger: QueryLedger) -> str:
    """One-line rendering for EXPLAIN ANALYZE / logs: route plus every
    nonzero cost field."""
    parts = []
    if ledger.route:
        parts.append(f"route={ledger.route}")
    if ledger.kernel:
        parts.append(f"kernel={ledger.kernel}")
    for k, v in ledger.nonzero().items():
        if isinstance(v, float) and not v.is_integer():
            parts.append(f"{k}={v:.4f}")
        else:
            parts.append(f"{k}={int(v)}")
    return " ".join(parts) if parts else "(no costs recorded)"
