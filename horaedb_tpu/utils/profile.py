"""On-demand profiling (ref: components/profile/src/lib.rs:91-170 — the
reference dumps pprof CPU profiles and jemalloc heap stats over
/debug/profile/{cpu,heap}/{seconds}, server/src/http.rs:539-563).

Python equivalents with no native agent:

- CPU: a sampling wall-clock profiler over ``sys._current_frames()`` —
  aggregates stack samples across ALL threads (a cProfile attach can't
  see other threads), the same shape py-spy/pprof reports reduce to.
- Heap: tracemalloc growth between two snapshots over the window.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter

# tracemalloc is process-global state: concurrent heap profiles must
# serialize, or the first to finish stops tracing under the second.
_heap_lock = threading.Lock()


def sample_cpu(seconds: float, interval_s: float = 0.01, top: int = 40) -> str:
    """Sample every thread's stack for ``seconds``; text report of the
    hottest frames (self samples) and hottest whole stacks."""
    frames: Counter = Counter()
    stacks: Counter = Counter()
    deadline = time.monotonic() + seconds
    n_samples = 0
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            stack = traceback.extract_stack(frame)
            # Filter the profiler's own frames from the WHOLE stack, not
            # just the last two: the sampling thread is often caught
            # deeper (inside extract_stack / Counter / sleep internals),
            # where a 2-frame tail check misses it and the profiler
            # pollutes its own hot-stack report.
            if any("utils/profile" in f.filename for f in stack):
                continue
            if not stack:
                continue
            leaf = stack[-1]
            frames[f"{leaf.filename}:{leaf.lineno} {leaf.name}"] += 1
            stacks[
                " <- ".join(f"{f.name}" for f in reversed(stack[-6:]))
            ] += 1
        n_samples += 1
        time.sleep(interval_s)
    lines = [f"cpu profile: {n_samples} sampling rounds over {seconds:.1f}s", ""]
    lines.append("hottest frames (self samples):")
    for name, count in frames.most_common(top):
        lines.append(f"  {count:6d}  {name}")
    lines.append("")
    lines.append("hottest stacks (leaf <- callers):")
    for name, count in stacks.most_common(top // 2):
        lines.append(f"  {count:6d}  {name}")
    return "\n".join(lines) + "\n"


def sample_heap(seconds: float, top: int = 40) -> str:
    """tracemalloc growth over the window, by allocation site.

    Serialized process-wide (see _heap_lock); concurrent callers queue."""
    import tracemalloc

    with _heap_lock:
        return _sample_heap_locked(tracemalloc, seconds, top)


def _sample_heap_locked(tracemalloc, seconds: float, top: int) -> str:
    started_here = not tracemalloc.is_tracing()
    if started_here:
        tracemalloc.start(10)
    try:
        before = tracemalloc.take_snapshot()
        time.sleep(seconds)
        after = tracemalloc.take_snapshot()
        stats = after.compare_to(before, "lineno")
        current, peak = tracemalloc.get_traced_memory()
        lines = [
            f"heap profile: growth over {seconds:.1f}s "
            f"(traced current={current >> 10}KiB peak={peak >> 10}KiB)",
            "",
        ]
        for stat in stats[:top]:
            lines.append(f"  {stat}")
        return "\n".join(lines) + "\n"
    finally:
        if started_here:
            tracemalloc.stop()
