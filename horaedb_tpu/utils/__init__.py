"""Cross-cutting components (ref: src/components/*)."""
