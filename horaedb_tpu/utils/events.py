"""Engine event journal — typed, bounded, trace-linked
(ref: the reference's tracing spans around flush/compaction in
analytic_engine, and StreamBox-HBM's stance that the system's own
telemetry is just another high-rate stream worth first-class treatment).

Discrete engine lifecycle events (a flush froze a memtable, a writer hit
the stall bound, admission shed a query, a shard froze) vanish into
counters the moment they happen — an operator debugging "why was p99 bad
at 14:32" needs the *sequence*, not just the totals. ``record_event``
appends one typed entry to a bounded in-memory ring served as the
virtual table ``system.public.events`` (all three wire protocols) and at
``/debug/events``; each entry carries the active ``trace_id`` so events
cross-link to the span store (/debug/trace/{id}) and the query ledger.

Registry discipline (the same contract as the metric-family lints):
every event ``kind`` emitted anywhere must be declared in
``EVENT_KINDS`` below — ``record_event`` refuses undeclared kinds — and
each kind has an eagerly-registered ``horaedb_events_total{kind=...}``
counter and a docs/OBSERVABILITY.md row. tests/test_observability.py
enforces all of it.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from typing import Any, Optional

from .metrics import REGISTRY

# kind -> one-line meaning (the single source of truth: the counter HELP,
# the docs table, and the lint all derive from or are checked against it).
EVENT_KINDS: dict[str, str] = {
    "flush_freeze": "a table's mutable memtable was frozen for flush",
    "flush_dump": "frozen memtables were dumped to L0 SSTs",
    "flush_install": "a flush's manifest edits + version swap installed",
    "flush_failed": "a flush raised before installing",
    "compaction": "a compaction pass merged L0 runs / dropped expired SSTs",
    "compaction_failed": "a compaction pass raised",
    "write_stall_enter": "a writer began blocking on the immutable-memtable bound",
    "write_stall_exit": "a stalled writer resumed or shed (see outcome attr)",
    "admission_shed": "admission control shed a query (queue full / deadline)",
    "quota_reject": "a tenant/table token bucket rejected a request",
    "wal_replay": "a table replayed WAL entries at open",
    "ddl_create_table": "a table was created",
    "ddl_drop_table": "a table was dropped",
    "ddl_alter_table": "a table's schema or options were altered",
    "shard_freeze": "the lease watch froze a shard (lease lapsed)",
    "shard_thaw": "a frozen shard thawed (lease renewed)",
    "self_scrape_skipped": "a self-monitoring scrape round was shed by backpressure",
    "self_retention": "self-monitoring retention dropped expired sample SSTs",
    "alert_fired": "an alert rule's series transitioned pending -> firing",
    "alert_resolved": "a firing alert series stopped matching and resolved",
    "rule_eval_failed": "a rule/rollup evaluation raised (or a round was shed)",
    "rollup_catchup": "a rollup tier advanced over a multi-bucket backlog (restart/backfill)",
    "slo_burn": "an SLO objective's fast+slow burn rates crossed the threshold",
    "slo_recovered": "a burning SLO objective's fast window came back under threshold",
    "elastic_decision": "the elastic control loop decided a round's actions (dry-run rounds journal here without acting)",
    "elastic_action": "the elastic control loop applied one guarded action (scale_up/scale_down/move/prewarm)",
    "elastic_quarantined": "the elastic circuit breaker quarantined a shard after repeated failed moves",
    "elastic_released": "an operator released a quarantined shard (horaectl elastic release)",
    "query_timeout": "a query exceeded its time budget and unwound at a checkpoint",
    "query_cancelled": "a query was cooperatively cancelled (KILL QUERY / ctl / disconnect)",
    "kernel_compile": "a device kernel shape compiled for the first time (XLA compile)",
    "decision_resolved": "an adaptive loop's journaled decision got its realized outcome (sampled per loop)",
    "loop_miscalibrated": "an adaptive loop's fast+slow calibration windows crossed the error threshold",
}

_EVENTS_FAMILY = "horaedb_events_total"

# Ring overflow is ACCOUNTED, never silent: the journal's "no seq gaps"
# invariant (tools/tenantsim asserts it from system.public.events) is
# only falsifiable if drops are visible — min(seq) - 1 must equal the
# dropped count. Sized by the [observability] event_ring knob.
_M_DROPPED = REGISTRY.counter(
    "horaedb_events_dropped_total",
    "journal entries discarded by the bounded ring (oldest-first)",
)

# Eager registration: every kind's labeled counter exists from the first
# scrape (and for the registry lint) even before the event ever fires —
# same discipline as the ledger/admission families.
_KIND_COUNTERS = {
    kind: REGISTRY.counter(
        _EVENTS_FAMILY,
        "engine lifecycle events recorded in the journal, by kind",
        labels={"kind": kind},
    )
    for kind in EVENT_KINDS
}


class EventStore:
    """Bounded ring of event entries (plain dicts — readers never race a
    live mutation). One per process, like TRACE_STORE / STATS_STORE."""

    DEFAULT_CAPACITY = 512

    def __init__(self, maxlen: int = DEFAULT_CAPACITY) -> None:
        from collections import deque

        self._ring: "deque[dict]" = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        self._issued = 0  # last seq handed out (survives clear())
        self.dropped = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def resize(self, maxlen: int) -> None:
        """Re-bound the ring ([observability] event_ring). Shrinking
        discards oldest-first and ACCOUNTS the discards like any other
        overflow; growing keeps everything."""
        from collections import deque

        maxlen = max(1, int(maxlen))
        with self._lock:
            if maxlen == self._ring.maxlen:
                return
            old = list(self._ring)
            cut = max(0, len(old) - maxlen)
            if cut:
                self.dropped += cut
                _M_DROPPED.inc(cut)
            self._ring = deque(old[cut:], maxlen=maxlen)

    def record(self, entry: dict) -> dict:
        with self._lock:
            entry["seq"] = self._issued = next(self._seq)
            if len(self._ring) == self._ring.maxlen:
                # deque(maxlen) evicts silently; the journal must not —
                # an unaccounted drop would make a seq gap in the ring
                # indistinguishable from a lost event
                self.dropped += 1
                _M_DROPPED.inc()
            self._ring.append(entry)
        return entry

    def stats(self) -> dict:
        # one consistent snapshot: dropped/issued read OUTSIDE the lock
        # could tear against a concurrent evicting record(), breaking the
        # documented `first_seq - 1 == dropped` invariant readers check
        with self._lock:
            size = len(self._ring)
            first = self._ring[0]["seq"] if size else 0
            last = self._ring[-1]["seq"] if size else 0
            dropped = self.dropped
            issued = self._issued
        return {
            "capacity": self.capacity,
            "size": size,
            "dropped": dropped,
            "first_seq": first,
            "last_seq": last,
            # last seq ever handed out — unlike last_seq this survives
            # clear(), so drop accounting across a clear stays exact
            "issued": issued,
        }

    def list(
        self, kind: Optional[str] = None, limit: Optional[int] = None
    ) -> list[dict]:
        """Oldest-first snapshot, optionally filtered by kind and tailed
        to the newest ``limit`` entries."""
        with self._lock:
            out = list(self._ring)
        if kind is not None:
            out = [e for e in out if e["kind"] == kind]
        if limit is not None:
            # 0 means zero entries; negative is clamped to 0, never
            # "no limit" (out[-0:] would return the whole ring)
            out = out[-limit:] if limit > 0 else []
        return out

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


EVENT_STORE = EventStore()


def record_event(kind: str, table: Optional[str] = None, **attrs: Any) -> dict:
    """Append one typed event to the journal (and bump its counter).

    ``kind`` must be declared in ``EVENT_KINDS`` — an undeclared kind is
    a programming error and fails loudly HERE, at the emit site, instead
    of silently minting a new category no dashboard knows about. The
    active trace/request id (utils/tracectx) rides along so the event
    links back to the request's span tree and ledger; emit sites on
    background threads get it when the scheduler copied the requester's
    context onto the worker.
    """
    counter = _KIND_COUNTERS.get(kind)
    if counter is None:
        raise ValueError(
            f"undeclared event kind {kind!r}: add it to "
            "horaedb_tpu.utils.events.EVENT_KINDS (and document it)"
        )
    counter.inc()
    from .tracectx import get_request_id

    entry = {
        "timestamp": int(time.time() * 1000),
        "kind": kind,
        "table": table or "",
        "trace_id": get_request_id(),
        "attrs": attrs,
    }
    return EVENT_STORE.record(entry)


def render_attrs(attrs: dict) -> str:
    """Stable one-string rendering of an event's attrs for the SQL
    column (JSON, sorted keys; non-serializable values become strings)."""
    try:
        return json.dumps(attrs, sort_keys=True, default=str)
    except (TypeError, ValueError):
        return str(attrs)
