"""Server/engine configuration (ref: src/horaedb/src/config.rs).

One TOML file -> typed ``Config`` with unknown-key rejection (the
reference's serde ``deny_unknown_fields``), plus environment-variable
overrides for the deployment-varying fields (ref: bin/horaedb-server.rs
:89-102 overrides addr/meta/cluster from env).

    [server]
    http_port = 5440
    host = "127.0.0.1"

    [engine]
    data_dir = "/data/horaedb"
    wal = true                      # false = disable_data_wal semantics
    space_write_buffer_size = "256mb"
    compaction_l0_trigger = 4
    compaction_workers = 2          # background compaction pool size
    background_flush = true         # false = inline flush on the writer
    flush_workers = 2               # background flush pool size
    write_stall_immutable_count = 8   # frozen-memtable backpressure bound
    write_stall_immutable_bytes = "1gb"
    write_stall_deadline = "30s"      # stall wait before shedding 503

    [limits]
    slow_threshold = "1s"
    admission_slots = 8               # weighted admission slot units
    admission_queue_depth = 32        # bounded per-class wait queue
    admission_deadline = "5s"         # queue wait before shedding
    admission_memory_budget = "1gb"  # working-set budget for admits
    dedup = true                      # single-flight identical reads
    query_timeout = "60s"             # default per-query time budget
                                      # (0s = unbounded; header/session
                                      # knobs override per request)
    forward_timeout = "30s"           # per-hop cap for forwarded calls
                                      # (effective = min(cap, remaining))

    [wlm.batch]
    enabled = false                   # cohort batching (wlm/batch)
    window = "2ms"                    # micro-batching gather window
    max_cohort = 32                   # fused dispatch width ceiling
    shapes = []                       # substrings of normalized SQL
                                      # shapes eligible ([] = any
                                      # batchable aggregate SELECT)

    [observability]
    self_scrape = true                # node scrapes its own registry
    self_scrape_interval = "10s"      # into system_metrics.samples
    self_metrics_retention = "24h"    # 0s = keep forever
    event_ring = 512                  # bounded event-journal capacity
    decision_ring = 1024              # bounded decision-journal capacity
    profile_keys = 1024               # profile-aggregator LRU key bound
    trace_ring = 64                   # recent finished-trace ring
    trace_slow_ring = 256             # slow finished-trace ring
    slow_threshold = "1s"             # slow-trace/slow-log admission
                                      # (promoted from [limits]; either
                                      # location accepted, this one wins)

    [rules]
    enabled = true                    # continuous-query engine (rules/)
    eval_interval = "15s"             # rule + rollup evaluation cadence
    grace = "5s"                      # rollup bucket close grace (late rows)
    recording = ["error_rate := rate(errors_total[1m])"]
    alerts = ["HighErrors := rate(errors_total[1m]) > 5 for 30s"]
    rollup_tables = ["cpu"]           # maintain raw -> 1m -> 1h ladders
    rollup_raw_ttl = "24h"            # applied to each source (0s = leave)
    rollup_1m_ttl = "30d"
    rollup_1h_ttl = "0s"              # 0s = keep forever
    recording_ttl = "30d"             # recording-rule output tables

    [slo]
    objectives = ["cheap_p99 := histogram_quantile(0.99, rate(horaedb_query_class_duration_seconds_bucket{class=\"cheap\"}[1m])) <= 0.5 target 99%"]
    fast_window = "5m"                # fast burn-rate window
    slow_window = "1h"                # slow burn-rate window
    burn_threshold = 1.0              # burn on fast AND slow >= threshold

Env overrides: HORAEDB_HTTP_PORT, HORAEDB_HOST, HORAEDB_DATA_DIR.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Optional

try:
    import tomllib  # Python 3.11+
except ImportError:  # 3.10: fall back to the minimal subset parser below
    tomllib = None

from ..engine.options import parse_duration_ms, parse_size_bytes


class ConfigError(ValueError):
    pass


def _strip_toml_comment(line: str) -> str:
    """Drop a trailing ``# comment`` — only a ``#`` OUTSIDE quoted
    strings starts one (``"#"`` inside a value must survive)."""
    quote = None
    for i, ch in enumerate(line):
        if quote:
            if ch == quote:
                quote = None
        elif ch in ("'", '"'):
            quote = ch
        elif ch == "#":
            return line[:i]
    return line


def _minitoml_value(v: str, lineno: int) -> Any:
    import json

    if v.startswith("'") and v.endswith("'") and len(v) >= 2:
        return v[1:-1]  # TOML literal string: no escapes
    if v.startswith('"') or v.startswith("["):
        # quoted strings and inline string/number arrays are valid JSON
        try:
            return json.loads(v)
        except json.JSONDecodeError as e:
            raise ConfigError(f"bad TOML value at line {lineno}: {v!r}") from e
    if v in ("true", "false"):
        return v == "true"
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        raise ConfigError(f"bad TOML value at line {lineno}: {v!r}")


def _minitoml_loads(text: str) -> dict:
    """Minimal TOML subset parser (sections incl. dotted, key = value
    with strings / ints / floats / booleans / inline arrays) — only used
    when the stdlib ``tomllib`` is absent (Python < 3.11). Covers every
    shape this module documents; anything fancier errors loudly."""
    root: dict[str, Any] = {}
    cur = root
    for lineno, raw_line in enumerate(text.splitlines(), 1):
        line = _strip_toml_comment(raw_line).strip()
        if not line:
            continue
        if line.startswith("["):
            if not line.endswith("]"):
                raise ConfigError(f"bad TOML section at line {lineno}")
            cur = root
            for part in line[1:-1].strip().split("."):
                nxt = cur.setdefault(part.strip(), {})
                if not isinstance(nxt, dict):
                    raise ConfigError(
                        f"section {part!r} collides with a value (line {lineno})"
                    )
                cur = nxt
            continue
        key, eq, value = line.partition("=")
        if not eq:
            raise ConfigError(f"bad TOML line {lineno}: {raw_line!r}")
        cur[key.strip()] = _minitoml_value(value.strip(), lineno)
    return root


@dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    http_port: int = 5440  # ref default, config.rs:176
    # 0 = derive from http_port + remote.GRPC_PORT_OFFSET; -1 = disabled
    grpc_port: int = 0
    # MySQL / PostgreSQL wire listeners (ref defaults 3307 / 5433).
    # 0 = derive from http_port (+2000 / +3000); -1 = disabled
    mysql_port: int = 0
    pg_port: int = 0
    # when set, /admin/* and /debug/* require
    # "Authorization: Bearer <token>" (ref: proxy/src/auth/)
    auth_token: str = ""


@dataclass
class EngineSection:
    data_dir: Optional[str] = None  # None = in-memory
    wal: bool = True
    wal_backend: str = "disk"  # "disk" | "object_store" | "shared_log"
    space_write_buffer_size: int = 256 << 20
    compaction_l0_trigger: int = 4
    compaction_workers: int = 2
    # pipelined background flush + write-stall backpressure (engine/flush)
    background_flush: bool = True
    flush_workers: int = 2
    write_stall_immutable_count: int = 8
    write_stall_immutable_bytes: int = 1 << 30
    write_stall_deadline_s: float = 30.0


@dataclass
class LimitsConfig:
    slow_threshold_s: float = 1.0
    # workload manager (wlm/): weighted admission slots, bounded wait
    # queues with a deadline, a memory budget, and read dedup
    admission_slots: int = 8
    admission_queue_depth: int = 32
    admission_deadline_s: float = 5.0
    admission_memory_budget: int = 1 << 30
    dedup: bool = True
    # deadline propagation (utils/deadline): the default per-query time
    # budget when the client sent no X-HoraeDB-Timeout-Ms / session
    # knob (0 = unbounded); every layer charges it and forwarding hops
    # ship the REMAINING budget
    query_timeout_s: float = 60.0
    # per-hop ceiling for forwarded HTTP calls and remote RPCs — the
    # effective per-call timeout is min(forward_timeout, remaining
    # budget) instead of the old fixed 30s constants
    forward_timeout_s: float = 30.0


@dataclass
class BatchSection:
    """Cohort batching ([wlm.batch] — wlm/batch.CohortBatcher): in-flight
    SELECTs sharing one normalized plan shape but differing literals
    gather for a micro-batching window, then the whole cohort is served
    by ONE fused device dispatch (vmap over the query axis of the packed
    scan-agg kernel). Disabled by default: with ``enabled = false`` the
    proxy read path is bit-for-bit the pre-batching single-flight path."""

    enabled: bool = False
    window_s: float = 0.002  # gather window before the fused dispatch
    max_cohort: int = 32  # cohort width ceiling (vmap batch axis bound)
    # substrings matched against the normalized (literal-stripped) SQL
    # shape; non-empty restricts batching to the listed shapes
    shapes: list[str] = field(default_factory=list)


@dataclass
class WlmSection:
    """Workload-manager extensions beyond [limits] (which predates this
    section and keeps the admission/dedup knobs for compatibility)."""

    batch: BatchSection = field(default_factory=BatchSection)


@dataclass
class ObservabilitySection:
    """Self-monitoring (engine/metrics_recorder): the node periodically
    snapshots its own metrics registry into the real time-series table
    ``system_metrics.samples`` through the normal write path, bounded by
    ``self_metrics_retention`` (0 = unbounded)."""

    self_scrape: bool = True
    self_scrape_interval_s: float = 10.0
    self_metrics_retention_s: float = 24 * 3600.0
    # bounded event-journal (utils/events) ring capacity; drops are
    # accounted in horaedb_events_dropped_total and /debug/status
    event_ring: int = 512
    # bounded decision-journal (obs/decisions) ring capacity; drops are
    # accounted in horaedb_decision_dropped_total and every eviction of
    # an unresolved entry is a counted expiry
    decision_ring: int = 1024
    # profile plane (obs/profile): LRU bound on live (path, route,
    # shape) keys; evictions are exactly accounted in
    # horaedb_profile_dropped_total + the aggregator's evicted totals
    profile_keys: int = 1024
    # finished-trace rings (utils/tracectx.TRACE_STORE): recent + slow,
    # served as system.public.traces and /debug/trace
    trace_ring: int = 64
    trace_slow_ring: int = 256


@dataclass
class RulesSection:
    """Continuous queries (rules/): PromQL recording rules and alert
    rules in the compact ``NAME := EXPR [for 30s]`` line form, plus the
    tiered rollup ladder (raw -> 1m -> 1h with TTL laddering) for the
    listed source tables. All evaluated on one periodic loop; runtime
    additions via /admin/rules persist beside wlm_state.json."""

    enabled: bool = True
    eval_interval_s: float = 15.0
    grace_s: float = 5.0
    recording: list[str] = field(default_factory=list)
    alerts: list[str] = field(default_factory=list)
    rollup_tables: list[str] = field(default_factory=list)
    rollup_raw_ttl_s: float = 24 * 3600.0
    rollup_1m_ttl_s: float = 30 * 24 * 3600.0
    rollup_1h_ttl_s: float = 0.0
    recording_ttl_s: float = 30 * 24 * 3600.0


@dataclass
class SloSection:
    """Service-level objectives (slo/): each objective line declares a
    PromQL indicator over the node's own telemetry history
    (system_metrics.samples / query_stats) with a compliance bound and a
    good-time target; the evaluator rides the [rules] eval cadence and
    maintains fast/slow sliding burn-rate windows incrementally. Served
    as ``system.public.slo`` on every wire and at ``/debug/slo``."""

    objectives: list[str] = field(default_factory=list)
    fast_window_s: float = 5 * 60.0
    slow_window_s: float = 3600.0
    burn_threshold: float = 1.0


@dataclass
class ElasticSection:
    """Elastic shard management (meta/elastic): the coordinator reads the
    fleet's own telemetry history (``system.public.query_stats`` over the
    ordinary distributed read path) and emits guarded actions — per-shard
    read-replica scale-up/-down, load-aware rebalancing of the hottest
    shard off the most-loaded node with a pre-warmed cutover — through
    the same lease-fenced machinery the admin APIs use. Every action is
    railed: per-shard cooldown, a global per-round action budget,
    hysteresis (fast window scales out now; scale-in needs the slow
    window quiet too), a circuit breaker that quarantines a shard after
    repeated failed moves, and degraded-telemetry hold (stale or missing
    samples ⇒ no action). ``dry_run`` journals decisions as typed events
    without acting."""

    enabled: bool = False
    dry_run: bool = False
    # replica-count policy bounds (replaces the static --read-replicas)
    min_replicas: int = 0
    max_replicas: int = 2
    # per-shard read QPS thresholds, with SLO-burn-style dual windows:
    # scale-up triggers on the FAST window alone (a spike scales out
    # now); scale-in requires BOTH windows under the down threshold
    # (sustained quiet), so the two can never oscillate on a blip
    scale_up_qps: float = 50.0
    scale_down_qps: float = 5.0
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    # control-loop cadence + rails
    decide_interval_s: float = 15.0
    cooldown_s: float = 120.0  # per-shard: min time between actions
    action_budget: int = 2  # max actions applied per decision round
    quarantine_after: int = 3  # failed/reverted moves before the breaker opens
    node_stable_s: float = 30.0  # a (re)joined node must be online this
    # long before it attracts replicas or rebalance moves (flap guard)
    rebalance: bool = True  # load-aware move of the hottest shard
    min_move_qps: float = 1.0  # never move a shard colder than this
    # GLOBAL move cadence: after any move decision, no new move for this
    # long (<= 0 derives slow_window). Per-shard cooldowns alone cannot
    # stop churn — a loop cycling through shards moves SOMETHING every
    # round while each individual shard looks rested.
    move_cooldown_s: float = 0.0
    prewarm: bool = True  # target tails the manifest before cutover
    prewarm_timeout_s: float = 30.0
    telemetry_timeout_s: float = 3.0  # per-node query_stats poll timeout


@dataclass
class ClusterSection:
    enabled: bool = False
    self_endpoint: str = ""
    endpoints: list[str] = field(default_factory=list)
    # explicit table -> endpoint pins; unlisted tables hash over endpoints
    rules: dict[str, str] = field(default_factory=dict)
    # coordinator mode: meta server endpoints (overrides static routing)
    meta_endpoints: list[str] = field(default_factory=list)
    # follower read-replicas per shard (advisory on data nodes — the
    # coordinator's --read-replicas flag is authoritative; documented so
    # one config file can describe the whole deployment)
    read_replicas: int = 0
    # default bounded-staleness opt-in for follower reads: a query whose
    # range reaches past a follower's watermark may still be served there
    # when the follower lags by at most this much (0 = watermark-covered
    # ranges only; per-request override: X-HoraeDB-Read-Staleness)
    read_staleness_s: float = 0.0
    # [cluster.elastic] — the coordinator's self-driving control loop
    elastic: ElasticSection = field(default_factory=ElasticSection)


@dataclass
class S3Section:
    """Cloud object storage (ref: components/object_store s3.rs). When
    ``bucket`` is set the engine stores SSTs/manifests (and the
    object-store WAL) in S3 instead of the local disk; an optional
    CRC-paged disk cache fronts reads (disk_cache.rs analog)."""

    bucket: str = ""
    endpoint: str = ""
    region: str = "us-east-1"
    access_key: str = ""
    secret_key: str = ""
    prefix: str = ""
    disk_cache_dir: str = ""
    disk_cache_bytes: int = 1 << 30
    mem_cache_bytes: int = 256 << 20


@dataclass
class Config:
    server: ServerConfig = field(default_factory=ServerConfig)
    engine: EngineSection = field(default_factory=EngineSection)
    limits: LimitsConfig = field(default_factory=LimitsConfig)
    wlm: WlmSection = field(default_factory=WlmSection)
    observability: ObservabilitySection = field(
        default_factory=ObservabilitySection
    )
    rules: RulesSection = field(default_factory=RulesSection)
    slo: SloSection = field(default_factory=SloSection)
    cluster: ClusterSection = field(default_factory=ClusterSection)
    s3: S3Section = field(default_factory=S3Section)

    @staticmethod
    def load(path: Optional[str] = None) -> "Config":
        raw: dict[str, Any] = {}
        if path is not None:
            if tomllib is not None:
                with open(path, "rb") as f:
                    raw = tomllib.load(f)
            else:
                with open(path, "r", encoding="utf-8") as f:
                    raw = _minitoml_loads(f.read())
        cfg = Config()
        _apply(cfg, raw)
        _apply_env(cfg)
        return cfg


_KNOWN = {
    "server": {
        "host", "http_port", "grpc_port", "mysql_port", "pg_port", "auth_token",
    },
    "engine": {
        "data_dir", "wal", "wal_backend",
        "space_write_buffer_size", "compaction_l0_trigger",
        "compaction_workers", "background_flush", "flush_workers",
        "write_stall_immutable_count", "write_stall_immutable_bytes",
        "write_stall_deadline",
    },
    "limits": {
        "slow_threshold", "admission_slots", "admission_queue_depth",
        "admission_deadline", "admission_memory_budget", "dedup",
        "query_timeout", "forward_timeout",
    },
    "wlm": {"batch"},
    "observability": {
        "self_scrape", "self_scrape_interval", "self_metrics_retention",
        "event_ring", "decision_ring", "profile_keys",
        "trace_ring", "trace_slow_ring", "slow_threshold",
    },
    "rules": {
        "enabled", "eval_interval", "grace", "recording", "alerts",
        "rollup_tables", "rollup_raw_ttl", "rollup_1m_ttl",
        "rollup_1h_ttl", "recording_ttl",
    },
    "slo": {
        "objectives", "fast_window", "slow_window", "burn_threshold",
    },
    "cluster": {
        "self_endpoint", "endpoints", "rules", "meta_endpoints",
        "read_replicas", "read_staleness", "elastic",
    },
    "s3": {
        "bucket", "endpoint", "region", "access_key", "secret_key", "prefix",
        "disk_cache_dir", "disk_cache_bytes", "mem_cache_bytes",
    },
}


def _apply(cfg: Config, raw: dict) -> None:
    unknown_sections = set(raw) - set(_KNOWN)
    if unknown_sections:
        raise ConfigError(f"unknown config section(s): {sorted(unknown_sections)}")
    for section, keys in raw.items():
        if not isinstance(keys, dict):
            raise ConfigError(f"section [{section}] must be a table")
        unknown = set(keys) - _KNOWN[section]
        if unknown:
            raise ConfigError(
                f"unknown key(s) in [{section}]: {sorted(unknown)}"
            )
    s = raw.get("server", {})
    if "host" in s:
        cfg.server.host = str(s["host"])
    if "http_port" in s:
        cfg.server.http_port = int(s["http_port"])
    if "grpc_port" in s:
        cfg.server.grpc_port = int(s["grpc_port"])
    if "mysql_port" in s:
        cfg.server.mysql_port = int(s["mysql_port"])
    if "pg_port" in s:
        cfg.server.pg_port = int(s["pg_port"])
    if "auth_token" in s:
        cfg.server.auth_token = str(s["auth_token"])
    e = raw.get("engine", {})
    if "data_dir" in e:
        cfg.engine.data_dir = str(e["data_dir"]) or None
    if "wal" in e:
        if not isinstance(e["wal"], bool):
            raise ConfigError("engine.wal must be a boolean")
        cfg.engine.wal = e["wal"]
    if "wal_backend" in e:
        if e["wal_backend"] not in ("disk", "object_store", "shared_log"):
            raise ConfigError(
                "engine.wal_backend must be 'disk', 'object_store' or 'shared_log'"
            )
        cfg.engine.wal_backend = str(e["wal_backend"])
    if "space_write_buffer_size" in e:
        cfg.engine.space_write_buffer_size = parse_size_bytes(e["space_write_buffer_size"])
    if "compaction_l0_trigger" in e:
        cfg.engine.compaction_l0_trigger = int(e["compaction_l0_trigger"])
    if "compaction_workers" in e:
        cfg.engine.compaction_workers = int(e["compaction_workers"])
    if "background_flush" in e:
        if not isinstance(e["background_flush"], bool):
            raise ConfigError("engine.background_flush must be a boolean")
        cfg.engine.background_flush = e["background_flush"]
    if "flush_workers" in e:
        cfg.engine.flush_workers = int(e["flush_workers"])
    if "write_stall_immutable_count" in e:
        cfg.engine.write_stall_immutable_count = int(
            e["write_stall_immutable_count"]
        )
    if "write_stall_immutable_bytes" in e:
        cfg.engine.write_stall_immutable_bytes = parse_size_bytes(
            e["write_stall_immutable_bytes"]
        )
    if "write_stall_deadline" in e:
        cfg.engine.write_stall_deadline_s = (
            parse_duration_ms(e["write_stall_deadline"]) / 1000.0
        )
    l = raw.get("limits", {})
    if "slow_threshold" in l:
        cfg.limits.slow_threshold_s = parse_duration_ms(l["slow_threshold"]) / 1000.0
    if "admission_slots" in l:
        cfg.limits.admission_slots = int(l["admission_slots"])
    if "admission_queue_depth" in l:
        cfg.limits.admission_queue_depth = int(l["admission_queue_depth"])
    if "admission_deadline" in l:
        cfg.limits.admission_deadline_s = (
            parse_duration_ms(l["admission_deadline"]) / 1000.0
        )
    if "admission_memory_budget" in l:
        cfg.limits.admission_memory_budget = parse_size_bytes(
            l["admission_memory_budget"]
        )
    if "dedup" in l:
        if not isinstance(l["dedup"], bool):
            raise ConfigError("limits.dedup must be a boolean")
        cfg.limits.dedup = l["dedup"]
    if "query_timeout" in l:
        cfg.limits.query_timeout_s = (
            parse_duration_ms(l["query_timeout"]) / 1000.0
        )
        if cfg.limits.query_timeout_s < 0:
            raise ConfigError("limits.query_timeout must be >= 0 (0 = unbounded)")
    if "forward_timeout" in l:
        cfg.limits.forward_timeout_s = (
            parse_duration_ms(l["forward_timeout"]) / 1000.0
        )
        if cfg.limits.forward_timeout_s <= 0:
            raise ConfigError("limits.forward_timeout must be positive")
    w = raw.get("wlm", {})
    if "batch" in w:
        _apply_batch(cfg.wlm.batch, w["batch"])
    o = raw.get("observability", {})
    if "self_scrape" in o:
        if not isinstance(o["self_scrape"], bool):
            raise ConfigError("observability.self_scrape must be a boolean")
        cfg.observability.self_scrape = o["self_scrape"]
    if "self_scrape_interval" in o:
        cfg.observability.self_scrape_interval_s = (
            parse_duration_ms(o["self_scrape_interval"]) / 1000.0
        )
        if cfg.observability.self_scrape_interval_s <= 0:
            raise ConfigError(
                "observability.self_scrape_interval must be positive"
            )
    if "self_metrics_retention" in o:
        cfg.observability.self_metrics_retention_s = (
            parse_duration_ms(o["self_metrics_retention"]) / 1000.0
        )
    if "event_ring" in o:
        cfg.observability.event_ring = int(o["event_ring"])
        if cfg.observability.event_ring < 1:
            raise ConfigError("observability.event_ring must be >= 1")
    if "decision_ring" in o:
        cfg.observability.decision_ring = int(o["decision_ring"])
        if cfg.observability.decision_ring < 1:
            raise ConfigError("observability.decision_ring must be >= 1")
    if "profile_keys" in o:
        cfg.observability.profile_keys = int(o["profile_keys"])
        if cfg.observability.profile_keys < 1:
            raise ConfigError("observability.profile_keys must be >= 1")
    if "trace_ring" in o:
        cfg.observability.trace_ring = int(o["trace_ring"])
        if cfg.observability.trace_ring < 1:
            raise ConfigError("observability.trace_ring must be >= 1")
    if "trace_slow_ring" in o:
        cfg.observability.trace_slow_ring = int(o["trace_slow_ring"])
        if cfg.observability.trace_slow_ring < 1:
            raise ConfigError("observability.trace_slow_ring must be >= 1")
    if "slow_threshold" in o:
        # promoted from [limits] (ISSUE 20 satellite): the proxy's slow
        # trace/slow-log admission is an observability knob; when both
        # sections set it, [observability] wins (applied after [limits])
        cfg.limits.slow_threshold_s = (
            parse_duration_ms(o["slow_threshold"]) / 1000.0
        )
        if cfg.limits.slow_threshold_s <= 0:
            raise ConfigError("observability.slow_threshold must be positive")
    ru = raw.get("rules", {})
    if "enabled" in ru:
        if not isinstance(ru["enabled"], bool):
            raise ConfigError("rules.enabled must be a boolean")
        cfg.rules.enabled = ru["enabled"]
    if "eval_interval" in ru:
        cfg.rules.eval_interval_s = parse_duration_ms(ru["eval_interval"]) / 1000.0
        if cfg.rules.eval_interval_s <= 0:
            raise ConfigError("rules.eval_interval must be positive")
    if "grace" in ru:
        cfg.rules.grace_s = parse_duration_ms(ru["grace"]) / 1000.0
        if cfg.rules.grace_s < 0:
            raise ConfigError("rules.grace must be >= 0")
    for key in ("recording", "alerts", "rollup_tables"):
        if key in ru:
            v = ru[key]
            if not isinstance(v, list) or not all(isinstance(x, str) for x in v):
                raise ConfigError(f"rules.{key} must be a list of strings")
            setattr(cfg.rules, key, list(v))
    for key, attr in (
        ("rollup_raw_ttl", "rollup_raw_ttl_s"),
        ("rollup_1m_ttl", "rollup_1m_ttl_s"),
        ("rollup_1h_ttl", "rollup_1h_ttl_s"),
        ("recording_ttl", "recording_ttl_s"),
    ):
        if key in ru:
            setattr(cfg.rules, attr, parse_duration_ms(ru[key]) / 1000.0)
    if ru:
        # rule lines fail HERE, at load, not at the first evaluation
        from ..rules.model import RuleError, parse_rule_line

        try:
            for line in cfg.rules.recording:
                parse_rule_line(line, "recording")
            for line in cfg.rules.alerts:
                parse_rule_line(line, "alert")
        except RuleError as e:
            raise ConfigError(f"[rules]: {e}") from None
    sl = raw.get("slo", {})
    if "objectives" in sl:
        v = sl["objectives"]
        if not isinstance(v, list) or not all(isinstance(x, str) for x in v):
            raise ConfigError("slo.objectives must be a list of strings")
        cfg.slo.objectives = list(v)
    for key, attr in (
        ("fast_window", "fast_window_s"),
        ("slow_window", "slow_window_s"),
    ):
        if key in sl:
            setattr(cfg.slo, attr, parse_duration_ms(sl[key]) / 1000.0)
            if getattr(cfg.slo, attr) <= 0:
                raise ConfigError(f"slo.{key} must be positive")
    if "burn_threshold" in sl:
        cfg.slo.burn_threshold = float(sl["burn_threshold"])
        if cfg.slo.burn_threshold <= 0:
            raise ConfigError("slo.burn_threshold must be positive")
    if sl:
        if cfg.slo.fast_window_s > cfg.slo.slow_window_s:
            raise ConfigError("slo.fast_window must be <= slo.slow_window")
        # objective lines fail HERE, at load, not at the first evaluation
        from ..slo.model import SloError, parse_objective_line

        try:
            seen = set()
            for line in cfg.slo.objectives:
                obj = parse_objective_line(line)
                if obj.name in seen:
                    raise SloError(f"duplicate objective name {obj.name!r}")
                seen.add(obj.name)
        except SloError as e:
            raise ConfigError(f"[slo]: {e}") from None
    s3 = raw.get("s3", {})
    if s3:
        for k in ("bucket", "endpoint", "region", "access_key", "secret_key",
                  "prefix", "disk_cache_dir"):
            if k in s3:
                setattr(cfg.s3, k, str(s3[k]))
        for k in ("disk_cache_bytes", "mem_cache_bytes"):
            if k in s3:
                setattr(cfg.s3, k, parse_size_bytes(s3[k]))
        if not cfg.s3.bucket or not cfg.s3.endpoint:
            raise ConfigError("[s3] requires both bucket and endpoint")
    c = raw.get("cluster", {})
    if c:
        cfg.cluster.enabled = True
        cfg.cluster.self_endpoint = str(c.get("self_endpoint", ""))
        eps = c.get("endpoints", [])
        if not isinstance(eps, list) or not all(isinstance(e, str) for e in eps):
            raise ConfigError("cluster.endpoints must be a list of strings")
        cfg.cluster.endpoints = eps
        rules = c.get("rules", {})
        if not isinstance(rules, dict):
            raise ConfigError("cluster.rules must be a table of table -> endpoint")
        cfg.cluster.rules = {str(k): str(v) for k, v in rules.items()}
        meps = c.get("meta_endpoints", [])
        if not isinstance(meps, list) or not all(isinstance(e, str) for e in meps):
            raise ConfigError("cluster.meta_endpoints must be a list of strings")
        cfg.cluster.meta_endpoints = meps
        if "read_replicas" in c:
            cfg.cluster.read_replicas = int(c["read_replicas"])
            if cfg.cluster.read_replicas < 0:
                raise ConfigError("cluster.read_replicas must be >= 0")
        if "read_staleness" in c:
            cfg.cluster.read_staleness_s = (
                parse_duration_ms(c["read_staleness"]) / 1000.0
            )
            if cfg.cluster.read_staleness_s < 0:
                raise ConfigError("cluster.read_staleness must be >= 0")
        if "elastic" in c:
            _apply_elastic(cfg.cluster.elastic, c["elastic"])
        if not cfg.cluster.self_endpoint:
            raise ConfigError("cluster.self_endpoint is required in [cluster]")
        if not meps and not eps:
            raise ConfigError(
                "[cluster] needs either meta_endpoints (coordinator mode) "
                "or endpoints (static mode)"
            )


_ELASTIC_KEYS = {
    "enabled", "dry_run", "min_replicas", "max_replicas", "scale_up_qps",
    "scale_down_qps", "fast_window", "slow_window", "decide_interval",
    "cooldown", "action_budget", "quarantine_after", "node_stable",
    "rebalance", "min_move_qps", "prewarm", "prewarm_timeout",
    "move_cooldown",
}


def _apply_elastic(es: ElasticSection, raw: Any) -> None:
    """[cluster.elastic] — validated at load like every other section; a
    typo'd knob or an oscillation-prone threshold pair fails HERE, not
    at the first decision round."""
    if not isinstance(raw, dict):
        raise ConfigError("cluster.elastic must be a table")
    unknown = set(raw) - _ELASTIC_KEYS
    if unknown:
        raise ConfigError(
            f"unknown key(s) in [cluster.elastic]: {sorted(unknown)}"
        )
    for key in ("enabled", "dry_run", "rebalance", "prewarm"):
        if key in raw:
            if not isinstance(raw[key], bool):
                raise ConfigError(f"cluster.elastic.{key} must be a boolean")
            setattr(es, key, raw[key])
    for key in ("min_replicas", "max_replicas", "action_budget",
                "quarantine_after"):
        if key in raw:
            setattr(es, key, int(raw[key]))
    for key, attr in (
        ("fast_window", "fast_window_s"),
        ("slow_window", "slow_window_s"),
        ("decide_interval", "decide_interval_s"),
        ("cooldown", "cooldown_s"),
        ("node_stable", "node_stable_s"),
        ("prewarm_timeout", "prewarm_timeout_s"),
        ("move_cooldown", "move_cooldown_s"),
    ):
        if key in raw:
            setattr(es, attr, parse_duration_ms(raw[key]) / 1000.0)
    for key in ("scale_up_qps", "scale_down_qps", "min_move_qps"):
        if key in raw:
            setattr(es, key, float(raw[key]))
    if es.min_replicas < 0:
        raise ConfigError("cluster.elastic.min_replicas must be >= 0")
    if es.max_replicas < es.min_replicas:
        raise ConfigError(
            "cluster.elastic.max_replicas must be >= min_replicas"
        )
    if es.scale_down_qps >= es.scale_up_qps:
        # equal thresholds would let one borderline sample scale out and
        # back in on alternating rounds — the hysteresis gap is mandatory
        raise ConfigError(
            "cluster.elastic.scale_down_qps must be < scale_up_qps"
        )
    if es.fast_window_s <= 0 or es.slow_window_s < es.fast_window_s:
        raise ConfigError(
            "cluster.elastic windows need 0 < fast_window <= slow_window"
        )
    if es.decide_interval_s <= 0:
        raise ConfigError("cluster.elastic.decide_interval must be positive")
    if es.action_budget < 1:
        raise ConfigError("cluster.elastic.action_budget must be >= 1")
    if es.quarantine_after < 1:
        raise ConfigError("cluster.elastic.quarantine_after must be >= 1")


_BATCH_KEYS = {"enabled", "window", "max_cohort", "shapes"}


def _apply_batch(bs: BatchSection, raw: Any) -> None:
    """[wlm.batch] — validated at load like every other section."""
    if not isinstance(raw, dict):
        raise ConfigError("wlm.batch must be a table")
    unknown = set(raw) - _BATCH_KEYS
    if unknown:
        raise ConfigError(f"unknown key(s) in [wlm.batch]: {sorted(unknown)}")
    if "enabled" in raw:
        if not isinstance(raw["enabled"], bool):
            raise ConfigError("wlm.batch.enabled must be a boolean")
        bs.enabled = raw["enabled"]
    if "window" in raw:
        bs.window_s = parse_duration_ms(raw["window"]) / 1000.0
        if bs.window_s <= 0:
            raise ConfigError("wlm.batch.window must be positive")
    if "max_cohort" in raw:
        bs.max_cohort = int(raw["max_cohort"])
        if bs.max_cohort < 2:
            # a 1-wide "cohort" is just the solo path plus a window wait
            raise ConfigError("wlm.batch.max_cohort must be >= 2")
    if "shapes" in raw:
        v = raw["shapes"]
        if not isinstance(v, list) or not all(isinstance(x, str) for x in v):
            raise ConfigError("wlm.batch.shapes must be a list of strings")
        bs.shapes = list(v)


def _apply_env(cfg: Config) -> None:
    if v := os.environ.get("HORAEDB_HTTP_PORT"):
        cfg.server.http_port = int(v)
    if v := os.environ.get("HORAEDB_HOST"):
        cfg.server.host = v
    if v := os.environ.get("HORAEDB_DATA_DIR"):
        cfg.engine.data_dir = v
