"""Per-request deadline propagation + cooperative cancellation — every
query carries a time budget from wire to kernel (ref: the reference
proxy's Context deadline threading through proxy/route/remote engine;
the reference forwards `timeout` in its RPC contexts instead of a fixed
per-hop constant).

One ``Deadline`` rides a ContextVar beside the trace (utils/tracectx)
and the cost ledger (utils/querystats): the gateway parses
``X-HoraeDB-Timeout-Ms`` (or the MySQL/PG session knob, or the
``[limits] query_timeout`` default) at ingress, the proxy opens the
scope, and every layer *charges* it —

- admission queue wait counts against the budget and sheds immediately
  when the remaining budget cannot fit the shape's expected cost
  (wlm/admission);
- the executor observes ``checkpoint()`` at cheap points (per scan
  batch / SST read, per partial-agg window, before each device
  dispatch);
- remote RPC envelopes and forwarding hops send the *remaining* budget
  as their per-call timeout (remote/client, server/http, cluster/
  meta_client) and the receiving side refuses already-expired work;
- object-store waits cap at ``min(op_cap, remaining)``.

Cooperative cancellation rides the same object: a live-query registry
(served as ``system.public.queries`` on every wire) lets
``KILL QUERY <id>`` / ``horaectl query kill`` / ``DELETE
/debug/queries/{id}`` (and a client disconnect) flip the cancel flag,
which the SAME checkpoints observe. The hard invariant: a cancelled or
expired query always releases its admission slots (the admit context
manager's finally), its dedup flight (leader finally; followers get a
typed retryable error, wlm/dedup) and its cohort membership (a
cancelled member demuxes out, the cohort survives — wlm/batch).

Typed errors map to all three wire protocols: ``DeadlineExceeded`` →
HTTP 504 + Retry-After, MySQL 1317/SQLSTATE 70100, PG SQLSTATE 57014;
``QueryCancelled`` → HTTP 499-style, same native codes.

Registry discipline (the PR-2 contract): the families below are
declared in ``DEADLINE_METRIC_FAMILIES`` / ``CANCEL_METRIC_FAMILIES``,
eagerly registered, documented in docs/OBSERVABILITY.md, and linted in
tests/test_observability.py (no stray ``horaedb_query_deadline_*`` /
``horaedb_query_cancel*`` family may exist outside them).
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from contextlib import contextmanager
from typing import Any, Optional

from .metrics import REGISTRY

# Stages a budget can die at — the label set of the expiry counter and
# the `stage` attr of `query_timeout` events. "ingress" = already
# expired on arrival (a forwarded hop received <= 0 remaining);
# "queued" = the admission wait ate the budget (or the remaining budget
# could not fit the expected cost); "executing" = an executor/scan/agg
# checkpoint; "dispatch" = just before a device dispatch; "remote" =
# the remote-engine client/server hop; "forward" = an HTTP forwarding
# hop; "store" = an object-store read wait.
DEADLINE_STAGES = (
    "ingress", "queued", "executing", "dispatch", "remote", "forward",
    "store",
)

CANCEL_SOURCES = ("kill", "disconnect")

# rides a gRPC DEADLINE_EXCEEDED status detail when (and only when) the
# serving side refused or stopped work against the SHIPPED budget — the
# remote client maps marked errors back to the typed DeadlineExceeded
# (same discipline as wlm.admission.SHED_MARKER)
DEADLINE_MARKER = "deadline exceeded"

# family -> help; single source of truth the registry lint walks. The
# ledger-derived families (horaedb_query_deadline_ms_total from the
# `deadline_ms` field, horaedb_query_cancelled_total from `cancelled`)
# share the prefixes and are declared here too so the no-stray check
# has one complete inventory.
DEADLINE_METRIC_FAMILIES: dict[str, str] = {
    "horaedb_query_deadline_expired_total":
        "queries whose time budget expired, by the stage that observed it",
    "horaedb_query_deadline_budget_seconds":
        "per-request time budgets observed at proxy ingress",
    "horaedb_query_deadline_ms_total":
        "summed per-request deadline budgets (ledger field deadline_ms)",
}
CANCEL_METRIC_FAMILIES: dict[str, str] = {
    "horaedb_query_cancel_total":
        "cooperative query cancellations, by source (kill/disconnect)",
    "horaedb_query_cancelled_total":
        "queries that surfaced QueryCancelled (ledger field cancelled)",
}

# Eager registration: the labeled series exist from the first scrape
# (same discipline as the admission/event families). The two
# ledger-derived families register in utils/querystats.
_M_EXPIRED = {
    stage: REGISTRY.counter(
        "horaedb_query_deadline_expired_total",
        DEADLINE_METRIC_FAMILIES["horaedb_query_deadline_expired_total"],
        labels={"stage": stage},
    )
    for stage in DEADLINE_STAGES
}
_M_BUDGET = REGISTRY.histogram(
    "horaedb_query_deadline_budget_seconds",
    DEADLINE_METRIC_FAMILIES["horaedb_query_deadline_budget_seconds"],
)
_M_CANCEL = {
    src: REGISTRY.counter(
        "horaedb_query_cancel_total",
        CANCEL_METRIC_FAMILIES["horaedb_query_cancel_total"],
        labels={"source": src},
    )
    for src in CANCEL_SOURCES
}


class DeadlineExceeded(RuntimeError):
    """The query's time budget ran out. Retryable by contract — the
    node is healthy, the budget was just too small for the load (HTTP
    maps it to 504 + Retry-After, MySQL to 1317/SQLSTATE 70100, PG to
    SQLSTATE 57014)."""

    retryable = True

    def __init__(self, msg: str, stage: str = "executing",
                 budget_ms: Optional[float] = None,
                 retry_after_s: float = 1.0) -> None:
        super().__init__(msg)
        self.stage = stage if stage in DEADLINE_STAGES else "executing"
        self.budget_ms = budget_ms
        self.retry_after_s = retry_after_s


class QueryCancelled(RuntimeError):
    """The query was cooperatively cancelled (KILL QUERY / horaectl
    query kill / DELETE /debug/queries/{id} / client disconnect). Not
    retryable: someone asked for this work to stop."""

    retryable = False

    def __init__(self, msg: str, query_id: Optional[int] = None,
                 source: str = "kill") -> None:
        super().__init__(msg)
        self.query_id = query_id
        self.source = source if source in CANCEL_SOURCES else "kill"


class Deadline:
    """One request's time budget + cancel flag. ``budget_ms`` None means
    unbounded (cancellation still observed). Thread-safe by design: the
    fields checkpoints read are set-once/monotonic (a torn read of
    ``_cancelled`` only delays the observation to the next checkpoint).
    """

    __slots__ = ("budget_ms", "started", "_deadline_at", "_cancelled",
                 "cancel_source", "state", "proto")

    def __init__(self, budget_ms: Optional[float] = None,
                 proto: str = "sql") -> None:
        if budget_ms is not None and budget_ms <= 0:
            budget_ms = None
        self.budget_ms = budget_ms
        self.started = time.monotonic()
        self._deadline_at = (
            None if budget_ms is None else self.started + budget_ms / 1000.0
        )
        self._cancelled = False
        self.cancel_source = ""
        # coarse live-query state for system.public.queries
        # (running -> queued -> executing as the layers report in)
        self.state = "running"
        # which wire the request came in on (system.public.queries'
        # protocol column; the gateway stamps http/mysql/postgres)
        self.proto = proto

    # ---- budget ----------------------------------------------------------
    def remaining_s(self) -> Optional[float]:
        """Seconds left, or None when unbounded. May be <= 0."""
        if self._deadline_at is None:
            return None
        return self._deadline_at - time.monotonic()

    def remaining_ms(self) -> Optional[int]:
        rem = self.remaining_s()
        return None if rem is None else int(rem * 1000)

    def expired(self) -> bool:
        rem = self.remaining_s()
        return rem is not None and rem <= 0

    def elapsed_ms(self) -> float:
        return (time.monotonic() - self.started) * 1000.0

    # ---- cancellation ----------------------------------------------------
    def cancel(self, source: str = "kill") -> None:
        if not self._cancelled:
            self._cancelled = True
            self.cancel_source = source if source in CANCEL_SOURCES else "kill"

    def cancelled(self) -> bool:
        return self._cancelled

    # ---- the checkpoint --------------------------------------------------
    def check(self, stage: str = "executing") -> None:
        """Raise the typed error when cancelled or out of budget; the
        caller's cleanup (admission slot release, dedup flight pop,
        cohort demux) runs in the ordinary finally/except unwinding."""
        if self._cancelled:
            raise QueryCancelled(
                "query cancelled cooperatively "
                f"({self.cancel_source or 'kill'})",
                source=self.cancel_source or "kill",
            )
        rem = self.remaining_s()
        if rem is not None and rem <= 0:
            counter = _M_EXPIRED.get(stage)
            if counter is not None:
                counter.inc()
            raise DeadlineExceeded(
                f"query exceeded its {self.budget_ms:.0f}ms time budget "
                f"(observed at {stage})",
                stage=stage,
                budget_ms=self.budget_ms,
            )

    def cap_timeout(self, op_cap_s: float) -> float:
        """``min(op_cap, remaining)`` for a blocking sub-operation's
        timeout — never below a small positive floor so a just-expiring
        budget surfaces as a typed deadline error at the next
        checkpoint, not as an opaque 0-second transport failure."""
        rem = self.remaining_s()
        if rem is None:
            return op_cap_s
        return max(0.05, min(op_cap_s, rem))


_current_deadline: contextvars.ContextVar[Optional[Deadline]] = (
    contextvars.ContextVar("horaedb_deadline", default=None)
)


def current_deadline() -> Optional[Deadline]:
    return _current_deadline.get()


def checkpoint(stage: str = "executing") -> None:
    """The cooperative checkpoint: a cheap no-op outside a deadline
    scope (one ContextVar read), a typed raise when the current query is
    cancelled or out of budget."""
    d = _current_deadline.get()
    if d is not None:
        d.check(stage)


def cap_timeout(op_cap_s: float) -> float:
    """min(op_cap, remaining budget) — the per-call timeout every
    outbound hop (forward, RPC, store wait) should use instead of a
    fixed constant. Without an active deadline, the cap itself."""
    d = _current_deadline.get()
    return op_cap_s if d is None else d.cap_timeout(op_cap_s)


def bind(deadline: Optional[Deadline]) -> contextvars.Context:
    """A context COPY with ``deadline`` installed — for running a
    callable on an executor thread under the budget without changing
    the callable's signature (``loop.run_in_executor(None, ctx.run,
    fn)``); the caller's own context is left untouched."""
    token = _current_deadline.set(deadline)
    try:
        return contextvars.copy_context()
    finally:
        _current_deadline.reset(token)


@contextmanager
def deadline_scope(deadline: Optional[Deadline]):
    """Install ``deadline`` as the current scope (None = explicit
    no-budget scope, shadowing any outer one)."""
    token = _current_deadline.set(deadline)
    try:
        yield deadline
    finally:
        _current_deadline.reset(token)


@contextmanager
def serving_deadline(deadline_ms: Optional[float], stage: str = "remote"):
    """Serve an RPC/forwarded request under the origin's REMAINING
    budget. ``deadline_ms`` <= 0 means the work was already expired on
    arrival — refuse it before doing anything (the typed error maps to
    the wire; the origin's own checkpoint fires regardless)."""
    if deadline_ms is None:
        yield None
        return
    if deadline_ms <= 0:
        counter = _M_EXPIRED.get("ingress")
        if counter is not None:
            counter.inc()
        raise DeadlineExceeded(
            "request arrived with an exhausted time budget",
            stage="ingress",
            budget_ms=float(deadline_ms),
        )
    d = Deadline(float(deadline_ms))
    with deadline_scope(d):
        yield d


def observe_budget(budget_ms: Optional[float]) -> None:
    """Record a request's ingress budget into the histogram (and the
    ledger's ``deadline_ms`` field via the caller)."""
    if budget_ms is not None and budget_ms > 0:
        _M_BUDGET.observe(budget_ms / 1000.0)


def note_expired(stage: str) -> None:
    """Count one budget expiry observed outside a Deadline.check (e.g.
    a wire front end refusing an explicit zero budget on arrival)."""
    counter = _M_EXPIRED.get(stage)
    if counter is not None:
        counter.inc()


def note_cancel(source: str) -> None:
    counter = _M_CANCEL.get(source if source in CANCEL_SOURCES else "kill")
    if counter is not None:
        counter.inc()


# ---- live-query registry ---------------------------------------------------


class _LiveQuery:
    __slots__ = ("query_id", "request_id", "sql", "tenant", "protocol",
                 "admission_class", "started_at", "deadline")

    def __init__(self, query_id: int, request_id, sql: str, tenant: str,
                 protocol: str, deadline: Deadline) -> None:
        self.query_id = query_id
        self.request_id = request_id
        self.sql = sql
        self.tenant = tenant
        self.protocol = protocol
        self.admission_class = ""
        self.started_at = time.time()
        self.deadline = deadline


class LiveQueryRegistry:
    """Every in-flight proxy statement, keyed by a process-global query
    id — the KILL QUERY / horaectl query kill / DELETE
    /debug/queries/{id} target, served as ``system.public.queries``.
    Registration is cheap (one dict insert under a lock); a query that
    never deregisters cannot exist — the proxy's finally owns it."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._live: dict[int, _LiveQuery] = {}

    def register(self, request_id, sql: str, tenant: str,
                 deadline: Deadline, protocol: str = "sql") -> _LiveQuery:
        entry = _LiveQuery(
            next(self._ids), request_id, sql, tenant, protocol, deadline
        )
        with self._lock:
            self._live[entry.query_id] = entry
        return entry

    def deregister(self, entry: _LiveQuery) -> None:
        with self._lock:
            self._live.pop(entry.query_id, None)

    def kill(self, query_id: int, source: str = "kill") -> bool:
        """Flip the cancel flag on a live query. True when the id was
        live (the query unwinds at its next checkpoint); False when no
        such query is running here."""
        with self._lock:
            entry = self._live.get(int(query_id))
        if entry is None:
            return False
        entry.deadline.cancel(source)
        note_cancel(source)
        return True

    def get(self, query_id: int) -> Optional[_LiveQuery]:
        with self._lock:
            return self._live.get(int(query_id))

    def list(self) -> list[dict[str, Any]]:
        """Snapshot rows for system.public.queries / /debug/queries."""
        with self._lock:
            entries = list(self._live.values())
        out = []
        for e in entries:
            d = e.deadline
            rem = d.remaining_ms()
            out.append(
                {
                    "query_id": e.query_id,
                    "request_id": e.request_id or 0,
                    "sql": e.sql[:200],
                    "tenant": e.tenant,
                    "protocol": e.protocol,
                    "class": e.admission_class,
                    "state": (
                        "cancelled" if d.cancelled() else d.state
                    ),
                    "started_ms": int(e.started_at * 1000),
                    "elapsed_ms": round(d.elapsed_ms(), 3),
                    "deadline_ms": int(d.budget_ms or 0),
                    "remaining_ms": -1 if rem is None else rem,
                    "cancelled": 1 if d.cancelled() else 0,
                }
            )
        out.sort(key=lambda r: r["query_id"])
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._live)


QUERY_REGISTRY = LiveQueryRegistry()
