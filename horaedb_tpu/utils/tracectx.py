"""Hierarchical request tracing — a span tree follows the query across
threads and nodes (ref: trace_metric's MetricsCollector span trees +
RemoteTaskContext.remote_metrics carrying EXPLAIN ANALYZE data home;
RequestId in common_types).

A ContextVar pair holds the current ``Trace`` and ``Span``; the proxy
starts one trace per SQL statement and runs the executor inside a copied
context so priority-pool threads observe it. ``span("name", **attrs)``
opens a child of the current span (a cheap no-op when no trace is
active — the hot path pays O(spans) only while a sink is attached).

Cross-node: ``wire_context()`` serializes ``(trace_id, parent_span_id)``
into the RPC envelope; the owning node serves the call under
``serving_trace(...)`` and ships its finished subtree back in the
response, where ``graft(...)`` attaches it to the coordinator's tree —
one request id correlates the coordinator's slow-log/EXPLAIN ANALYZE
tree with every remote span it fanned out.

Finished traces land in the bounded in-process ``TRACE_STORE`` (ring of
recent + ring of slow), surfaced at /debug/trace and
/debug/trace/{request_id}.
"""

from __future__ import annotations

import contextvars
import itertools
import threading
import time
from contextlib import contextmanager
from typing import Any, Iterator, Optional

# ---- flat request id (set by start_trace; wire_context falls back to it
# when no span tree is active) ---------------------------------------------

_request_id: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "horaedb_request_id", default=None
)


def get_request_id() -> Optional[int]:
    return _request_id.get()


# ---- span tree -----------------------------------------------------------

# Bounds: a runaway loop opening spans (or a hostile remote payload) must
# not grow a request tree without limit — extra children are counted, not
# stored, and remote grafts are depth/width-clipped on arrival.
MAX_CHILDREN = 128
MAX_GRAFT_DEPTH = 8


class Span:
    __slots__ = (
        "span_id", "parent_id", "name", "start_at", "_t0",
        "duration_ms", "attrs", "children", "dropped_children",
    )

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 attrs: Optional[dict] = None) -> None:
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start_at = time.time()
        self._t0 = time.perf_counter()
        self.duration_ms: Optional[float] = None  # None = still open
        self.attrs: dict = attrs or {}
        self.children: list[Span] = []
        self.dropped_children = 0

    def set(self, **attrs: Any) -> "Span":
        """Attach attributes (stage metrics, row counts, paths)."""
        self.attrs.update(attrs)
        return self

    def finish(self) -> None:
        if self.duration_ms is None:
            self.duration_ms = round((time.perf_counter() - self._t0) * 1000, 3)

    def to_dict(self) -> dict:
        d: dict = {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_at": round(self.start_at, 6),
            "duration_ms": self.duration_ms,
        }
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        if self.dropped_children:
            d["dropped_children"] = self.dropped_children
        return d


class _NullSpan:
    """What ``span()`` yields when no trace is active: absorbs .set()."""

    __slots__ = ()

    def set(self, **attrs: Any) -> "_NullSpan":
        return self

    def finish(self) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Trace:
    """One request's span tree. Child creation/grafting is locked — the
    scatter pool and gRPC callbacks append from several threads."""

    def __init__(self, trace_id, name: str = "request",
                 attrs: Optional[dict] = None) -> None:
        self.trace_id = trace_id
        self._lock = threading.Lock()
        self._ids = itertools.count(2)
        self.root = Span(1, None, name, attrs)
        # profile-plane tags (obs/profile key dimensions): the serving
        # plane (query/ingest/ddl/flush/compaction/rules) and the
        # normalized plan-key class. Set via tag_trace once known.
        self.route = ""
        self.shape = ""

    def new_span(self, parent: Span, name: str,
                 attrs: Optional[dict] = None) -> Optional[Span]:
        with self._lock:
            if len(parent.children) >= MAX_CHILDREN:
                parent.dropped_children += 1
                return None
            s = Span(next(self._ids), parent.span_id, name, attrs)
            parent.children.append(s)
            return s

    def graft(self, parent: Span, remote: dict,
              attrs: Optional[dict] = None) -> None:
        """Attach a remote node's serialized subtree under ``parent``,
        re-numbering span ids into this trace (depth/width bounded)."""
        if not isinstance(remote, dict):
            return
        with self._lock:
            self._graft_locked(parent, remote, attrs, depth=0)

    def _graft_locked(self, parent: Span, node: dict,
                      extra: Optional[dict], depth: int) -> None:
        if depth >= MAX_GRAFT_DEPTH or len(parent.children) >= MAX_CHILDREN:
            parent.dropped_children += 1
            return
        s = Span(next(self._ids), parent.span_id, str(node.get("name", "remote")))
        a = node.get("attrs")
        if isinstance(a, dict):
            s.attrs.update(a)
        s.attrs.setdefault("origin", "remote")
        if extra:
            s.attrs.update(extra)
        start = node.get("start_at")
        if isinstance(start, (int, float)):
            s.start_at = float(start)
        dur = node.get("duration_ms")
        s.duration_ms = float(dur) if isinstance(dur, (int, float)) else 0.0
        parent.children.append(s)
        kids = node.get("children")
        if isinstance(kids, list):
            for k in kids[:MAX_CHILDREN]:
                if isinstance(k, dict):
                    self._graft_locked(s, k, None, depth + 1)
            if len(kids) > MAX_CHILDREN:
                s.dropped_children += len(kids) - MAX_CHILDREN
        drop = node.get("dropped_children")
        if isinstance(drop, int):
            s.dropped_children += drop

    def num_spans(self) -> int:
        def count(s: Span) -> int:
            return 1 + sum(count(c) for c in s.children)

        with self._lock:
            return count(self.root)

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "trace_id": self.trace_id,
                "root": self.root.to_dict(),
            }


_current_trace: contextvars.ContextVar[Optional[Trace]] = contextvars.ContextVar(
    "horaedb_trace", default=None
)
_current_span: contextvars.ContextVar[Optional[Span]] = contextvars.ContextVar(
    "horaedb_span", default=None
)


def current_trace() -> Optional[Trace]:
    return _current_trace.get()


def current_span() -> Optional[Span]:
    trace = _current_trace.get()
    if trace is None:
        return None
    return _current_span.get() or trace.root


def start_trace(trace_id, name: str = "request", **attrs: Any):
    """Begin a trace in the current context. Returns ``(trace, handle)``;
    pass the handle to ``finish_trace``."""
    trace = Trace(trace_id, name, attrs or None)
    tokens = (
        _current_trace.set(trace),
        _current_span.set(trace.root),
        _request_id.set(trace_id),
    )
    return trace, tokens


def tag_trace(route: Optional[str] = None, shape: Optional[str] = None) -> None:
    """Stamp the current trace's profile-plane dimensions (no-op outside
    a trace). The proxy tags by plan kind after parse; background planes
    tag at round start."""
    trace = _current_trace.get()
    if trace is None:
        return
    if route is not None:
        trace.route = route
    if shape is not None:
        trace.shape = shape


def finish_trace(handle, record: bool = True, slow: bool = False) -> None:
    """End the trace started with ``start_trace`` and (by default) record
    its snapshot in the global TRACE_STORE and fold it into the profile
    aggregator (obs/profile). ``record=False`` (serving_trace) skips
    BOTH: the subtree ships home and folds once, at the coordinator —
    never double-counted fleetwide."""
    t_tok, s_tok, r_tok = handle
    trace = _current_trace.get()
    _current_trace.reset(t_tok)
    _current_span.reset(s_tok)
    _request_id.reset(r_tok)
    if trace is None:
        return
    trace.root.finish()
    if record:
        root = trace.to_dict()["root"]  # ONE locked walk per request
        TRACE_STORE.record_snapshot(trace.trace_id, root, slow=slow)
        try:
            from ..obs.profile import fold_trace

            fold_trace(trace.trace_id, root,
                       route=trace.route, shape=trace.shape)
        except Exception:
            pass  # profiling must never fail the request


@contextmanager
def span(name: str, **attrs: Any):
    """Open a child span of the current one. Usable from sync and async
    code (ContextVars follow the task/thread context). No active trace →
    yields a shared no-op span and touches nothing."""
    trace = _current_trace.get()
    if trace is None:
        yield _NULL_SPAN
        return
    parent = _current_span.get() or trace.root
    s = trace.new_span(parent, name, attrs or None)
    if s is None:  # parent full: drop quietly, bound enforced
        yield _NULL_SPAN
        return
    token = _current_span.set(s)
    try:
        yield s
    finally:
        s.finish()
        _current_span.reset(token)


_bg_trace_ids = itertools.count(1)


@contextmanager
def owned_trace(name: str, route: str = "", shape: str = "", **attrs: Any):
    """A background plane's own trace round (flush, compaction, rules):
    starts a trace so the plane's spans fold into the profile aggregator
    through the SAME machinery as queries. If a trace is already active
    (a foreground-requested flush inside a request), opens a child span
    instead — never shadows the request's tree. Yields the root/child
    span (supports ``.set``)."""
    if _current_trace.get() is not None:
        with span(name, **attrs) as s:
            yield s
        return
    tid = f"{name}-{next(_bg_trace_ids)}"
    trace, handle = start_trace(tid, name, **attrs)
    trace.route = route or name
    trace.shape = shape
    try:
        yield trace.root
    finally:
        finish_trace(handle)


def annotate(**attrs: Any) -> None:
    """Attach attributes to the current span (no-op outside a trace)."""
    s = current_span()
    if s is not None:
        s.set(**attrs)


def wire_context() -> Optional[dict]:
    """The trace context an RPC envelope ships to a partition owner:
    ``{request_id, trace_id, parent_span_id}``. Outside a trace, falls
    back to the flat request id (older envelope shape); None when neither
    is set."""
    trace = _current_trace.get()
    if trace is None:
        rid = _request_id.get()
        return {"request_id": rid} if rid is not None else None
    parent = _current_span.get() or trace.root
    return {
        "request_id": trace.trace_id,
        "trace_id": trace.trace_id,
        "parent_span_id": parent.span_id,
    }


def graft(remote_span: Optional[dict], **attrs: Any) -> None:
    """Attach a remote node's serialized span tree (an RPC response's
    ``span`` field) under the current span. No-op outside a trace."""
    if remote_span is None:
        return
    trace = _current_trace.get()
    if trace is None:
        return
    parent = _current_span.get() or trace.root
    trace.graft(parent, remote_span, attrs or None)


@contextmanager
def serving_trace(trace_ctx: Optional[dict], name: str, **attrs: Any) -> Iterator[Optional[Trace]]:
    """Serve an RPC under a detached trace carrying the ORIGIN's trace id
    (ref: RemoteTaskContext). The handler runs with span() active; the
    finished root ships back in the response via ``root_dict(trace)``.
    ``trace_ctx`` None (old peer, no trace at origin) → no tracing."""
    if not isinstance(trace_ctx, dict) or (
        trace_ctx.get("trace_id") is None and trace_ctx.get("request_id") is None
    ):
        yield None
        return
    tid = trace_ctx.get("trace_id", trace_ctx.get("request_id"))
    trace, handle = start_trace(tid, name, **attrs)
    try:
        yield trace
    finally:
        # Remote subtrees ship home in the RPC response; recording them
        # locally too would double-count them in this node's store.
        finish_trace(handle, record=False)


def root_dict(trace: Optional[Trace]) -> Optional[dict]:
    """Serialize a serving_trace's tree for the RPC response."""
    if trace is None:
        return None
    trace.root.finish()
    return trace.to_dict()["root"]


# ---- trace store ---------------------------------------------------------


class TraceStore:
    """Bounded in-process sink: a ring of recent traces plus a (larger)
    ring of slow ones — sustained load can never grow it without bound.
    Stores SNAPSHOTS (dicts), so later mutation of a live trace (or ring
    eviction) never races a /debug/trace reader."""

    def __init__(self, recent: int = 64, slow: int = 256) -> None:
        from collections import deque

        self._recent: "deque[dict]" = deque(maxlen=recent)
        self._slow: "deque[dict]" = deque(maxlen=slow)
        self._lock = threading.Lock()

    def record(self, trace: Trace, slow: bool = False) -> None:
        trace.root.finish()
        self.record_snapshot(trace.trace_id, trace.to_dict()["root"],
                             slow=slow)

    def record_snapshot(self, trace_id, root: dict, slow: bool = False) -> None:
        """Record an already-serialized root (finish_trace snapshots once
        and shares the walk with the profile fold)."""

        def count(node: dict) -> int:
            return 1 + sum(count(c) for c in node.get("children", ()))

        entry = {
            "trace_id": trace_id,
            "name": root["name"],
            "at": root["start_at"],
            "duration_ms": root["duration_ms"],
            "spans": count(root),
            "slow": bool(slow),
            "root": root,
        }
        with self._lock:
            self._recent.append(entry)
            if slow:
                self._slow.append(entry)

    def get(self, trace_id) -> Optional[dict]:
        with self._lock:
            # newest wins on id reuse (per-proxy counters restart at 1)
            for ring in (self._recent, self._slow):
                for entry in reversed(ring):
                    if entry["trace_id"] == trace_id:
                        return entry
        return None

    def list(self) -> list[dict]:
        with self._lock:
            seen: set[int] = set()
            out: list[dict] = []
            for entry in (*reversed(self._recent), *reversed(self._slow)):
                if id(entry) in seen:
                    continue
                seen.add(id(entry))
                out.append({k: entry[k] for k in
                            ("trace_id", "name", "at", "duration_ms", "spans", "slow")})
            return out

    def resize(self, recent: Optional[int] = None,
               slow: Optional[int] = None) -> None:
        """Apply the [observability] trace_ring / trace_slow_ring knobs;
        shrinking drops oldest entries (deque maxlen semantics)."""
        from collections import deque

        with self._lock:
            if recent is not None and recent != self._recent.maxlen:
                self._recent = deque(self._recent, maxlen=max(1, int(recent)))
            if slow is not None and slow != self._slow.maxlen:
                self._slow = deque(self._slow, maxlen=max(1, int(slow)))

    def sizes(self) -> tuple[int, int]:
        return self._recent.maxlen or 0, self._slow.maxlen or 0

    def clear(self) -> None:
        with self._lock:
            self._recent.clear()
            self._slow.clear()


TRACE_STORE = TraceStore()


def render_tree(node: dict, indent: int = 0) -> list[str]:
    """Render a serialized span tree as indented text lines — what
    EXPLAIN ANALYZE prints under its plan (ref: trace_metric's formatted
    collector output)."""
    dur = node.get("duration_ms")
    dur_s = f"{dur:.3f}ms" if isinstance(dur, (int, float)) else "…"
    attrs = node.get("attrs") or {}
    label = str(node.get("name", "?"))
    if attrs.get("origin") == "remote":
        ep = attrs.get("endpoint")
        label = f"[remote{' ' + str(ep) if ep else ''}] {label}"
    detail = " ".join(
        f"{k}={v}" for k, v in attrs.items()
        if k not in ("origin", "endpoint") and not isinstance(v, (dict, list))
    )
    line = "  " * indent + f"{label} {dur_s}" + (f" {detail}" if detail else "")
    out = [line]
    for child in node.get("children", ()):  # already bounded at insert
        out.extend(render_tree(child, indent + 1))
    dropped = node.get("dropped_children")
    if dropped:
        out.append("  " * (indent + 1) + f"(+{dropped} spans dropped)")
    return out
