"""Request trace context — the coordinator's request id follows the query
across threads and nodes (ref: trace_metric's MetricsCollector spans +
RemoteTaskContext.remote_metrics carrying EXPLAIN ANALYZE data home;
RequestId in common_types).

A ContextVar holds the current request id; the proxy sets it per SQL
statement and runs the executor inside a copied context so priority-pool
threads observe it. Remote partial-agg calls ship it in the wire spec, and
the owning node tags its span ring with it — so one request id correlates
the coordinator's slow-log entry with every remote span it fanned out.
"""

from __future__ import annotations

import contextvars
from typing import Optional

_request_id: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "horaedb_request_id", default=None
)


def set_request_id(rid: Optional[int]) -> contextvars.Token:
    return _request_id.set(rid)


def get_request_id() -> Optional[int]:
    return _request_id.get()


def reset_request_id(token: contextvars.Token) -> None:
    _request_id.reset(token)
