"""Data-node cluster runtime
(ref: src/cluster/src/cluster_impl.rs:59-116 — the heartbeat loop;
shard_operator.rs:123-404 — open/close/create-table shard ops;
shard_lock_manager.rs — lease-fenced single-writer discipline).

``ClusterImpl`` owns the node's shard set and reconciles it against the
coordinator's declarative orders, delivered two ways (both feed
``apply_shard_order``): heartbeat replies, and direct /meta_event pushes.

Fencing: every order carries a shard version (stale ones rejected by the
Shard state machine) and a lease TTL; the lease deadline renews on every
successful heartbeat. Writes check ``ensure_table_writable`` — shard READY
and lease unexpired — so a node cut off from the coordinator stops
accepting writes after one TTL, BEFORE the coordinator hands the shard to
someone else (lease_ttl < heartbeat_timeout).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

from .meta_client import MetaClient, MetaError
from .shard import Shard, ShardError, ShardInfo, ShardSet, ShardState

logger = logging.getLogger("horaedb_tpu.cluster")


def _metrics():
    from ..utils.metrics import REGISTRY

    return REGISTRY


def _record_event(kind: str, **attrs):
    # Lazy: the cluster runtime must not pull the metrics/events modules
    # at import (same reason _metrics() is deferred).
    from ..utils.events import record_event

    record_event(kind, **attrs)


class ClusterImpl:
    def __init__(
        self,
        conn,  # db.Connection — DDL replay + table close on shard moves
        self_endpoint: str,
        meta_client: MetaClient,
        heartbeat_interval_s: float = 2.0,
    ) -> None:
        self.conn = conn
        self.self_endpoint = self_endpoint
        self.meta = meta_client
        self.heartbeat_interval_s = heartbeat_interval_s
        self.shard_set = ShardSet()
        self._table_shard: dict[str, int] = {}  # table name -> shard id
        self._lease_deadline: dict[int, float] = {}  # shard id -> monotonic
        self._last_lease_ttl: Optional[float] = None  # learned from heartbeats
        self._order_applied_at: dict[int, float] = {}  # shard id -> monotonic
        # ---- follower (read-replica) state -------------------------------
        # Shards this node serves READ-ONLY: epoch (shard version) fences
        # replica reads the same way versions fence leader orders, and the
        # replica lease deadline (renewed by OUR heartbeat) bounds how
        # stale our view of the topology can be before reads refuse.
        self._replica_shards: dict[int, int] = {}  # shard id -> version
        self._replica_tables: dict[str, int] = {}  # table name -> shard id
        self._replica_deadline: dict[int, float] = {}
        self._replica_applied_at: dict[int, float] = {}
        # Replicas of shards this node LEADS (from leader orders) — the
        # proxy sheds eligible reads here when the leader is overloaded.
        self._shard_replicas: dict[int, tuple[str, ...]] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._poke = threading.Event()  # kick_heartbeat() wakes the loop
        # fault injection (tools/tenantsim lease flaps): while set in the
        # future, the loop SKIPS renewals — leases lapse, the watch
        # freezes shards, writes fence; resuming renewal thaws them
        self._pause_until = 0.0
        self._thread: Optional[threading.Thread] = None
        self._watch_thread: Optional[threading.Thread] = None
        self._tail_thread: Optional[threading.Thread] = None

    # ---- lifecycle ------------------------------------------------------
    def start(self) -> None:
        # Restart-safe: a stop()ed impl can start fresh threads (the
        # simulator kills and never restarts, but tests flap). Each of
        # the THREE loops is checked independently — a stop() whose 5s
        # join timed out can leave the heartbeat thread alive while the
        # watch/tail loops (which saw _stop) already exited; an
        # early-return on the heartbeat check alone would then renew
        # leases forever without lease-lapse fencing or manifest tailing.
        self._stop.clear()
        if self._thread is None or not self._thread.is_alive():
            # Best-effort eager registration; a temporarily unreachable
            # coordinator must not abort node startup (the loop keeps
            # retrying — the node serves what it can meanwhile).
            try:
                self._heartbeat_once()
            except MetaError as e:
                logger.warning("initial heartbeat failed (will retry): %s", e)
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="cluster-heartbeat"
            )
            self._thread.start()
        if self._watch_thread is None or not self._watch_thread.is_alive():
            self._watch_thread = threading.Thread(
                target=self._lease_watch_loop, daemon=True, name="lease-watch"
            )
            self._watch_thread.start()
        if self._tail_thread is None or not self._tail_thread.is_alive():
            self._tail_thread = threading.Thread(
                target=self._manifest_tail_loop, daemon=True,
                name="replica-tail",
            )
            self._tail_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._poke.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=5)
        if self._tail_thread is not None:
            self._tail_thread.join(timeout=5)

    def kick_heartbeat(self) -> None:
        """Wake the heartbeat loop NOW — called after a /meta_event push
        applies a lease-less membership order so the lease arrives in
        milliseconds instead of one renewal interval later."""
        self._poke.set()

    def pause_heartbeats(self, seconds: float) -> None:
        """Fault injection (the tenant simulator's replica-lease flaps):
        suppress heartbeat renewal for ``seconds``. Leases lapse, the
        lease watch freezes owned shards (writes fence with the typed
        retryable error), replica reads refuse on their lapsed lease —
        and everything thaws when renewal resumes. The node itself keeps
        serving; only the *renewal* stops, exactly like a network
        partition between node and coordinator."""
        self._pause_until = time.monotonic() + max(0.0, float(seconds))

    def _loop(self) -> None:
        while True:
            if self._poke.wait(self._interval()):
                self._poke.clear()
            if self._stop.is_set():
                return
            if time.monotonic() < self._pause_until:
                continue
            try:
                self._heartbeat_once()
            except MetaError as e:
                logger.warning("heartbeat failed: %s", e)
            except Exception:
                logger.exception("heartbeat loop error")

    def _interval(self) -> float:
        """Renew well inside the lease TTL (~TTL/3, etcd-keepalive style) —
        a configured interval longer than the TTL would leave the write
        fence closed between renewals in steady state. The anti-busy-spin
        floor is small enough to stay under any sane TTL."""
        ttl = self._last_lease_ttl
        if ttl is None:
            return self.heartbeat_interval_s
        return max(0.02, min(self.heartbeat_interval_s, ttl / 3.0))

    def _heartbeat_once(self) -> None:
        # Lease deadlines measure from when the successful request was
        # SENT (stamped per-call by the client): a reply delayed across a
        # long stall (process suspension, network hiccup) must not renew a
        # lease the coordinator already considers lapsed — with
        # arrival-time accounting a pre-transfer reply buffered in the
        # socket would reopen the write fence on resume (split brain).
        resp, sent_at = self.meta.heartbeat_timed(self.self_endpoint)
        self._last_lease_ttl = float(resp.get("lease_ttl_s", 0)) or None
        desired = resp.get("desired", [])
        desired_ids = {o["shard_id"] for o in desired}
        for order in desired:
            try:
                self.apply_shard_order(order, granted_at=sent_at)
            except ShardError as e:
                logger.warning("shard order rejected: %s", e)
        # Shards the coordinator no longer grants us: close them — UNLESS
        # a newer order arrived (direct /meta_event push) while this reply
        # was in flight; the reply predates it and must not undo it.
        for shard in self.shard_set.all_shards():
            if shard.shard_id in desired_ids:
                continue
            with self._lock:
                applied_at = self._order_applied_at.get(shard.shard_id, 0.0)
            if applied_at > sent_at:
                continue
            self.close_shard(shard.shard_id, version=None)
        # Follower (read-replica) reconcile: same discipline, read side.
        desired_reps = resp.get("desired_replicas", [])
        rep_ids = {o["shard_id"] for o in desired_reps}
        for order in desired_reps:
            try:
                self.apply_replica_order(order, granted_at=sent_at)
            except ShardError as e:
                logger.warning("replica order rejected: %s", e)
        with self._lock:
            stale_reps = [
                sid for sid in self._replica_shards
                if sid not in rep_ids
                and self._replica_applied_at.get(sid, 0.0) <= sent_at
            ]
        for sid in stale_reps:
            self.close_replica_shard(sid)

    def _lease_watch_loop(self) -> None:
        """The lock-loss WATCH (ref: shard_lock_manager.rs:23-60 — etcd
        watch events freeze the shard the moment the lock is lost, rather
        than every write path discovering expiry on its own).

        Here the lease is heartbeat-granted, so the watch is a deadline
        scan at ~TTL/4 cadence: a READY shard whose lease lapsed FREEZES
        (one state flip, visible in /debug/shards and metrics, fails all
        writers fast); a FROZEN shard whose owner re-heartbeated before
        the coordinator moved it THAWS. ensure_table_writable keeps its
        own deadline check — the watch narrows the fencing gap, it is not
        the only fence."""
        while not self._stop.wait(self._watch_interval()):
            for shard in self.shard_set.all_shards():
                # Deadline re-read UNDER THE LOCK per shard, at decision
                # time: freezing from a loop-start snapshot would reject
                # writes for a whole watch interval after a renewal that
                # landed mid-scan.
                now = time.monotonic()
                with self._lock:
                    deadline = self._lease_deadline.get(shard.shard_id)
                if deadline is None or deadline == 0.0:
                    # 0.0 = just opened via /meta_event push, lease grant
                    # in flight on the kicked heartbeat. ensure_writable's
                    # own deadline check fences it; freezing here would
                    # churn every push-open through FROZEN.
                    continue
                try:
                    if shard.state is ShardState.READY and now > deadline:
                        shard.freeze()
                        _metrics().counter(
                            "horaedb_cluster_shard_freezes_total",
                            "shards frozen by the lease watch",
                        ).inc()
                        _record_event(
                            "shard_freeze", shard_id=shard.shard_id,
                            lapsed_s=round(now - deadline, 3),
                        )
                        logger.warning(
                            "shard %d FROZEN: lease lapsed %.2fs ago",
                            shard.shard_id, now - deadline,
                        )
                    elif shard.state is ShardState.FROZEN and now <= deadline:
                        shard.thaw()
                        _metrics().counter(
                            "horaedb_cluster_shard_thaws_total",
                            "shards thawed by the lease watch after renewal",
                        ).inc()
                        _record_event("shard_thaw", shard_id=shard.shard_id)
                        logger.info(
                            "shard %d thawed: lease renewed", shard.shard_id
                        )
                except ShardError:
                    pass  # state moved under us (open/close race): benign

    def _watch_interval(self) -> float:
        ttl = self._last_lease_ttl
        return max(0.05, (ttl / 4.0) if ttl else 0.5)

    # ---- shard orders (heartbeat reply or /meta_event push) -------------
    def apply_shard_order(self, order: dict, granted_at: Optional[float] = None) -> None:
        """Reconcile one declarative shard order (idempotent).

        ``granted_at``: monotonic instant the grant is valid FROM (the
        heartbeat request-send time); the lease deadline is measured from
        there, not from when the reply got processed. ``None`` (the
        /meta_event push path) applies MEMBERSHIP ONLY and grants no
        lease: a push buffered in the socket across a long stall could be
        arbitrarily stale, and unlike a heartbeat there is no local send
        timestamp to bound its age — so pushes open/seed the shard and an
        immediate heartbeat (kicked by the caller) fetches the lease."""
        shard_id = int(order["shard_id"])
        version = int(order["version"])
        ttl = float(order.get("lease_ttl_s", 5.0))
        tables = order.get("tables", [])
        with self._lock:
            if shard_id in self._replica_shards:
                # Promotion (follower -> leader): release the read-only
                # follower handles FIRST so the leader open below goes
                # through the normal path (WAL replay picks up the old
                # leader's unflushed rows; writes unfence).
                self._drop_replica_state_locked(shard_id)
            shard = self.shard_set.get(shard_id)
            if shard is None:
                shard = Shard(ShardInfo(shard_id, version=0))
                self.shard_set.insert(shard)
                shard.begin_open()
                try:
                    self._open_tables_of_shard(tables)
                except Exception:
                    # Failed first open: remove the half-open shard so the
                    # next order starts clean instead of wedging OPENING.
                    self.shard_set.remove(shard_id)
                    raise
                shard.finish_open()
                shard.apply_update(
                    ShardInfo(shard_id, version, tuple(t["table_id"] for t in tables))
                )
            elif version > shard.version:
                # Membership changed (table create/drop or reassignment).
                self._open_tables_of_shard(tables)
                shard.apply_update(
                    ShardInfo(shard_id, version, tuple(t["table_id"] for t in tables))
                )
            elif version < shard.version:
                raise ShardError(
                    f"stale order for shard {shard_id}: v{version} < v{shard.version}"
                )
            now = time.monotonic()
            if granted_at is not None:
                # Never SHORTEN an existing lease: a slow reply racing a
                # newer grant must not roll the deadline backwards.
                self._lease_deadline[shard_id] = max(
                    self._lease_deadline.get(shard_id, 0.0), granted_at + ttl
                )
                # Renewal unfences NOW — a shard the watch froze during a
                # delayed heartbeat must not stay frozen up to a watch
                # interval after the lease came back.
                if (shard.state is ShardState.FROZEN
                        and now <= self._lease_deadline[shard_id]):
                    try:
                        shard.thaw()
                        # keep freezes - thaws == currently-fenced count
                        _metrics().counter(
                            "horaedb_cluster_shard_thaws_total",
                            "shards thawed by the lease watch after renewal",
                        ).inc()
                        _record_event("shard_thaw", shard_id=shard_id)
                    except ShardError:
                        pass
            else:
                self._lease_deadline.setdefault(shard_id, 0.0)
            self._order_applied_at[shard_id] = now
            # replica endpoints ride the (version-fenced) leader order —
            # the shed-to-follower path reads them for shards we lead
            self._shard_replicas[shard_id] = tuple(order.get("replicas", ()))
            ordered = {t["name"] for t in tables}
            # PRUNE names this shard no longer carries (dropped tables /
            # moved partitions) — an add-only map would leave the write
            # fence open (and the local handles would keep serving stale
            # data / flushing stale memtables) for tables the node no
            # longer owns.
            for name in [
                n for n, sid in self._table_shard.items()
                if sid == shard_id and n not in ordered
            ]:
                self._release_table(name)
            for t in tables:
                self._table_shard[t["name"]] = shard_id

    def _open_tables_of_shard(self, tables: list[dict]) -> None:
        """Make every table of the shard servable locally.

        Tables created elsewhere exist in the SHARED object store; reload
        the catalog registry, then replay create_sql for any still missing
        (first assignment of a brand-new table). Partition sub-tables
        (``sub_of`` set) open through their logical parent's registry
        entry — they have no DDL of their own."""
        if not tables:
            return
        missing = [
            t for t in tables
            if not t.get("sub_of") and not self.conn.catalog.exists(t["name"])
        ]
        subs = [t for t in tables if t.get("sub_of")]
        if missing or subs:
            reload_fn = getattr(self.conn.catalog, "reload", None)
            if reload_fn is not None:
                reload_fn()
        for t in tables:
            if t.get("sub_of"):
                if self.conn.catalog.open_sub_table(t["name"]) is None:
                    # storage not visible yet (create in flight on another
                    # node): the next heartbeat reconcile retries
                    logger.info("partition %s not openable yet", t["name"])
                continue
            if not self.conn.catalog.exists(t["name"]):
                try:
                    self.conn.execute(t["create_sql"])
                except Exception as e:
                    logger.warning("replaying DDL for %s failed: %s", t["name"], e)
            else:
                # Ensure open (manifest load + WAL replay happen here).
                self.conn.catalog.open(t["name"])

    def close_shard(self, shard_id: int, version: Optional[int]) -> None:
        with self._lock:
            shard = self.shard_set.get(shard_id)
            if shard is None:
                return
            if version is not None and version < shard.version:
                raise ShardError(
                    f"stale close for shard {shard_id}: v{version} < v{shard.version}"
                )
            dropped_tables = [
                name for name, sid in self._table_shard.items() if sid == shard_id
            ]
            for name in dropped_tables:
                self._release_table(name)
            self._lease_deadline.pop(shard_id, None)
            self._order_applied_at.pop(shard_id, None)
            self._shard_replicas.pop(shard_id, None)
            self.shard_set.remove(shard_id)

    # ---- follower (read-replica) orders ---------------------------------
    def apply_replica_order(
        self, order: dict, granted_at: Optional[float] = None
    ) -> None:
        """Reconcile one follower order: open the shard's plain tables
        READ-ONLY over the shared object store (manifest state, no WAL
        replay) and record the epoch + replica lease. Same delivery
        contract as leader orders: heartbeat replies carry a lease
        (measured from request-send time), /meta_event pushes carry
        membership only (the kicked heartbeat fetches the lease)."""
        shard_id = int(order["shard_id"])
        version = int(order["version"])
        ttl = float(order.get("lease_ttl_s", 5.0))
        tables = [t for t in order.get("tables", []) if not t.get("sub_of")]
        with self._lock:
            if self.shard_set.get(shard_id) is not None:
                # We LEAD this shard; a replica order for it is stale
                # (raced a promotion) — leadership wins.
                return
            cur = self._replica_shards.get(shard_id)
            if cur is not None and version < cur:
                raise ShardError(
                    f"stale replica order for shard {shard_id}: "
                    f"v{version} < v{cur}"
                )
            opened = self._open_follower_tables(tables)
            ordered = {t["name"] for t in tables}
            for name in [
                n for n, sid in self._replica_tables.items()
                if sid == shard_id and n not in ordered
            ]:
                self._replica_tables.pop(name, None)
                self.conn.catalog.release(name)
            # Only tables that actually OPENED read-only serve here: a
            # name registered without a handle would take a doomed
            # follower hop (fenced refusal) on every routed read. The
            # not-yet-openable ones retry on the next heartbeat order.
            for t in tables:
                if t["name"] in opened:
                    self._replica_tables[t["name"]] = shard_id
            self._replica_shards[shard_id] = version
            now = time.monotonic()
            if granted_at is not None:
                self._replica_deadline[shard_id] = max(
                    self._replica_deadline.get(shard_id, 0.0), granted_at + ttl
                )
            else:
                self._replica_deadline.setdefault(shard_id, 0.0)
            self._replica_applied_at[shard_id] = now

    def _open_follower_tables(self, tables: list[dict]) -> set[str]:
        """Open each plain table read-only; returns the names that are
        actually serving. Partitioned PARENTS are skipped silently (their
        sub-tables route per-shard; replication doesn't cover them yet)."""
        missing = [
            t["name"] for t in tables
            if not self.conn.catalog.exists(t["name"])
        ]
        if missing:
            reload_fn = getattr(self.conn.catalog, "reload", None)
            if reload_fn is not None:
                reload_fn()
        opened: set[str] = set()
        for t in tables:
            name = t["name"]
            entry = self.conn.catalog.entry(name)
            if entry is not None and entry.partition_info is not None:
                continue  # parent of a partitioned table: not replicable
            try:
                if self.conn.catalog.open_follower(name) is not None:
                    opened.add(name)
                else:
                    # registry entry or manifest not visible yet (create
                    # in flight on the leader): next heartbeat retries
                    logger.info("replica table %s not openable yet", name)
            except Exception:
                logger.exception("opening follower table %s", name)
        return opened

    def close_replica_shard(self, shard_id: int) -> None:
        with self._lock:
            self._drop_replica_state_locked(shard_id)

    def _drop_replica_state_locked(self, shard_id: int) -> None:
        for name in [
            n for n, sid in self._replica_tables.items() if sid == shard_id
        ]:
            self._replica_tables.pop(name, None)
            self.conn.catalog.release(name)
        self._replica_shards.pop(shard_id, None)
        self._replica_deadline.pop(shard_id, None)
        self._replica_applied_at.pop(shard_id, None)

    def _manifest_tail_loop(self) -> None:
        """Follower freshness: periodically re-load each replica table's
        manifest from the shared object store and install the delta
        (files/schema/flushed-seq) into the read-only handle. Cadence
        rides the lease TTL (~TTL/2, floor 0.25s) — freshness tighter
        than the fencing bound buys nothing. Also publishes the worst
        watermark lag to the horaedb_replica_watermark_lag_seconds
        gauge."""
        from .replica import set_watermark_lag

        while not self._stop.wait(self._tail_interval()):
            with self._lock:
                names = list(self._replica_tables)
            if not names:
                # no replicas served: the gauge must read 0, not freeze
                # at the last value from a role this node no longer has
                set_watermark_lag(0.0)
                continue
            worst_lag = 0.0
            refreshed = 0
            now_ms = time.time() * 1000
            for name in names:
                data = self._follower_data(name)
                if data is None:
                    continue
                try:
                    data.refresh_from_manifest()
                except Exception:
                    logger.exception("manifest tail for %s", name)
                    continue
                refreshed += 1
                wm = data.follower_watermark_ms()
                if wm > 0:
                    worst_lag = max(worst_lag, (now_ms - wm) / 1000.0)
            if refreshed:
                # all-failed rounds keep the last honest value instead of
                # publishing a misleading 0
                set_watermark_lag(worst_lag)

    def _tail_interval(self) -> float:
        ttl = self._last_lease_ttl
        return max(0.25, (ttl / 2.0) if ttl else 1.0)

    def _follower_data(self, table: str):
        """The read-only TableData behind a replica-served table name
        (None when the handle isn't open)."""
        t = self.conn.catalog.open_handle(table)
        if t is None:
            return None
        datas = t.physical_datas()
        if not datas or not datas[0].read_only:
            return None
        return datas[0]

    # ---- replica serving checks -----------------------------------------
    def serves_replica(self, table: str) -> bool:
        with self._lock:
            return table in self._replica_tables

    def replicas_of_table(self, table: str) -> tuple[str, ...]:
        """Follower endpoints for a table this node LEADS (for
        shed-to-follower on leader overload)."""
        with self._lock:
            sid = self._table_shard.get(table)
            if sid is None:
                return ()
            return self._shard_replicas.get(sid, ())

    def replica_read_state(self, table: str, expected_epoch: Optional[int] = None):
        """Fencing gate for one follower read. Returns (epoch, TableData)
        when this node may serve; raises the typed retryable
        ``ReplicaFencedError`` when it may not: replica lease lapsed (we
        are cut off from the coordinator — our topology view is
        unbounded-stale) or our epoch trails a transfer the caller has
        already observed."""
        from .replica import ReplicaFencedError

        with self._lock:
            sid = self._replica_tables.get(table)
            if sid is None:
                raise ReplicaFencedError(
                    f"table {table!r} not replicated on this node"
                )
            epoch = self._replica_shards.get(sid, 0)
            deadline = self._replica_deadline.get(sid, 0.0)
        if time.monotonic() > deadline:
            raise ReplicaFencedError(
                f"replica lease for shard {sid} lapsed — follower read "
                "fenced (node cut off from coordinator)",
                epoch=epoch,
            )
        if expected_epoch is not None and epoch < int(expected_epoch):
            raise ReplicaFencedError(
                f"replica epoch v{epoch} trails the observed transfer "
                f"v{int(expected_epoch)} for shard {sid} — refusing to "
                "serve a pre-fence view",
                epoch=epoch,
            )
        data = self._follower_data(table)
        if data is None:
            raise ReplicaFencedError(
                f"replica handle for {table!r} not open yet", epoch=epoch
            )
        return epoch, data

    def create_table_on_shard(self, shard_id: int, name: str, create_sql: str) -> dict:
        """Meta-dispatched DDL; returns catalog ids (idempotent)."""
        with self._lock:
            # The registry lives in the SHARED store: another node may have
            # persisted tables since we loaded. Reload before a
            # read-modify-write persist, or we'd clobber their entries.
            self.conn.catalog.reload()
            if not self.conn.catalog.exists(name):
                self.conn.execute(create_sql)
            self._table_shard[name] = shard_id
            entry = self.conn.catalog.entry(name)
            return {
                "table_id": entry.table_id,
                "sub_table_ids": list(entry.sub_table_ids or []),
            }

    def _release_table(self, name: str) -> None:
        """Stop serving a table this node no longer owns: fence writes,
        close local handles, forget catalog entries.

        With a WAL, the close does NOT flush: this node LOST the table —
        its unflushed rows are durable in the SHARED WAL and the new
        owner replays them; flushing a stale memtable here would race
        the new owner's manifest appends (two writers, one log sequence —
        last writer wins, edits LOST). Without a WAL (explicit
        no-durability config) flushing on close is the only way to hand
        the rows over, racy or not."""
        self._table_shard.pop(name, None)
        try:
            t = self.conn.catalog.open(name)
            if t is not None:
                for data in t.physical_datas():
                    self.conn.instance.close_table(
                        data, flush=self.conn.instance.wal is None
                    )
            self.conn.catalog.forget(name)
        except Exception:
            logger.exception("releasing table %s", name)

    def forget_table(self, name: str) -> None:
        """Remove a table from the serving map WITHOUT touching storage
        (its partition was dropped or moved; see remote DropSub)."""
        with self._lock:
            self._table_shard.pop(name, None)

    def drop_table_on_shard(self, shard_id: int, name: str) -> None:
        with self._lock:
            self._table_shard.pop(name, None)
            self.conn.catalog.reload()
            if self.conn.catalog.exists(name):
                self.conn.catalog.drop_table(name, if_exists=True)

    def debug_shard_info(self) -> list[dict]:
        """Lock-consistent snapshot of this node's shard set for the
        /debug/shards surface (ref: /debug/shards, http.rs:587)."""
        now = time.monotonic()
        out = []
        with self._lock:
            for shard in self.shard_set.all_shards():
                deadline = self._lease_deadline.get(shard.shard_id, 0.0)
                out.append(
                    {
                        "shard_id": shard.shard_id,
                        "state": shard.state.value,
                        "version": shard.version,
                        "role": "leader",
                        "replicas": list(
                            self._shard_replicas.get(shard.shard_id, ())
                        ),
                        "lease_remaining_s": round(max(0.0, deadline - now), 2),
                        "tables": sorted(
                            t for t, sid in self._table_shard.items()
                            if sid == shard.shard_id
                        ),
                    }
                )
            for sid, version in sorted(self._replica_shards.items()):
                deadline = self._replica_deadline.get(sid, 0.0)
                names = sorted(
                    t for t, s in self._replica_tables.items() if s == sid
                )
                watermarks = {}
                for t in names:
                    data = self._follower_data(t)
                    if data is not None:
                        watermarks[t] = data.follower_watermark_ms()
                out.append(
                    {
                        "shard_id": sid,
                        "state": "ready",
                        "version": version,
                        "role": "replica",
                        "lease_remaining_s": round(max(0.0, deadline - now), 2),
                        "tables": names,
                        "watermarks_ms": watermarks,
                    }
                )
        return out

    # ---- serving checks --------------------------------------------------
    def owns_table(self, table: str) -> bool:
        with self._lock:
            return table in self._table_shard

    def shard_of_table(self, table: str) -> Optional[int]:
        with self._lock:
            return self._table_shard.get(table)

    def ensure_table_writable(self, table: str) -> None:
        """Raise unless this node holds a live, READY shard for the table
        (the lease-fencing write barrier, ref: shard_lock_manager.rs)."""
        with self._lock:
            shard_id = self._table_shard.get(table)
            if shard_id is None:
                raise ShardError(f"table {table!r} not served by this node")
            shard = self.shard_set.get(shard_id)
            if shard is None:
                raise ShardError(f"shard {shard_id} not open on this node")
            shard.ensure_writable()
            deadline = self._lease_deadline.get(shard_id, 0.0)
            if time.monotonic() > deadline:
                raise ShardError(
                    f"shard {shard_id} lease expired — write fenced "
                    "(node cut off from coordinator)"
                )
