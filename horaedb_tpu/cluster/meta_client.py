"""Data-node client for the meta service
(ref: src/meta_client/src/lib.rs:100-116 — the MetaClient trait:
send_heartbeat / create_table / drop_table / route_tables / get_nodes —
and load_balance.rs round-robin over meta endpoints).

Synchronous HTTP with failover: calls rotate through the configured meta
endpoints; the first answering endpoint is remembered until it fails.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Optional, Sequence


class MetaError(RuntimeError):
    pass


class MetaClient:
    def __init__(self, endpoints: Sequence[str], timeout_s: float = 5.0) -> None:
        if not endpoints:
            raise ValueError("meta endpoints must not be empty")
        self.endpoints = list(endpoints)
        self.timeout_s = timeout_s
        self._preferred = 0
        self._leader_hint: Optional[str] = None  # advertised leader (HA)
        self._lock = threading.Lock()

    # ---- transport ------------------------------------------------------
    def _call(
        self,
        method: str,
        path: str,
        payload: Optional[dict] = None,
        sent_at_out: Optional[dict] = None,
    ) -> dict:
        from collections import deque

        # Deadline propagation: a meta hop issued while serving a query
        # (meta-serialized DDL, route refreshes mid-statement) charges
        # the query's remaining budget instead of burning the full
        # fixed timeout per failover attempt.
        from ..utils.deadline import cap_timeout, checkpoint

        last_err: Exception | None = None
        with self._lock:
            start = self._preferred
            leader_hint = self._leader_hint
        n = len(self.endpoints)
        attempts = deque(self.endpoints[(start + i) % n] for i in range(n))
        hinted: set[str] = set()
        if leader_hint is not None and leader_hint not in self.endpoints:
            # a previously learned leader (advertised name differs from
            # the configured endpoints) goes FIRST — no follower hop tax
            attempts.appendleft(leader_hint)
            hinted.add(leader_hint)
        while attempts:
            ep = attempts.popleft()
            checkpoint("forward")  # typed raise once the budget is gone
            try:
                data = json.dumps(payload).encode() if payload is not None else None
                req = urllib.request.Request(
                    f"http://{ep}{path}",
                    data=data,
                    headers={"Content-Type": "application/json"},
                    method=method,
                )
                # Stamp the send time of THIS attempt (not the start of
                # the failover walk): lease deadlines derive from it, and
                # dead-endpoint connect timeouts burned before the
                # successful attempt must not be charged against the lease.
                sent_at = time.monotonic()
                with urllib.request.urlopen(
                    req, timeout=cap_timeout(self.timeout_s)
                ) as resp:
                    body = json.loads(resp.read().decode() or "{}")
                with self._lock:
                    if ep in self.endpoints:
                        self._preferred = self.endpoints.index(ep)
                        self._leader_hint = None
                    else:
                        self._leader_hint = ep  # remember the real leader
                if sent_at_out is not None:
                    sent_at_out["sent_at"] = sent_at
                return body
            except urllib.error.HTTPError as e:
                try:
                    detail_body = json.loads(e.read().decode())
                    detail = detail_body.get("error", str(e))
                except Exception:
                    detail_body, detail = {}, str(e)
                if e.code == 421:
                    # HA mode: a follower names the leader — try it NEXT
                    # (ref: horaemeta non-leader forwarding); each hint is
                    # followed at most once to bound the walk.
                    leader = detail_body.get("leader")
                    if leader and leader != ep and leader not in hinted:
                        hinted.add(leader)
                        attempts.appendleft(leader)
                    last_err = MetaError(detail)
                    continue
                if e.code == 404:
                    raise MetaError(f"not found: {detail}") from e
                raise MetaError(detail) from e
            except Exception as e:  # connection refused / timeout -> next
                last_err = e
        raise MetaError(f"no meta endpoint reachable: {last_err}")

    # ---- API ------------------------------------------------------------
    def heartbeat(self, endpoint: str) -> dict:
        return self._call("POST", "/meta/v1/node/heartbeat", {"endpoint": endpoint})

    def heartbeat_timed(self, endpoint: str) -> tuple[dict, float]:
        """Heartbeat plus the monotonic send time of the SUCCESSFUL
        request attempt — the instant its lease grants are valid from.
        Returned per-call (not via shared state): any concurrent meta
        call from another thread must not be able to push a lease
        deadline later than the coordinator's actual grant."""
        out: dict = {}
        body = self._call(
            "POST",
            "/meta/v1/node/heartbeat",
            {"endpoint": endpoint},
            sent_at_out=out,
        )
        return body, out.get("sent_at", time.monotonic())

    def create_table(self, name: str, create_sql: str) -> dict:
        return self._call(
            "POST", "/meta/v1/table/create", {"name": name, "create_sql": create_sql}
        )

    def drop_table(self, name: str) -> dict:
        return self._call("POST", "/meta/v1/table/drop", {"name": name})

    def route(self, table: str) -> Optional[dict]:
        try:
            return self._call("GET", f"/meta/v1/route/{table}")
        except MetaError as e:
            if "not found" in str(e):
                return None
            raise
