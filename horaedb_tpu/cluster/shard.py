"""Shard state machine (ref: cluster/src/shard_set.rs:38-228).

A shard is the unit of table placement and failover. States and the legal
transitions mirror the reference:

    INIT -> OPENING -> READY -> FROZEN
                 \\______________/
                  (close: any -> INIT)

Version fencing: every mutation carries the shard version; stale updates
(version <= current) are rejected (ref: cluster/src/lib.rs:145-158 —
without this, a node that lost its lease could double-apply changes).
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field
from typing import Optional


class ShardState(enum.Enum):
    INIT = "init"
    OPENING = "opening"
    READY = "ready"
    FROZEN = "frozen"


class ShardError(RuntimeError):
    pass


@dataclass
class ShardInfo:
    shard_id: int
    version: int = 0
    table_ids: tuple[int, ...] = ()


class Shard:
    def __init__(self, info: ShardInfo) -> None:
        self._info = info
        self._state = ShardState.INIT
        self._lock = threading.Lock()
        # Bootstrap sentinel: only a shard created at version 0 may accept
        # its first update unfenced; after that every update must advance
        # the version (ref: shard-version checks, cluster/src/lib.rs:145).
        self._installed = info.version > 0

    @property
    def shard_id(self) -> int:
        return self._info.shard_id

    @property
    def state(self) -> ShardState:
        return self._state

    @property
    def version(self) -> int:
        return self._info.version

    @property
    def table_ids(self) -> tuple[int, ...]:
        return self._info.table_ids

    # ---- transitions ----------------------------------------------------
    def begin_open(self) -> None:
        with self._lock:
            if self._state is not ShardState.INIT:
                raise ShardError(f"shard {self.shard_id}: open from {self._state}")
            self._state = ShardState.OPENING

    def finish_open(self) -> None:
        with self._lock:
            if self._state is not ShardState.OPENING:
                raise ShardError(
                    f"shard {self.shard_id}: finish_open from {self._state}"
                )
            self._state = ShardState.READY

    def freeze(self) -> None:
        """Stop serving writes ahead of a transfer, or on lease loss
        (ref: Frozen state; shard_lock_manager.rs lock-loss reaction)."""
        with self._lock:
            if self._state is not ShardState.READY:
                raise ShardError(f"shard {self.shard_id}: freeze from {self._state}")
            self._state = ShardState.FROZEN

    def thaw(self) -> None:
        """Resume serving after the lease came back (a frozen shard whose
        owner re-heartbeated before the coordinator moved it)."""
        with self._lock:
            if self._state is not ShardState.FROZEN:
                raise ShardError(f"shard {self.shard_id}: thaw from {self._state}")
            self._state = ShardState.READY

    def close(self) -> None:
        with self._lock:
            self._state = ShardState.INIT

    def ensure_writable(self) -> None:
        if self._state is ShardState.FROZEN:
            # Frozen IS the fence (lease lapsed, or a transfer in
            # flight) — say so: operators and clients look for the word.
            raise ShardError(
                f"shard {self.shard_id} frozen — write fenced "
                "(lease lapsed or transfer in progress)"
            )
        if self._state is not ShardState.READY:
            raise ShardError(
                f"shard {self.shard_id} not writable (state={self._state.value})"
            )

    # ---- version-fenced updates ----------------------------------------
    def apply_update(self, new_info: ShardInfo) -> None:
        """Install new membership; stale versions are fenced off."""
        with self._lock:
            if self._installed and new_info.version <= self._info.version:
                raise ShardError(
                    f"stale shard update: v{new_info.version} <= v{self._info.version}"
                )
            self._info = new_info
            self._installed = True


class ShardSet:
    """All shards this node serves (ref: shard_set.rs ShardSet)."""

    def __init__(self) -> None:
        self._shards: dict[int, Shard] = {}
        self._lock = threading.Lock()

    def insert(self, shard: Shard) -> None:
        with self._lock:
            if shard.shard_id in self._shards:
                raise ShardError(f"shard {shard.shard_id} already present")
            self._shards[shard.shard_id] = shard

    def get(self, shard_id: int) -> Optional[Shard]:
        with self._lock:
            return self._shards.get(shard_id)

    def remove(self, shard_id: int) -> Optional[Shard]:
        with self._lock:
            return self._shards.pop(shard_id, None)

    def all_shards(self) -> list[Shard]:
        with self._lock:
            return list(self._shards.values())

    def ready_count(self) -> int:
        return sum(1 for s in self.all_shards() if s.state is ShardState.READY)
