"""Table -> node routing (ref: src/router — Router trait lib.rs:80,
RuleBasedRouter rule_based.rs, hash.rs).

``RuleBasedRouter``: static config assigns tables to endpoints explicitly;
unlisted tables hash onto the endpoint list (stable, like the reference's
hash router), so a fixed topology needs no per-table configuration.
``ClusterBasedRouter``: meta-driven routes with a TTL cache
(ref: cluster_based.rs + the route cache config, router/src/lib.rs:100).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional, Sequence

import xxhash


@dataclass(frozen=True)
class Route:
    table: str
    endpoint: str  # "host:port" — the shard LEADER (write target)
    is_local: bool
    # Where the answer came from — the write path treats these
    # differently (see server/http.py write fencing):
    #   "owned"        this node's shard set says local
    #   "meta"         the coordinator answered
    #   "meta-unknown" the coordinator answered: no such table
    #   "static"       rule/hash config (static clustering, standalone)
    #   "fallback"     coordinator UNREACHABLE — not authoritative
    source: str = "static"
    # Follower (read-replica) endpoints serving bounded-staleness reads,
    # and the shard epoch (version) the route was learned at — forwarded
    # replica reads carry the epoch so a follower trailing a transfer
    # refuses instead of serving a pre-fence view.
    replicas: tuple[str, ...] = ()
    epoch: int = 0


class Router(ABC):
    @abstractmethod
    def route(self, table: str) -> Route: ...

    def endpoints(self) -> list[str]:
        return []

    def invalidate(self, table: str) -> None:
        """Drop any cached route for ``table`` (no-op for cache-less
        routers) — called when a caller observes a stale-route error."""


class LocalOnlyRouter(Router):
    """Standalone mode: this node owns everything."""

    def __init__(self, self_endpoint: str = "local") -> None:
        self.self_endpoint = self_endpoint

    def route(self, table: str) -> Route:
        return Route(table, self.self_endpoint, True)

    def endpoints(self) -> list[str]:
        return [self.self_endpoint]


class ClusterBasedRouter(Router):
    """Routes via the coordinator's table->shard->node map, cached with a
    TTL. The local shard set short-circuits: tables this node serves are
    local without a meta round-trip (and stay correct through failover —
    shard orders update the cluster impl before routes matter)."""

    def __init__(
        self,
        cluster,
        meta_client,
        cache_ttl_s: float = 5.0,
        negative_ttl_s: float = 1.0,
    ) -> None:
        import time

        self.cluster = cluster
        self.meta = meta_client
        self.cache_ttl_s = cache_ttl_s
        # Unknown-table and coordinator-down answers are also cached
        # (briefly): without this, an outage makes EVERY request pay the
        # full meta endpoint sweep with connect timeouts.
        self.negative_ttl_s = negative_ttl_s
        self._cache: dict[str, tuple[float, Route]] = {}
        self._time = time.monotonic

    @property
    def self_endpoint(self) -> str:
        return self.cluster.self_endpoint

    def pick_replica(self, route: Route, exclude: str = "") -> Optional[str]:
        """Least-loaded follower for a replica-served read: a per-router
        round-robin over the route's replica set (uniform spread is the
        least-loaded policy available without follower load feedback),
        skipping ``exclude`` (usually self)."""
        candidates = [r for r in route.replicas if r and r != exclude]
        if not candidates:
            return None
        import itertools

        rr = self.__dict__.setdefault("_replica_rr", itertools.count())
        return candidates[next(rr) % len(candidates)]

    def route(self, table: str) -> Route:
        if self.cluster.owns_table(table):
            return Route(
                table, self.self_endpoint, True, source="owned",
                replicas=self.cluster.replicas_of_table(table),
            )
        now = self._time()
        hit = self._cache.get(table)
        if hit is not None:
            ttl = (
                self.cache_ttl_s if hit[1].source == "meta" else self.negative_ttl_s
            )
            if now - hit[0] < ttl:
                return hit[1]
        try:
            info = self.meta.route(table)
        except Exception:
            # Coordinator unreachable. Reads degrade to local (owned
            # tables keep serving; others produce table-not-found); the
            # write path sees source="fallback" and REFUSES — accepting a
            # write here could make two nodes write one table.
            r = Route(table, self.self_endpoint, True, source="fallback")
            self._cache[table] = (now, r)
            return r
        if info is None or not info.get("node"):
            r = Route(table, self.self_endpoint, True, source="meta-unknown")
            self._cache[table] = (now, r)
            return r
        ep = info["node"]
        r = Route(
            table, ep, ep == self.self_endpoint, source="meta",
            replicas=tuple(info.get("replicas") or ()),
            epoch=int(info.get("version") or 0),
        )
        self._cache[table] = (now, r)
        return r

    def invalidate(self, table: str) -> None:
        self._cache.pop(table, None)


class RuleBasedRouter(Router):
    def __init__(
        self,
        self_endpoint: str,
        endpoints: Sequence[str],
        table_rules: Optional[dict[str, str]] = None,
    ) -> None:
        """``endpoints``: every node in the topology (must include self).
        ``table_rules``: explicit table -> endpoint pins."""
        if self_endpoint not in endpoints:
            raise ValueError(
                f"self endpoint {self_endpoint!r} not in topology {list(endpoints)}"
            )
        self.self_endpoint = self_endpoint
        self._endpoints = list(endpoints)
        self._rules = dict(table_rules or {})
        for t, ep in self._rules.items():
            if ep not in self._endpoints:
                raise ValueError(f"rule for {t!r} targets unknown endpoint {ep!r}")

    def route(self, table: str) -> Route:
        ep = self._rules.get(table)
        if ep is None:
            # Stable hash over the table name onto the endpoint ring.
            idx = xxhash.xxh64_intdigest(table.encode()) % len(self._endpoints)
            ep = self._endpoints[idx]
        return Route(table, ep, ep == self.self_endpoint)

    def endpoints(self) -> list[str]:
        return list(self._endpoints)
