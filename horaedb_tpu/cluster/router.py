"""Table -> node routing (ref: src/router — Router trait lib.rs:80,
RuleBasedRouter rule_based.rs, hash.rs).

``RuleBasedRouter``: static config assigns tables to endpoints explicitly;
unlisted tables hash onto the endpoint list (stable, like the reference's
hash router), so a fixed topology needs no per-table configuration.
``ClusterBasedRouter`` (meta-driven, cached routes) arrives with the
coordinator in a later round behind the same interface.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional, Sequence

import xxhash


@dataclass(frozen=True)
class Route:
    table: str
    endpoint: str  # "host:port"
    is_local: bool


class Router(ABC):
    @abstractmethod
    def route(self, table: str) -> Route: ...

    def endpoints(self) -> list[str]:
        return []


class LocalOnlyRouter(Router):
    """Standalone mode: this node owns everything."""

    def __init__(self, self_endpoint: str = "local") -> None:
        self.self_endpoint = self_endpoint

    def route(self, table: str) -> Route:
        return Route(table, self.self_endpoint, True)

    def endpoints(self) -> list[str]:
        return [self.self_endpoint]


class RuleBasedRouter(Router):
    def __init__(
        self,
        self_endpoint: str,
        endpoints: Sequence[str],
        table_rules: Optional[dict[str, str]] = None,
    ) -> None:
        """``endpoints``: every node in the topology (must include self).
        ``table_rules``: explicit table -> endpoint pins."""
        if self_endpoint not in endpoints:
            raise ValueError(
                f"self endpoint {self_endpoint!r} not in topology {list(endpoints)}"
            )
        self.self_endpoint = self_endpoint
        self._endpoints = list(endpoints)
        self._rules = dict(table_rules or {})
        for t, ep in self._rules.items():
            if ep not in self._endpoints:
                raise ValueError(f"rule for {t!r} targets unknown endpoint {ep!r}")

    def route(self, table: str) -> Route:
        ep = self._rules.get(table)
        if ep is None:
            # Stable hash over the table name onto the endpoint ring.
            idx = xxhash.xxh64_intdigest(table.encode()) % len(self._endpoints)
            ep = self._endpoints[idx]
        return Route(table, ep, ep == self.self_endpoint)

    def endpoints(self) -> list[str]:
        return list(self._endpoints)
