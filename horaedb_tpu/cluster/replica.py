"""Replicated follower reads — the shared vocabulary of the scale-out
read path (ref: the shard_lock_manager.rs lease-fencing model applied to
READ scale-out: writes stay single-leader, but all durable data lives in
shared object storage, so follower nodes can open a shard read-only,
tail the leader's manifest, and serve bounded-staleness reads; the
TiKV-PD stance in PAPER.md, and StreamBox-HBM's replicate-the-read-side
scaling in PAPERS.md).

This module holds what every layer agrees on:

- the typed, retryable refusal errors a follower raises instead of
  serving past its guarantees (``ReplicaFencedError`` — lease lapsed or
  epoch trails a transfer; ``ReplicaStaleError`` — the query's range
  needs data beyond the follower's watermark);
- the ``horaedb_replica_*`` metric families (lint-enforced registry);
- the ContextVars that carry "this statement is being served from a
  follower" into the proxy's ledger (``route=follower`` +
  ``replica_lag_ms`` in ``system.public.query_stats`` on every wire)
  and back out to the HTTP response headers.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Optional

from ..utils.metrics import REGISTRY

# Declared registry of the replica metric families — the lint in
# tests/test_observability.py checks each is registered live,
# convention-clean, and documented in docs/OBSERVABILITY.md, and that no
# stray horaedb_replica_* family exists outside it.
REPLICA_METRIC_FAMILIES = (
    "horaedb_replica_reads_total",
    "horaedb_replica_watermark_lag_seconds",
)

# Outcomes of one replica-read attempt, labeled on the reads family:
#   served          a follower answered from its manifest snapshot
#   fenced          a follower refused: lease lapsed / epoch trails
#   stale_fallback  the read fell back to the leader (range beyond the
#                   follower's watermark, or a follower refusal)
REPLICA_READ_OUTCOMES = ("served", "fenced", "stale_fallback")

# Eager registration: series exist from the first scrape (and the lint).
_M_READS = {
    o: REGISTRY.counter(
        "horaedb_replica_reads_total",
        "replica (follower) read attempts by outcome",
        labels={"outcome": o},
    )
    for o in REPLICA_READ_OUTCOMES
}
_M_WM_LAG = REGISTRY.gauge(
    "horaedb_replica_watermark_lag_seconds",
    "worst follower freshness lag (now - last installed flush) across "
    "the replica tables this node serves",
)


def note_replica_read(outcome: str) -> None:
    c = _M_READS.get(outcome)
    if c is not None:
        c.inc()


def set_watermark_lag(lag_s: float) -> None:
    _M_WM_LAG.set(max(0.0, lag_s))


class ReplicaFencedError(RuntimeError):
    """A follower refusing to serve because it can no longer prove its
    view of the topology: its replica lease lapsed (cut off from the
    coordinator past one TTL) or its shard epoch trails a transfer the
    caller has already observed. Retryable by contract — the caller
    falls back to the leader (or retries after the fence heals)."""

    def __init__(self, msg: str, epoch: int = 0, retry_after_s: float = 1.0):
        super().__init__(msg)
        self.epoch = epoch
        self.retry_after_s = retry_after_s


class ReplicaStaleError(RuntimeError):
    """A follower refusing a read whose time range needs data beyond its
    freshness watermark (and no staleness opt-in covers the lag).
    Retryable by contract — the caller serves it from the leader."""

    def __init__(self, msg: str, epoch: int = 0,
                 watermark_ms: int = 0, retry_after_s: float = 0.5):
        super().__init__(msg)
        self.epoch = epoch
        self.watermark_ms = watermark_ms
        self.retry_after_s = retry_after_s


# ---- serving context -------------------------------------------------------

# Set (in the worker thread) around a follower-served statement so the
# proxy's ledger finalization stamps route=follower + replica_lag_ms, and
# EXPLAIN renders the Replica: line — without threading a parameter
# through every layer.
_REPLICA_CTX: contextvars.ContextVar[Optional[dict]] = contextvars.ContextVar(
    "horaedb_replica_serving", default=None
)

# Set in the REQUEST TASK's context (async side) so the HTTP handler can
# attach X-HoraeDB-Replica-* headers after gateway.execute returns.
REPLICA_RESPONSE: contextvars.ContextVar[Optional[dict]] = (
    contextvars.ContextVar("horaedb_replica_response", default=None)
)


@contextlib.contextmanager
def replica_serving(table: str, epoch: int, lag_ms: int):
    token = _REPLICA_CTX.set(
        {"table": table, "epoch": int(epoch), "lag_ms": int(lag_ms)}
    )
    try:
        yield
    finally:
        _REPLICA_CTX.reset(token)


def replica_context() -> Optional[dict]:
    return _REPLICA_CTX.get()
