"""Cluster mode: shard membership + routing + forwarding
(ref: src/cluster, src/router, proxy/src/forward.rs).

Round-1 scope is the data plane of static clustering:

- ``shard``  — the Shard/ShardSet state machine {INIT, OPENING, READY,
               FROZEN} with version fencing (ref: shard_set.rs:38-228);
- ``router`` — table -> node routing; ``RuleBasedRouter`` from static
               config (ref: rule_based.rs), hash fallback for unlisted
               tables;
- HTTP forwarding in the server: a request for a table owned by another
  node proxies to the owner with loop protection (ref: forward.rs).

The coordinator (horaemeta analog: heartbeats, shard scheduling, etcd
leases) is round-2 work; the interfaces here are shaped so it slots in as
a ``ClusterBasedRouter`` + shard-event handlers.
"""

from .router import Route, Router, RuleBasedRouter
from .shard import Shard, ShardSet, ShardState

__all__ = ["Route", "Router", "RuleBasedRouter", "Shard", "ShardSet", "ShardState"]
