"""Cluster mode: shard membership + routing + forwarding
(ref: src/cluster, src/router, proxy/src/forward.rs).

- ``shard``        — the Shard/ShardSet state machine {INIT, OPENING,
                     READY, FROZEN} with version fencing
                     (ref: shard_set.rs:38-228);
- ``router``       — table -> node routing; ``RuleBasedRouter`` from
                     static config (ref: rule_based.rs), hash fallback for
                     unlisted tables; ``ClusterBasedRouter`` from the
                     coordinator with a TTL route cache
                     (ref: cluster_based.rs);
- ``meta_client``  — HTTP client to the coordinator with endpoint
                     failover (ref: meta_client/src/lib.rs:100-116);
- ``cluster_impl`` — the node's heartbeat loop + shard reconciliation +
                     lease-fenced write barrier
                     (ref: cluster_impl.rs, shard_lock_manager.rs);
- HTTP forwarding in the server: a request for a table owned by another
  node proxies to the owner with loop protection (ref: forward.rs);
- ``replica``      — replicated follower reads: the typed retryable
                     fencing/staleness refusals, the horaedb_replica_*
                     metric registry, and the serving ContextVars that
                     stamp route=follower into the ledger.

The coordinator itself lives in ``horaedb_tpu.meta``.
"""

from .cluster_impl import ClusterImpl
from .meta_client import MetaClient, MetaError
from .replica import (
    REPLICA_METRIC_FAMILIES,
    ReplicaFencedError,
    ReplicaStaleError,
)
from .router import ClusterBasedRouter, Route, Router, RuleBasedRouter
from .shard import Shard, ShardError, ShardSet, ShardState

__all__ = [
    "ClusterBasedRouter",
    "ClusterImpl",
    "MetaClient",
    "MetaError",
    "REPLICA_METRIC_FAMILIES",
    "ReplicaFencedError",
    "ReplicaStaleError",
    "Route",
    "Router",
    "RuleBasedRouter",
    "Shard",
    "ShardError",
    "ShardSet",
    "ShardState",
]
