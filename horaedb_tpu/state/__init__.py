"""Streaming state: device-resident incremental aggregates.

``livewindow`` keeps hot (table, window, group-set) partial aggregates
in device ring buffers, folded at write time, so an open-tail dashboard
refresh is a gather over O(buckets) partials instead of a raw rescan.
"""

from .livewindow import (  # noqa: F401
    LIVEWINDOW_METRIC_FAMILIES,
    LiveWindowDecision,
    STORE,
    livewindow_decision_for,
    livewindow_enabled,
    try_livewindow_counter,
    try_livewindow_serve,
)
