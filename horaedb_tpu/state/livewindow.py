"""Live window state: device-resident incremental aggregates for the
open tail (ROADMAP item 1; ref: StreamBox-HBM's ingest-time grouping
into HBM, PAPERS.md).

Rollups (rules/rewrite.py) answer for CLOSED buckets; the open tail —
the "last 5m" edge every dashboard re-asks — still rescanned raw. This
module keeps that tail as STATE: per hot (table, window, group-set)
shape, a fixed-size device ring of (count, sum, min, max) partials per
time bucket, folded per ingest batch by ONE fused scatter kernel
(ops/livewindow.py), so an open-tail refresh is a gather over
O(buckets) partials instead of a raw rescan.

Correctness contract (answers are never wrong):

- Additive partials are order-free — a late row landing in a
  still-RESIDENT bucket folds in exactly.
- A row OLDER than the ring's tail cannot fold (its slot was reused);
  its bucket is marked dirty-for-rescan. Dirty buckets sit below the
  serving floor by construction — any query touching them reads raw
  (``horaedb_livewindow_dirty_rescan_total`` counts those reads).
- ``valid_from`` guards the promotion race: the state registers (so
  concurrent commits fold) BEFORE the table's max timestamp is read;
  serving starts strictly above that bucket, so every pre-registration
  row sits below the floor.
- NULL / non-finite values in the value column cannot be represented by
  the monoid cells; a batch carrying one drops the state (the shape can
  re-promote; meanwhile every read is raw).
- PromQL counter chains are order-SENSITIVE: per-bucket increments are
  folded at write time (same-bucket consecutive pairs), per-bucket
  first/last samples ride a packed host sidecar, and cross-bucket
  deltas are reconstructed at read time. An out-of-order sample marks
  the spanned buckets counter-dirty — counter reads above that span
  stay exact, reads into it fall back to raw.

Promotion is usage-driven (the PR-6 dtype auto-tuner discipline): the
executor hook counts eligible open-tail reads per shape and promotes at
``HORAEDB_LIVEWINDOW_PROMOTE`` sightings. Eviction is LRU under the
``HORAEDB_LIVEWINDOW_BUDGET`` byte budget; every byte is accounted
through ``register_occupancy_provider`` (component="state" rows in
``system.public.device``). Promote/evict choices are journaled in the
decision plane (loop="livewindow": predicted hit-count vs realized hits
before eviction). ``HORAEDB_LIVEWINDOW=0`` kills fold, serve, and
promotion; states dropped on the next write so a re-enable can never
serve a fold gap.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..common_types.dict_column import DictColumn
from ..common_types.schema import TSID_COLUMN
from ..common_types.time_range import MAX_TIMESTAMP, MIN_TIMESTAMP
from ..engine.options import UpdateMode
from ..query import ast
from ..query.plan import QueryPlan
from ..utils.env import env_int
from ..utils.metrics import REGISTRY

_FOLDABLE = ("sum", "count", "min", "max", "avg")

_INT64_MAX = np.iinfo(np.int64).max
_FAR_PAST = -(2**61)

# Registry discipline (lint-enforced like DEVICE_METRIC_FAMILIES):
# declared here, registered eagerly, documented in docs/OBSERVABILITY.md,
# no stray horaedb_livewindow_* family outside this tuple.
LIVEWINDOW_METRIC_FAMILIES = (
    "horaedb_livewindow_reads_total",
    "horaedb_livewindow_folds_total",
    "horaedb_livewindow_dirty_rescan_total",
    "horaedb_livewindow_evictions_total",
    "horaedb_livewindow_resident_bytes",
)

_M_READS = REGISTRY.counter(
    "horaedb_livewindow_reads_total",
    "queries served (in part) from live window state, by read kind",
    labels={"kind": "sql"},
)
_M_READS_PROMQL = REGISTRY.counter(
    "horaedb_livewindow_reads_total",
    "queries served (in part) from live window state, by read kind",
    labels={"kind": "promql"},
)
_M_FOLDS = REGISTRY.counter(
    "horaedb_livewindow_folds_total",
    "ingest batches folded into live window rings",
)
_M_DIRTY = REGISTRY.counter(
    "horaedb_livewindow_dirty_rescan_total",
    "reads that rescanned raw because of dirty (below-tail/out-of-order) buckets",
)
_M_EVICTIONS = REGISTRY.counter(
    "horaedb_livewindow_evictions_total",
    "live window states evicted (LRU under the byte budget)",
)
_M_RESIDENT = REGISTRY.gauge(
    "horaedb_livewindow_resident_bytes",
    "device bytes held by live window ring state",
)


# ---- knobs ([state] table in docs/WORKLOAD.md) ---------------------------


def livewindow_enabled() -> bool:
    """HORAEDB_LIVEWINDOW=0 kills fold + serve + promotion (read per
    call so tests/operators can flip it live)."""
    return os.environ.get("HORAEDB_LIVEWINDOW", "1") != "0"


def budget_bytes() -> int:
    return env_int("HORAEDB_LIVEWINDOW_BUDGET", 64 << 20)


def ring_depth() -> int:
    return max(8, env_int("HORAEDB_LIVEWINDOW_DEPTH", 128))


def promote_reads() -> int:
    return max(1, env_int("HORAEDB_LIVEWINDOW_PROMOTE", 3))


def max_groups() -> int:
    return max(8, env_int("HORAEDB_LIVEWINDOW_MAX_GROUPS", 4096))


# ---- tag-filter conjuncts -------------------------------------------------
# The serve side applies tag filters to the state's group tuples on
# host, so the ONE predicate must only admit conjunct shapes the tiny
# evaluator below supports (SQL three-valued logic: NULL compares false).


def _cmp(op: str, a, b) -> bool:
    if a is None or b is None:
        return False
    try:
        if op == "=":
            return bool(a == b)
        if op in ("!=", "<>"):
            return bool(a != b)
        if op == "<":
            return bool(a < b)
        if op == "<=":
            return bool(a <= b)
        if op == ">":
            return bool(a > b)
        if op == ">=":
            return bool(a >= b)
    except TypeError:
        return False
    return False


def _conj_supported(e: ast.Expr, tags: set) -> bool:
    if isinstance(e, ast.BinaryOp):
        if e.op in ("AND", "OR"):
            return _conj_supported(e.left, tags) and _conj_supported(e.right, tags)
        if e.op in ("=", "!=", "<>", "<", "<=", ">", ">="):
            l, r = e.left, e.right
            if isinstance(l, ast.Literal) and isinstance(r, ast.Column):
                l, r = r, l
            return (
                isinstance(l, ast.Column)
                and l.name in tags
                and isinstance(r, ast.Literal)
            )
        return False
    if isinstance(e, ast.UnaryOp) and e.op == "NOT":
        return _conj_supported(e.operand, tags)
    if isinstance(e, ast.InList):
        return (
            isinstance(e.expr, ast.Column)
            and e.expr.name in tags
            and all(isinstance(i, ast.Literal) for i in e.values)
        )
    if isinstance(e, ast.Between):
        return (
            isinstance(e.expr, ast.Column)
            and e.expr.name in tags
            and isinstance(e.low, ast.Literal)
            and isinstance(e.high, ast.Literal)
        )
    return False


def _eval_conj(e: ast.Expr, vals: dict) -> bool:
    if isinstance(e, ast.BinaryOp):
        if e.op == "AND":
            return _eval_conj(e.left, vals) and _eval_conj(e.right, vals)
        if e.op == "OR":
            return _eval_conj(e.left, vals) or _eval_conj(e.right, vals)
        l, r, op = e.left, e.right, e.op
        if isinstance(l, ast.Literal) and isinstance(r, ast.Column):
            l, r = r, l
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        return _cmp(op, vals.get(l.name), r.value)
    if isinstance(e, ast.UnaryOp) and e.op == "NOT":
        return not _eval_conj(e.operand, vals)
    if isinstance(e, ast.InList):
        v = vals.get(e.expr.name)
        hit = v is not None and any(v == i.value for i in e.values)
        return (not hit) if e.negated else hit
    if isinstance(e, ast.Between):
        v = vals.get(e.expr.name)
        hit = v is not None and e.low.value <= v <= e.high.value
        return (not hit) if e.negated else hit
    return False


# ---- the per-shape state --------------------------------------------------


class LiveState:
    """One promoted (table, window, group-set) shape's ring state."""

    def __init__(self, key: str, table_name: str, ts_col: str,
                 value_col: str, tags: tuple, bucket_ms: int,
                 depth: int, table_data) -> None:
        from ..ops.livewindow import alloc_rings

        self.key = key
        self.table_name = table_name
        self.ts_col = ts_col
        self.value_col = value_col
        self.tags = tags  # grouping tags, plan order
        self.all_tags = False  # set by the store: group-set == full tag set
        self.bucket_ms = int(bucket_ms)
        self.depth = int(depth)
        self.cap = 64
        self.lock = threading.RLock()
        self.rings = alloc_rings(self.depth, self.cap)
        # host sidecar for the counter chain: packed (ts_rel<<32 | f32
        # bits) first/last sample per (slot, group)
        self.firsts = np.full((self.depth, self.cap), _INT64_MAX, np.int64)
        self.lasts = np.full((self.depth, self.cap), -1, np.int64)
        self.head = None  # highest folded bucket id; None = empty ring
        self.valid_from = _INT64_MAX  # first servable bucket id
        self.max_folded_ts = _FAR_PAST
        self.group_slots: dict[tuple, int] = {}
        self.group_vals: list[tuple] = []
        self.tsid_slot: dict[int, int] = {}
        self.series_last: dict[int, tuple] = {}  # tsid -> (ts, value)
        self.dirty: set[int] = set()  # below-tail late-row buckets
        self.counter_dirty: set[int] = set()  # broken counter-chain buckets
        self.reads_served = 0
        self.last_hit = time.time()
        self.created_at = time.time()
        self.anchor = weakref.ref(table_data)

    # -- residency --------------------------------------------------------

    def nbytes(self) -> int:
        from ..ops.livewindow import rings_nbytes

        return rings_nbytes(self.depth, self.cap)

    def tail(self) -> int:
        """Lowest resident bucket id (the ring covers [tail, head])."""
        return (self.head - self.depth + 1) if self.head is not None else _INT64_MAX

    def serve_floor(self, counter: bool = False) -> int:
        """First bucket id servable from state."""
        lo = max(self.valid_from, self.tail())
        if counter and self.counter_dirty:
            lo = max(lo, max(self.counter_dirty) + 1)
        return lo

    # -- write-time fold --------------------------------------------------

    def fold(self, rows) -> bool:
        """Fold one committed RowGroup; False => state must be dropped
        (unrepresentable batch: NULL/non-finite values)."""
        from ..ops.livewindow import fold_batch

        w = self.bucket_ms
        ts = np.asarray(rows.timestamps, dtype=np.int64)
        n = len(ts)
        if n == 0:
            return True
        raw = rows.column(self.value_col)
        if isinstance(raw, DictColumn):
            return False
        vals = np.asarray(raw, dtype=np.float64)
        if not rows.valid_mask(self.value_col).all() or not np.isfinite(vals).all():
            return False
        bucket = ts // w

        # group mapping: tsid -> dense slot (vectorized over UNIQUE series)
        if self.tags:
            if TSID_COLUMN not in rows.columns:
                return False
            tsid = np.asarray(rows.column(TSID_COLUMN), dtype=np.int64)
            uniq, inv = np.unique(tsid, return_inverse=True)
            first_idx = np.full(len(uniq), n, dtype=np.int64)
            np.minimum.at(first_idx, inv, np.arange(n, dtype=np.int64))
            slot_of = np.empty(len(uniq), dtype=np.int32)
            for j, sid in enumerate(uniq):
                g = self.tsid_slot.get(int(sid))
                if g is None:
                    i = int(first_idx[j])
                    key = tuple(_tag_at(rows, t, i) for t in self.tags)
                    g = self.group_slots.get(key)
                    if g is None:
                        g = self._add_group(key)
                        if g is None:
                            return False  # over HORAEDB_LIVEWINDOW_MAX_GROUPS
                    self.tsid_slot[int(sid)] = g
                slot_of[j] = g
            grp = slot_of[inv]
        else:
            tsid = np.zeros(n, dtype=np.int64)
            if not self.group_vals:
                self._add_group(())
            grp = np.zeros(n, dtype=np.int32)

        # ring advance: slots for buckets (old head, new head] re-init
        # INSIDE the fold dispatch via reset_mask
        bmax = int(bucket.max())
        reset = np.zeros(self.depth, dtype=np.bool_)
        if self.head is None:
            self.head = bmax  # fresh rings are already at init state
        elif bmax > self.head:
            adv = bmax - self.head
            if adv >= self.depth:
                reset[:] = True
            else:
                ids = np.arange(self.head + 1, bmax + 1, dtype=np.int64)
                reset[ids % self.depth] = True
            self.head = bmax
            self.firsts[reset] = _INT64_MAX
            self.lasts[reset] = -1
            if self.dirty:
                horizon = self.tail() - 4 * self.depth
                self.dirty = {b for b in self.dirty if b >= horizon}
            if self.counter_dirty:
                self.counter_dirty = {
                    b for b in self.counter_dirty if b >= self.tail()
                }

        tail = self.tail()
        in_ring = bucket >= tail
        if not in_ring.all():
            # older than the ring's tail: can't fold (slot reused) —
            # dirty-for-rescan; those buckets are below the serving
            # floor so answers stay exact
            self.dirty.update(int(b) for b in np.unique(bucket[~in_ring]))
        slot = np.where(in_ring, bucket % self.depth, self.depth).astype(np.int32)

        p_slot, p_grp, p_delta = self._counter_prep(
            ts, vals, bucket, slot, grp, tsid, tail
        )
        self.rings = fold_batch(
            self.rings, reset, slot, grp, vals.astype(np.float32),
            p_slot, p_grp, p_delta,
        )
        self.max_folded_ts = max(self.max_folded_ts, int(ts.max()))
        _M_FOLDS.inc()
        return True

    def _add_group(self, key: tuple) -> Optional[int]:
        import jax.numpy as jnp

        g = len(self.group_vals)
        if g >= max_groups():
            return None
        if g >= self.cap:
            newcap = self.cap * 2
            extra = newcap - self.cap
            pad = lambda a, v: jnp.pad(  # noqa: E731
                a, ((0, 0), (0, extra)), constant_values=v
            )
            c, s, mn, mx, inc = self.rings
            self.rings = (
                pad(c, 0), pad(s, 0.0),
                pad(mn, jnp.inf), pad(mx, -jnp.inf), pad(inc, 0.0),
            )
            self.firsts = np.pad(
                self.firsts, ((0, 0), (0, extra)), constant_values=_INT64_MAX
            )
            self.lasts = np.pad(
                self.lasts, ((0, 0), (0, extra)), constant_values=-1
            )
            self.cap = newcap
        self.group_slots[key] = g
        self.group_vals.append(key)
        return g

    def _counter_prep(self, ts, vals, bucket, slot, grp, tsid, tail):
        """Write-time counter chain: reset-adjusted deltas of
        consecutive SAME-SERIES SAME-BUCKET pairs (cross-bucket pairs
        are reconstructed at read time from the first/last sidecar).
        Returns the pair scatter arrays; updates sidecar + dirty sets.
        Vectorized over rows; python loops touch UNIQUE series only."""
        empty = (np.empty(0, np.int32), np.empty(0, np.int32),
                 np.empty(0, np.float32))
        if not self.all_tags:
            return empty
        w = self.bucket_ms
        order = np.lexsort((ts, tsid))
        sts, sv = ts[order], vals[order]
        sbucket, sslot = bucket[order], slot[order]
        sgrp, stsid = grp[order], tsid[order]
        n = len(sts)

        new_series = np.empty(n, dtype=np.bool_)
        new_series[0] = True
        new_series[1:] = stsid[1:] != stsid[:-1]
        starts = np.flatnonzero(new_series)
        ends = np.append(starts[1:], n) - 1

        # splice the carried per-series last sample in front of each run
        prev_ts = np.empty(n, dtype=np.int64)
        prev_v = np.empty(n, dtype=np.float64)
        prev_ok = np.empty(n, dtype=np.bool_)
        prev_ts[1:], prev_v[1:] = sts[:-1], sv[:-1]
        prev_ok[1:] = ~new_series[1:]
        prev_ok[0] = False
        for i in starts:
            carried = self.series_last.get(int(stsid[i]))
            if carried is not None:
                prev_ts[i], prev_v[i] = carried
                prev_ok[i] = True
        # update carried lasts to each run's final sample
        for i, j in zip(starts, ends):
            self.series_last[int(stsid[i])] = (int(sts[j]), float(sv[j]))

        # out-of-order / duplicate timestamps break the chain for the
        # spanned buckets: additive partials stay exact, counter reads
        # into the span fall back to raw
        ooo = prev_ok & (prev_ts >= sts)
        for i in np.flatnonzero(ooo):
            lo_b, hi_b = int(sts[i] // w), int(prev_ts[i] // w)
            self.counter_dirty.update(range(lo_b, hi_b + 1))
            if len(self.counter_dirty) > 8192:
                self.counter_dirty = {max(self.counter_dirty)}
        good = prev_ok & ~ooo

        # packed first/last sidecar per (slot, group) — in-ring rows only
        ring_rows = sslot < self.depth
        ts_rel = sts - sbucket * w
        packed = (ts_rel.astype(np.int64) << 32) | (
            sv.astype(np.float32).view(np.uint32).astype(np.int64)
        )
        ri = np.flatnonzero(ring_rows)
        if len(ri):
            np.minimum.at(self.firsts, (sslot[ri], sgrp[ri]), packed[ri])
            np.maximum.at(self.lasts, (sslot[ri], sgrp[ri]), packed[ri])

        # same-bucket consecutive pairs -> write-time increments
        pair = good & (prev_ts // w == sbucket) & ring_rows
        pi = np.flatnonzero(pair)
        if not len(pi):
            return empty
        delta = sv[pi] - prev_v[pi]
        delta = np.where(delta < 0, sv[pi], delta)  # counter reset
        return (
            sslot[pi].astype(np.int32),
            sgrp[pi].astype(np.int32),
            delta.astype(np.float32),
        )

    # -- read-time gather -------------------------------------------------

    def read_buckets(self, b_lo: int, b_hi: int):
        """Host partials for bucket ids [b_lo, b_hi] (must be resident):
        (bucket_ids, counts, sums, mins, maxs, inc, firsts, lasts) with
        arrays shaped [n_buckets, n_groups]."""
        from ..ops.livewindow import gather_buckets

        hi = min(b_hi, self.head if self.head is not None else b_lo - 1)
        if hi < b_lo:
            z = np.zeros((0, len(self.group_vals)))
            return ([], z.astype(np.int64), z, z, z, z,
                    z.astype(np.int64), z.astype(np.int64))
        ids = list(range(b_lo, hi + 1))
        slots = [b % self.depth for b in ids]
        counts, sums, mins, maxs, inc = gather_buckets(self.rings, slots)
        g = len(self.group_vals)
        return (
            ids, counts[:, :g], sums[:, :g], mins[:, :g], maxs[:, :g],
            inc[:, :g], self.firsts[slots, :g], self.lasts[slots, :g],
        )


def _unpack_v(packed: np.ndarray) -> np.ndarray:
    """Low 32 bits of a packed sidecar cell -> the f32 sample value."""
    return (
        (packed & 0xFFFFFFFF).astype(np.uint32).view(np.float32)
        .astype(np.float64)
    )


def try_livewindow_counter(table_name: str, table, value_col: str,
                           start_ms: int, end_ms: int, step_ms: int,
                           push_matchers: list):
    """Serve the PromQL counter chain's resident COMPLETE buckets from
    the write-time folded increments (proxy/promql._counter_series):
    same-bucket consecutive-pair deltas were folded at ingest into the
    ``inc`` ring; cross-bucket deltas are reconstructed here from the
    packed first/last sidecar. Returns None (raw fold) or::

        {"serve_lo": ms, "tail_lo": ms, "n_buckets": int,
         "series": {canonical_key: {"buckets": {prom_bucket_ms: inc},
                                    "first": (ts, v), "last": (ts, v)}}}

    The caller bounds its raw scan to ``ts < serve_lo OR ts >= tail_lo``
    and stitches the chain at both boundaries; a head boundary delta
    counts only when the raw side has samples for the series (prom's
    in-range consecutive-pair rule). Only all-tag states qualify (the
    prom series key IS the full tag set) and the state window must
    divide the step so every ring bucket lands in exactly one step.
    """
    if not livewindow_enabled():
        return None
    cand = None
    for s in STORE.states_for_table(table_name):
        if (
            s.all_tags
            and s.value_col == value_col
            and step_ms % s.bucket_ms == 0
            and s.anchor() is getattr(table, "data", None)
        ):
            cand = s
            break
    if cand is None:
        return None
    w = cand.bucket_ms
    with cand.lock:
        if cand.head is None:
            return None
        b_lo = max(cand.serve_floor(counter=True), -(-start_ms // w))
        b_hi = min(cand.head, (end_ms + 1) // w - 1)
        # a counter-dirty span that actually cut servable buckets in
        # this range is a forced rescan
        plain_lo = max(cand.serve_floor(), -(-start_ms // w))
        if plain_lo < b_lo and plain_lo <= b_hi:
            _M_DIRTY.inc()
        if b_hi < b_lo:
            return None
        ids, counts, _s, _mn, _mx, inc, firsts, lasts = cand.read_buckets(
            b_lo, b_hi
        )
        groups = list(cand.group_vals)
        tags = cand.tags
        cand.reads_served += 1
        cand.last_hit = time.time()

    ids_arr = np.asarray(ids, dtype=np.int64)
    has = firsts != _INT64_MAX
    out_series: dict = {}
    for g, gv in enumerate(groups):
        # the pushed =/!= matchers the raw scan applies in SQL, with
        # SQL's three-valued semantics: a NULL tag fails both
        keep = True
        for label, op, val in push_matchers:
            try:
                tv = gv[tags.index(label)]
            except ValueError:
                keep = False
                break
            if tv is None or (str(tv) == str(val)) != (op == "="):
                keep = False
                break
        if not keep:
            continue
        ks = np.flatnonzero(has[:, g])
        if not len(ks):
            continue
        f_rel = (firsts[ks, g] >> 32).astype(np.int64)
        l_rel = (lasts[ks, g] >> 32).astype(np.int64)
        f_v = _unpack_v(firsts[ks, g])
        l_v = _unpack_v(lasts[ks, g])
        b_ms = ids_arr[ks] * w
        pb = (b_ms // step_ms) * step_ms  # W | step: one step per bucket
        buckets: dict = {}
        inc_g = np.asarray(inc)[ks, g]
        cnt_g = np.asarray(counts)[ks, g]
        for k in range(len(ks)):
            d = float(inc_g[k])
            pairs = int(cnt_g[k]) - 1  # intra-bucket consecutive pairs
            if k:
                cd = float(f_v[k] - l_v[k - 1])
                if cd < 0:
                    cd = float(f_v[k])  # counter reset across buckets
                d += cd
                pairs += 1
            # parity with the raw fold: a pair's delta lands in the
            # bucket even at 0.0; a single-sample bucket emits no point
            if pairs > 0:
                b = int(pb[k])
                buckets[b] = buckets.get(b, 0.0) + d
        key = tuple(sorted(zip(tags, gv)))
        out_series[key] = {
            "buckets": buckets,
            "first": (int(b_ms[0] + f_rel[0]), float(f_v[0])),
            "last": (int(b_ms[-1] + l_rel[-1]), float(l_v[-1])),
        }
    if not out_series:
        return None  # nothing resident matched: one raw scan is simpler
    _M_READS_PROMQL.inc()
    return {
        "serve_lo": b_lo * w,
        "tail_lo": (b_hi + 1) * w,
        "n_buckets": int(b_hi - b_lo + 1),
        "series": out_series,
    }


def _tag_at(rows, name: str, i: int):
    if not rows.valid_mask(name)[i]:
        return None
    col = rows.column(name)
    if isinstance(col, DictColumn):
        v = col.values[int(col.codes[i])]
    else:
        v = col[i]
    return v.item() if isinstance(v, np.generic) else v


# ---- the store ------------------------------------------------------------


class LiveWindowStore:
    """Process-global registry of promoted shapes: usage-driven
    promotion, LRU eviction under the byte budget, the occupancy
    provider, and the write-path fold entry point."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._states: dict[str, LiveState] = {}
        self._by_table: dict[str, list[str]] = {}
        self._usage: dict[str, int] = {}
        self._evictions: dict[str, int] = {}
        self._registered = False

    # -- lookup -----------------------------------------------------------

    def get(self, key: str) -> Optional[LiveState]:
        with self._lock:
            return self._states.get(key)

    def states_for_table(self, table_name: str) -> list[LiveState]:
        with self._lock:
            keys = self._by_table.get(table_name, [])
            return [self._states[k] for k in keys if k in self._states]

    def total_bytes(self) -> int:
        with self._lock:
            return sum(s.nbytes() for s in self._states.values())

    # -- occupancy provider (obs/device) ----------------------------------

    def snapshot_device(self) -> list[dict]:
        now = time.time()
        with self._lock:
            states = list(self._states.values())
            evictions = dict(self._evictions)
        rows = []
        for s in states:
            rows.append({
                "table_name": s.table_name,
                "column_name": s.value_col,
                "component": "state",
                "dtype": "f32",
                "bytes": int(s.nbytes()),
                "rows": int(s.depth * s.cap),
                "last_hit_age_ms": int((now - s.last_hit) * 1000),
                "evictions": int(evictions.get(s.table_name, 0)),
            })
        return rows

    def _refresh_gauge(self) -> None:
        _M_RESIDENT.set(float(self.total_bytes()))

    # -- write path (engine/instance ingest hook) -------------------------

    def on_write(self, table_data, rows) -> None:
        """Called after each committed write group. Cheap when the table
        has no state. Never raises into the write path."""
        states = self.states_for_table(table_data.name)
        if not states:
            return
        if not livewindow_enabled():
            # a fold gap would poison a later re-enable: drop now
            for s in states:
                self.drop(s.key, outcome="disabled")
            return
        for s in states:
            if s.anchor() is not table_data:
                continue  # another incarnation of the name owns writes
            with s.lock:
                ok = s.fold(rows)
            if not ok:
                self.drop(s.key, outcome="unfoldable")

    # -- promotion / eviction ---------------------------------------------

    def note_usage(self, shape_key: str, catalog, table, shape) -> None:
        """One eligible open-tail read that could NOT be state-served;
        at the promote threshold the shape becomes live state."""
        if not livewindow_enabled():
            return
        with self._lock:
            n = self._usage.get(shape_key, 0) + 1
            self._usage[shape_key] = n
        if n < promote_reads():
            return
        self.promote(shape_key, table, shape, observed_reads=n)

    def promote(self, shape_key: str, table, shape,
                observed_reads: int = 0) -> Optional[LiveState]:
        from ..obs.decisions import record_decision
        from ..obs.device import refresh_occupancy, register_occupancy_provider

        table_data = getattr(table, "data", None)
        if table_data is None:
            return None  # no engine write path -> the hook never fires
        if table.options.update_mode is not UpdateMode.APPEND:
            return None  # overwrite dedup would double-fold re-writes
        table_name, ts_col, value_col, tags, step_ms = shape
        with self._lock:
            if shape_key in self._states:
                return self._states[shape_key]
            state = LiveState(
                shape_key, table_name, ts_col, value_col, tags, step_ms,
                ring_depth(), table_data,
            )
            schema = table.schema
            all_tags = tuple(
                schema.columns[i].name for i in schema.tag_indexes
            )
            state.all_tags = set(tags) == set(all_tags)
            # register FIRST: concurrent commits fold from here on, so
            # the max-ts read below can only OVER-estimate valid_from
            self._states[shape_key] = state
            self._by_table.setdefault(table_name, []).append(shape_key)
            self._usage.pop(shape_key, None)
            if not self._registered:
                register_occupancy_provider(self)
                self._registered = True
        try:
            rg = table.read(projection=[ts_col])
            max_ts = int(rg.timestamps.max()) if len(rg) else None
        except Exception:
            self.drop(shape_key, journal=False)
            return None
        with state.lock:
            state.valid_from = (
                (max_ts // step_ms) + 1 if max_ts is not None
                else _FAR_PAST // step_ms
            )
        record_decision(
            "livewindow", key=shape_key, choice="promote",
            features={
                "reads_before": int(observed_reads),
                "depth": state.depth,
                "window_ms": step_ms,
                "bytes": state.nbytes(),
            },
            # grade: at least as many state-served reads before eviction
            # as eligible reads observed before promotion
            predicted=float(max(observed_reads, promote_reads())),
        )
        self._evict_over_budget()
        self._refresh_gauge()
        refresh_occupancy(force=True)
        return state

    def drop(self, key: str, outcome: str = "dropped",
             journal: bool = True) -> None:
        from ..obs.decisions import DECISION_JOURNAL
        from ..obs.device import refresh_occupancy

        with self._lock:
            state = self._states.pop(key, None)
            if state is None:
                return
            keys = self._by_table.get(state.table_name)
            if keys and key in keys:
                keys.remove(key)
                if not keys:
                    self._by_table.pop(state.table_name, None)
            if outcome == "evict":
                self._evictions[state.table_name] = (
                    self._evictions.get(state.table_name, 0) + 1
                )
        if journal:
            DECISION_JOURNAL.resolve_matching(
                "livewindow", key,
                actual=float(state.reads_served), outcome=outcome,
            )
        if outcome == "evict":
            _M_EVICTIONS.inc()
        self._refresh_gauge()
        refresh_occupancy(force=True)

    def _evict_over_budget(self) -> None:
        budget = budget_bytes()
        while True:
            with self._lock:
                total = sum(s.nbytes() for s in self._states.values())
                if total <= budget or not self._states:
                    return
                victim = min(
                    self._states.values(), key=lambda s: s.last_hit
                )
            self.drop(victim.key, outcome="evict")

    def clear(self) -> None:
        for key in list(self._states):
            self.drop(key, journal=False)
        with self._lock:
            self._usage.clear()

    def stats(self) -> dict:
        with self._lock:
            states = list(self._states.values())
            usage = dict(self._usage)
        return {
            "enabled": livewindow_enabled(),
            "budget_bytes": budget_bytes(),
            "resident_bytes": sum(s.nbytes() for s in states),
            "states": [
                {
                    "key": s.key,
                    "table": s.table_name,
                    "window_ms": s.bucket_ms,
                    "tags": list(s.tags),
                    "depth": s.depth,
                    "groups": len(s.group_vals),
                    "bytes": s.nbytes(),
                    "head_bucket": s.head,
                    "valid_from": s.valid_from,
                    "dirty_buckets": len(s.dirty),
                    "counter_dirty": len(s.counter_dirty),
                    "reads_served": s.reads_served,
                }
                for s in states
            ],
            "pending": usage,
        }


STORE = LiveWindowStore()


def on_write(table_data, rows) -> None:
    """The engine write-path hook (engine/instance._commit_write_group)."""
    STORE.on_write(table_data, rows)


# ---- the ONE eligibility predicate (executor + EXPLAIN) -------------------


@dataclass(frozen=True)
class LiveWindowDecision:
    state_key: str
    table: str
    step_ms: int
    # the state serves COMPLETE buckets [s_lo, s_hi); raw computes the
    # partial head [start, s_lo) and (for a bounded end at/below the
    # folded watermark) the partial tail [s_hi, end)
    s_lo: int
    s_hi: int
    start: int
    end: int
    n_buckets: int


def _plan_shape(catalog, plan):
    """Structural eligibility (state existence NOT required): the same
    dashboard shape family as rules/rewrite.rollup_decision_for.
    -> (table, ts_col, value_col, tags, step_ms) or None."""
    if not isinstance(plan, QueryPlan) or not plan.is_aggregate:
        return None
    if plan.agg_exprs:
        return None
    sel = plan.select
    if (
        sel.join is not None
        or sel.joins
        or sel.distinct
        or sel.having is not None
    ):
        return None
    schema = plan.schema
    ts_col = schema.timestamp_name
    bucket_keys = [k for k in plan.group_keys if k.time_bucket_ms]
    if len(bucket_keys) != 1:
        return None
    step_ms = int(bucket_keys[0].time_bucket_ms)
    if step_ms <= 0 or step_ms >= (1 << 31):
        return None
    all_tags = {schema.columns[i].name for i in schema.tag_indexes}
    group_tags = []
    for k in plan.group_keys:
        if k.time_bucket_ms:
            continue
        if k.column is None or k.column not in all_tags:
            return None
        group_tags.append(k.column)
    if not plan.aggs:
        return None
    value_col = plan.aggs[0].column
    if value_col is None:
        return None  # count(*): NULL semantics differ from count(value)
    for a in plan.aggs:
        if (
            a.func not in _FOLDABLE
            or a.distinct
            or a.filter_where is not None
            or a.column2 is not None
            or a.params
            or a.column != value_col
        ):
            return None
    col = schema.column(value_col)
    if col.name in all_tags or value_col == ts_col:
        return None
    out_names = []
    for item in sel.items:
        e = item.expr
        if _is_bucket_expr(e, ts_col):
            pass
        elif isinstance(e, ast.Column) and e.name in all_tags:
            if e.name not in group_tags:
                return None
        elif isinstance(e, ast.FuncCall) and e.name in _FOLDABLE:
            pass
        else:
            return None
        out_names.append(item.output_name)
    for o in sel.order_by:
        name = o.expr.name if isinstance(o.expr, ast.Column) else str(o.expr)
        if name not in out_names:
            return None
    tag_conjuncts, ok = _split_where(plan, all_tags, ts_col)
    if not ok:
        return None
    for c in tag_conjuncts:
        if not _conj_supported(c, all_tags):
            return None
        # a filter over a tag OUTSIDE the group-set partitions rows the
        # state folded together: refuse (the grouped state can't apply it)
        from ..query.executor import _columns_of

        if {cc.name for cc in _columns_of(c)} - set(group_tags):
            return None
    return (plan.table, ts_col, value_col, tuple(group_tags), step_ms)


def _is_bucket_expr(e: ast.Expr, ts_col: str) -> bool:
    return (
        isinstance(e, ast.FuncCall)
        and e.name in ("time_bucket", "date_trunc")
        and e.args
        and isinstance(e.args[0], ast.Column)
        and e.args[0].name == ts_col
    )


def _split_where(plan, tags, ts_col):
    from ..rules.rewrite import _split_where as _impl

    return _impl(plan, tags, ts_col)


def _shape_key(shape) -> str:
    table, _ts, value_col, tags, step_ms = shape
    return f"{table}|{value_col}|{','.join(tags)}|{step_ms}"


def _open_tail(end: int, step_ms: int) -> bool:
    """Is this the live edge a dashboard re-asks? Unbounded, or an upper
    bound within two buckets of now."""
    if end == MAX_TIMESTAMP:
        return True
    return end >= int(time.time() * 1000) - 2 * step_ms


def livewindow_decision_for(catalog, plan) -> Optional[LiveWindowDecision]:
    """THE shared serve-from-state predicate (executor hook + EXPLAIN).
    Pure: no usage counting, no promotion."""
    if not livewindow_enabled():
        return None
    shape = _plan_shape(catalog, plan)
    if shape is None:
        return None
    key = _shape_key(shape)
    state = STORE.get(key)
    if state is None:
        return None
    table = catalog.open(plan.table)
    if table is None or getattr(table, "data", None) is not state.anchor():
        return None
    w = state.bucket_ms
    tr = plan.predicate.time_range
    start, end = tr.inclusive_start, tr.exclusive_end
    with state.lock:
        floor_b = state.serve_floor()
        head = state.head
        max_ts = state.max_folded_ts
    if head is None:
        return None
    s_lo_b = floor_b
    if start != MIN_TIMESTAMP:
        s_lo_b = max(s_lo_b, -(-start // w))  # first COMPLETE bucket
    s_lo = s_lo_b * w
    if end == MAX_TIMESTAMP or end > max_ts:
        s_hi = end  # open tail: buckets past the head hold no rows
        hi_b = head
    else:
        s_hi = (end // w) * w  # partial end bucket stays raw
        hi_b = min(head, s_hi // w - 1)
    if s_lo >= s_hi or hi_b < s_lo_b:
        return None
    return LiveWindowDecision(
        state_key=key,
        table=plan.table,
        step_ms=w,
        s_lo=s_lo,
        s_hi=s_hi,
        start=start,
        end=end,
        n_buckets=hi_b - s_lo_b + 1,
    )


# ---- the serve ------------------------------------------------------------


def try_livewindow_serve(factory, plan):
    """Serve an eligible open-tail aggregate head-from-rollup/raw +
    tail-from-state; None when the predicate refuses (caller runs the
    normal path, including the rollup rewrite). ``factory`` is the
    InterpreterFactory (catalog + executor)."""
    if not livewindow_enabled() or not isinstance(plan, QueryPlan):
        return None
    shape = _plan_shape(factory.catalog, plan)
    if shape is None:
        return None
    decision = livewindow_decision_for(factory.catalog, plan)
    if decision is None:
        # an eligible open-tail read the state could not serve: usage
        # feeds the promotion loop (the dtype auto-tuner discipline)
        tr = plan.predicate.time_range
        if _open_tail(tr.exclusive_end, shape[4]):
            table = factory.catalog.open(plan.table)
            if table is not None:
                STORE.note_usage(_shape_key(shape), factory.catalog, table, shape)
        return None
    state = STORE.get(decision.state_key)
    if state is None:
        return None  # evicted between decision and serve: run raw

    from ..query.interpreters import _concat_results, _order_limit_result
    from ..utils import querystats
    from ..utils.tracectx import span as _span

    sel = plan.select
    table_name, ts_col, value_col, tags, step_ms = shape
    schema = plan.schema
    all_tags = {schema.columns[i].name for i in schema.tag_indexes}
    tag_conjuncts, _ = _split_where(plan, all_tags, ts_col)

    with _span("livewindow_gather", table=table_name):
        part = _state_result(state, decision, sel, shape, tag_conjuncts)
    if part is None:
        return None  # state mutated underneath (evicted/reset): run raw
    results = [part]

    # raw/rollup halves: the partial HEAD [start, s_lo) and — for a
    # bounded end below the folded watermark — the partial TAIL [s_hi, end)
    raw_metrics = None
    raw_ranges = []
    if decision.start < decision.s_lo:
        raw_ranges.append((decision.start, decision.s_lo))
    if decision.s_hi < decision.end:
        raw_ranges.append((decision.s_hi, decision.end))
    if raw_ranges and any(
        (lo // step_ms) in state.dirty or (hi - 1) // step_ms in state.dirty
        for lo, hi in raw_ranges
    ):
        _M_DIRTY.inc()
    if raw_ranges:
        import dataclasses

        from ..query.planner import Planner
        from ..rules.rewrite import _and, try_rollup_serve

        planner = Planner(factory.catalog.schema_of)
        ts = ast.Column(ts_col)
        for r_start, r_end in raw_ranges:
            raw_where = list(tag_conjuncts)
            if r_start > MIN_TIMESTAMP:
                raw_where.append(ast.BinaryOp(">=", ts, ast.Literal(r_start)))
            if r_end < MAX_TIMESTAMP:
                raw_where.append(ast.BinaryOp("<", ts, ast.Literal(r_end)))
            raw_select = dataclasses.replace(
                sel,
                items=tuple(
                    ast.SelectItem(i.expr, alias=i.output_name)
                    for i in sel.items
                ),
                where=_and(raw_where),
                order_by=(),
                limit=None,
                offset=0,
            )
            raw_plan = planner.plan(raw_select)
            src_table = factory.catalog.open(plan.table)
            with _span("livewindow_raw_part", table=plan.table):
                # the closed head may itself serve from the rollup ladder
                served = try_rollup_serve(factory, raw_plan)
                if served is None:
                    served = factory.executor.execute(raw_plan, src_table)
                results.append(served)
            m_part = factory.executor.last_metrics or {}
            raw_metrics = (
                m_part if raw_metrics is None else {
                    "rows_scanned": raw_metrics.get("rows_scanned", 0)
                    + m_part.get("rows_scanned", 0)
                }
            )

    combined = results[0] if len(results) == 1 else _concat_results(results)
    combined = _order_limit_result(
        combined, sel.order_by, sel.limit, sel.offset
    )
    with state.lock:
        state.reads_served += 1
        state.last_hit = time.time()
    m = {
        "table": plan.table,
        "path": "livewindow",
        "window_ms": decision.step_ms,
        "state_buckets": decision.n_buckets,
        "serve_lo": decision.s_lo,
        "serve_hi": decision.s_hi,
        "raw_head_rows": (
            raw_metrics.get("rows_scanned", 0) if raw_metrics else 0
        ),
        "result_rows": combined.num_rows,
    }
    combined.metrics = m
    factory.executor.last_path = "livewindow"
    factory.executor.last_metrics = m
    # first-class route in the ledger/query_stats (set AFTER the halves
    # so their sub-executions' routes don't win)
    querystats.set_route("livewindow")
    querystats.record(state_buckets=decision.n_buckets)
    _M_READS.inc()
    return combined


def _state_result(state, decision, sel, shape, tag_conjuncts):
    """Materialize the state-served buckets [s_lo, s_hi) as a ResultSet
    aligned to the original select items; None if the state can no
    longer cover the range (evicted/reset mid-query)."""
    from ..query.executor import ResultSet

    table_name, ts_col, value_col, tags, w = shape
    b_lo = decision.s_lo // w
    b_hi_req = decision.s_hi // w - (0 if decision.s_hi % w else 1)
    with state.lock:
        if STORE.get(decision.state_key) is not state:
            return None
        if state.head is None or state.serve_floor() > b_lo:
            return None
        (ids, counts, sums, mins, maxs, _inc, _f, _l) = state.read_buckets(
            b_lo, b_hi_req
        )
        groups = list(state.group_vals)

    # tag filters evaluate against the group tuples on host
    if tag_conjuncts and groups:
        keep = []
        for gi, gv in enumerate(groups):
            vals = dict(zip(tags, gv))
            if all(_eval_conj(c, vals) for c in tag_conjuncts):
                keep.append(gi)
        gsel = np.asarray(keep, dtype=np.int64)
    else:
        gsel = np.arange(len(groups), dtype=np.int64)

    nb = len(ids)
    if nb and len(gsel):
        counts = counts[:, gsel]
        cells = counts > 0  # a (bucket, group) cell with no rows emits none
        bi, gj = np.nonzero(cells)
    else:
        bi = gj = np.empty(0, dtype=np.int64)
        counts = np.zeros((nb, len(gsel)), dtype=np.int64)
    bucket_vals = (np.asarray(ids, dtype=np.int64)[bi] * w) if len(bi) else \
        np.empty(0, dtype=np.int64)
    cnt = counts[bi, gj].astype(np.int64) if len(bi) else \
        np.empty(0, dtype=np.int64)

    def cells_of(arr):
        if not len(bi):
            return np.empty(0, dtype=np.float64)
        return arr[:, gsel][bi, gj].astype(np.float64)

    names, cols, nulls = [], [], {}
    for item in sel.items:
        e = item.expr
        name = item.output_name
        names.append(name)
        if _is_bucket_expr(e, ts_col):
            cols.append(bucket_vals)
        elif isinstance(e, ast.Column):
            gvals = [groups[int(gsel[j])][tags.index(e.name)] for j in gj]
            arr = np.array(gvals, dtype=object)
            mask = np.array([v is None for v in gvals], dtype=bool)
            cols.append(arr)
            if mask.any():
                nulls[name] = mask
        else:  # a foldable aggregate (the predicate admitted nothing else)
            f = e.name
            if f == "count":
                cols.append(cnt)
            elif f == "sum":
                cols.append(cells_of(sums))
            elif f == "min":
                cols.append(cells_of(mins))
            elif f == "max":
                cols.append(cells_of(maxs))
            else:  # avg
                with np.errstate(invalid="ignore"):
                    cols.append(
                        cells_of(sums) / np.maximum(cnt, 1).astype(np.float64)
                    )
    return ResultSet(names, cols, nulls or None)
