"""horaedb_tpu — a TPU-native distributed time-series database framework.

A ground-up re-design of the capabilities of Apache HoraeDB (incubating)
(/root/reference, Rust+Go) for TPU hardware: queries compile to fused
JAX/XLA kernels (scan → filter → time-bucket → group-by → aggregate in one
jit program), compaction's k-way merge-dedup runs as a device sort kernel,
and distributed execution is expressed as sharded partial aggregation over a
``jax.sharding.Mesh`` with XLA collectives instead of gRPC-shipped plans.

Layer map (mirrors reference SURVEY layer map, re-architected TPU-first):

    server/     HTTP front end                  (ref: src/server)
    proxy/      request orchestration, routing  (ref: src/proxy)
    query/      SQL front end -> Plan -> interpreters -> executor
                (ref: src/query_frontend, src/interpreters, src/query_engine)
    ops/        the TPU compute path: fused scan/agg, merge-dedup sort
                (ref: DataFusion's vectorized operators, re-built on XLA)
    table_engine/  Table/TableEngine abstraction, partition rules
                (ref: src/table_engine, src/partition_table_engine)
    engine/     analytic LSM storage engine: memtable, SST, WAL, manifest,
                flush, compaction                (ref: src/analytic_engine)
    parallel/   device mesh, sharded distributed aggregation
                (ref: src/df_engine_extensions dist push-down)
    cluster/    shard membership, routing        (ref: src/cluster, src/router)
    utils/      object store, codecs, config, metrics, runtime
                (ref: src/components/*)
"""

__version__ = "0.1.0"

from .db import Connection, connect  # noqa: E402

__all__ = ["Connection", "connect", "__version__"]
