"""SST inspection tool (ref: src/tools sst-metadata bin — dumps the
custom metadata + parquet layout of an SST file).

    python -m horaedb_tpu.tools.sst_metadata PATH [PATH...]
    python -m horaedb_tpu.tools.sst_metadata --dir DATA_DIR  # every .sst
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def describe(path: str) -> dict:
    import pyarrow.parquet as pq

    from ..engine.sst.meta import footer_payload

    pf = pq.ParquetFile(path, memory_map=True)
    md = pf.metadata
    try:
        own = footer_payload(pf, path)
    except ValueError:
        own = None
    row_groups = []
    for rg in range(md.num_row_groups):
        g = md.row_group(rg)
        cols = {}
        for ci in range(g.num_columns):
            col = g.column(ci)
            st = col.statistics
            if st is not None and st.has_min_max:
                cols[col.path_in_schema] = {
                    "min": _plain(st.min),
                    "max": _plain(st.max),
                    "nulls": st.null_count,
                }
        row_groups.append(
            {
                "rows": g.num_rows,
                "bytes": g.total_byte_size,
                "column_stats": cols,
            }
        )
    return {
        "path": path,
        "file_bytes": os.path.getsize(path),
        "rows": md.num_rows,
        "row_groups": md.num_row_groups,
        "columns": [md.schema.column(i).name for i in range(md.num_columns)],
        "sst_meta": own,
        "row_group_stats": row_groups,
    }


def _plain(v):
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    if hasattr(v, "isoformat"):
        return v.isoformat()
    return v


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="dump horaedb_tpu SST metadata")
    p.add_argument("paths", nargs="*", help=".sst files")
    p.add_argument("--dir", default=None, help="scan a data dir for .sst files")
    p.add_argument("--brief", action="store_true", help="one summary line per file")
    args = p.parse_args(argv)
    paths = list(args.paths)
    if args.dir:
        for root, _, files in os.walk(args.dir):
            paths += [os.path.join(root, f) for f in files if f.endswith(".sst")]
    if not paths:
        p.error("no SST paths given")
    for path in paths:
        try:
            d = describe(path)
        except Exception as e:
            print(f"{path}: ERROR {e}", file=sys.stderr)
            continue
        if args.brief:
            m = d["sst_meta"] or {}
            print(
                f"{path}\trows={d['rows']}\tgroups={d['row_groups']}\t"
                f"bytes={d['file_bytes']}\tfile_id={m.get('file_id')}\t"
                f"max_seq={m.get('max_sequence')}\t"
                f"time_range={m.get('time_range')}"
            )
        else:
            print(json.dumps(d, indent=2, default=str))
    return 0


if __name__ == "__main__":
    sys.exit(main())
